#!/usr/bin/env python
"""Throughput benchmark: GPT-2 trusted training, detection ON vs OFF.

Measures tokens/sec/chip of the jitted trusted train step (engine/step.py)
on the available accelerator, with the full in-step detection battery
(17-stat batteries, Byzantine/backdoor checks, verification, trust update,
trust-gated aggregation) enabled vs disabled.  The detection overhead is the
framework's headline number — BASELINE.md sets a ≤15 % target (the reference
publishes no numbers of its own).

Prints ONE JSON line on stdout:
  {"metric": ..., "value": tokens/sec/chip with detection ON,
   "unit": "tokens/sec/chip",
   "vs_baseline": ON/OFF throughput ratio (1.0 = free detection; the
                  baseline is this framework's own detection-off path)}
Diagnostics go to stderr.

Env overrides: TDDL_BENCH_MODEL (gpt2), TDDL_BENCH_NODES (4),
TDDL_BENCH_BATCH (per-node, 16), TDDL_BENCH_SEQ (512),
TDDL_BENCH_STEPS (20), TDDL_BENCH_WARMUP (3), TDDL_BENCH_REMAT (1),
TDDL_BENCH_CHUNK (unset = model default "auto"; 0 forces the
materialised-logits CE; >0 forces the fused vocab-chunked head),
TDDL_BENCH_ATTN (model default), TDDL_BENCH_ACCUM (grad accumulation
microbatches, 1).  Optional legs: TDDL_BENCH_LONGCTX=1 (flash vs XLA
long-context A/B), TDDL_BENCH_GEN=1 (decode), TDDL_BENCH_SERVE=1
(continuous-batching offered-load sweep + paged-vs-stripe KV A/B at
equal HBM: concurrent-request capacity ratio, tokens-in-flight
occupancy, prefix-cache hit rate — "serve_paged" record key,
TDDL_BENCH_PAGED_* knobs; TDDL_BENCH_SPEC=1 rides it and adds the
speculative-decode A/B — spec off vs spec_k ∈ {2,4} over identical
seeded traffic, accepted_rate + draft/verify tick fractions +
tokens/s per arm, "spec" record key whose accepted_rate feeds the
sentinel fingerprint, TDDL_BENCH_SPEC_* knobs; TDDL_BENCH_PAGED_ATTN=1
also rides it and adds the paged-attention kernel A/B — attn_impl
"pallas" vs the jnp gather fallback over identical seeded traffic,
tokens/s + decode-tick fraction + standalone monitor-reduction cost
delta, "paged_attn" record key whose decode_tick_fraction feeds the
sentinel fingerprint; honest skip off-TPU where compiled Mosaic cannot
dispatch, TDDL_BENCH_PAGED_ATTN_* knobs), TDDL_BENCH_CHAOS=1 (seeded
chaos survival sweep through the self-healing supervisor),
TDDL_BENCH_ASYNC=1 (async host-pipeline A/B: trainer loop at
async_host_depth 0 vs default, tokens/sec + obs phase shares),
TDDL_BENCH_QUANT=1 (int8 KV quantization A/B: model-dtype vs int8 KV
pool at EQUAL HBM budget — slots, KV bytes and tokens/s per arm;
TDDL_BENCH_QUANT_W8=1 adds weight-only int8 to the quantized arm),
TDDL_BENCH_MIGRATE=1 (live KV-migration A/B: capacity loss as block
copy vs prompt replay + unified vs disaggregated prefill/decode pools
under a bimodal prompt mix, "migrate" record key whose
migration_fraction feeds the sentinel fingerprint,
TDDL_BENCH_MIGRATE_* knobs),
TDDL_BENCH_SHARD=1 (equal-chip replicated vs FSDP train state:
tokens/s, per-device HBM watermark, params/opt bytes per device from
the placed shardings — ratio near 1/shards; TDDL_BENCH_SHARD_* knobs),
TDDL_BENCH_FLEET=1 (serving-fleet goodput-under-SLO vs offered load,
chaos OFF vs ON over identical seeded workloads — "fleet" record key,
TDDL_BENCH_FLEET_* knobs), TDDL_BENCH_ADVERSARY=1 (goodput under an
adaptive sub-threshold poison attack, verdict voting OFF vs ON over
identical seeded traffic — "adversary" record key,
TDDL_BENCH_ADVERSARY_* knobs), TDDL_BENCH_AUTOSCALE=1 (fleet control
plane A/B: static fleet at max replicas vs autoscaled min→max over
identical seeded bursty traffic — replica-count trace, scale event
counts and per-class goodput per arm, "autoscale" record key,
TDDL_BENCH_AUTOSCALE_* knobs; the fleet leg's rows also carry
per-class goodput now).
Infra knobs: TDDL_BENCH_PROBE_TIMEOUT (backend liveness probe seconds,
default 180; a successful probe is cached for the process AND persisted
to disk — TDDL_BENCH_PROBE_CACHE sets the file, default
<tmpdir>/tddl_bench_probe.json, TDDL_BENCH_PROBE_REFRESH=1 forces a
fresh probe — so one healthy probe stops later rounds from re-probing
a flaky tunnel into 3x180 s timeouts),
TDDL_BENCH_COMPILE_CACHE=1 (persistent XLA compilation cache under
TDDL_BENCH_OBS_DIR, so repeat runs skip recompiles);
TDDL_BENCH_LINT=1 (tddl-lint static-analysis leg in a jax-free
subprocess before any device work: clean -> "lint" record section,
findings -> rc 4; TDDL_BENCH_LINT_TIMEOUT seconds, default 300).

``--config <preset>`` selects a BASELINE.md benchmark-matrix shape
(`--config list` prints them); env overrides still apply on top.  The
default preset is the measured single-v5e sweet spot: per-node batch 16
(64 x 512 tokens/step) with block rematerialisation.
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# Successful backend-probe result, cached per process (count, platform):
# one slow init must not skip a whole multi-leg sweep that re-probes.
_PROBE_CACHE = None


def _probe_cache_path() -> str:
    """Disk home of the backend-probe success cache
    (TDDL_BENCH_PROBE_CACHE overrides).  Cross-PROCESS: one healthy probe
    must stop later bench rounds in the same container from re-probing a
    flaky tunnel into 3x180 s timeouts (BENCH_r04/r05 lost whole rounds
    to exactly that)."""
    import tempfile

    return os.environ.get(
        "TDDL_BENCH_PROBE_CACHE",
        os.path.join(tempfile.gettempdir(), "tddl_bench_probe.json"),
    )


def _read_probe_cache() -> "tuple[int, str] | None":
    """(device_count, platform) from a prior healthy probe, or None.
    A probe taken under a DIFFERENT backend selection (JAX_PLATFORMS)
    is stale, not reusable — a cpu debug round must not label the next
    TPU round's artifact cpu/1-chip."""
    try:
        with open(_probe_cache_path()) as f:
            saved = json.load(f)
        if saved.get("jax_platforms") != os.environ.get("JAX_PLATFORMS",
                                                        ""):
            return None
        return max(int(saved["device_count"]), 1), str(saved["platform"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _write_probe_cache(count: int, platform: str) -> None:
    """Best-effort persist of a healthy probe (atomic; failures only
    cost the next round a re-probe, never the current one)."""
    path = _probe_cache_path()
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"device_count": int(count),
                       "platform": str(platform),
                       "jax_platforms": os.environ.get("JAX_PLATFORMS",
                                                       ""),
                       "probed_at": time.time()}, f)
        os.replace(tmp, path)
    except OSError as exc:
        log(f"probe cache not persisted to {path}: {exc}")


def _perf_ledger_path() -> str:
    """Home of the rolling bench perf-fingerprint ledger
    (TDDL_BENCH_PERF_LEDGER overrides; default: PERF_LEDGER.jsonl in the
    cwd, which the driver runs from the repo root — one trajectory file
    across rounds)."""
    return os.environ.get("TDDL_BENCH_PERF_LEDGER", "PERF_LEDGER.jsonl")


def _prior_ledger_pointer() -> "dict | None":
    """Compact pointer at the prior round's perf-ledger entry, stamped
    into SKIP records so BENCH_r04/r05-style infra skips stay
    attributable in the perf trajectory: a reader sees what the LAST
    healthy round measured instead of a bare {"skipped": true}."""
    try:
        from trustworthy_dl_tpu.obs.sentinel import PerfLedger

        path = _perf_ledger_path()
        entries = PerfLedger(path).read()
        if not entries:
            return None
        last = entries[-1]
        return {
            "path": path,
            "entries": len(entries),
            "last": {k: last.get(k) for k in
                     ("key", "t", "tokens_per_s", "compile_total",
                      "hbm_watermark_bytes", "regressed")
                     if k in last},
        }
    except Exception:  # the pointer must never break the skip contract
        return None


def _skip_record(reason: str, **extra) -> dict:
    """The one-line skip JSON (driver contract: rc 0, parsable,
    attributable).  Carries a HOST-ONLY run-metadata stamp — the
    backend is the very thing that is broken, so device discovery must
    not run — plus the prior-round ledger pointer."""
    record = {
        "metric": "skipped", "value": 0, "unit": "none",
        "vs_baseline": None, "skipped": True, "reason": reason,
        "prior_ledger": _prior_ledger_pointer(),
    }
    if _LINT_RECORD is not None:
        # A lint leg that ran before the backend died still reports.
        record["lint"] = _LINT_RECORD
    try:
        from trustworthy_dl_tpu.obs.meta import run_metadata

        record["run_metadata"] = run_metadata(host_only=True)
    except Exception:
        record["run_metadata"] = None
    record.update(extra)
    return record


def _sentinel_rc(record: dict) -> int:
    """Exit code for the sentinel CI arm: TDDL_BENCH_SENTINEL=1 turns a
    confirmed regression (outside the ledger noise band) into rc 3 —
    off by default so the driver's rc-0 contract is unchanged."""
    if os.environ.get("TDDL_BENCH_SENTINEL") != "1":
        return 0
    sentinel = record.get("sentinel") or {}
    return 3 if sentinel.get("regressed") else 0


_LINT_RECORD = None


def bench_lint() -> "dict | None":
    """Static-analysis leg (TDDL_BENCH_LINT=1): run trustworthy-dl-lint
    in a SUBPROCESS — the lint process is host-only by contract and
    never imports jax, so this leg works (and matters most) when the
    accelerator backend is the broken thing.  No-op (None) when unset.

    Clean lint attaches a compact "lint" section to whatever record the
    round emits (perf row or skip record); findings fail the round
    loudly with rc 4 BEFORE any device work is paid for — the CI arm
    asserts rc 0 exactly like the sentinel's rc-3 contract."""
    if os.environ.get("TDDL_BENCH_LINT") != "1":
        return None
    import subprocess

    t0 = time.time()
    timeout = float(os.environ.get("TDDL_BENCH_LINT_TIMEOUT", "300"))
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "trustworthy_dl_tpu.analysis",
             "--format", "json"],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        # A hung lint subprocess must degrade to a reportable failure,
        # never a raw traceback — same contract as the backend probe.
        return {"rc": -1, "timeout_s": timeout,
                "wall_s": round(time.time() - t0, 2),
                "files_scanned": None, "findings": [], "by_rule": {},
                "baselined": 0, "stale_baseline": [],
                "error": f"lint subprocess exceeded {timeout:g}s"}
    try:
        payload = json.loads(proc.stdout.strip() or "{}")
    except ValueError:
        payload = {}
    record = {
        "rc": proc.returncode,
        "wall_s": round(time.time() - t0, 2),
        "files_scanned": payload.get("files_scanned"),
        "findings": payload.get("findings", []),
        "by_rule": payload.get("by_rule", {}),
        "baselined": payload.get("baselined", 0),
        "stale_baseline": payload.get("stale_baseline", []),
    }
    if proc.returncode != 0 and proc.stderr:
        record["stderr"] = proc.stderr[-2000:]
    return record


def _attach_perf_sections(record: dict, compiles=None, hbm=None) -> dict:
    """The performance-observability sections every NON-SKIP bench
    record carries: "compile" (XLA compilations observed during the
    body), "hbm" (live-buffer sweep + watermark), "sentinel" (the perf
    fingerprint appended to the rolling ledger + the noise-band
    verdict against prior rounds)."""
    from trustworthy_dl_tpu.obs.compilewatch import CompileRegistry
    from trustworthy_dl_tpu.obs.hbm import HbmMonitor
    from trustworthy_dl_tpu.obs.sentinel import (
        PerfLedger,
        PerfSentinel,
        fingerprint,
    )

    if compiles is None:
        compiles = CompileRegistry()   # uninstalled: an all-zero section
    record["compile"] = compiles.summary()
    if hbm is None:
        hbm = HbmMonitor()
    sweep = hbm.sweep()
    record["hbm"] = {
        "live_bytes_per_device": sweep["per_device"],
        "total_bytes": sweep["total_bytes"],
        "watermark_bytes": sweep["watermark_bytes"],
    }
    ledger = PerfLedger(_perf_ledger_path())
    fp = fingerprint(
        "bench",
        metric=record.get("metric"),
        tokens_per_s=record.get("value") or None,
        compile_total=(record.get("compile") or {}).get("total"),
        compile_seconds=(record.get("compile") or {}).get("seconds"),
        hbm_watermark_bytes=sweep["watermark_bytes"] or None,
        # Speculative-decode draft quality (TDDL_BENCH_SPEC rounds):
        # rides the fingerprint so the sentinel bands it (direction
        # higher-is-better) like any perf metric.
        accepted_rate=(record.get("spec") or {}).get("accepted_rate"),
        # Decode-phase serve-wall share of the paged-attention kernel arm
        # (TDDL_BENCH_PAGED_ATTN rounds): direction lower-is-better — a
        # silent fallback to the jnp gather path inflates it.
        decode_tick_fraction=(record.get("paged_attn")
                              or {}).get("decode_tick_fraction"),
        # Prefill-chunk / spec-verify serve-wall shares of the kernel
        # arms (same rounds): direction lower-is-better — a silent
        # fallback of the chunked-prefill flash program or the fused
        # verify tail inflates exactly one of them, and the per-program
        # attn-kernel gauge names which.
        prefill_chunk_fraction=(record.get("paged_attn")
                                or {}).get("prefill_chunk_fraction"),
        spec_verify_fraction=(record.get("paged_attn")
                              or {}).get("spec_verify_fraction"),
        # Adapter-pool locality + equal-HBM personalisation cost
        # (TDDL_BENCH_ADAPTERS rounds): both higher-is-better — a
        # colder pool or a pricier adapter path bands like a perf
        # regression.
        adapter_hit_rate=(record.get("adapters") or {}).get("hit_rate"),
        adapter_tokens_ratio=(record.get("adapters")
                              or {}).get("tokens_per_s_ratio"),
        # Live-migration success under capacity loss (TDDL_BENCH_MIGRATE
        # rounds): higher-is-better — a silent fall-back to prompt
        # replay (geometry drift, claim refusals) drops it.
        migration_fraction=(record.get("migrate")
                            or {}).get("migration_fraction"),
        run_metadata=record.get("run_metadata"),
        extra={"vs_baseline": record.get("vs_baseline")},
    )
    verdict = PerfSentinel(ledger).check(fp)
    fp["regressed"] = verdict["regressed"]
    ledger.append(fp)
    record["sentinel"] = {
        "ledger": ledger.path,
        "baseline_n": verdict["baseline_n"],
        "regressed": verdict["regressed"],
        "checks": verdict["checks"],
        "fingerprint": fp,
    }
    if verdict["regressed"]:
        log(f"perf sentinel: REGRESSION outside the noise band: "
            f"{[c['metric'] for c in verdict['checks'] if c.get('regressed')]}"
            f" (TDDL_BENCH_SENTINEL=1 makes this exit non-zero)")
    return record


def _invalidate_probe_cache(reason: str) -> None:
    """Drop the healthy-probe record: the backend just proved unhealthy
    AFTER a cached probe (watchdog fire, body failure), so the next
    round must re-probe instead of skipping straight into another hang.
    Without this, one stale 'healthy' entry would cost every later
    round the full watchdog wait — strictly worse than the 3x probe
    timeout the cache exists to avoid."""
    try:
        os.remove(_probe_cache_path())
        log(f"probe cache invalidated ({reason})")
    except OSError:
        pass


# BASELINE.md benchmark-matrix presets (configs 1-4 shapes + extras), so
# driver BENCH_r*.json runs can capture any row reproducibly instead of
# builder-transcribed tables.  Values are defaults; TDDL_BENCH_* env
# overrides still win.
PRESETS = {
    # The headline row: GPT-2 small, 4 nodes x b16 x T512, remat.
    "default": {},
    # BASELINE config 1 shape: ResNet-32 / CIFAR-10.
    "resnet32": dict(model="resnet32", batch=64),
    # BASELINE config 2 shape: VGG-16 / CIFAR-10 (the conv-battery row).
    "vgg16": dict(model="vgg16", batch=64),
    "resnet50": dict(model="resnet50", batch=64),
    # BASELINE config 5's model (the sweep itself is an experiments
    # preset; this row gives its throughput baseline).
    "resnet101": dict(model="resnet101", batch=32),
    # BASELINE config 4 shape: GPT-2 medium.
    "gpt2-medium": dict(model="gpt2-medium", batch=8),
    # Long-context row: GPT-2 medium at T=1024, auto attention.
    "longctx": dict(model="gpt2-medium", batch=4, seq=1024),
}


def apply_preset(name: str) -> None:
    """Materialise a preset as TDDL_BENCH_* defaults (env wins)."""
    if name == "list":
        log("available presets: " + ", ".join(sorted(PRESETS)))
        sys.exit(0)
    if name not in PRESETS:
        log(f"unknown preset {name!r}; available: {sorted(PRESETS)}")
        sys.exit(2)
    keymap = {"model": "TDDL_BENCH_MODEL", "nodes": "TDDL_BENCH_NODES",
              "batch": "TDDL_BENCH_BATCH", "seq": "TDDL_BENCH_SEQ"}
    for key, value in PRESETS[name].items():
        os.environ.setdefault(keymap[key], str(value))


def bench_mode(detection: bool, model: str, num_nodes: int,
               per_node_batch: int, seq_len: int, steps: int,
               warmup: int, _attempt: int = 0) -> "tuple[float, int]":
    """(steps/sec, param count) of the jitted step, driven device-side
    (no host sync in the timed loop beyond dispatch).

    The remote-TPU compile tunnel fails transiently (HTTP 500 /
    truncated-body from the compile helper); such infrastructure errors —
    not OOMs or NaNs — are retried up to twice before giving up."""
    try:
        return _bench_mode(detection, model, num_nodes, per_node_batch,
                           seq_len, steps, warmup)
    except Exception as exc:
        msg = str(exc)
        transient = ("remote_compile" in msg or "response body" in msg
                     or "tpu_compile_helper" in msg)
        if transient and _attempt < 2:
            log(f"transient compile-tunnel failure (attempt {_attempt + 1})"
                f": {msg[:120]}; retrying")
            time.sleep(10 * (_attempt + 1))
            return bench_mode(detection, model, num_nodes, per_node_batch,
                              seq_len, steps, warmup, _attempt + 1)
        raise


def _build_bench_trainer(detection: bool, model: str, num_nodes: int,
                         per_node_batch: int, seq_len: int):
    """(trainer, initial state, node batch) — ONE construction shared by
    the sequential and interleaved measurement paths so their model
    overrides (remat / attention / lm-head chunk) can never diverge."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.core.config import TrainingConfig
    from trustworthy_dl_tpu.engine import DistributedTrainer

    config = TrainingConfig(
        model_name=model,
        dataset_name="openwebtext",
        batch_size=num_nodes * per_node_batch,
        num_nodes=num_nodes,
        optimizer=os.environ.get("TDDL_BENCH_OPT", "adamw"),
        learning_rate=1e-4,
        checkpoint_interval=10 ** 9,
        attack_detection_enabled=detection,
        gradient_verification_enabled=detection,
        parallelism="data",
        grad_accum_steps=int(os.environ.get("TDDL_BENCH_ACCUM", "1")),
        moment_dtype=os.environ.get("TDDL_BENCH_MU_DTYPE") or None,
    )
    overrides: dict = {}
    if model.startswith("gpt"):
        # Unset -> the model's lm_head_chunk="auto" dispatch; an explicit
        # value (including 0 = force materialised) overrides it.
        chunk_env = os.environ.get("TDDL_BENCH_CHUNK", "")
        if chunk_env != "":
            overrides["lm_head_chunk"] = int(chunk_env)
        overrides["seq_len"] = seq_len
        if seq_len > 1024:
            # Long-context runs need the position table to match.
            overrides["n_positions"] = seq_len
        attn = os.environ.get("TDDL_BENCH_ATTN")
        if attn:
            overrides["attn_impl"] = attn
        if os.environ.get("TDDL_BENCH_REMAT", "1") == "1":
            overrides["remat"] = True
            overrides["remat_policy"] = os.environ.get(
                "TDDL_BENCH_REMAT_POLICY", "block"
            )
    trainer = DistributedTrainer(config, model_overrides=overrides)
    trainer.initialize()
    batch = trainer._node_batch(jax.tree_util.tree_map(
        np.asarray,
        trainer.model.example_batch(num_nodes * per_node_batch,
                                    jax.random.PRNGKey(0)),
    ))
    return trainer, trainer.state, batch


def _bench_mode(detection: bool, model: str, num_nodes: int,
                per_node_batch: int, seq_len: int, steps: int,
                warmup: int) -> "tuple[float, int]":
    import jax
    import numpy as np

    trainer, state, batch = _build_bench_trainer(
        detection, model, num_nodes, per_node_batch, seq_len
    )
    n_params = trainer.model.num_params(state.params)
    plan = trainer.attack_plan

    for _ in range(max(warmup, 1)):
        state, metrics = trainer._train_step(state, batch, plan)
    jax.block_until_ready(metrics.loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer._train_step(state, batch, plan)
    jax.block_until_ready(metrics.loss)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(float(metrics.loss)), "bench step produced NaN loss"
    return steps / elapsed, n_params


def bench_overhead_interleaved(model: str, num_nodes: int,
                               per_node_batch: int, seq_len: int,
                               block_steps: int, rounds: int,
                               warmup: int) -> "tuple[float, float, int]":
    """(steps/sec detection-ON, ON/OFF ratio, param count), measured as
    INTERLEAVED paired blocks: both step functions are compiled up front,
    then each round times one OFF block and one ON block back-to-back and
    the ratio is the median of per-round ratios.

    Rationale: the remote-TPU tunnel's throughput drifts by ±15 % across
    multi-second windows, so the sequential all-OFF-then-all-ON design
    reads anything from −1 % to +26 % overhead for short-step (vision)
    configs.  Pairing blocks a few hundred ms apart cancels the drift;
    the remaining per-round scatter is reported to stderr."""
    import numpy as np

    tr_on, st_on, b_on = _build_bench_trainer(
        True, model, num_nodes, per_node_batch, seq_len
    )
    tr_off, st_off, b_off = _build_bench_trainer(
        False, model, num_nodes, per_node_batch, seq_len
    )
    n_params = tr_on.model.num_params(st_on.params)

    def block(trainer, state, batch, steps):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = trainer._train_step(state, batch,
                                           trainer.attack_plan)
        loss = float(np.asarray(m.loss))  # host close: real execution
        assert np.isfinite(loss)
        return state, time.perf_counter() - t0

    for _ in range(max(warmup, 1)):
        st_on, _ = block(tr_on, st_on, b_on, 1)
        st_off, _ = block(tr_off, st_off, b_off, 1)

    ratios, on_rates = [], []
    for r in range(rounds):
        st_off, t_off = block(tr_off, st_off, b_off, block_steps)
        st_on, t_on = block(tr_on, st_on, b_on, block_steps)
        ratios.append(t_off / t_on)
        on_rates.append(block_steps / t_on)
        log(f"  round {r}: OFF {block_steps / t_off:7.2f} ON "
            f"{block_steps / t_on:7.2f} steps/s (ratio {t_off / t_on:.4f})")
    return (float(np.median(on_rates)), float(np.median(ratios)), n_params)


def bench_longctx() -> None:
    """Optional long-context A/B (TDDL_BENCH_LONGCTX=1): flash-kernel vs
    XLA full attention, fwd+bwd, at sequence lengths where the [T, T]
    score matrix starts to dominate HBM.  Iterations chain (q feeds back)
    inside one jitted fori_loop, the close is a HOST MATERIALISATION
    (``block_until_ready`` does not wait on the remote tunnel — measured
    r4), and the per-call RPC constant is removed with a two-iteration-
    count slope.  Diagnostics only — stderr."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from trustworthy_dl_tpu.models.gpt2 import full_attention
    from trustworthy_dl_tpu.ops.flash_attention import flash_attention

    b, h, d = 1, 12, 64
    i1 = int(os.environ.get("TDDL_BENCH_LONGCTX_ITERS", "4"))
    i2 = 4 * i1
    for t in (4096, 8192, 16384):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
                   for kk in ks)

        def make(attn, iters):
            def loss(q):
                return jnp.sum(attn(q, k, v, True).astype(jnp.float32) ** 2)

            def body(_, q):
                return q + 1e-3 * jax.grad(loss)(q)

            @jax.jit
            def run(q):
                out = jax.lax.fori_loop(0, iters, body, q)
                return jnp.sum(out.astype(jnp.float32))

            return run

        for name, attn in (("flash", flash_attention),
                           ("full", full_attention)):
            try:
                f1, f2 = make(attn, i1), make(attn, i2)
                np.asarray(f1(q)); np.asarray(f2(q))  # compile + settle

                def timed(fn):
                    t0 = time.perf_counter()
                    np.asarray(fn(q))  # host close: real execution
                    return time.perf_counter() - t0

                t_1 = min(timed(f1) for _ in range(3))
                t_2 = min(timed(f2) for _ in range(3))
                ms = (t_2 - t_1) / (i2 - i1) * 1e3
                log(f"longctx T={t:5d} {name:5s} fwd+bwd "
                    f"{ms:8.2f} ms/iter ({b * t / ms * 1e3:,.0f} tok/s; "
                    f"slope over {i2}-{i1} iters)")
            except Exception as exc:  # OOM on the full path is the point
                log(f"longctx T={t:5d} {name:5s} failed: "
                    f"{type(exc).__name__}: {str(exc)[:120]}")


def _drive_serve_open_loop(engine, workload) -> int:
    """Drive seeded ``(t_arrive, request)`` pairs through an engine
    open-loop (arrivals honoured against the wall clock, so queueing
    delay is real) — the ONE spelling of the serve-bench driver, shared
    by the offered-load sweep and the speculative-decode A/B so their
    rows measure the same thing.  Returns how many requests were shed."""
    t0 = time.perf_counter()
    pending = list(workload)
    shed = 0
    while pending or engine.busy:
        # A slot is only quarantined at retirement, so zero capacity
        # implies nothing is in flight either.
        if engine.in_service_capacity == 0:
            # Every slot quarantined mid-bench: nothing queued or
            # pending can ever be served — shed the remainder rather
            # than spin until the watchdog kills the whole body
            # (run_until_idle has the same guard).
            shed += len(pending)
            pending.clear()
            engine.run_until_idle()  # records queued as no_capacity
            break
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, req = pending.pop(0)
            if engine.submit(req) is None:
                shed += 1
        if not engine.busy and pending:
            # Idle gap before the next arrival: sleep instead of
            # spinning step() — empty iterations would pile metrics
            # bookkeeping onto the numbers this sweep reports.
            time.sleep(min(max(pending[0][0] - now, 0.0), 0.05))
            continue
        engine.step()
    return shed


def _serve_sweep_row(engine, watcher, rate, shed) -> dict:
    """The serve-bench record row (throughput/latency/SLO keys) — one
    builder, so every arm that claims "today's serve record shape"
    really has it."""
    summary = engine.metrics_summary()
    status = watcher.status()
    return {
        "offered_rps": rate,
        "tokens_per_s": round(summary["tokens_per_s"], 1),
        # Decode-phase share of the serve wall + the attention path that
        # produced it — the pair the perf sentinel / attn-kernel gauge
        # watch for silent fallbacks to the slow jnp gather.
        "decode_tick_fraction": round(summary["decode_tick_fraction"], 4),
        "attn_kernel_path": summary["attn_kernel_path"],
        "itl_p50_ms": round(summary.get("itl_p50_ms", 0.0), 3),
        "itl_p99_ms": round(summary.get("itl_p99_ms", 0.0), 3),
        "ttft_p50_ms": round(summary.get("ttft_p50_ms", 0.0), 3),
        "completed": summary["requests_completed"],
        "shed": shed,
        "slo": {
            "rules": [{"name": r["name"], "target": r["target"],
                       "burn_rate": round(r["burn_rate"], 4),
                       "active": r["active"]}
                      for r in status["rules"]],
            "breach_total": status["breach_total"],
            "shed_slo": summary.get("requests_shed_slo", 0),
            "ttft_s": {k: round(v, 6) if isinstance(v, float) else v
                       for k, v in watcher.percentiles(
                           "ttft_s").items()},
            "itl_s": {k: round(v, 6) if isinstance(v, float) else v
                      for k, v in watcher.percentiles(
                          "itl_s").items()},
        },
    }


def bench_serve() -> "list[dict]":
    """Serving-engine leg (TDDL_BENCH_SERVE=1): offered-load sweep over the
    continuous-batching engine (serve/) — tokens/s, p50/p99 inter-token
    latency and p50 TTFT per offered request rate.  Returned as a list of
    per-rate records merged into the bench JSON under "serve" (the skip
    contract is untouched: a dead backend never reaches this leg).

    Arrivals are simulated open-loop: requests carry seeded arrival times
    and are submitted when the wall clock passes them, so queueing delay is
    real — TTFT degrades visibly once the offered rate passes the slot
    pool's capacity."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.serve import ServeRequest, ServingEngine

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_SERVE_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    max_slots = int(os.environ.get("TDDL_BENCH_SERVE_SLOTS", "8"))
    max_seq = int(os.environ.get("TDDL_BENCH_SERVE_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_SERVE_REQUESTS", "32"))
    max_new = int(os.environ.get("TDDL_BENCH_SERVE_NEW", "32"))
    rates = [float(r) for r in os.environ.get(
        "TDDL_BENCH_SERVE_RATES", "4,16,64").split(",")]
    rng = np.random.default_rng(0)

    records = []
    for rate in rates:
        # SLO evidence rides every sweep arm: streaming P2 TTFT/ITL
        # estimates + breach counts land in the record's "slo" section
        # (stamped run_metadata at the bench-JSON top level as always).
        from trustworthy_dl_tpu.obs.slo import SLOWatcher, \
            default_serve_rules

        watcher = SLOWatcher(default_serve_rules())
        engine = ServingEngine(params, cfg, max_slots=max_slots,
                               max_seq=max_seq, queue_limit=n_requests,
                               rng=jax.random.PRNGKey(1), slo=watcher)
        workload = []
        t_arrive = 0.0
        # Exclusive draw bound: plen <= max_seq - max_new, so prompt+new
        # can never exceed the slot depth whatever the env overrides say.
        plen_hi = min(64, max_seq - max_new + 1)
        if plen_hi <= 8:
            raise ValueError(
                f"TDDL_BENCH_SERVE_SEQ={max_seq} leaves no room for "
                f"prompts >= 8 tokens at TDDL_BENCH_SERVE_NEW={max_new}"
            )
        for _ in range(n_requests):
            t_arrive += rng.exponential(1.0 / rate)
            plen = int(rng.integers(8, plen_hi))
            workload.append((t_arrive, ServeRequest(
                prompt=rng.integers(0, cfg.vocab_size, plen).tolist(),
                max_new_tokens=int(rng.integers(min(4, max_new),
                                                max_new + 1)),
                temperature=0.8,
            )))
        shed = _drive_serve_open_loop(engine, workload)
        row = _serve_sweep_row(engine, watcher, rate, shed)
        log(f"serve offered={rate:6.1f} req/s: "
            f"{row['tokens_per_s']:8.1f} tok/s, ITL p50 "
            f"{row['itl_p50_ms']:.2f} ms / p99 {row['itl_p99_ms']:.2f} ms, "
            f"TTFT p50 {row['ttft_p50_ms']:.1f} ms, shed {shed}")
        records.append(row)
    return records


def bench_paged() -> "dict":
    """Paged-vs-stripe KV A/B (runs with TDDL_BENCH_SERVE=1): concurrency
    at an EQUAL HBM BUDGET.  The budget is what the stripe pool of
    TDDL_BENCH_PAGED_SLOTS full MAX_SEQ stripes costs; the paged arm gets
    ``paged_pool_blocks(budget)`` blocks and one decode row per block, so
    its admission is bounded by TOKENS in flight, not request count.  Two
    workloads:

    * **short-request mix** (both arms): every request uses a small
      fraction of a stripe — the stripe arm strands the rest, the paged
      arm packs blocks.  ``capacity_ratio`` = peak concurrently-active
      requests paged/stripe (the >= 1.5x acceptance bar lives in
      tests/test_bench_contract.py).
    * **shared-prefix** (paged only): every prompt shares a multi-block
      prefix — the radix cache prefills it once and later admissions
      reuse it copy-on-write (``prefix.hit_rate`` > 0, suffix-only
      prefill).

    Env: TDDL_BENCH_PAGED_MODEL (gpt2), TDDL_BENCH_PAGED_SLOTS (8),
    TDDL_BENCH_PAGED_SEQ (256), TDDL_BENCH_PAGED_BLOCK (16),
    TDDL_BENCH_PAGED_REQUESTS (32), TDDL_BENCH_PAGED_NEW (8)."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.serve import (
        ServeRequest,
        ServingEngine,
        kv_bytes_per_token,
        paged_pool_blocks,
    )

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_PAGED_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    stripe_slots = int(os.environ.get("TDDL_BENCH_PAGED_SLOTS", "8"))
    max_seq = int(os.environ.get("TDDL_BENCH_PAGED_SEQ", "256"))
    block = int(os.environ.get("TDDL_BENCH_PAGED_BLOCK", "16"))
    n_requests = int(os.environ.get("TDDL_BENCH_PAGED_REQUESTS", "32"))
    max_new = int(os.environ.get("TDDL_BENCH_PAGED_NEW", "8"))

    budget = stripe_slots * max_seq * kv_bytes_per_token(cfg)
    num_blocks = paged_pool_blocks(cfg, budget, block)
    # Short-request mix: prompt + new spans 1-2 blocks, a small fraction
    # of a stripe — the workload shape where request-count capacity and
    # token capacity diverge the most.
    plen_lo, plen_hi = 8, max(9, min(2 * block - max_new, max_seq // 8))

    def short_workload(rng):
        return [ServeRequest(
            prompt=rng.integers(0, cfg.vocab_size,
                                int(rng.integers(plen_lo, plen_hi))
                                ).tolist(),
            max_new_tokens=int(rng.integers(min(4, max_new), max_new + 1)),
            temperature=0.0,
        ) for _ in range(n_requests)]

    record = {
        "budget_bytes": int(budget), "block_size": block,
        "max_seq": max_seq, "arms": {},
    }
    arm_defs = (
        ("stripe", dict(paged=False, max_slots=stripe_slots)),
        # One decode row per block: row count can never bind before the
        # block pool does — admission is genuinely token-bounded.
        ("paged", dict(paged=True, max_slots=num_blocks,
                       num_blocks=num_blocks, block_size=block)),
    )
    for label, kw in arm_defs:
        engine = ServingEngine(params, cfg, max_seq=max_seq,
                               queue_limit=n_requests,
                               rng=jax.random.PRNGKey(1), **kw)
        reqs = short_workload(np.random.default_rng(0))
        t0 = time.perf_counter()
        for req in reqs:
            engine.submit(req)
        engine.run_until_idle()
        elapsed = time.perf_counter() - t0
        summary = engine.metrics_summary()
        row = {
            "kv_bytes": int(engine.scheduler.kv.pool_bytes),
            "peak_active_requests": summary["peak_active_requests"],
            "peak_tokens_in_flight": summary["peak_tokens_in_flight"],
            "tokens_per_s": round(summary["tokens_per_s"], 1),
            "completed": summary["requests_completed"],
            "wall_s": round(elapsed, 3),
        }
        if label == "paged":
            row["num_blocks"] = num_blocks
            row["blocks_in_use_final"] = summary["blocks_in_use"]
        else:
            row["slots"] = stripe_slots
        record["arms"][label] = row
        log(f"paged A/B [{label}]: peak {row['peak_active_requests']} "
            f"active / {row['peak_tokens_in_flight']} tokens in flight, "
            f"{row['tokens_per_s']:.1f} tok/s "
            f"({row['completed']} completed)")
    stripe, paged = record["arms"]["stripe"], record["arms"]["paged"]
    record["capacity_ratio"] = round(
        paged["peak_active_requests"]
        / max(stripe["peak_active_requests"], 1), 3)
    record["tokens_per_s_ratio"] = round(
        paged["tokens_per_s"] / max(stripe["tokens_per_s"], 1e-9), 3)

    # Shared-prefix leg (paged only — the stripe pool cannot share):
    # every prompt = one multi-block common prefix + a short unique
    # suffix; rows are scarce relative to requests so later admissions
    # find the prefix already cached.
    prefix_len = 2 * block
    rows = max(2, n_requests // 4)
    engine = ServingEngine(params, cfg, max_seq=max_seq,
                           queue_limit=n_requests, max_slots=rows,
                           block_size=block,
                           rng=jax.random.PRNGKey(1))
    rng = np.random.default_rng(7)
    common = rng.integers(0, cfg.vocab_size, prefix_len).tolist()
    for _ in range(n_requests):
        suffix = rng.integers(0, cfg.vocab_size,
                              int(rng.integers(2, 6))).tolist()
        engine.submit(ServeRequest(
            prompt=common + suffix,
            max_new_tokens=int(rng.integers(min(4, max_new),
                                            max_new + 1)),
            temperature=0.0,
        ))
    engine.run_until_idle()
    summary = engine.metrics_summary()
    record["prefix"] = {
        "prefix_len": prefix_len,
        "lookups": summary["prefix_lookups"],
        "hits": summary["prefix_hits"],
        "hit_rate": round(summary["prefix_hit_rate"], 3),
        "tokens_reused": summary["prefix_tokens_reused"],
        "completed": summary["requests_completed"],
        "tokens_per_s": round(summary["tokens_per_s"], 1),
    }
    log(f"paged A/B: capacity {record['capacity_ratio']}x at equal HBM "
        f"({budget / 1e6:.1f} MB), prefix hit rate "
        f"{record['prefix']['hit_rate']} "
        f"({record['prefix']['tokens_reused']} tokens reused)")
    return record


def bench_spec() -> "dict":
    """Speculative-decode A/B (TDDL_BENCH_SPEC=1, riding
    TDDL_BENCH_SERVE=1): the SAME seeded open-loop workload through a
    spec-off arm and spec_k ∈ {2, 4} arms of the paged engine.  The off
    arm's row is built by the exact same helpers as the offered-load
    sweep — today's serve record shape, key for key — so the contract
    test can pin that enabling spec never mutates the baseline record;
    the spec arms add a "spec" block: accepted_rate (drafted tokens the
    model-dtype verify kept), draft/verify tick fractions, near-tie
    flips, and the end-to-end tokens/s already in the shared row.
    Greedy workload: acceptance is then the pure int8-draft-vs-target
    agreement the sentinel fingerprint tracks."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.obs.slo import SLOWatcher, default_serve_rules
    from trustworthy_dl_tpu.serve import ServeRequest, ServingEngine

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_SERVE_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    max_slots = int(os.environ.get("TDDL_BENCH_SPEC_SLOTS", "4"))
    max_seq = int(os.environ.get("TDDL_BENCH_SPEC_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_SPEC_REQUESTS", "16"))
    max_new = int(os.environ.get("TDDL_BENCH_SPEC_NEW", "32"))
    rate = float(os.environ.get("TDDL_BENCH_SPEC_RATE", "64"))
    ks = [int(x) for x in os.environ.get("TDDL_BENCH_SPEC_KS",
                                         "2,4").split(",")]
    plen_hi = min(64, max_seq - max_new + 1)
    if plen_hi <= 8:
        raise ValueError(
            f"TDDL_BENCH_SPEC_SEQ={max_seq} leaves no room for prompts "
            f">= 8 tokens at TDDL_BENCH_SPEC_NEW={max_new}"
        )

    def build_workload():
        # Re-seeded per arm: every arm serves an IDENTICAL request
        # sequence, so tokens/s differences are the spec tier's alone.
        rng = np.random.default_rng(17)
        workload = []
        t_arrive = 0.0
        for _ in range(n_requests):
            t_arrive += rng.exponential(1.0 / rate)
            plen = int(rng.integers(8, plen_hi))
            workload.append((t_arrive, ServeRequest(
                prompt=rng.integers(0, cfg.vocab_size, plen).tolist(),
                max_new_tokens=int(rng.integers(min(4, max_new),
                                                max_new + 1)),
                temperature=0.0,
            )))
        return workload

    record: dict = {"arms": {}, "offered_rps": rate}
    for label, spec_k in [("off", 0)] + [(f"k{k}", k) for k in ks]:
        watcher = SLOWatcher(default_serve_rules())
        engine = ServingEngine(params, cfg, max_slots=max_slots,
                               max_seq=max_seq, queue_limit=n_requests,
                               rng=jax.random.PRNGKey(1), slo=watcher,
                               spec_k=spec_k)
        shed = _drive_serve_open_loop(engine, build_workload())
        row = _serve_sweep_row(engine, watcher, rate, shed)
        if spec_k:
            sched = engine.scheduler
            wall = max(sched.spec_draft_s + sched.spec_verify_s, 1e-9)
            summary = engine.metrics_summary()
            row["spec"] = {
                "spec_k": spec_k,
                "proposed": summary["spec_proposed"],
                "accepted": summary["spec_accepted"],
                "accepted_rate": summary["accepted_rate"],
                "near_tie_flips": summary["spec_near_tie_flips"],
                "spec_ticks": summary["spec_ticks"],
                "fallback_ticks": summary["spec_fallback_ticks"],
                # Fractions of the spec-phase wall (host-observed; the
                # draft chain syncs at its token pull, the verify at
                # the packed pull) — where a tick's time actually goes.
                "draft_frac": round(sched.spec_draft_s / wall, 4),
                "verify_frac": round(sched.spec_verify_s / wall, 4),
            }
            log(f"spec k={spec_k}: {row['tokens_per_s']:8.1f} tok/s, "
                f"accepted_rate {row['spec']['accepted_rate']:.3f} "
                f"(draft {row['spec']['draft_frac']:.0%} / verify "
                f"{row['spec']['verify_frac']:.0%} of spec time)")
        else:
            log(f"spec off:  {row['tokens_per_s']:8.1f} tok/s (baseline)")
        record["arms"][label] = row
    best = f"k{max(ks)}"
    record["accepted_rate"] = \
        record["arms"][best]["spec"]["accepted_rate"]
    off_tps = record["arms"]["off"]["tokens_per_s"]
    record["tokens_per_s_ratio"] = round(
        record["arms"][best]["tokens_per_s"] / max(off_tps, 1e-9), 3)
    return record


def bench_paged_attn() -> "dict":
    """Paged-attention kernel-tier A/B (TDDL_BENCH_PAGED_ATTN=1, riding
    TDDL_BENCH_SERVE=1): the SAME seeded open-loop workload through a
    kernel-on arm (``attn_impl="pallas"`` — the ragged Pallas
    paged-decode attention + fused trust epilogue) and the jnp-fallback
    arm (``attn_impl="jnp"`` — today's gather path), both rows in the
    shared serve record shape (tokens/s, latency percentiles, SLO block,
    decode_tick_fraction + attn_kernel_path).  Two more A/B pairs cover
    the rest of the tier over the same workload: a chunked-prefill pair
    (``prefill_chunk`` on — the flash chunk program vs the gathered
    view; ``prefill_chunk_fraction``) and a speculative-verify pair
    (``spec_k`` on — the fused verify tail vs materialise-then-reduce;
    ``spec_verify_fraction``), each fraction joining the sentinel
    fingerprint direction lower.  On top it microbenches
    the output monitor's per-token reductions standalone — the jnp
    log_softmax/exp/top-k battery vs the single-pass trust epilogue over
    decode-shaped [slots, vocab] logits — so the "trust monitoring is
    literally free" claim has its own number (``monitor_cost_delta_us``
    per tick).

    HONEST SKIP: compiled Mosaic cannot dispatch on a non-TPU backend
    (interpret mode measures the Pallas interpreter, not the kernel), so
    off-TPU this returns a skip record with the reason — unless
    TDDL_BENCH_PAGED_ATTN_INTERPRET=1, the record-shape smoke knob the
    contract test uses (its numbers are interpreter wall time, never a
    perf claim).  An untileable pool geometry (int8 KV with block_size
    not a multiple of 32, f32 not a multiple of 8) skips the same way.

    Env: TDDL_BENCH_SERVE_MODEL (gpt2), TDDL_BENCH_PAGED_ATTN_SLOTS (4),
    TDDL_BENCH_PAGED_ATTN_SEQ (256), TDDL_BENCH_PAGED_ATTN_BLOCK (16),
    TDDL_BENCH_PAGED_ATTN_REQUESTS (16), TDDL_BENCH_PAGED_ATTN_NEW (32),
    TDDL_BENCH_PAGED_ATTN_RATE (64), TDDL_BENCH_PAGED_ATTN_CHUNK
    (2*block), TDDL_BENCH_PAGED_ATTN_SPEC_K (2)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.obs.slo import SLOWatcher, default_serve_rules
    from trustworthy_dl_tpu.ops.paged_attention import (
        logit_trust_stats,
        supports_paged_attention,
    )
    from trustworthy_dl_tpu.serve import ServeRequest, ServingEngine
    from trustworthy_dl_tpu.serve.scheduler import _logit_signals

    backend = jax.default_backend()
    interpret_smoke = \
        os.environ.get("TDDL_BENCH_PAGED_ATTN_INTERPRET") == "1"
    if backend != "tpu" and not interpret_smoke:
        log(f"paged_attn A/B skipped: backend={backend} cannot dispatch "
            "compiled Mosaic (interpret mode would measure the "
            "interpreter, not the kernel)")
        return {"skipped": True,
                "reason": f"pallas_undispatchable:backend={backend}"}
    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_SERVE_MODEL", "gpt2")
    )
    max_slots = int(os.environ.get("TDDL_BENCH_PAGED_ATTN_SLOTS", "4"))
    max_seq = int(os.environ.get("TDDL_BENCH_PAGED_ATTN_SEQ", "256"))
    block = int(os.environ.get("TDDL_BENCH_PAGED_ATTN_BLOCK", "16"))
    n_requests = int(os.environ.get("TDDL_BENCH_PAGED_ATTN_REQUESTS",
                                    "16"))
    max_new = int(os.environ.get("TDDL_BENCH_PAGED_ATTN_NEW", "32"))
    rate = float(os.environ.get("TDDL_BENCH_PAGED_ATTN_RATE", "64"))
    kernel_impl = "interpret" if backend != "tpu" else "pallas"
    if not supports_paged_attention(
            head_dim=cfg.n_embd // cfg.n_head, block_size=block,
            kv_dtype=cfg.dtype, interpret=(kernel_impl == "interpret")):
        log(f"paged_attn A/B skipped: geometry does not tile "
            f"(head_dim={cfg.n_embd // cfg.n_head}, block_size={block})")
        return {"skipped": True,
                "reason": f"pallas_untileable:block_size={block}"}
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    plen_hi = min(64, max_seq - max_new + 1)
    if plen_hi <= 8:
        raise ValueError(
            f"TDDL_BENCH_PAGED_ATTN_SEQ={max_seq} leaves no room for "
            f"prompts >= 8 tokens at TDDL_BENCH_PAGED_ATTN_NEW={max_new}"
        )

    def build_workload():
        # Re-seeded per arm: identical request sequences, so tokens/s
        # differences are the attention path's alone.
        rng = np.random.default_rng(23)
        workload = []
        t_arrive = 0.0
        for _ in range(n_requests):
            t_arrive += rng.exponential(1.0 / rate)
            plen = int(rng.integers(8, plen_hi))
            workload.append((t_arrive, ServeRequest(
                prompt=rng.integers(0, cfg.vocab_size, plen).tolist(),
                max_new_tokens=int(rng.integers(min(4, max_new),
                                                max_new + 1)),
                temperature=0.0,
            )))
        return workload

    record: dict = {"arms": {}, "offered_rps": rate,
                    "backend": backend, "block_size": block}
    streams = {}
    for label, impl in (("pallas", kernel_impl), ("jnp", "jnp")):
        watcher = SLOWatcher(default_serve_rules())
        engine = ServingEngine(params, cfg, max_slots=max_slots,
                               max_seq=max_seq, queue_limit=n_requests,
                               rng=jax.random.PRNGKey(1), slo=watcher,
                               block_size=block, attn_impl=impl)
        shed = _drive_serve_open_loop(engine, build_workload())
        row = _serve_sweep_row(engine, watcher, rate, shed)
        record["arms"][label] = row
        streams[label] = {r: v.tokens
                          for r, v in engine.results.items()
                          if v.status == "completed"}
        log(f"paged_attn [{label}/{engine.attn_kernel_path}]: "
            f"{row['tokens_per_s']:8.1f} tok/s, decode-tick fraction "
            f"{row['decode_tick_fraction']:.3f}")
    # Greedy workload: the two paths must emit the same streams for the
    # A/B to mean anything (near-tie flips are possible in principle —
    # report, don't assert; the kernel tests pin equality properly).
    record["streams_identical"] = streams["pallas"] == streams["jnp"]
    record["tokens_per_s_ratio"] = round(
        record["arms"]["pallas"]["tokens_per_s"]
        / max(record["arms"]["jnp"]["tokens_per_s"], 1e-9), 3)
    # The headline the sentinel fingerprint lifts: the KERNEL arm's
    # decode-phase share of the serve wall.
    record["decode_tick_fraction"] = \
        record["arms"]["pallas"]["decode_tick_fraction"]

    # Prefill-chunk arm: the SAME seeded workload with chunked prefill
    # on, kernel tier vs jnp — the chunk program is the only prefill
    # path an adapter-carrying or prefix-resumed prompt can take, so
    # its wall share gets its own A/B and fingerprint entry.
    chunk = int(os.environ.get("TDDL_BENCH_PAGED_ATTN_CHUNK",
                               str(2 * block)))
    record["prefill_arms"] = {}
    prefill_streams = {}
    for label, impl in (("pallas", kernel_impl), ("jnp", "jnp")):
        watcher = SLOWatcher(default_serve_rules())
        engine = ServingEngine(params, cfg, max_slots=max_slots,
                               max_seq=max_seq, queue_limit=n_requests,
                               rng=jax.random.PRNGKey(1), slo=watcher,
                               block_size=block, attn_impl=impl,
                               prefill_chunk=chunk)
        shed = _drive_serve_open_loop(engine, build_workload())
        row = _serve_sweep_row(engine, watcher, rate, shed)
        row["prefill_chunk_fraction"] = round(
            engine.metrics_summary()["prefill_chunk_fraction"], 4)
        record["prefill_arms"][label] = row
        prefill_streams[label] = {r: v.tokens
                                  for r, v in engine.results.items()
                                  if v.status == "completed"}
        log(f"paged_attn prefill [{label}]: "
            f"{row['tokens_per_s']:8.1f} tok/s, prefill-chunk fraction "
            f"{row['prefill_chunk_fraction']:.3f}")
    record["prefill_streams_identical"] = \
        prefill_streams["pallas"] == prefill_streams["jnp"]
    record["prefill_tokens_per_s_ratio"] = round(
        record["prefill_arms"]["pallas"]["tokens_per_s"]
        / max(record["prefill_arms"]["jnp"]["tokens_per_s"], 1e-9), 3)
    record["prefill_chunk_fraction"] = \
        record["prefill_arms"]["pallas"]["prefill_chunk_fraction"]

    # Speculative-verify arm: drafting on (spec_k), kernel tier vs jnp
    # — the fused one-pass verify tail vs materialise-then-reduce; the
    # verify-tick wall share is the fingerprint entry.
    spec_k = int(os.environ.get("TDDL_BENCH_PAGED_ATTN_SPEC_K", "2"))
    record["verify_arms"] = {}
    verify_streams = {}
    for label, impl in (("pallas", kernel_impl), ("jnp", "jnp")):
        watcher = SLOWatcher(default_serve_rules())
        engine = ServingEngine(params, cfg, max_slots=max_slots,
                               max_seq=max_seq, queue_limit=n_requests,
                               rng=jax.random.PRNGKey(1), slo=watcher,
                               block_size=block, attn_impl=impl,
                               spec_k=spec_k)
        shed = _drive_serve_open_loop(engine, build_workload())
        row = _serve_sweep_row(engine, watcher, rate, shed)
        summary = engine.metrics_summary()
        row["spec_verify_fraction"] = round(
            summary["spec_verify_fraction"], 4)
        if "accepted_rate" in summary:
            row["accepted_rate"] = round(summary["accepted_rate"], 4)
        record["verify_arms"][label] = row
        verify_streams[label] = {r: v.tokens
                                 for r, v in engine.results.items()
                                 if v.status == "completed"}
        log(f"paged_attn verify [{label}]: "
            f"{row['tokens_per_s']:8.1f} tok/s, spec-verify fraction "
            f"{row['spec_verify_fraction']:.3f}")
    record["verify_streams_identical"] = \
        verify_streams["pallas"] == verify_streams["jnp"]
    record["verify_tokens_per_s_ratio"] = round(
        record["verify_arms"]["pallas"]["tokens_per_s"]
        / max(record["verify_arms"]["jnp"]["tokens_per_s"], 1e-9), 3)
    record["spec_verify_fraction"] = \
        record["verify_arms"]["pallas"]["spec_verify_fraction"]

    # Monitor-cost microbench: the output monitor's per-token reductions
    # over decode-shaped logits, jnp battery vs fused epilogue, jitted
    # and timed standalone.  This is the "trust monitoring becomes
    # literally free" delta, per decode tick.
    logits = jax.random.normal(jax.random.PRNGKey(3),
                               (max_slots, cfg.vocab_size),
                               jnp.float32) * 4.0
    def _jnp_reductions(x):
        return _logit_signals(x, "jnp")

    def _kernel_reductions(x):
        return _logit_signals(x, kernel_impl)

    jnp_fn = jax.jit(_jnp_reductions)
    ker_fn = jax.jit(_kernel_reductions)
    timings = {}
    for name, fn in (("jnp", jnp_fn), ("kernel", ker_fn)):
        jax.block_until_ready(fn(logits))          # compile + warm
        reps = 20
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(logits)
        jax.block_until_ready(out)
        timings[name] = (time.perf_counter() - t0) / reps * 1e6
    record["monitor_us_jnp"] = round(timings["jnp"], 2)
    record["monitor_us_kernel"] = round(timings["kernel"], 2)
    record["monitor_cost_delta_us"] = round(
        timings["jnp"] - timings["kernel"], 2)
    log(f"paged_attn monitor reductions: jnp {timings['jnp']:.1f} us vs "
        f"epilogue {timings['kernel']:.1f} us per tick "
        f"(delta {record['monitor_cost_delta_us']:.1f} us)")
    return record


def bench_fleet() -> "dict":
    """Serving-fleet leg (TDDL_BENCH_FLEET=1): goodput-under-SLO vs
    offered load, chaos OFF vs ON, over a replica fleet driven by the
    seeded workload generator (bursty arrivals, heavy-tailed lengths,
    tenant priority skew — serve/workload.py).

    Per offered rate, two arms on IDENTICAL traffic (same workload
    seed): *baseline* (no faults) and *chaos* (a seeded REPLICA_* fault
    plan: crash + stall + poison).  Goodput counts only tokens from
    requests that COMPLETED inside their deadline — the number the
    robustness layer is supposed to defend; the gap between the arms at
    each rate is the price of the injected failures after fail-over,
    drain and quarantine have done their work.  Each row also carries a
    ``per_class`` breakdown (the fleet runs the default SLO-class
    ladder, so the workload's tenant priorities map onto batch/
    standard/premium): goodput-per-class curves show WHO paid for the
    chaos — the control-plane contract is that the bottom class pays
    first.

    Env: TDDL_BENCH_FLEET_MODEL (gpt2), TDDL_BENCH_FLEET_REPLICAS (3),
    TDDL_BENCH_FLEET_SLOTS (4, per replica), TDDL_BENCH_FLEET_SEQ (256),
    TDDL_BENCH_FLEET_REQUESTS (32), TDDL_BENCH_FLEET_RATES ("4,16"),
    TDDL_BENCH_FLEET_SEED (0)."""
    import jax

    from trustworthy_dl_tpu.chaos import FaultEvent, FaultInjector, \
        FaultKind, FaultPlan
    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.serve import (
        DEFAULT_SLO_CLASSES,
        FleetConfig,
        ServeRequest,
        ServingFleet,
        WorkloadConfig,
        generate_workload,
    )
    from trustworthy_dl_tpu.serve.workload import replay_workload

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_FLEET_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    replicas = int(os.environ.get("TDDL_BENCH_FLEET_REPLICAS", "3"))
    max_slots = int(os.environ.get("TDDL_BENCH_FLEET_SLOTS", "4"))
    max_seq = int(os.environ.get("TDDL_BENCH_FLEET_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_FLEET_REQUESTS", "32"))
    rates = [float(r) for r in os.environ.get(
        "TDDL_BENCH_FLEET_RATES", "4,16").split(",")]
    seed = int(os.environ.get("TDDL_BENCH_FLEET_SEED", "0"))

    def fault_plan() -> FaultPlan:
        # One scripted arc per chaos arm: an early poison (flag-rate →
        # drain → quarantine), a mid-run crash (fail-over + restart) and
        # a stall (heartbeat drain) — replica indices spread so the
        # fleet is never down to zero.
        return FaultPlan.scripted([
            FaultEvent(step=4, kind=FaultKind.REPLICA_POISON,
                       target=replicas - 1),
            FaultEvent(step=8, kind=FaultKind.REPLICA_CRASH, target=0),
            FaultEvent(step=14, kind=FaultKind.REPLICA_STALL,
                       target=min(1, replicas - 1), severity=8),
        ], seed=seed)

    arms: "dict[str, list]" = {"baseline": [], "chaos": []}
    # Forensic incident counts, by reason, summed over every chaos arm
    # (the baseline arms have no faults to assemble incidents for).
    # In-memory assembler: directory=None counts without writing files.
    incident_counts: "dict[str, int]" = {}
    for rate in rates:
        workload = generate_workload(
            WorkloadConfig(seed=seed, num_requests=n_requests,
                           mean_rps=rate),
            cfg.vocab_size, max_seq,
        )
        for arm in ("baseline", "chaos"):
            chaos = (FaultInjector(fault_plan()) if arm == "chaos"
                     else None)
            forensics = None
            if chaos is not None:
                from trustworthy_dl_tpu.obs.forensics import \
                    IncidentAssembler

                forensics = IncidentAssembler()
            fleet = ServingFleet(
                params, cfg,
                # Cool-off pinned past the run: an unhealed poisoned
                # replica re-trips on every readmission probe, and this
                # sweep wants the injected faults' cost, not a
                # quarantine-probe-quarantine churn tail.
                fleet_config=FleetConfig(num_replicas=replicas,
                                         max_retries=6,
                                         quarantine_cooloff_ticks=10 ** 6,
                                         slo_classes=DEFAULT_SLO_CLASSES),
                chaos=chaos, rng=jax.random.PRNGKey(1),
                max_slots=max_slots, max_seq=max_seq,
                queue_limit=n_requests, forensics=forensics,
            )
            t0 = time.perf_counter()
            replay_workload(fleet, workload, lambda item: ServeRequest(
                prompt=list(item.prompt),
                max_new_tokens=item.max_new_tokens,
                temperature=0.8, priority=item.priority,
                deadline_s=item.deadline_s,
                tenant=item.tenant,
            ))
            wall = time.perf_counter() - t0
            summary = fleet.metrics_summary()
            statuses = summary["statuses"]
            good_tokens = summary["completed_tokens"]
            row = {
                "offered_rps": rate,
                "goodput_tokens_per_s": round(good_tokens / wall, 1)
                if wall > 0 else 0.0,
                "completed": statuses.get("completed", 0),
                "deadline_exceeded": statuses.get("deadline_exceeded", 0),
                "shed": (statuses.get("shed_slo", 0)
                         + statuses.get("no_capacity", 0)
                         + statuses.get("failover_exhausted", 0)
                         + fleet.rejected),
                "failovers": summary["fleet_failovers"],
                "drains": summary["fleet_drains"],
                "quarantines": summary["fleet_quarantines"],
                "restarts": summary["fleet_restarts"],
                "wall_s": round(wall, 2),
                # Goodput-per-class: completed requests/tokens (and the
                # per-class goodput rate) for each SLO class this arm.
                "per_class": {
                    name: {
                        "completed": cls["completed"],
                        "tokens": cls["tokens"],
                        "shed": cls["shed"],
                        "goodput_tokens_per_s":
                            round(cls["tokens"] / wall, 1)
                            if wall > 0 else 0.0,
                    }
                    for name, cls in summary["per_class"].items()
                },
            }
            arms[arm].append(row)
            if forensics is not None:
                for why, n in forensics.counts_by_reason().items():
                    incident_counts[why] = (
                        incident_counts.get(why, 0) + n)
            log(f"fleet {arm:8s} offered={rate:6.1f} req/s: "
                f"goodput {row['goodput_tokens_per_s']:8.1f} tok/s, "
                f"completed {row['completed']}/{n_requests}, "
                f"failovers {row['failovers']}, drains {row['drains']}, "
                f"quarantines {row['quarantines']}")
    return {
        "replicas": replicas,
        "max_slots_per_replica": max_slots,
        "requests_per_arm": n_requests,
        "arms": arms,
        "incidents": dict(sorted(incident_counts.items())),
    }


def bench_migrate() -> "dict":
    """Live KV-migration A/B (TDDL_BENCH_MIGRATE=1): what a capacity
    loss costs when in-flight work moves as a block copy vs replaying
    from the prompt, plus what disaggregated prefill/decode pools buy
    under a bimodal prompt mix.  Two pairs of arms, each pair on
    IDENTICAL seeded traffic:

    * **drain** — a scripted mid-run REPLICA_PREEMPT: the ``runout``
      arm pins ``FleetConfig(live_migration=False)`` (the preempted
      replica's accepted requests replay from scratch elsewhere — the
      pre-PR arc), the ``migration`` arm leaves the default on (each
      loss is a block-table copy).  The gap is recomputed tokens.
    * **disagg** — a bimodal prompt workload (short chat head + a long
      RAG tail): ``unified`` (pool_roles=None) vs ``disaggregated``
      (one prefill specialist, the rest decode — requests hand off at
      first decode token).

    The migration arm's ``migration_fraction`` (migrations over
    migrations + replay failovers) joins the sentinel fingerprint: a
    structural regression that quietly degrades losses back to replay
    bands before goodput noise shows it.

    Env: TDDL_BENCH_MIGRATE_MODEL (gpt2), TDDL_BENCH_MIGRATE_REPLICAS
    (3), TDDL_BENCH_MIGRATE_SLOTS (4), TDDL_BENCH_MIGRATE_SEQ (256),
    TDDL_BENCH_MIGRATE_REQUESTS (24), TDDL_BENCH_MIGRATE_RATE (16),
    TDDL_BENCH_MIGRATE_SEED (0), TDDL_BENCH_MIGRATE_BIMODAL (0.25),
    TDDL_BENCH_MIGRATE_LONG_MEDIAN (seq/4)."""
    import jax

    from trustworthy_dl_tpu.chaos import FaultEvent, FaultInjector, \
        FaultKind, FaultPlan
    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.serve import (
        FleetConfig,
        ServeRequest,
        ServingFleet,
        WorkloadConfig,
        generate_workload,
    )
    from trustworthy_dl_tpu.serve.workload import replay_workload

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_MIGRATE_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    replicas = int(os.environ.get("TDDL_BENCH_MIGRATE_REPLICAS", "3"))
    max_slots = int(os.environ.get("TDDL_BENCH_MIGRATE_SLOTS", "4"))
    max_seq = int(os.environ.get("TDDL_BENCH_MIGRATE_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_MIGRATE_REQUESTS", "24"))
    rate = float(os.environ.get("TDDL_BENCH_MIGRATE_RATE", "16"))
    seed = int(os.environ.get("TDDL_BENCH_MIGRATE_SEED", "0"))
    bimodal = float(os.environ.get("TDDL_BENCH_MIGRATE_BIMODAL", "0.25"))
    long_median = int(os.environ.get("TDDL_BENCH_MIGRATE_LONG_MEDIAN",
                                     str(max(max_seq // 4, 16))))

    def run_arm(workload, fleet_cfg, chaos):
        fleet = ServingFleet(
            params, cfg, fleet_config=fleet_cfg, chaos=chaos,
            rng=jax.random.PRNGKey(1), max_slots=max_slots,
            max_seq=max_seq, queue_limit=n_requests,
        )
        t0 = time.perf_counter()
        replay_workload(fleet, workload, lambda item: ServeRequest(
            prompt=list(item.prompt),
            max_new_tokens=item.max_new_tokens,
            temperature=0.8, priority=item.priority,
            deadline_s=item.deadline_s, tenant=item.tenant,
        ))
        wall = time.perf_counter() - t0
        summary = fleet.metrics_summary()
        statuses = summary["statuses"]
        good_tokens = summary["completed_tokens"]
        return {
            "goodput_tokens_per_s": round(good_tokens / wall, 1)
            if wall > 0 else 0.0,
            "completed": statuses.get("completed", 0),
            "deadline_exceeded": statuses.get("deadline_exceeded", 0),
            "migrations": fleet.counters["migrations"],
            "preempts": fleet.counters["preempts"],
            "failovers": summary["fleet_failovers"],
            "wall_s": round(wall, 2),
        }

    # -- drain pair: preempt mid-run, runout vs migration --------------
    drain_workload = generate_workload(
        WorkloadConfig(seed=seed, num_requests=n_requests, mean_rps=rate),
        cfg.vocab_size, max_seq,
    )

    def preempt_plan() -> FaultInjector:
        return FaultInjector(FaultPlan.scripted([
            FaultEvent(step=6, kind=FaultKind.REPLICA_PREEMPT, target=0),
        ], seed=seed))

    drain = {}
    for arm, live in (("runout", False), ("migration", True)):
        drain[arm] = run_arm(
            drain_workload,
            FleetConfig(num_replicas=replicas, max_retries=6,
                        live_migration=live),
            preempt_plan(),
        )
        log(f"migrate drain {arm:9s}: goodput "
            f"{drain[arm]['goodput_tokens_per_s']:8.1f} tok/s, "
            f"migrations {drain[arm]['migrations']}, "
            f"failovers {drain[arm]['failovers']}")

    # -- disagg pair: bimodal prompts, unified vs split pools ----------
    disagg_workload = generate_workload(
        WorkloadConfig(seed=seed, num_requests=n_requests, mean_rps=rate,
                       prompt_bimodal_frac=bimodal,
                       prompt_long_median=long_median),
        cfg.vocab_size, max_seq,
    )
    roles = ("prefill",) + ("decode",) * (replicas - 1)
    disagg = {}
    for arm, pool_roles in (("unified", None), ("disaggregated", roles)):
        disagg[arm] = run_arm(
            disagg_workload,
            FleetConfig(num_replicas=replicas, max_retries=6,
                        pool_roles=pool_roles),
            None,
        )
        log(f"migrate disagg {arm:13s}: goodput "
            f"{disagg[arm]['goodput_tokens_per_s']:8.1f} tok/s, "
            f"migrations {disagg[arm]['migrations']}")

    mig = drain["migration"]
    frac = (mig["migrations"]
            / max(mig["migrations"] + mig["failovers"], 1))
    return {
        "replicas": replicas,
        "max_slots_per_replica": max_slots,
        "requests_per_arm": n_requests,
        "bimodal_frac": bimodal,
        "prompt_long_median": long_median,
        "drain": drain,
        "disagg": disagg,
        # The headline the sentinel fingerprint lifts: the share of
        # capacity-loss recoveries that were block copies, not replays.
        "migration_fraction": round(frac, 3),
    }


def bench_shard() -> "dict":
    """Equal-chip sharded-train-state A/B (TDDL_BENCH_SHARD=1):
    replicated vs FSDP train state on the SAME chips and the same
    seeded batch.  Both arms run the identical jitted step; the FSDP
    arm turns on ``TrainingConfig.shard_params`` (+ opt-state
    sharding), so params and optimizer moments live ZeRO-sharded over
    the data axis via the core/sharding registry and GSPMD gathers per
    layer.  Reported per arm: tokens/s, the per-device HBM watermark
    (obs/hbm.py live-buffer sweep while the arm's state is still
    resident), and ``params_bytes_per_device``/``opt_bytes_per_device``
    measured from the placed shardings (core/sharding.
    tree_bytes_per_device) — bytes the registry actually returned to
    the budget, not an estimate.  The headline ``params_bytes_ratio``
    (fsdp / replicated) must sit near 1/shards.

    Env: TDDL_BENCH_SHARD_MODEL (gpt2), TDDL_BENCH_SHARD_NODES (device
    count), TDDL_BENCH_SHARD_BATCH (per-node, 4), TDDL_BENCH_SHARD_SEQ
    (256), TDDL_BENCH_SHARD_STEPS (8), TDDL_BENCH_SHARD_WARMUP (2)."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.core import sharding as shreg
    from trustworthy_dl_tpu.core.config import TrainingConfig
    from trustworthy_dl_tpu.engine import DistributedTrainer
    from trustworthy_dl_tpu.obs.hbm import HbmMonitor

    model = os.environ.get("TDDL_BENCH_SHARD_MODEL", "gpt2")
    num_nodes = int(os.environ.get("TDDL_BENCH_SHARD_NODES",
                                   str(jax.device_count())))
    per_node_batch = int(os.environ.get("TDDL_BENCH_SHARD_BATCH", "4"))
    seq_len = int(os.environ.get("TDDL_BENCH_SHARD_SEQ", "256"))
    steps = int(os.environ.get("TDDL_BENCH_SHARD_STEPS", "8"))
    warmup = int(os.environ.get("TDDL_BENCH_SHARD_WARMUP", "2"))
    tokens_per_step = num_nodes * per_node_batch * seq_len

    def run_arm(shard: bool) -> "dict":
        config = TrainingConfig(
            model_name=model,
            dataset_name="openwebtext",
            batch_size=num_nodes * per_node_batch,
            num_nodes=num_nodes,
            learning_rate=1e-4,
            checkpoint_interval=10 ** 9,
            attack_detection_enabled=False,
            gradient_verification_enabled=False,
            parallelism="data",
            shard_params=shard,
            shard_opt_state=shard,
        )
        overrides: dict = {}
        if model.startswith("gpt"):
            overrides["seq_len"] = seq_len
            if seq_len > 1024:
                overrides["n_positions"] = seq_len
        trainer = DistributedTrainer(config, model_overrides=overrides)
        trainer.initialize()
        state = trainer.state
        batch = trainer._node_batch(jax.tree_util.tree_map(
            np.asarray,
            trainer.model.example_batch(num_nodes * per_node_batch,
                                        jax.random.PRNGKey(0)),
        ))
        plan = trainer.attack_plan
        for _ in range(max(warmup, 1)):
            state, metrics = trainer._train_step(state, batch, plan)
        jax.block_until_ready(metrics.loss)
        monitor = HbmMonitor()
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = trainer._train_step(state, batch, plan)
        jax.block_until_ready(metrics.loss)
        elapsed = time.perf_counter() - t0
        assert np.isfinite(float(metrics.loss)), "shard arm NaN loss"
        # Sweep while the arm's state is still resident — the watermark
        # is the arm's true peak, not a post-teardown floor.
        monitor.sweep()
        return {
            "tokens_per_s": round(steps * tokens_per_step / elapsed, 1)
            if elapsed > 0 else 0.0,
            "hbm_watermark_bytes": monitor.watermark_bytes,
            "params_bytes_per_device":
                shreg.tree_bytes_per_device(state.params),
            "opt_bytes_per_device":
                shreg.tree_bytes_per_device(state.opt_state),
            "final_loss": round(float(metrics.loss), 4),
        }

    arms = {}
    for name, shard in (("replicated", False), ("fsdp", True)):
        arms[name] = run_arm(shard)
        log(f"shard {name:10s}: {arms[name]['tokens_per_s']:10.1f} tok/s,"
            f" params "
            f"{arms[name]['params_bytes_per_device'] / 2 ** 20:8.1f} "
            f"MiB/dev, opt "
            f"{arms[name]['opt_bytes_per_device'] / 2 ** 20:8.1f} MiB/dev")

    repl, fsdp = arms["replicated"], arms["fsdp"]
    params_ratio = (fsdp["params_bytes_per_device"]
                    / max(repl["params_bytes_per_device"], 1))
    opt_ratio = (fsdp["opt_bytes_per_device"]
                 / max(repl["opt_bytes_per_device"], 1))
    log(f"shard ratios: params {params_ratio:.3f}, opt {opt_ratio:.3f} "
        f"(ideal {1.0 / num_nodes:.3f} over {num_nodes} shards)")
    return {
        "model": model,
        "shards": num_nodes,
        "tokens_per_step": tokens_per_step,
        "replicated": repl,
        "fsdp": fsdp,
        # The headline the A/B exists for: the per-device param bytes
        # the registry's ZeRO placement returned (ideal = 1/shards).
        "params_bytes_ratio": round(params_ratio, 4),
        "opt_bytes_ratio": round(opt_ratio, 4),
    }


def bench_adversary() -> "dict":
    """Goodput-under-attack leg (TDDL_BENCH_ADVERSARY=1): an adaptive
    poisoned replica that corrupts served streams while holding its
    public flag rate just below the quarantine threshold, measured with
    cross-replica verdict voting OFF vs ON over IDENTICAL seeded
    traffic.

    The number that matters is ``corrupted_served``: with voting off
    the sub-threshold attacker is never quarantined and keeps serving
    corrupted streams for the whole run; with voting on it is outvoted
    (``quarantines >= 1``) and the corruption stops at the verdict.
    Both arms pay the same fleet overheads, so the goodput gap is the
    audit cost of voting (replays on K clean replicas).

    The driver is CLOSED-LOOP (a saturating in-flight target over the
    seeded request list, tick-driven) rather than the open-loop
    wall-clock replay the fleet leg uses: the suspicion/vote arc needs
    the degraded suspect to keep receiving work, which only happens
    when the healthy replicas' bounded queues backpressure — a
    condition an open-loop rate only meets on a machine-specific
    service-rate knife edge.

    Env: TDDL_BENCH_ADVERSARY_MODEL (gpt2),
    TDDL_BENCH_ADVERSARY_REPLICAS (3), TDDL_BENCH_ADVERSARY_SLOTS (4),
    TDDL_BENCH_ADVERSARY_SEQ (256), TDDL_BENCH_ADVERSARY_REQUESTS (64),
    TDDL_BENCH_ADVERSARY_SEED (0), TDDL_BENCH_ADVERSARY_K (2),
    TDDL_BENCH_ADVERSARY_QUEUE (6 — kept BOUNDED so the backpressure
    above exists), TDDL_BENCH_ADVERSARY_MONITOR (margin threshold,
    14)."""
    import jax

    from trustworthy_dl_tpu.chaos import (
        AdaptivePoisonAttacker,
        AdversaryConfig,
        FaultEvent,
        FaultInjector,
        FaultKind,
        FaultPlan,
        MarginSignatureMonitor,
    )
    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.serve import (
        FleetConfig,
        ServeRequest,
        ServingFleet,
        WorkloadConfig,
        drive_closed_loop,
        generate_workload,
    )

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_ADVERSARY_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    replicas = int(os.environ.get("TDDL_BENCH_ADVERSARY_REPLICAS", "3"))
    max_slots = int(os.environ.get("TDDL_BENCH_ADVERSARY_SLOTS", "4"))
    max_seq = int(os.environ.get("TDDL_BENCH_ADVERSARY_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_ADVERSARY_REQUESTS", "64"))
    seed = int(os.environ.get("TDDL_BENCH_ADVERSARY_SEED", "0"))
    vote_k = int(os.environ.get("TDDL_BENCH_ADVERSARY_K", "2"))
    queue_limit = int(os.environ.get("TDDL_BENCH_ADVERSARY_QUEUE", "6"))
    monitor_th = float(os.environ.get("TDDL_BENCH_ADVERSARY_MONITOR",
                                      "14"))
    target = replicas - 1

    workload = generate_workload(
        WorkloadConfig(seed=seed, num_requests=n_requests),
        cfg.vocab_size, max_seq,
    )
    inflight_target = replicas * (max_slots + queue_limit)
    arms: "dict[str, dict]" = {}
    for arm, k in (("voting_off", 0), ("voting_on", vote_k)):
        adversary = AdaptivePoisonAttacker(AdversaryConfig(
            target=target, seed=seed, signal_jitter=0.5,
            vocab_size=cfg.vocab_size,
            # Conservative walk: with ~max_slots requests in flight the
            # flag-rate observation LAGS the corruption, so an
            # aggressive climb overshoots into ladder territory before
            # the backoff lands — this attacker climbs gently and bails
            # early, which is exactly what keeps it sub-threshold.
            step_up=0.05, safety_margin=0.08,
        ))
        injector = FaultInjector(FaultPlan.scripted([FaultEvent(
            step=1, kind=FaultKind.REPLICA_ADAPTIVE_POISON,
            target=target,
        )], seed=seed), adversary=adversary)
        fleet = ServingFleet(
            params, cfg,
            fleet_config=FleetConfig(
                num_replicas=replicas, max_retries=6,
                flag_window=16, flag_min_count=4,
                vote_k=k, vote_outvote_limit=2,
                # Cool-off pinned past the run (same reasoning as
                # bench_fleet: measure the catch, not probe churn).
                quarantine_cooloff_ticks=10 ** 6,
            ),
            chaos=injector, rng=jax.random.PRNGKey(1),
            max_slots=max_slots, max_seq=max_seq,
            queue_limit=queue_limit,
            # Deterministic margin-threshold monitor: the attacker's
            # flag probability is then a smooth function of strength
            # (chaos/adversary.py) on both arms identically.
            monitor=MarginSignatureMonitor(monitor_th),
        )
        t0 = time.perf_counter()
        # ONE spelling of the closed-loop bounded-queue driver, shared
        # with the drills and the autoscale leg (serve/workload.py).
        drive_closed_loop(
            fleet, workload,
            lambda item: ServeRequest(
                prompt=list(item.prompt),
                max_new_tokens=item.max_new_tokens,
                temperature=0.8, priority=item.priority,
                deadline_s=item.deadline_s,
                tenant=item.tenant,
            ),
            inflight_target,
        )
        wall = time.perf_counter() - t0
        summary = fleet.metrics_summary()
        statuses = summary["statuses"]
        corrupted_served = sum(
            1 for r in fleet.results.values()
            if r.status == "completed" and r.replica == target
        )
        row = {
            "vote_k": k,
            "inflight_target": inflight_target,
            "goodput_tokens_per_s":
                round(summary["completed_tokens"] / wall, 1)
                if wall > 0 else 0.0,
            "completed": statuses.get("completed", 0),
            "corrupted_served": corrupted_served,
            "final_attacker_strength": round(adversary.strength, 4),
            "attacker_flag_rate":
                round(fleet.replicas[target].flag_rate, 4),
            "suspicions": summary["fleet_suspicions"],
            "votes": summary["fleet_votes"],
            "outvotes": summary["fleet_outvotes"],
            "drains": summary["fleet_drains"],
            "quarantines": summary["fleet_quarantines"],
            "wall_s": round(wall, 2),
        }
        arms[arm] = row
        log(f"adversary {arm:10s}: goodput "
            f"{row['goodput_tokens_per_s']:8.1f} tok/s, corrupted "
            f"served {corrupted_served}, votes {row['votes']}, "
            f"quarantines {row['quarantines']}")
    return {
        "replicas": replicas,
        "max_slots_per_replica": max_slots,
        "requests_per_arm": n_requests,
        "vote_k": vote_k,
        "arms": arms,
    }


def bench_autoscale() -> "dict":
    """Autoscale A/B (TDDL_BENCH_AUTOSCALE=1): a STATIC fleet pinned at
    ``max`` replicas vs an AUTOSCALED fleet breathing between ``min``
    and ``max``, over IDENTICAL seeded bursty traffic (the closed-loop
    bounded-queue driver — backpressure keeps the scaling decisions
    engaged deterministically).

    Reading it: the autoscaled arm's ``replica_trace`` is the replica
    count over fleet ticks (scale-ups chase the bursts, scale-downs
    drain the troughs); ``scale_ups``/``scale_downs`` count the control
    actions; both arms report goodput and the per-class breakdown, so
    the cost of breathing — goodput given up while warming — is read
    directly against the static fleet's always-on capacity.

    Env: TDDL_BENCH_AUTOSCALE_MODEL (gpt2),
    TDDL_BENCH_AUTOSCALE_MIN (1), TDDL_BENCH_AUTOSCALE_MAX (3),
    TDDL_BENCH_AUTOSCALE_SLOTS (4), TDDL_BENCH_AUTOSCALE_SEQ (256),
    TDDL_BENCH_AUTOSCALE_REQUESTS (48), TDDL_BENCH_AUTOSCALE_SEED (0),
    TDDL_BENCH_AUTOSCALE_INFLIGHT (default 3x slots)."""
    import jax

    from trustworthy_dl_tpu.serve import (
        DEFAULT_SLO_CLASSES,
        AutoscalerConfig,
        FleetConfig,
        ServeRequest,
        ServingFleet,
        WorkloadConfig,
        drive_closed_loop,
        generate_workload,
    )
    from trustworthy_dl_tpu.models import gpt2

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_AUTOSCALE_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    n_min = int(os.environ.get("TDDL_BENCH_AUTOSCALE_MIN", "1"))
    n_max = int(os.environ.get("TDDL_BENCH_AUTOSCALE_MAX", "3"))
    max_slots = int(os.environ.get("TDDL_BENCH_AUTOSCALE_SLOTS", "4"))
    max_seq = int(os.environ.get("TDDL_BENCH_AUTOSCALE_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_AUTOSCALE_REQUESTS",
                                    "48"))
    seed = int(os.environ.get("TDDL_BENCH_AUTOSCALE_SEED", "0"))
    inflight = int(os.environ.get("TDDL_BENCH_AUTOSCALE_INFLIGHT",
                                  str(3 * max_slots)))

    workload = generate_workload(
        WorkloadConfig(seed=seed, num_requests=n_requests,
                       burstiness=0.8),
        cfg.vocab_size, max_seq,
    )
    arms: "dict[str, dict]" = {}
    for arm in ("static", "autoscaled"):
        autoscale = None
        if arm == "autoscaled":
            autoscale = AutoscalerConfig(
                min_replicas=n_min, max_replicas=n_max,
                scale_up_queue_per_replica=float(max_slots),
                scale_down_queue_per_replica=max(max_slots / 8.0, 0.5),
                scale_up_cooldown_ticks=8,
                scale_down_cooldown_ticks=16,
                scale_down_idle_ticks=8,
            )
        fleet = ServingFleet(
            params, cfg,
            fleet_config=FleetConfig(
                num_replicas=(n_max if arm == "static" else n_min),
                max_retries=6,
                quarantine_cooloff_ticks=10 ** 6,
                slo_classes=DEFAULT_SLO_CLASSES,
                autoscale=autoscale,
            ),
            rng=jax.random.PRNGKey(1),
            max_slots=max_slots, max_seq=max_seq,
            queue_limit=n_requests,
        )
        t0 = time.perf_counter()
        accepted = drive_closed_loop(
            fleet, workload,
            lambda item: ServeRequest(
                prompt=list(item.prompt),
                max_new_tokens=item.max_new_tokens,
                temperature=0.8, priority=item.priority,
                deadline_s=item.deadline_s, tenant=item.tenant,
            ),
            inflight,
        )
        # Let a trailing scale-down land before reading the trace: the
        # drive exits at drain, the controller breathes a beat later.
        for _ in range(64):
            fleet.step()
        wall = time.perf_counter() - t0
        summary = fleet.metrics_summary()
        statuses = summary["statuses"]
        row = {
            "accepted": accepted,
            "completed": statuses.get("completed", 0),
            "goodput_tokens_per_s":
                round(summary["completed_tokens"] / wall, 1)
                if wall > 0 else 0.0,
            "scale_ups": summary["fleet_scale_ups"],
            "scale_downs": summary["fleet_scale_downs"],
            "replica_trace": summary.get(
                "replica_trace",
                [(0, n_max if arm == "static" else n_min)]),
            "per_class": {
                name: {
                    "completed": cls["completed"],
                    "tokens": cls["tokens"],
                    "shed": cls["shed"],
                    "goodput_tokens_per_s":
                        round(cls["tokens"] / wall, 1)
                        if wall > 0 else 0.0,
                }
                for name, cls in summary["per_class"].items()
            },
            "wall_s": round(wall, 2),
        }
        arms[arm] = row
        log(f"autoscale {arm:10s}: goodput "
            f"{row['goodput_tokens_per_s']:8.1f} tok/s, completed "
            f"{row['completed']}/{n_requests}, scale_ups "
            f"{row['scale_ups']}, scale_downs {row['scale_downs']}")
    return {
        "replicas_min": n_min,
        "replicas_max": n_max,
        "max_slots_per_replica": max_slots,
        "requests_per_arm": n_requests,
        "inflight_target": inflight,
        "arms": arms,
    }


def bench_chaos() -> "list[dict]":
    """Survival sweep (TDDL_BENCH_CHAOS=1): seeded chaos fault plans
    driven through the self-healing supervisor on a tiny GPT-2, one row
    per seed — survived?, rollbacks/retries/restarts, recovered final
    loss vs the fault-free baseline on the same data.  Runs inside the
    TDDL_BENCH_WATCHDOG subprocess like every other leg, so a wedged
    recovery path still yields the skip JSON.

    Env: TDDL_BENCH_CHAOS_SEEDS ("0,1,2"), TDDL_BENCH_CHAOS_EPOCHS (3),
    TDDL_BENCH_CHAOS_RATE (0.04)."""
    import shutil
    import tempfile

    import numpy as np

    from trustworthy_dl_tpu import (
        DistributedTrainer,
        TrainingConfig,
        TrainingSupervisor,
        get_dataloader,
    )
    from trustworthy_dl_tpu.chaos import FaultInjector, FaultKind, FaultPlan

    seeds = [int(s) for s in os.environ.get(
        "TDDL_BENCH_CHAOS_SEEDS", "0,1,2").split(",")]
    epochs = int(os.environ.get("TDDL_BENCH_CHAOS_EPOCHS", "3"))
    rate = float(os.environ.get("TDDL_BENCH_CHAOS_RATE", "0.04"))
    tiny = dict(n_layer=2, n_embd=64, n_head=4, vocab_size=512,
                n_positions=64, seq_len=32)
    ckpt_dir = tempfile.mkdtemp(prefix="tddl_bench_chaos_")
    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext", batch_size=16,
        num_nodes=4, learning_rate=3e-3, detector_warmup=4,
        checkpoint_interval=5, checkpoint_dir=ckpt_dir, num_epochs=epochs,
        # FaultPlan.predict's retry/rollback arithmetic assumes the
        # synchronous step guard; the async pipeline's lagged guard skips
        # in-place retries (engine/async_host.py).
        async_host_depth=0,
    )
    trainer = DistributedTrainer(config, model_overrides=tiny)
    dl = get_dataloader("openwebtext", batch_size=16, seq_len=32,
                        vocab_size=512, num_examples=128)
    steps_per_epoch = 128 // 16
    horizon = steps_per_epoch * epochs

    trainer.initialize()
    base = trainer.train(dl, num_epochs=epochs)
    base_loss = base["epochs"][-1]["train_loss"]
    log(f"chaos baseline (fault-free): final loss {base_loss:.4f} "
        f"({horizon} steps)")

    rows = []
    for seed in seeds:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        trainer.reset_for_run()
        plan = FaultPlan.generate(seed, horizon, {
            FaultKind.GRAD_NAN: rate,
            FaultKind.DATA_LOSS: rate,
            FaultKind.STALL: rate / 2,
            FaultKind.PREEMPT: rate / 2,
            FaultKind.CKPT_CRASH: rate / 2,
            FaultKind.CKPT_CORRUPT: rate / 2,
        }, severity=0.05)
        injector = FaultInjector(plan)
        supervisor = TrainingSupervisor(
            trainer, max_retries=1, rollback_after=2,
            max_restarts=plan.count(FaultKind.PREEMPT) + 1,
            chaos=injector,
        )
        row = {"seed": seed, "faults_planned": len(plan.events)}
        try:
            res = supervisor.run(dl, num_epochs=epochs)
            rep = res["supervisor"]
            final = res["epochs"][-1]["train_loss"]
            row.update(
                survived=True,
                final_loss=round(final, 4),
                baseline_loss=round(base_loss, 4),
                loss_gap=round(final - base_loss, 4),
                rollbacks=rep["rollbacks"], retries=rep["retries"],
                restarts=rep["restarts"],
                faults_fired=rep.get("faults_fired", {}),
            )
        except Exception as exc:  # survival is the metric, not a crash
            row.update(survived=False,
                       error=f"{type(exc).__name__}: {str(exc)[:120]}")
        log(f"chaos seed {seed}: {row}")
        rows.append(row)
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    return rows


def bench_async() -> "dict | None":
    """Async host-pipeline A/B (TDDL_BENCH_ASYNC=1): the REAL trainer host
    loop (``train_epoch``) at ``async_host_depth=0`` (every step blocks on
    the host pulls) vs the config default (bounded in-flight dispatch,
    lagged host drain) — tokens/sec and the obs phase shares per arm, so
    the record shows the blocked-on-host time collapsing.  LM-only (the
    headline row); one trainer is built and the arms share its compiled
    step via ``reset_for_run``.

    Env: TDDL_BENCH_ASYNC_STEPS (measured steps per arm; default
    TDDL_BENCH_STEPS), plus the usual TDDL_BENCH_MODEL/NODES/BATCH/SEQ
    shape overrides."""
    import dataclasses

    from trustworthy_dl_tpu.core.config import TrainingConfig
    from trustworthy_dl_tpu.data import get_dataloader
    from trustworthy_dl_tpu.obs import ObsSession

    model = os.environ.get("TDDL_BENCH_MODEL", "gpt2")
    if not model.startswith("gpt"):
        log("async A/B skipped: defined for the LM headline row "
            f"(TDDL_BENCH_MODEL={model})")
        return None
    num_nodes = int(os.environ.get("TDDL_BENCH_NODES", "4"))
    per_node_batch = int(os.environ.get("TDDL_BENCH_BATCH", "16"))
    seq_len = int(os.environ.get("TDDL_BENCH_SEQ", "512"))
    steps = int(os.environ.get(
        "TDDL_BENCH_ASYNC_STEPS", os.environ.get("TDDL_BENCH_STEPS", "20")))
    n_chips = int(os.environ.get("_TDDL_BENCH_NCHIPS", "1"))
    batch_size = num_nodes * per_node_batch
    tokens_per_step = batch_size * seq_len
    default_depth = TrainingConfig().async_host_depth

    trainer, _, _ = _build_bench_trainer(True, model, num_nodes,
                                         per_node_batch, seq_len)
    vocab = trainer.model.config.vocab_size
    warm_dl = get_dataloader("openwebtext", batch_size=batch_size,
                             seq_len=seq_len, vocab_size=vocab,
                             num_examples=batch_size * 3)
    dl = get_dataloader("openwebtext", batch_size=batch_size,
                        seq_len=seq_len, vocab_size=vocab,
                        num_examples=batch_size * steps)

    arms = {}
    for label, depth in (("sync", 0), ("async", default_depth)):
        trainer.config = dataclasses.replace(trainer.config,
                                             async_host_depth=depth)
        trainer.reset_for_run()
        trainer.attach_obs(ObsSession(None))  # warmup arm — discarded
        trainer.train_epoch(warm_dl, 0)
        session = ObsSession(None)
        trainer.attach_obs(session)
        t0 = time.perf_counter()
        trainer.train_epoch(dl, 1)
        elapsed = time.perf_counter() - t0
        phases = session.step_timer.report().get("phases", {})
        arms[label] = {
            "async_host_depth": depth,
            "tokens_per_s_per_chip": round(
                steps * tokens_per_step / elapsed / n_chips, 1),
            "steps_per_s": round(steps / elapsed, 3),
            "phase_fractions": {
                name: round(stats["fraction"], 4)
                for name, stats in phases.items()
            },
        }
        log(f"async A/B [{label} depth={depth}]: "
            f"{arms[label]['steps_per_s']:.3f} steps/s, phases "
            f"{arms[label]['phase_fractions']}")
    speedup = (arms["async"]["tokens_per_s_per_chip"]
               / max(arms["sync"]["tokens_per_s_per_chip"], 1e-9))
    arms["speedup"] = round(speedup, 4)
    log(f"async A/B speedup (depth {default_depth} vs 0): {speedup:.4f}x")
    return arms


def bench_quant() -> "dict | None":
    """int8 quantization A/B (TDDL_BENCH_QUANT=1): serving throughput at
    an EQUAL HBM BUDGET — the budget is what the baseline (model-dtype)
    KV pool of TDDL_BENCH_QUANT_SLOTS slots costs; the int8 arm admits
    ``floor(budget / bytes_per_slot_int8)`` slots (>= 1.5x at GPT-2 head
    dims: 2*(Dh+4) int8+scale bytes vs 2*2*Dh bf16 bytes per cached
    position).  Both arms drain the same seeded closed-loop workload;
    the record reports slots, KV bytes and tokens/s per arm plus the
    slot and throughput ratios.  TDDL_BENCH_QUANT_W8=1 additionally
    puts weight-only int8 under the quantized arm (off by default so
    the A/B isolates the KV tier).

    Env: TDDL_BENCH_QUANT_MODEL (gpt2), TDDL_BENCH_QUANT_SLOTS (8),
    TDDL_BENCH_QUANT_SEQ (256), TDDL_BENCH_QUANT_REQUESTS (32),
    TDDL_BENCH_QUANT_NEW (32)."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.serve import (
        ServeRequest,
        ServingEngine,
        kv_bytes_per_slot,
    )

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_QUANT_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    base_slots = int(os.environ.get("TDDL_BENCH_QUANT_SLOTS", "8"))
    max_seq = int(os.environ.get("TDDL_BENCH_QUANT_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_QUANT_REQUESTS", "32"))
    max_new = int(os.environ.get("TDDL_BENCH_QUANT_NEW", "32"))
    w8 = os.environ.get("TDDL_BENCH_QUANT_W8") == "1"

    import jax.numpy as jnp

    budget = base_slots * kv_bytes_per_slot(cfg, max_seq)
    int8_slots = budget // kv_bytes_per_slot(cfg, max_seq, jnp.int8)
    plen_hi = min(64, max_seq - max_new + 1)
    if plen_hi <= 8:
        raise ValueError(
            f"TDDL_BENCH_QUANT_SEQ={max_seq} leaves no room for prompts "
            f">= 8 tokens at TDDL_BENCH_QUANT_NEW={max_new}"
        )

    def workload(rng):
        out = []
        for _ in range(n_requests):
            plen = int(rng.integers(8, plen_hi))
            out.append(ServeRequest(
                prompt=rng.integers(0, cfg.vocab_size, plen).tolist(),
                max_new_tokens=int(rng.integers(min(4, max_new),
                                                max_new + 1)),
                temperature=0.0,
            ))
        return out

    record = {"budget_bytes": int(budget), "arms": {}}
    arm_defs = (
        ("base", dict(max_slots=base_slots)),
        ("int8", dict(max_slots=int(int8_slots), kv_dtype="int8",
                      weight_dtype="int8" if w8 else "model")),
    )
    for label, kw in arm_defs:
        engine = ServingEngine(params, cfg, max_seq=max_seq,
                               queue_limit=n_requests,
                               rng=jax.random.PRNGKey(1), **kw)
        reqs = workload(np.random.default_rng(0))
        t0 = time.perf_counter()
        for req in reqs:
            engine.submit(req)
        engine.run_until_idle()
        elapsed = time.perf_counter() - t0
        summary = engine.metrics_summary()
        record["arms"][label] = {
            "slots": engine.scheduler.allocator.max_slots,
            "kv_bytes": int(engine.scheduler.kv.pool_bytes),
            "kv_dtype": engine.kv_dtype,
            "weight_dtype": engine.weight_dtype,
            "kv_fallback": engine.kv_fallback_reason,
            "tokens_per_s": round(summary["tokens_per_s"], 1),
            "completed": summary["requests_completed"],
            "wall_s": round(elapsed, 3),
        }
        log(f"quant A/B [{label}]: {record['arms'][label]['slots']} "
            f"slot(s) / {record['arms'][label]['kv_bytes'] / 1e6:.1f} MB "
            f"KV, {record['arms'][label]['tokens_per_s']:.1f} tok/s "
            f"({record['arms'][label]['completed']} completed)")
    base, quant = record["arms"]["base"], record["arms"]["int8"]
    record["slots_ratio"] = round(quant["slots"] / base["slots"], 3)
    record["tokens_per_s_ratio"] = round(
        quant["tokens_per_s"] / max(base["tokens_per_s"], 1e-9), 3)
    log(f"quant A/B: {record['slots_ratio']}x slots at equal HBM "
        f"budget ({budget / 1e6:.1f} MB), "
        f"{record['tokens_per_s_ratio']}x tokens/s")
    return record


def bench_adapters() -> "dict | None":
    """Paged adapter-pool A/B (TDDL_BENCH_ADAPTERS=1): multi-tenant
    serving throughput at an EQUAL HBM BUDGET — the budget is what the
    adapter-OFF arm's paged KV pool costs; the adapter arm carves its
    low-rank pool (serve/adapters.py) out of that SAME budget, giving
    back KV blocks block-for-block, so the row answers the deployment
    question: what does per-tenant personalisation cost at fixed HBM?
    Both arms drain an IDENTICAL seeded Zipf multi-tenant workload
    (``zipf_adapter_assignments`` — a hot adapter head + a long tail, so
    pool pages << adapters forces real LRU eviction traffic).  The
    record reports tokens/s per arm plus the pool's hit rate, eviction
    and upload counts; hit rate and the tokens/s ratio ride the perf
    sentinel fingerprint so pool-locality regressions band-check (and
    page) like throughput regressions.

    Env: TDDL_BENCH_ADAPTERS_MODEL (gpt2), TDDL_BENCH_ADAPTERS_SLOTS
    (8), TDDL_BENCH_ADAPTERS_SEQ (256), TDDL_BENCH_ADAPTERS_REQUESTS
    (48), TDDL_BENCH_ADAPTERS_NEW (16), TDDL_BENCH_ADAPTERS_RANK (8),
    TDDL_BENCH_ADAPTERS_PAGES (4), TDDL_BENCH_ADAPTERS_TENANTS (12),
    TDDL_BENCH_ADAPTERS_COUNT (8, distinct adapters),
    TDDL_BENCH_ADAPTERS_DTYPE (model|int8)."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.serve import ServeRequest, ServingEngine
    from trustworthy_dl_tpu.serve.adapters import adapter_pool_bytes
    from trustworthy_dl_tpu.serve.workload import (
        WorkloadConfig,
        generate_workload,
        make_tenant_population,
        zipf_adapter_assignments,
    )

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_ADAPTERS_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    max_slots = int(os.environ.get("TDDL_BENCH_ADAPTERS_SLOTS", "8"))
    max_seq = int(os.environ.get("TDDL_BENCH_ADAPTERS_SEQ", "256"))
    n_requests = int(os.environ.get("TDDL_BENCH_ADAPTERS_REQUESTS", "48"))
    max_new = int(os.environ.get("TDDL_BENCH_ADAPTERS_NEW", "16"))
    rank = int(os.environ.get("TDDL_BENCH_ADAPTERS_RANK", "8"))
    pages = int(os.environ.get("TDDL_BENCH_ADAPTERS_PAGES", "4"))
    n_tenants = int(os.environ.get("TDDL_BENCH_ADAPTERS_TENANTS", "12"))
    n_adapters = int(os.environ.get("TDDL_BENCH_ADAPTERS_COUNT", "8"))
    adapter_dtype = os.environ.get("TDDL_BENCH_ADAPTERS_DTYPE", "model")

    tenants = make_tenant_population(n_tenants)
    adapter_map = zipf_adapter_assignments(
        [t.name for t in tenants], n_adapters, seed=0)
    wl = generate_workload(
        WorkloadConfig(seed=0, num_requests=n_requests,
                       output_median=max_new // 2 or 1,
                       max_output=max_new, tenants=tenants),
        vocab_size=cfg.vocab_size, max_seq=max_seq)

    block_size = 16
    base_blocks = max_slots * (max_seq // block_size)

    def run_arm(label, num_blocks, **kw):
        engine = ServingEngine(params, cfg, max_slots=max_slots,
                               max_seq=max_seq, queue_limit=n_requests,
                               paged=True, block_size=block_size,
                               num_blocks=num_blocks,
                               rng=jax.random.PRNGKey(1), **kw)
        t0 = time.perf_counter()
        for item in wl:
            engine.submit(ServeRequest(
                prompt=list(item.prompt),
                max_new_tokens=item.max_new_tokens,
                temperature=0.0, tenant=item.tenant))
        engine.run_until_idle()
        elapsed = time.perf_counter() - t0
        summary = engine.metrics_summary()
        row = {
            "blocks": num_blocks,
            "kv_bytes": int(engine.scheduler.kv.pool_bytes),
            "tokens_per_s": round(summary["tokens_per_s"], 1),
            "completed": summary["requests_completed"],
            "wall_s": round(elapsed, 3),
        }
        if "adapters" in summary:
            row["adapters"] = summary["adapters"]
        log(f"adapters A/B [{label}]: {num_blocks} block(s), "
            f"{row['tokens_per_s']:.1f} tok/s "
            f"({row['completed']} completed)")
        return engine, row

    record = {"arms": {}, "rank": rank, "pages": pages,
              "adapter_dtype": adapter_dtype,
              "tenants": n_tenants, "adapters": n_adapters}
    engine, row = run_arm("off", base_blocks)
    record["budget_bytes"] = int(engine.scheduler.kv.pool_bytes)
    bpb = engine.scheduler.kv.bytes_per_block
    record["arms"]["off"] = row
    pool_bytes = adapter_pool_bytes(cfg, pages, rank, adapter_dtype)
    give_back = -(-int(pool_bytes) // bpb)   # ceil: the pool pays in full
    on_blocks = base_blocks - give_back
    if on_blocks < max_slots:
        raise ValueError(
            f"TDDL_BENCH_ADAPTERS_PAGES={pages} at rank {rank} costs "
            f"{give_back} of {base_blocks} KV blocks — under one block "
            f"per slot; shrink the pool or the rank")
    _, row = run_arm("on", on_blocks, adapter_rank=rank,
                     adapter_pool_pages=pages,
                     adapter_dtype=adapter_dtype,
                     adapter_map=adapter_map)
    record["arms"]["on"] = row
    record["adapter_pool_bytes"] = int(pool_bytes)
    pool = row["adapters"]
    record["hit_rate"] = round(pool["hit_rate"], 4)
    record["evictions"] = pool["evictions"]
    record["uploads"] = pool["uploads"]
    record["tokens_per_s_ratio"] = round(
        row["tokens_per_s"]
        / max(record["arms"]["off"]["tokens_per_s"], 1e-9), 3)
    log(f"adapters A/B: {record['tokens_per_s_ratio']}x tokens/s at "
        f"equal HBM ({record['budget_bytes'] / 1e6:.1f} MB; pool "
        f"{pool_bytes / 1e6:.2f} MB = {give_back} blocks), hit rate "
        f"{record['hit_rate']}, {record['evictions']} eviction(s)")
    return record


def bench_generate() -> None:
    """Optional decode benchmark (TDDL_BENCH_GEN=1): KV-cache generation
    steady-state cost on the full GPT-2.  Diagnostics only — stderr.

    Measurement notes (hard-won): on the axon remote-TPU tunnel,
    ``block_until_ready`` does NOT wait for remote execution — only host
    materialisation (np.asarray) does, so every call round-trips the
    result.  The per-call RPC constant (~130-160 ms, NOT a property of
    the decode program) is removed by differencing two generation
    lengths: slope = (t(N2) - t(N1)) / (N2 - N1) is the steady-state
    per-token cost.  Calls chain (output tail feeds the next prompt) so
    nothing can be served from a cache."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2
    from trustworthy_dl_tpu.models.generate import generate

    cfg = gpt2.GPT2Config.from_name(
        os.environ.get("TDDL_BENCH_GEN_MODEL", "gpt2")
    )
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    prompt_len = 32
    n1, n2 = 16, int(os.environ.get("TDDL_BENCH_GEN_NEW", "128"))
    if n2 <= n1:
        # TDDL_BENCH_GEN_NEW is the slope's LONG length; keep the
        # difference positive for small values instead of dividing by <=0.
        n1 = max(1, n2 // 2)
    reps = int(os.environ.get("TDDL_BENCH_GEN_REPS", "12"))

    def median_call(batch, new, **kw):
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab_size)
        cur = prompt
        full = generate(params, cfg, cur, new, **kw)
        np.asarray(full)  # compile + first execution
        cur = full[:, -prompt_len:]
        ts = []
        for i in range(reps):
            t0 = time.perf_counter()
            full = generate(params, cfg, cur, new,
                            rng=jax.random.PRNGKey(i), **kw)
            np.asarray(full)  # host materialisation = real execution
            ts.append(time.perf_counter() - t0)
            cur = full[:, -prompt_len:]
        return float(np.median(ts))

    for batch in (1, 32):
        for name, kw in (("greedy", {}),
                         ("top_k=40", dict(temperature=0.8, top_k=40))):
            t1 = median_call(batch, n1, **kw)
            t2 = median_call(batch, n2, **kw)
            slope = (t2 - t1) / (n2 - n1)
            log(f"generate b={batch:3d} {name:9s}: "
                f"{slope * 1e3:6.3f} ms/token steady-state "
                f"({batch / slope:,.0f} tok/s; RPC+prefill constant "
                f"{(t1 - n1 * slope) * 1e3:.0f} ms/call excluded)")


def main() -> None:
    if "--config" in sys.argv:
        idx = sys.argv.index("--config") + 1
        if idx >= len(sys.argv):
            log("usage: bench.py --config <preset>  (--config list to "
                "enumerate)")
            sys.exit(2)
        # Presets materialise as env defaults, so the watchdogged inner
        # process inherits them without re-parsing argv.
        apply_preset(sys.argv[idx])

    if os.environ.get("_TDDL_BENCH_INNER") == "1":
        _inner_main()
        return

    # Static-analysis leg first: host-only, cheapest, and its verdict
    # must not depend on backend health.
    global _LINT_RECORD
    _LINT_RECORD = bench_lint()
    if _LINT_RECORD is not None:
        log(f"lint: rc {_LINT_RECORD['rc']} over "
            f"{_LINT_RECORD['files_scanned']} files "
            f"({len(_LINT_RECORD['findings'])} finding(s), "
            f"{_LINT_RECORD['baselined']} baselined)")
        if _LINT_RECORD["rc"] != 0:
            print(json.dumps(_skip_record("lint_findings",
                                          lint=_LINT_RECORD)))
            sys.exit(4)

    # Evidence-proofing: the axon remote-TPU tunnel is documented-flaky
    # (BASELINE.md methodology notes).  A dead backend must still produce
    # the driver's one-line JSON — bounded retry, then a skip record at
    # rc 0, never a raw traceback (round-4 lost its perf row to exactly
    # that: jax.device_count() crashed with UNAVAILABLE at startup).
    import subprocess

    def _probe_backend():
        # The tunnel has a documented total-wedge mode where backend init
        # hangs >10 min inside native code — a SIGALRM can't interrupt
        # that, so the probe runs in a SUBPROCESS with a hard timeout
        # (TDDL_BENCH_PROBE_TIMEOUT seconds, default 180 — raise it for
        # slow-init backends instead of losing the round to a skip).
        # Only after the probe proves the backend answers does this
        # process touch jax itself.  A SUCCESSFUL probe is cached for the
        # process: multi-leg sweeps re-entering main() must not re-pay
        # (or re-risk) the init just because one probe was slow.
        global _PROBE_CACHE
        if _PROBE_CACHE is not None:
            return _PROBE_CACHE
        # Cross-process tier: a prior round's healthy probe persisted to
        # disk (TDDL_BENCH_PROBE_CACHE) short-circuits the subprocess
        # probe entirely — TDDL_BENCH_PROBE_REFRESH=1 forces a fresh one
        # (e.g. after the backend topology changed).
        if os.environ.get("TDDL_BENCH_PROBE_REFRESH") != "1":
            saved = _read_probe_cache()
            if saved is not None:
                log(f"backend probe skipped: prior healthy probe on "
                    f"disk ({saved[0]} x {saved[1]}; "
                    f"TDDL_BENCH_PROBE_REFRESH=1 to re-probe)")
                _PROBE_CACHE = saved
                return _PROBE_CACHE
        timeout = float(os.environ.get("TDDL_BENCH_PROBE_TIMEOUT", "180"))
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, json; "
             "print(json.dumps([jax.device_count(), "
             "jax.devices()[0].platform]))"],
            capture_output=True, text=True, timeout=timeout,
        )
        if proc.returncode != 0:
            tail = proc.stderr.strip().splitlines()
            raise RuntimeError(tail[-1] if tail else
                               f"probe rc={proc.returncode}")
        count, name = json.loads(proc.stdout.strip().splitlines()[-1])
        _PROBE_CACHE = max(int(count), 1), name
        _write_probe_cache(*_PROBE_CACHE)
        return _PROBE_CACHE

    n_chips = platform = None
    last_err = None
    for attempt in range(3):
        try:
            n_chips, platform = _probe_backend()
            break
        except Exception as e:  # probe failure or TimeoutExpired (wedge)
            last_err = e
            log(f"backend init failed (attempt {attempt + 1}/3): {e}")
            if attempt < 2:  # no pointless backoff after the last try
                time.sleep(
                    float(os.environ.get("TDDL_BENCH_RETRY_SLEEP", "10"))
                    * (attempt + 1))
    if n_chips is None:
        print(json.dumps(_skip_record(
            f"backend unavailable after 3 attempts: "
            f"{type(last_err).__name__}: {last_err}",
            # Triage hint: True means an earlier round DID reach this
            # backend (the disk cache holds a healthy probe — so either
            # TDDL_BENCH_PROBE_REFRESH=1 was set or the backend broke
            # since); False means no round has ever probed healthy here.
            prior_healthy_probe=_read_probe_cache() is not None,
        )))
        sys.exit(0)

    # The measured body runs in a SUBPROCESS under a hard wall-clock
    # watchdog: the liveness probe above only proves the backend answered
    # once — the tunnel's documented total-wedge mode can still hang the
    # body mid-measurement inside native code (where SIGALRM cannot
    # reach), which before this guard produced rc != 0 / no JSON and lost
    # the round's perf row.  On expiry the child is killed and the skip
    # record still goes out at rc 0.
    watchdog = float(os.environ.get("TDDL_BENCH_WATCHDOG", "3600"))
    env = dict(os.environ)
    env.update({
        "_TDDL_BENCH_INNER": "1",
        "_TDDL_BENCH_NCHIPS": str(n_chips),
        "_TDDL_BENCH_PLATFORM": str(platform),
    })
    # stderr inherits (diagnostics stream live); stdout is captured so the
    # parent republishes EXACTLY one JSON line whatever the child printed.
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    try:
        out, _ = proc.communicate(timeout=watchdog)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        _invalidate_probe_cache("watchdog expired")
        print(json.dumps(_skip_record(
            f"bench body exceeded the {watchdog:.0f}s watchdog "
            "(backend wedged after the liveness probe)",
        )))
        sys.exit(0)
    record = None
    for line in reversed((out or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                record = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if proc.returncode != 0 or record is None:
        # Could be a backend that died post-probe OR a bench-code bug —
        # either way a re-probe next round costs seconds, while trusting
        # a stale cache against a dead backend costs the full watchdog.
        _invalidate_probe_cache(f"body failed rc={proc.returncode}")
        print(json.dumps(_skip_record(
            f"bench body failed (rc={proc.returncode}, "
            f"parsable JSON line: {record is not None})",
        )))
        sys.exit(0)
    print(json.dumps(record))
    # Sentinel CI arm (off by default): a confirmed regression outside
    # the ledger noise band exits non-zero AFTER the record is out —
    # the one-JSON-line contract holds either way.
    sys.exit(_sentinel_rc(record))


def _inner_main() -> None:
    """The measured bench body (runs inside the watchdog subprocess)."""
    model = os.environ.get("TDDL_BENCH_MODEL", "gpt2")
    num_nodes = int(os.environ.get("TDDL_BENCH_NODES", "4"))
    per_node_batch = int(os.environ.get("TDDL_BENCH_BATCH", "16"))
    seq_len = int(os.environ.get("TDDL_BENCH_SEQ", "512"))
    steps = int(os.environ.get("TDDL_BENCH_STEPS", "20"))
    warmup = int(os.environ.get("TDDL_BENCH_WARMUP", "3"))
    n_chips = int(os.environ.get("_TDDL_BENCH_NCHIPS", "1"))
    platform = os.environ.get("_TDDL_BENCH_PLATFORM", "unknown")

    if os.environ.get("TDDL_BENCH_FAKE_WEDGE") == "1":
        # Watchdog test hook: simulate the tunnel's post-probe total wedge
        # (tests/test_bench_contract.py) without a real dead backend.
        log("FAKE_WEDGE: sleeping forever (watchdog should kill this)")
        time.sleep(10 ** 6)

    if os.environ.get("TDDL_BENCH_COMPILE_CACHE") == "1":
        # Persistent XLA compilation cache for the whole measured body:
        # repeat sweeps skip recompiles of identical SPMD programs.  The
        # cache lives under the obs dir when one is set (self-contained
        # run artifacts), else a stable temp path.
        import tempfile

        from trustworthy_dl_tpu.utils.compile_cache import (
            enable_persistent_cache,
        )

        cache_dir = os.environ.get("TDDL_BENCH_COMPILE_CACHE_DIR") or \
            os.path.join(os.environ.get("TDDL_BENCH_OBS_DIR")
                         or tempfile.gettempdir(), "tddl_bench_jax_cache")
        log(f"persistent compilation cache: {enable_persistent_cache(cache_dir)}")

    # Performance observability for the whole measured body: every XLA
    # compilation metered from here on (obs/compilewatch.py), live-HBM
    # swept at the end — both land in the record's "compile"/"hbm"
    # sections with the sentinel fingerprint/verdict.
    from trustworthy_dl_tpu.obs.compilewatch import CompileRegistry
    from trustworthy_dl_tpu.obs.hbm import HbmMonitor

    compiles = CompileRegistry().install()
    hbm_monitor = HbmMonitor()

    is_lm = model.startswith("gpt")
    log(f"bench: {model} nodes={num_nodes} batch/node={per_node_batch} "
        f"seq={seq_len} steps={steps} on {n_chips} {platform} device(s)")

    # Work per step: tokens for LMs, samples for vision models.
    tokens_per_step = num_nodes * per_node_batch * (seq_len if is_lm else 1)
    unit = "tokens/sec/chip" if is_lm else "samples/sec/chip"

    # Vision steps are ~20 ms — far below the remote tunnel's multi-second
    # throughput drift, so the sequential all-OFF-then-all-ON comparison
    # reads garbage there; interleaved paired blocks cancel the drift.
    # LM steps are 100s of ms and the sequential design is stable (and
    # keeps the single-trainer memory footprint for big models).
    interleave_env = os.environ.get("TDDL_BENCH_INTERLEAVE")
    interleave = (interleave_env == "1") if interleave_env else not is_lm
    if interleave:
        # Blocks must dwarf the ~140 ms host-close RPC constant (vision
        # steps are ~20 ms, so >=50 steps/block ≈ >=1 s).
        block_steps = max(50, steps)
        sps_on, ratio, n_params = bench_overhead_interleaved(
            model, num_nodes, per_node_batch, seq_len, block_steps,
            rounds=int(os.environ.get("TDDL_BENCH_ROUNDS", "7")),
            warmup=warmup,
        )
        log(f"interleaved: detection ON {sps_on:.3f} steps/s, "
            f"median ON/OFF ratio {ratio:.4f}")
    else:
        sps_off, n_params = bench_mode(False, model, num_nodes,
                                       per_node_batch, seq_len, steps,
                                       warmup)
        log(f"detection OFF: {sps_off:.3f} steps/s "
            f"({sps_off * tokens_per_step / n_chips:,.0f} {unit})")
        sps_on, _ = bench_mode(True, model, num_nodes, per_node_batch,
                               seq_len, steps, warmup)
        log(f"detection ON:  {sps_on:.3f} steps/s "
            f"({sps_on * tokens_per_step / n_chips:,.0f} {unit})")
        if not 0.3 <= sps_on / sps_off <= 1.2:
            # Implausible ratio — seen on the remote-TPU tunnel (execution
            # caching artifact).  Detection adds bounded work, so ON/OFF
            # far outside [0.3, 1.2] means a bogus measurement: redo both
            # once and trust the rerun.
            log(f"implausible ON/OFF ratio {sps_on / sps_off:.3f}; "
                "remeasuring")
            sps_off, _ = bench_mode(False, model, num_nodes,
                                    per_node_batch, seq_len, steps, warmup)
            sps_on, _ = bench_mode(True, model, num_nodes, per_node_batch,
                                   seq_len, steps, warmup)
            log(f"remeasured OFF {sps_off:.3f} / ON {sps_on:.3f} steps/s")
        ratio = sps_on / sps_off

    tps_on = sps_on * tokens_per_step / n_chips
    # Watermark sweep while the measured trainers' state is still live —
    # the optional legs below free/rebuild models, and the final sweep in
    # _attach_perf_sections would miss the training-footprint peak.
    hbm_monitor.sweep()
    overhead_pct = (1.0 - ratio) * 100.0
    log(f"detection overhead: {overhead_pct:.1f}% (target <=15%)")
    # Run-metadata stamp + MFU via the shared obs helpers — the bench
    # record carries the same metadata block every experiment artifact
    # does, and the MFU figure names its peak-FLOPs source instead of
    # leaving the roofline implicit (VERDICT r5: ~29% MFU, no artifact
    # explaining it).
    from trustworthy_dl_tpu.obs.meta import run_metadata
    from trustworthy_dl_tpu.obs.report import mfu_from_throughput

    meta = run_metadata()
    tflops = None
    mfu = None
    if is_lm:
        # Standard transformer-training estimate: ~6 FLOPs per param per
        # token (fwd 2 + bwd 4); remat adds recompute not counted here, so
        # this is a lower bound on hardware FLOPs actually executed.  (No
        # comparable param-count formula for convs, so vision skips it.)
        tflops = 6.0 * n_params * tps_on / 1e12
        mfu = mfu_from_throughput(n_params, tps_on,
                                  device_kind=meta["device_kind"])
        log(f"achieved model FLOPs: {tflops:.1f} TFLOP/s/chip "
            f"({n_params / 1e6:.0f}M params); MFU {mfu['mfu']:.3f} vs "
            f"{mfu['peak_flops_source']}")

    if os.environ.get("TDDL_BENCH_FUSED") == "1":
        # Native-tier A/B: detection ON with the Pallas fused moment battery
        # (ops/fused_stats.py) instead of XLA's fused reductions.
        os.environ["TDDL_FUSED_STATS"] = "1"
        try:
            sps_fused, _ = bench_mode(True, model, num_nodes, per_node_batch,
                                      seq_len, steps, warmup)
        finally:
            del os.environ["TDDL_FUSED_STATS"]
        log(f"detection ON (pallas fused stats): {sps_fused:.3f} steps/s "
            f"(vs {sps_on:.3f} XLA)")

    if os.environ.get("TDDL_BENCH_LONGCTX") == "1":
        bench_longctx()
    if os.environ.get("TDDL_BENCH_GEN") == "1":
        bench_generate()
    serve_records = None
    paged_record = None
    spec_record = None
    paged_attn_record = None
    if os.environ.get("TDDL_BENCH_SERVE") == "1":
        serve_records = bench_serve()
        paged_record = bench_paged()
        if os.environ.get("TDDL_BENCH_SPEC") == "1":
            spec_record = bench_spec()
        if os.environ.get("TDDL_BENCH_PAGED_ATTN") == "1":
            paged_attn_record = bench_paged_attn()
    fleet_record = None
    if os.environ.get("TDDL_BENCH_FLEET") == "1":
        fleet_record = bench_fleet()
    migrate_record = None
    if os.environ.get("TDDL_BENCH_MIGRATE") == "1":
        migrate_record = bench_migrate()
    shard_record = None
    if os.environ.get("TDDL_BENCH_SHARD") == "1":
        shard_record = bench_shard()
    adversary_record = None
    if os.environ.get("TDDL_BENCH_ADVERSARY") == "1":
        adversary_record = bench_adversary()
    autoscale_record = None
    if os.environ.get("TDDL_BENCH_AUTOSCALE") == "1":
        autoscale_record = bench_autoscale()
    chaos_records = None
    if os.environ.get("TDDL_BENCH_CHAOS") == "1":
        chaos_records = bench_chaos()
    async_records = None
    if os.environ.get("TDDL_BENCH_ASYNC") == "1":
        async_records = bench_async()
    quant_records = None
    if os.environ.get("TDDL_BENCH_QUANT") == "1":
        quant_records = bench_quant()
    adapters_record = None
    if os.environ.get("TDDL_BENCH_ADAPTERS") == "1":
        adapters_record = bench_adapters()

    record = {
        "metric": f"{model}_{unit.split('/')[0]}_per_sec_per_chip"
                  "_detection_on",
        "value": round(tps_on, 1),
        "unit": unit,
        "vs_baseline": round(ratio, 4),
        "detection_overhead_pct": round(overhead_pct, 2),
        "platform": platform,
        "num_chips": n_chips,
        ("tokens_per_step" if is_lm else "samples_per_step"):
            tokens_per_step,
        "model_tflops_per_chip": round(tflops, 2) if tflops else None,
        "mfu": mfu,
        "run_metadata": meta,
    }
    if _LINT_RECORD is not None:
        record["lint"] = _LINT_RECORD
    if spec_record is not None:
        # Attached BEFORE the perf sections: the sentinel fingerprint
        # lifts accepted_rate from it, so draft-quality regressions
        # band-check (and page) exactly like throughput regressions.
        record["spec"] = spec_record
    if paged_attn_record is not None:
        # Same contract: the fingerprint lifts the kernel arm's
        # decode_tick_fraction, so a silent fall-back to the jnp gather
        # bands (and pages) like a perf regression.
        record["paged_attn"] = paged_attn_record
    if adapters_record is not None:
        # Same contract: the fingerprint lifts the adapter pool's hit
        # rate and the equal-HBM tokens/s ratio, so pool-locality and
        # personalisation-cost regressions band (and page) like perf.
        record["adapters"] = adapters_record
    if migrate_record is not None:
        # Same contract: the fingerprint lifts migration_fraction, so a
        # structural break that degrades capacity losses back to prompt
        # replay bands (and pages) like a perf regression.
        record["migrate"] = migrate_record
    _attach_perf_sections(record, compiles=compiles, hbm=hbm_monitor)
    if serve_records is not None:
        record["serve"] = serve_records
    if paged_record is not None:
        record["serve_paged"] = paged_record
    if fleet_record is not None:
        record["fleet"] = fleet_record
    if shard_record is not None:
        record["shard"] = shard_record
    if adversary_record is not None:
        record["adversary"] = adversary_record
    if autoscale_record is not None:
        record["autoscale"] = autoscale_record
    if chaos_records is not None:
        record["chaos"] = chaos_records
    if async_records is not None:
        record["async"] = async_records
    if quant_records is not None:
        record["quant"] = quant_records
    obs_dir = os.environ.get("TDDL_BENCH_OBS_DIR")
    if obs_dir:
        # Attach the per-run obs report next to whatever artifact set the
        # caller is collecting (the driver's BENCH_r*.json rides stdout;
        # this is the on-disk copy experiments can join against).
        os.makedirs(obs_dir, exist_ok=True)
        report_path = os.path.join(obs_dir, "obs_report.json")
        from trustworthy_dl_tpu.utils.io import atomic_write_json

        atomic_write_json(report_path, {
            "source": "bench", "run_metadata": meta, "mfu": mfu,
            "steps_per_s_detection_on": sps_on,
            "throughput": record["value"], "unit": unit})
        log(f"obs report written to {report_path}")
    print(json.dumps(record))


if __name__ == "__main__":
    main()

"""Trust-aware serving fleet: N engine replicas behind one ``submit()``.

One ``ServingEngine`` is one failure domain — a wedged, preempted or
poisoned replica takes "heavy traffic from millions of users" down with
it.  ``ServingFleet`` is the robustness layer ROADMAP item 4 calls for,
reusing the training trust stack at REPLICA granularity:

* **Replica lifecycle state machine** — ``healthy → degraded →
  draining → quarantined → restarting`` — driven by the obs signals the
  engines already produce (anomaly-watcher episodes, SLO burn, output-
  monitor flag rate, missed-tick heartbeat), not new instrumentation.
  A replica whose monitor flag-rate crosses the quarantine threshold is
  DRAINED (no new admissions; existing slots run out or migrate) and
  QUARANTINED with a cool-off readmission probe — mirroring the
  training-side ``elastic/`` evict → probation → readmit ladder, where
  re-entry is earned by clean behaviour, not granted by time alone
  (a still-poisoned replica re-flags during its probe and goes straight
  back, with a doubled cool-off).
* **Request fail-over** — a request on a crashed/stalled/draining
  replica is resubmitted to a healthy one with bounded retries and
  exponential backoff, inheriting its ORIGINAL submission age
  (``ServeRequest.first_submit_id``) so sustained pressure cannot
  starve retries via the shed tie-break.  Requests near their deadline
  can launch a **hedged duplicate** on a second replica; dedup-at-
  retire keeps exactly ONE canonical stream per fleet request id — the
  first completed attempt wins, losers are cancelled and recorded
  ``admitted: false, status: "hedge_lost"``.
* **Fleet chaos** — the seeded ``chaos.FaultPlan`` REPLICA_* kinds
  (crash / stall / poison / slow-start) drive drills whose exact
  fail-over/drain/quarantine counts are pinned by
  ``FaultPlan.predict_fleet()``; every attempt is replayed with the
  request's own rng key, so a survivor's stream is bit-identical to a
  single-engine ``generate()`` run no matter how many replicas it
  crossed.

Time: the fleet is a synchronous tick loop (``step()`` = one fleet
tick: chaos hooks → step each live replica → process retirements →
supervise lifecycles → retries/hedges).  Backoff, heartbeats, drains,
cool-offs and restarts are all measured in TICKS so drills are
deterministic; request deadlines stay wall-clock (they are the user's
contract, not the scheduler's).

Attribution: each engine runs ledger-less; the FLEET writes one
canonical record per request at final retirement, carrying an
``attempts`` list (placement + journal key per attempt) so one record's
blocks can span two replicas' allocators and still reconcile —
``verify_attribution`` checks each attempt against its replica
GENERATION's lifecycle journal (``self.journals``; a crashed replica's
journal is retained like a flight recording, its successor's fresh pool
is a new generation).

Streaming caveat: ``on_token`` fires for the PRIMARY attempt's tokens
as they are produced — after a fail-over the new attempt re-streams
from token 0, and a winning hedge's tokens may never have streamed
(at-least-once streaming; exactly-once is the retired result/record).

Adversarial tier (README §Fleet/"Adversarial scenarios"): below the
flag-rate quarantine threshold sits a **suspicion** tier — an
EWMA-smoothed score over monitor verdicts (plus anomaly-watcher
episodes and explicit :meth:`ServingFleet.note_suspicion` boosts for
attribution irregularities) that emits ``fleet_suspicion`` events and
the ``tddl_fleet_suspicion{replica=}`` gauge even with voting disabled.
With ``FleetConfig.vote_k >= 1``, a completed request retiring on a
suspected (but sub-threshold) replica triggers a **cross-replica
verdict vote**: the request is replayed on K other replicas (replay is
bit-identical by construction — every attempt reuses the request's own
rng key) and the streams are majority-voted token-for-token via the
attribution ``token_hash``, without retaining full streams.  A replica
whose stream is outvoted (a >= 2-strong majority of replays agree with
each other AND against it) ``vote_outvote_limit`` times enters the
existing drain → quarantine ladder — an adaptive attacker holding its
flag rate just under ``flag_rate_quarantine`` is caught by
*disagreement* instead of flag rate.  A lone faulty voter can never
quarantine a clean replica: outvoting requires two agreeing dissenting
ballots, so a single lying replay only earns ITSELF suspicion.  Vote
replays never stream to the user, never publish their prompt blocks to
the replica's PrefixCache (``publish_prefix=False``), and are ledgered
``admitted: false, status: "vote_replay"`` — exactly one admitted
record per fleet id still holds.

Control plane (README §Fleet/"Control plane", serve/control.py): the
closed loop ROADMAP item 4 calls for, every piece opt-in via
``FleetConfig`` so the PR 8 fleet is unchanged by default.  (1) An
**autoscaler** drives the replica count between ``min_replicas`` and
``max_replicas`` from queue depth per replica, pool occupancy, the
fleet-wide ITL p99 and SLO burn — the FLEET aggregates, not the
last-writer per-engine gauges — plus a predictive arm that anticipates
the workload generator's seeded diurnal envelope.  Hysteresis is a
threshold band + per-direction cool-downs + a sustained-idle streak;
scale-up builds a replica through the existing HBM headroom gate and
warms through RESTARTING; scale-down always DRAINS (queue migrates,
in-flight runs out — never force-migrated, never killed) into the new
RETIRED state, whose journal is retained and whose index the next
scale-up revives as a fresh generation.  (2) **Per-tenant token-bucket
admission**: a submission costs prompt + max_new tokens against its
tenant's bucket (refilled per TICK — deterministic drills); a flooding
tenant throttles ITSELF, loudly (``tenant_throttle`` events +
``tddl_fleet_tenant_throttled_total{tenant=}``), while untagged
traffic is exempt.  (3) **SLO-class weighted-fair scheduling**:
submissions queue at the fleet in per-class deficit-round-robin queues
(token-cost fairness) and dispatch to engines each tick; under a
per-class TTFT/ITL breach the LOWEST class sheds first — replacing the
raw lowest-priority shed.  Overload is drillable like crash or poison:
``FaultKind.TENANT_FLOOD`` bursts a tenant through the real admission
path, and ``FaultPlan.predict_fleet(autoscale=, quota_tokens=,
flood_request_tokens=)`` pins the exact throttle and scale-up/-down
counts.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Set, Tuple

import jax

from trustworthy_dl_tpu.obs import attribution
from trustworthy_dl_tpu.obs.events import EventType
from trustworthy_dl_tpu.obs.registry import get_registry
from trustworthy_dl_tpu.serve.engine import ServeRequest, ServeResult, \
    ServingEngine

logger = logging.getLogger(__name__)


class ReplicaState(str, enum.Enum):
    """The replica lifecycle ladder (README §Fleet carries the
    transition table)."""

    HEALTHY = "healthy"          # admitting + serving
    DEGRADED = "degraded"        # admitting, under suspicion
    DRAINING = "draining"        # no admissions; slots run out or migrate
    QUARANTINED = "quarantined"  # out of service, cool-off running
    RESTARTING = "restarting"    # warming up (restart/probe/slow-start)
    RETIRED = "retired"          # scaled in (autoscaler); pool released,
    #                              journal retained, index reusable


#: States the router may place new work on.
ADMITTING = (ReplicaState.HEALTHY, ReplicaState.DEGRADED)

#: Statuses that end a fleet request (everything else is an attempt
#: outcome the fleet recovers from).
TERMINAL_STATUSES = ("completed", "deadline_exceeded", "shed_slo",
                     "no_capacity", "failover_exhausted")


@dataclasses.dataclass
class FleetConfig:
    """Host-side fleet knobs.  Tick-denominated fields follow the fleet
    clock (one ``step()`` = one tick), never wall time — drills must be
    seed-deterministic."""

    num_replicas: int = 2
    # -- trust: output-monitor flag rate over a sliding retirement window
    flag_window: int = 16          # retirements per replica remembered
    flag_min_count: int = 2        # flags before the rate can trip
    flag_rate_quarantine: float = 0.25  # drain+quarantine at/above this
    # -- heartbeat (missed fleet ticks without replica progress)
    heartbeat_miss_degraded: int = 2
    heartbeat_miss_limit: int = 4  # drain + fail-over at/above this
    # -- fail-over
    max_retries: int = 3           # resubmissions per request (all causes)
    backoff_base_ticks: int = 1    # retry n waits base * mult**(n-1)
    backoff_mult: float = 2.0
    # -- hedging (None = off): duplicate a request still unfinished when
    # its remaining deadline drops below this
    hedge_deadline_s: Optional[float] = None
    # -- lifecycle timing (ticks)
    restart_ticks: int = 2         # warmup after restart / probe re-entry
    quarantine_cooloff_ticks: int = 32  # first cool-off (doubles each trip)
    drain_grace_ticks: int = 8     # in-flight allowed this long to run out
    # -- per-replica watcher attachment (SLO/anomaly watchers as extra
    # degraded-signals; host-only, no registry gauges per replica)
    attach_watchers: bool = False
    # -- suspicion tier BELOW the quarantine threshold: an EWMA over
    # monitor verdicts (1 = flagged) per slot-side retirement.  A
    # replica is SUSPECTED once the score crosses suspicion_threshold
    # and it has accumulated suspicion_min_flags lifetime flags this
    # generation (or an explicit note_suspicion boost) — sustained
    # sub-threshold flagging, not one unlucky request.  Suspicion emits
    # fleet_suspicion + the tddl_fleet_suspicion{replica=} gauge even
    # with voting off.
    suspicion_ewma_alpha: float = 0.2
    suspicion_threshold: float = 0.1
    suspicion_min_flags: int = 2
    # -- cross-replica verdict voting (0 = off): replay a suspected
    # replica's completed requests on vote_k other replicas and
    # majority-vote the streams token-for-token by token_hash.  One
    # vote in flight per suspect, launched quorum-or-nothing;
    # vote_outvote_limit outvotes send the replica down the drain ->
    # quarantine ladder.  vote_k >= 2 is needed for any verdict (a
    # lone ballot can never form a majority, so clean replicas are
    # safe from a single faulty voter by construction; vote_k == 1
    # votes resolve "inconclusive").
    vote_k: int = 0
    vote_outvote_limit: int = 2
    # -- control plane (serve/control.py; ALL opt-in — the defaults
    # leave the PR 8 fleet byte-for-byte unchanged) --
    #: SLO classes (tuple of control.SLOClass): submissions queue at the
    #: FLEET in per-class deficit-round-robin queues and dispatch to
    #: engines by token-weighted fairness; under a per-class latency
    #: breach the LOWEST class sheds first.  None = legacy direct
    #: submit (requests go straight to a replica).
    slo_classes: Optional[Tuple[Any, ...]] = None
    class_queue_limit: int = 256       # per-class fleet queue bound
    drr_quantum_tokens: int = 32       # DRR quantum (tokens per round)
    class_latency_min_count: int = 8   # observations before a breach
    #: Per-tenant token-bucket admission (control.TenantQuotaConfig):
    #: a submission costs prompt + max_new tokens against its tenant's
    #: bucket; over-budget submissions are throttled loudly.  None =
    #: no quotas.  Requests with tenant=None bypass quota (untagged
    #: traffic is the operator's own).
    tenant_quota: Optional[Any] = None
    #: Per-ADAPTER token-bucket admission (control.TenantQuotaConfig,
    #: keyed by adapter id): one tenant's fine-tune must not starve the
    #: base-model traffic or another tenant's adapter — a submission
    #: resolving to an adapter spends against BOTH its tenant bucket and
    #: its adapter bucket.  None = no adapter quotas.  Requests that
    #: resolve to no adapter bypass this bucket entirely.
    adapter_quota: Optional[Any] = None
    #: Autoscaler (control.AutoscalerConfig): drives the replica count
    #: between min/max from queue depth, occupancy, ITL-p99, SLO burn
    #: and the predictive arm, with hysteresis + cool-downs.  Scale-up
    #: builds a replica through the existing HBM headroom gate;
    #: scale-down always DRAINS (queue migrates, in-flight runs out).
    #: None = static fleet.
    autoscale: Optional[Any] = None
    #: TENANT_FLOOD request shape: each flood submission is
    #: prompt [0] * flood_prompt_len, max_new = flood_new_tokens, so a
    #: flood request costs flood_prompt_len + flood_new_tokens bucket
    #: tokens (predict_fleet's flood_request_tokens).
    flood_prompt_len: int = 4
    flood_new_tokens: int = 4
    #: Disaggregated prefill/decode pools (None = unified fleet,
    #: byte-identical to the defaults): one role per INITIAL replica
    #: index, each "prefill" or "decode", at least one of each.  New
    #: submissions route to prefill-specialist replicas; once a request
    #: emits its first decode token the per-tick rebalance sweep moves
    #: it to a decode-specialist as a LIVE block-table migration
    #: (serve/migrate.py) — prefill capacity is never held hostage by
    #: long decodes, and the autoscaler (when configured) scales each
    #: pool INDEPENDENTLY from its own pool-local signals.
    pool_roles: Optional[Tuple[str, ...]] = None
    #: Operator escape hatch (and the bench A/B toggle): ``False``
    #: restores the pre-migration arcs everywhere — drains run out or
    #: replay, preemptions replay, disaggregated rebalance is inert —
    #: without touching any other knob.
    live_migration: bool = True
    #: Tensor-parallel width of every INITIAL replica: each engine owns
    #: a tp_size-device submesh over the 'model' axis and its weights
    #: carry the registry-declared TP layout (core/sharding.py), so the
    #: fleet's capacity is a replicas × model-shards grid.  1 (default)
    #: is the single-chip fleet, byte-for-byte.
    tp_size: int = 1
    #: Scale-UP headroom: the autoscaler may grow a replica's TP group
    #: up to this width (control.choose_scale_action — occupancy-driven
    #: pressure doubles the group; queue-driven pressure adds replicas).
    #: 0 (default) pins tp_max = tp_size: no scale-up dimension, the
    #: pre-TP autoscaler byte-for-byte.
    tp_max: int = 0

    def __post_init__(self) -> None:
        if self.tp_size < 1:
            raise ValueError(f"tp_size must be >= 1, got {self.tp_size}")
        if self.tp_max and self.tp_max < self.tp_size:
            raise ValueError(
                f"tp_max={self.tp_max} must be 0 (= tp_size) or >= "
                f"tp_size={self.tp_size}")
        if self.num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        if not 0.0 < self.flag_rate_quarantine <= 1.0:
            raise ValueError("flag_rate_quarantine must be in (0, 1]")
        if self.flag_min_count < 1 or self.flag_window < self.flag_min_count:
            raise ValueError("need 1 <= flag_min_count <= flag_window")
        if self.heartbeat_miss_limit < self.heartbeat_miss_degraded:
            raise ValueError("heartbeat_miss_limit must be >= "
                             "heartbeat_miss_degraded")
        if self.max_retries < 0 or self.backoff_base_ticks < 0:
            raise ValueError("max_retries/backoff_base_ticks must be >= 0")
        if self.backoff_mult < 1.0:
            raise ValueError("backoff_mult must be >= 1")
        if not 0.0 < self.suspicion_ewma_alpha <= 1.0:
            raise ValueError("suspicion_ewma_alpha must be in (0, 1]")
        if not 0.0 < self.suspicion_threshold < 1.0:
            raise ValueError("suspicion_threshold must be in (0, 1)")
        if self.suspicion_min_flags < 1:
            raise ValueError("suspicion_min_flags must be >= 1")
        if self.vote_k < 0 or self.vote_outvote_limit < 1:
            raise ValueError("vote_k must be >= 0 and "
                             "vote_outvote_limit >= 1")
        if self.class_queue_limit < 1 or self.drr_quantum_tokens < 1 \
                or self.class_latency_min_count < 1:
            raise ValueError("class_queue_limit, drr_quantum_tokens and "
                             "class_latency_min_count must be >= 1")
        if self.flood_prompt_len < 1 or self.flood_new_tokens < 1:
            raise ValueError("flood_prompt_len and flood_new_tokens "
                             "must be >= 1")
        if self.autoscale is not None and not (
                self.autoscale.min_replicas <= self.num_replicas
                <= self.autoscale.max_replicas):
            raise ValueError(
                f"num_replicas={self.num_replicas} must start inside "
                f"the autoscale bounds [{self.autoscale.min_replicas}, "
                f"{self.autoscale.max_replicas}]")
        if self.pool_roles is not None:
            roles = tuple(self.pool_roles)
            if len(roles) != self.num_replicas:
                raise ValueError(
                    f"pool_roles needs one role per replica: got "
                    f"{len(roles)} for num_replicas={self.num_replicas}")
            bad = sorted(set(roles) - {"prefill", "decode"})
            if bad:
                raise ValueError(f"pool_roles must be 'prefill' or "
                                 f"'decode', got {bad}")
            if not ({"prefill", "decode"} <= set(roles)):
                raise ValueError("pool_roles needs at least one prefill "
                                 "AND one decode replica")


def backoff_ticks(cfg: FleetConfig, attempt: int) -> int:
    """Ticks resubmission number ``attempt`` (1-based) waits:
    ``base * mult**(attempt-1)``, floored at the base."""
    if attempt < 1:
        raise ValueError("attempt is 1-based")
    return int(cfg.backoff_base_ticks * cfg.backoff_mult ** (attempt - 1))


@dataclasses.dataclass
class FleetResult:
    """Terminal record of one fleet request (the canonical stream)."""

    request_id: int                # fleet id
    tokens: List[int]
    status: str                    # see TERMINAL_STATUSES
    replica: Optional[int]         # replica that produced the stream
    attempts: int                  # submissions it took (1 = no fail-over)
    ttft_s: Optional[float]        # FIRST fleet submit -> first token
    flagged: bool = False
    monitor_z: float = 0.0
    tenant: Optional[str] = None   # end-to-end tenant identity
    slo_class: Optional[str] = None  # class it was scheduled under
    adapter: Optional[str] = None  # adapter the stream was served under


@dataclasses.dataclass
class _Attempt:
    replica: int
    gen: int
    local_id: int
    submit_t: float
    span: Optional[int] = None     # fleet.attempt span id
    loser: bool = False            # cancelled as hedge/dedup loser


@dataclasses.dataclass
class _Vote:
    """One in-flight cross-replica verdict vote (one per suspect at a
    time).  ``ballots`` maps voter replica -> replay token_hash
    (None = abstained: the replay failed, was cancelled, or its replica
    crashed); the vote resolves once ``pending`` empties."""

    fid: int
    target: int                    # the suspected replica under audit
    original_hash: str             # the canonical stream's token_hash
    ballots: Dict[int, Optional[str]] = dataclasses.field(
        default_factory=dict)
    pending: Set[int] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _FleetRequest:
    fid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    priority: int
    rng: Any                       # resolved key — EVERY attempt reuses it
    on_token: Optional[Callable[[int, int], None]]
    deadline_at: Optional[float]   # absolute perf_counter deadline
    submit_t: float = 0.0
    live: Dict[int, _Attempt] = dataclasses.field(default_factory=dict)
    closed: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    submissions: int = 0
    retry_due: Optional[int] = None   # tick a pending resubmit is due
    excluded: Set[int] = dataclasses.field(default_factory=set)
    hedged: bool = False
    done: bool = False
    span_root: Optional[int] = None
    tenant: Optional[str] = None
    slo_class: Optional[str] = None
    adapter: Optional[str] = None  # fleet-resolved adapter id (explicit
    #                              # request.adapter, else adapter_map)
    cost: int = 0                  # prompt + max_new (bucket/DRR tokens)


class _Replica:
    """One replica's supervision state (host-only)."""

    def __init__(self, index: int, engine: Any, flag_window: int):
        self.index = index
        self.engine = engine
        self.gen = 0
        self.role = "mixed"         # pool role; "mixed" = unified fleet
        self.tp = 1                 # tensor-parallel group width
        self.state = ReplicaState.HEALTHY
        self.last_progress_tick = 0
        self.stalled_until = -1     # chaos wedge: step() suspended until
        self.warm_until = -1        # RESTARTING exits at this tick
        self.cooloff_until = -1     # QUARANTINED exits at this tick
        self.cooloff_ticks = 0      # current cool-off length (doubles)
        self.drain_deadline = -1
        self.quarantine_pending = False
        self.retire_pending = False  # scale-down drain: retire at empty
        self.reason = ""
        self.flags: Deque[int] = deque(maxlen=flag_window)
        # -- suspicion tier (EWMA over verdicts + explicit boosts) --
        self.suspicion = 0.0
        self.total_flags = 0        # lifetime flags this generation
        self.suspicion_noted = False  # note_suspicion() boost received
        self.suspicion_episode = False  # currently suspected (hysteresis)
        # -- verdict voting --
        self.outvotes = 0
        self.vote_open = False      # one vote in flight per suspect

    def reset_trust_window(self) -> None:
        """Fresh trust evidence for a fresh generation (rebuild /
        readmission probe): the window, the suspicion score and the
        outvote tally all start over — re-conviction must come from new
        behaviour, not stale history."""
        self.flags.clear()
        self.suspicion = 0.0
        self.total_flags = 0
        self.suspicion_noted = False
        self.suspicion_episode = False
        self.outvotes = 0
        self.vote_open = False

    @property
    def journal_key(self) -> str:
        return f"{self.index}:{self.gen}"

    @property
    def flag_count(self) -> int:
        return sum(self.flags)

    @property
    def flag_rate(self) -> float:
        return self.flag_count / len(self.flags) if self.flags else 0.0

    def ladder_tripped(self, cfg: "FleetConfig") -> bool:
        """ONE spelling of the flag-rate trip predicate (shared by the
        supervision pass and the vote tier's ladder-ownership guard)."""
        return (self.flag_count >= cfg.flag_min_count
                and self.flag_rate >= cfg.flag_rate_quarantine)


class ServingFleet:
    """N ``ServingEngine`` replicas behind one ``submit()`` surface with
    replica supervision, fail-over and trust-aware routing (module
    docstring).  ``engine_kwargs`` pass through to every engine build
    (max_slots, max_seq, kv_dtype, paged geometry, ...); ``chaos`` is a
    ``chaos.FaultInjector`` whose REPLICA_* events this loop executes.
    ``engine_factory(replica_index, **kwargs)`` is the test seam — it
    must honour the ``replica_id``/``retire_hook``/``monitor`` kwargs
    the fleet threads through."""

    def __init__(self, params: Any = None, cfg: Any = None, *,
                 fleet_config: Optional[FleetConfig] = None,
                 num_replicas: Optional[int] = None,
                 chaos: Any = None, trace: Any = None, registry: Any = None,
                 spans: Any = None, ledger: Any = None,
                 rng: Optional[jax.Array] = None,
                 engine_factory: Optional[Callable[..., Any]] = None,
                 slo_rules: Any = None,
                 forensics: Any = None,
                 **engine_kwargs: Any):
        self.config = fleet_config or FleetConfig(
            num_replicas=num_replicas or 2)
        if num_replicas is not None:
            self.config = dataclasses.replace(self.config,
                                              num_replicas=num_replicas)
        self.chaos = chaos
        self.trace = trace
        self.spans = spans
        self.ledger = ledger
        # Forensics (obs/forensics.py): quarantines, adapter impounds,
        # preemptions and full-walk migration refusals each assemble an
        # incident; the assembler's VerdictStore (when it has one) gets
        # the durable suspicion/vote/quarantine history rows.
        self.forensics = forensics
        self.verdicts = getattr(forensics, "verdicts", None) \
            if forensics is not None else None
        #: Per-destination refusals of the LAST failed _live_migrate
        #: walk (diagnostics + the migration_refused incident payload).
        self._last_migration_refusals: List[Dict[str, Any]] = []
        self._params = params
        self._cfg = cfg
        self._engine_kwargs = dict(engine_kwargs)
        # Tensor-parallel replica width: FleetConfig.tp_size governs;
        # a tp_size riding engine_kwargs (from_config passes the
        # ServeConfig knob through) seeds it when the fleet config
        # leaves the default.  Per-replica widths can then diverge via
        # scale-UP, so the knob is popped here and threaded per build.
        self._base_tp = max(
            int(self._engine_kwargs.pop("tp_size", 1) or 1),
            self.config.tp_size)
        # Per-replica SLO rules (None + attach_watchers=False = no
        # watchers).  Watchers are built per REPLICA, not per fleet —
        # a breach is a replica-local signal (one slow replica must not
        # shed the whole fleet's admissions) and feeds that replica's
        # ``watcher_bad`` degraded signal.
        self._slo_rules = slo_rules
        self._factory = engine_factory or self._default_factory
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        if registry is None:
            registry = get_registry()
        self.registry = registry
        self._replicas_gauge = registry.gauge(
            "tddl_fleet_replicas", "Replicas per lifecycle state",
            labels=("state",),
        )
        self._failover_counter = registry.counter(
            "tddl_fleet_failovers_total",
            "Requests resubmitted after a replica failure/drain",
        )
        self._hedge_counter = registry.counter(
            "tddl_fleet_hedges_total",
            "Hedged duplicates launched for deadline-pressed requests",
        )
        self._transition_counter = registry.counter(
            "tddl_fleet_transitions_total",
            "Replica lifecycle transitions, by destination state",
            labels=("to_state",),
        )
        # Adversarial tier: the sub-threshold suspicion score per
        # replica (an adversary holding its flag rate under the
        # quarantine threshold still moves THIS gauge), suspicion
        # episodes, and verdict votes by outcome.
        self._suspicion_gauge = registry.gauge(
            "tddl_fleet_suspicion",
            "EWMA suspicion score per replica (sub-threshold tier)",
            labels=("replica",),
        )
        self._suspicion_counter = registry.counter(
            "tddl_fleet_suspicions_total",
            "Suspicion episodes opened (score crossed the threshold)",
        )
        self._vote_counter = registry.counter(
            "tddl_fleet_votes_total",
            "Cross-replica verdict votes resolved, by outcome",
            labels=("outcome",),
        )
        # Fleet-wide occupancy aggregates, refreshed every tick.  The
        # ENGINE serve gauges (tddl_serve_blocks_in_use, ...) carry a
        # ``replica=`` label in fleet mode (the fleet threads
        # replica_id into every engine build), so per-replica
        # occupancy/blocks/tokens are individually readable; THESE
        # aggregates remain the deployment-level sums an autoscaler
        # reads without summing label sets itself.
        self._tif_gauge = registry.gauge(
            "tddl_fleet_tokens_in_flight",
            "Cached tokens backing live sequences, summed over replicas",
        )
        self._queue_gauge = registry.gauge(
            "tddl_fleet_queue_depth",
            "Queued + in-flight requests, summed over live replicas",
        )
        # Control plane (serve/control.py): throttles by tenant, scale
        # events by direction, per-class fleet-queue depth.
        self._throttle_counter = registry.counter(
            "tddl_fleet_tenant_throttled_total",
            "Submissions throttled by the per-tenant token bucket",
            labels=("tenant",),
        )
        self._adapter_throttle_counter = registry.counter(
            "tddl_fleet_adapter_throttled_total",
            "Submissions throttled by the per-adapter token bucket",
            labels=("adapter",),
        )
        self._scale_counter = registry.counter(
            "tddl_fleet_scale_events_total",
            "Autoscaler replica-count changes, by direction",
            labels=("direction",),
        )
        # Live migration tier (serve/migrate.py): in-flight requests
        # moved between replicas as block copies, by the capacity-loss
        # reason that moved them; replicas per pool role when the
        # disaggregated prefill/decode split is on.
        self._migration_counter = registry.counter(
            "tddl_fleet_migrations_total",
            "In-flight requests live-migrated as KV block copies",
            labels=("reason",),
        )
        self._pool_gauge = registry.gauge(
            "tddl_fleet_pool_replicas",
            "In-service replicas per disaggregated pool role",
            labels=("role",),
        )
        self._chips_gauge = registry.gauge(
            "tddl_fleet_chips",
            "Devices occupied: in-service replicas weighted by their "
            "tensor-parallel group width",
        )
        self._classq_gauge = registry.gauge(
            "tddl_fleet_class_queue_depth",
            "Fleet admission-queue depth, by SLO class",
            labels=("slo_class",),
        )
        self.tick = 0
        self._next_fid = 0
        self.rejected = 0
        self._max_seq: Optional[int] = None
        self._max_bucket: Optional[int] = None
        self.requests: Dict[int, _FleetRequest] = {}
        # Fid whose terminal is mid-processing: an adapter conviction
        # fired from inside its own retirement must not usurp it.
        self._terminal_fid: Optional[int] = None
        self.results: Dict[int, FleetResult] = {}
        self._local2fleet: Dict[Tuple[int, int], int] = {}
        self._terminal: Deque[Tuple[int, ServeResult, Optional[dict]]] = \
            deque()
        #: journal key ("replica:gen") -> BlockAllocator — RETAINED
        #: across restarts so records naming a dead generation's blocks
        #: still reconcile (the post-mortem journal, not the live pool).
        #: RETIRED (scaled-in) generations keep theirs the same way.
        self.journals: Dict[str, Any] = {}
        # Drill-facing recovery counters (diffed against predict_fleet).
        self.counters: Dict[str, int] = {
            "crashes": 0, "restarts": 0, "stalls": 0, "poisons": 0,
            "adaptive_poisons": 0, "slowstarts": 0,
            "failover_episodes": 0, "drains": 0,
            "quarantines": 0, "readmissions": 0, "failovers": 0,
            "hedges": 0, "hedge_lost": 0,
            "suspicions": 0, "votes": 0, "outvotes": 0,
            "tenant_floods": 0, "throttles": 0,
            "scale_ups": 0, "scale_downs": 0, "tp_scale_ups": 0,
            "adapter_poisons": 0, "adapter_quarantines": 0,
            "adapter_throttles": 0,
            "preempts": 0, "migrations": 0,
        }
        # Verdict-vote working state: (voter replica, engine-local id)
        # -> the vote its replay ballots into.  Vote replays never enter
        # _local2fleet — they are audits, not fleet requests.
        self._vote_ballots: Dict[Tuple[int, int], _Vote] = {}
        # Deferred drain resubmissions; normally armed inside
        # _supervise, but a vote-triggered drain can queue moves from
        # terminal processing too, so the list outlives one pass.
        self._drain_moves: List[Tuple[int, int, str]] = []
        # -- control plane (serve/control.py; every piece opt-in) --
        from trustworthy_dl_tpu.serve.control import (
            Autoscaler,
            ClassLatencyTracker,
            ClassQueues,
            TenantBuckets,
            class_for_priority,
        )

        self._class_for_priority = class_for_priority
        cfg = self.config
        self._classes = tuple(cfg.slo_classes) if cfg.slo_classes else None
        self._classq = None
        self._class_latency = None
        self._class_stats: Dict[str, Dict[str, int]] = {}
        if self._classes:
            self._classq = ClassQueues(
                self._classes, quantum_tokens=cfg.drr_quantum_tokens,
                per_class_limit=cfg.class_queue_limit)
            self._class_latency = ClassLatencyTracker(
                self._classes, min_count=cfg.class_latency_min_count)
            self._class_stats = {
                c.name: {"completed": 0, "tokens": 0, "shed": 0}
                for c in self._classes}
        self._buckets = (TenantBuckets(cfg.tenant_quota)
                         if cfg.tenant_quota is not None else None)
        # -- adapter trust plane (serve/adapters.py) --
        # The SAME TenantBuckets machinery, keyed by ADAPTER id: QoS
        # follows the artifact being served, not just who asked.
        self._adapter_buckets = (TenantBuckets(cfg.adapter_quota)
                                 if cfg.adapter_quota is not None else None)
        #: Fleet-resolved tenant -> adapter assignments, mirroring the
        #: engines' own map (engine_kwargs["adapter_map"]) so submit()
        #: can police quarantines/quotas BEFORE picking a replica.
        self._adapter_map: Dict[str, str] = dict(
            engine_kwargs.get("adapter_map") or {})
        #: Fleet-wide per-ADAPTER flag-rate windows.  An adapter is one
        #: artifact resident on MANY replicas: its evidence pools
        #: fleet-wide (same window/thresholds as the replica ladder) and
        #: a trip quarantines the ADAPTER everywhere while the replicas
        #: that served it stay HEALTHY — trust follows attribution.
        self._adapter_flags: Dict[str, Deque[int]] = {}
        self.quarantined_adapters: Set[str] = set()
        #: Engine-side slot impounds whose flags were ADAPTER-attributed
        #: (adapter -> [(replica, gen, slot)]).  The engine impounds the
        #: slot at retire time without knowing fleet policy; once the
        #: fleet convicts the ADAPTER the evidence transfers to the
        #: artifact and the slots release — otherwise a poisoned adapter
        #: would exhaust a healthy replica's capacity and drag it down
        #: the drain ladder by attrition.
        self._adapter_impounds: Dict[str, List[Tuple[int, int, int]]] = {}
        self.autoscaler = (Autoscaler(cfg.autoscale)
                           if cfg.autoscale is not None else None)
        # -- disaggregated prefill/decode pools (opt-in) --
        self._roles_active = cfg.pool_roles is not None
        #: role -> Autoscaler: each pool's hysteresis/cool-down state is
        #: its own — a decode-pool scale-up must not eat the prefill
        #: pool's cool-down (and vice versa).  The shared AutoscalerConfig
        #: bounds apply PER POOL when roles are active.
        self._pool_scalers: Dict[str, Any] = {}
        if self._roles_active and cfg.autoscale is not None:
            self._pool_scalers = {
                role: Autoscaler(cfg.autoscale)
                for role in ("prefill", "decode")}
        # Fleet-wide completed-request ITL sketch: the autoscaler's
        # latency signal (per-class sketches serve the shed predicate).
        from trustworthy_dl_tpu.obs.slo import StreamingPercentiles

        self._itl_est = StreamingPercentiles()
        #: (tick, in-service replicas) on every change — the bench's
        #: replica-count trace.  Bounded: a pathological flap cannot
        #: grow host memory without bound.
        self.replica_trace: List[Tuple[int, int]] = []
        self.replicas: List[_Replica] = []
        for i in range(self.config.num_replicas):
            self.replicas.append(self._build_replica(i))
        self._note_replica_trace()
        self._set_state_gauge()

    @classmethod
    def from_config(cls, params: Any, cfg: Any, serve_config: Any,
                    **kwargs: Any) -> "ServingFleet":
        """Build a fleet whose replicas all use a validated
        ``core.config.ServeConfig`` — ONE source of truth for the
        serving knobs, exactly like ``ServingEngine.from_config``
        (``kwargs`` pass through for the fleet surfaces: fleet_config,
        chaos, trace, ledger, ... and any extra engine kwargs)."""
        return cls(
            params, cfg,
            max_slots=serve_config.max_slots,
            max_seq=serve_config.max_seq,
            queue_limit=serve_config.queue_limit,
            kv_dtype=serve_config.kv_dtype,
            weight_dtype=serve_config.weight_dtype,
            paged=serve_config.paged,
            block_size=serve_config.block_size,
            num_blocks=serve_config.num_blocks,
            prefix_cache=serve_config.prefix_cache,
            prefill_chunk=serve_config.prefill_chunk,
            # Speculative decoding inherits across replica RESTARTS too:
            # spec_k rides engine_kwargs, so the cool-off probe's
            # rebuilt engine drafts exactly like the one it replaces.
            spec_k=serve_config.spec_k,
            # Adapter knobs ride engine_kwargs the same way: a replica
            # rebuilt after a crash re-creates its pool with the exact
            # geometry (and deterministic weights) of the one it lost.
            adapter_rank=serve_config.adapter_rank,
            adapter_pool_pages=serve_config.adapter_pool_pages,
            adapter_dtype=serve_config.adapter_dtype,
            # TP width rides engine_kwargs too; the fleet pops it into
            # its per-replica width bookkeeping (scale-UP can diverge
            # individual replicas from this base).
            tp_size=serve_config.tp_size,
            **kwargs,
        )

    # -- replica construction ---------------------------------------------

    def _default_factory(self, index: int, **kwargs: Any) -> Any:
        return ServingEngine(self._params, self._cfg, **kwargs)

    def _tp_devices(self, index: int, tp: int) -> Optional[List[Any]]:
        """Carve replica ``index``'s TP device slice: contiguous groups
        of ``tp`` local devices when the host has enough for disjoint
        slices, else None (the engine defaults to the first ``tp``
        devices — simulation aliasing on small hosts; real deployments
        size the host to replicas × tp chips)."""
        devices = jax.devices()
        lo, hi = index * tp, (index + 1) * tp
        if hi <= len(devices):
            return list(devices[lo:hi])
        return None

    def _engine_build_kwargs(self, index: int,
                             tp: Optional[int] = None) -> Dict[str, Any]:
        kwargs = dict(self._engine_kwargs)
        tp = tp or self._base_tp
        if tp > 1:
            kwargs["tp_size"] = tp
            kwargs["tp_devices"] = self._tp_devices(index, tp)
        kwargs.setdefault("rng", jax.random.fold_in(self._rng, index))
        kwargs["replica_id"] = index
        kwargs["chaos"] = self.chaos
        kwargs["trace"] = self.trace
        kwargs["spans"] = self.spans
        kwargs["registry"] = self.registry
        kwargs["retire_hook"] = \
            lambda result, placement, _i=index: \
            self._terminal.append((_i, result, placement))
        if self.config.attach_watchers or self._slo_rules is not None:
            from trustworthy_dl_tpu.obs.anomaly import AnomalyWatcher
            from trustworthy_dl_tpu.obs.slo import SLOWatcher, \
                default_serve_rules

            # Host-only per-replica watchers (no registry: N replicas
            # would fight over one un-labelled gauge set).
            kwargs.setdefault("slo", SLOWatcher(
                self._slo_rules if self._slo_rules is not None
                else default_serve_rules()))
            kwargs.setdefault("anomaly", AnomalyWatcher())
        return kwargs

    def _build_replica(self, index: int,
                       prev: Optional[_Replica] = None,
                       role: Optional[str] = None,
                       tp: Optional[int] = None) -> _Replica:
        # TP width is sticky like the role: a rebuild/restart keeps the
        # width it had; only an explicit scale-UP changes it.
        if tp is None:
            tp = prev.tp if prev is not None and prev.tp > 1 \
                else self._base_tp
        engine = self._factory(index,
                               **self._engine_build_kwargs(index, tp))
        rep = prev if prev is not None else _Replica(
            index, engine, self.config.flag_window)
        rep.engine = engine
        rep.tp = tp
        # Pool role is a property of the INDEX (initial assignment) or
        # of the scale-up that created the replica — a rebuild/restart
        # keeps the role it had; chaos must not reshuffle the pools.
        if role is not None:
            rep.role = role
        elif prev is None and self._roles_active \
                and index < len(self.config.pool_roles):
            rep.role = self.config.pool_roles[index]
        rep.reset_trust_window()
        # A rebuilt replica must inherit the fleet's standing adapter
        # verdicts: the quarantine is against the ARTIFACT, and a crash
        # restart must not reopen a door the fleet already closed.
        for name in self.quarantined_adapters:
            if hasattr(engine, "quarantine_adapter"):
                engine.quarantine_adapter(name)
        self.journals[rep.journal_key] = self._engine_journal(engine)
        # Geometry limits for submit-time validation, captured ONCE so
        # impossible requests fail in submit() even when every engine is
        # momentarily down mid-chaos (all replicas share one geometry).
        sched = getattr(engine, "scheduler", None)
        if sched is not None and self._max_seq is None:
            self._max_seq = sched.max_seq
            self._max_bucket = max(sched.buckets)
        return rep

    @staticmethod
    def _engine_journal(engine: Any) -> Any:
        sched = getattr(engine, "scheduler", None)
        return getattr(sched, "blocks", None) or \
            getattr(sched, "allocator", None)

    # -- submission --------------------------------------------------------

    def submit(self, request: ServeRequest) -> Optional[int]:
        """Enqueue one request; returns its FLEET id (engine-local ids
        are namespaced per replica and never surface).  Returns None —
        backpressure, exactly like the engine — when every admitting
        replica rejected it (queues full).  A transiently replica-less
        fleet (everything draining/restarting mid-chaos) instead PARKS
        the accepted request and resubmits as capacity returns: an
        accepted request is never silently dropped."""
        now = time.perf_counter()
        # Fail impossible requests HERE, with the engine's own submit
        # semantics — a parked request must never explode inside the
        # tick loop, and the record below must never be registered for
        # a request no replica could ever serve (an orphan would keep
        # ``busy`` True forever).
        prompt_len = len(list(request.prompt))
        if prompt_len == 0:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self._max_seq is not None:
            total = prompt_len + int(request.max_new_tokens)
            if total > self._max_seq:
                raise ValueError(
                    f"prompt+new = {total} exceeds max_seq="
                    f"{self._max_seq}")
            if prompt_len > self._max_bucket:
                raise ValueError(
                    f"prompt of {prompt_len} tokens exceeds the largest "
                    f"prefill bucket {self._max_bucket}")
        cost = prompt_len + int(request.max_new_tokens)
        tenant = request.tenant
        # Resolve the adapter at the FLEET boundary (explicit wins, else
        # the tenant map), mirroring the engine's own resolution, so the
        # quarantine/quota verdicts land before any replica is picked.
        adapter = getattr(request, "adapter", None)
        if adapter is None and tenant is not None:
            adapter = self._adapter_map.get(tenant)
        if adapter is not None and adapter in self.quarantined_adapters:
            # Fleet-wide adapter quarantine: the refusal is loud and
            # replica-independent — every replica would refuse it too.
            logger.warning(
                "fleet: adapter %r is quarantined fleet-wide; "
                "submission for tenant %r refused", adapter, tenant)
            return None
        # Per-tenant token-bucket admission: the flooding tenant's own
        # bucket refuses the submission — loudly — before any fleet
        # state is touched.  Untagged traffic (tenant None) bypasses
        # quota: it is the operator's own.
        if self._buckets is not None and tenant is not None:
            if not self._buckets.try_spend(tenant, cost, self.tick):
                self.counters["throttles"] += 1
                self._throttle_counter.inc(tenant=tenant)
                level = self._buckets.level(tenant, self.tick)
                logger.warning(
                    "fleet: tenant %r throttled (%d tokens, bucket at "
                    "%.1f)", tenant, cost, level)
                if self.trace is not None:
                    self.trace.emit(EventType.TENANT_THROTTLE,
                                    tenant=tenant, tokens=cost,
                                    bucket_level=round(level, 2),
                                    tick=self.tick)
                return None
        # Per-ADAPTER bucket SECOND: a refusal here must hand back the
        # tenant spend above (a throttled submission does no work).
        if self._adapter_buckets is not None and adapter is not None:
            if not self._adapter_buckets.try_spend(adapter, cost,
                                                   self.tick):
                if self._buckets is not None and tenant is not None:
                    self._buckets.refund(tenant, cost, self.tick)
                self.counters["adapter_throttles"] += 1
                self._adapter_throttle_counter.inc(adapter=adapter)
                level = self._adapter_buckets.level(adapter, self.tick)
                logger.warning(
                    "fleet: adapter %r throttled (%d tokens, bucket at "
                    "%.1f)", adapter, cost, level)
                if self.trace is not None:
                    self.trace.emit(EventType.TENANT_THROTTLE,
                                    tenant=tenant, adapter=adapter,
                                    tokens=cost,
                                    bucket_level=round(level, 2),
                                    tick=self.tick)
                return None
        fid = self._next_fid
        self._next_fid += 1
        rng = request.rng
        if rng is None:
            # Resolved ONCE per fleet request: every attempt replays the
            # same key stream, so the stream is replica-independent.
            rng = jax.random.fold_in(self._rng, fid)
        rec = _FleetRequest(
            fid=fid, prompt=list(request.prompt),
            max_new_tokens=int(request.max_new_tokens),
            temperature=float(request.temperature), eos_id=request.eos_id,
            priority=int(request.priority), rng=rng,
            on_token=request.on_token,
            deadline_at=(now + request.deadline_s
                         if request.deadline_s is not None else None),
            submit_t=now,
            tenant=tenant, adapter=adapter, cost=cost,
        )
        if self._classes:
            rec.slo_class = self._class_for_priority(
                self._classes, rec.priority).name
        if self.spans is not None:
            rec.span_root = self.spans.start(
                "fleet.request", kind="serve", request_id=fid,
                prompt_len=len(rec.prompt),
                max_new_tokens=rec.max_new_tokens,
                tenant=tenant, slo_class=rec.slo_class)
        self.requests[fid] = rec
        if self._classq is not None:
            # Class-scheduled admission: the request queues at the
            # FLEET and the deficit-round-robin dispatcher places it —
            # token-weighted fairness across classes, not arrival order.
            if not self._classq.push(rec.slo_class, fid, cost):
                del self.requests[fid]
                self.rejected += 1
                self._refund_bucket(rec)
                if self.spans is not None and rec.span_root is not None:
                    self.spans.end(rec.span_root, status="rejected")
                return None
            return fid
        try:
            outcome = self._try_submit(rec)
        except Exception:
            # Never leave an orphaned record behind an engine-side
            # raise: unwind so ``busy`` reflects only servable work.
            del self.requests[fid]
            self._refund_bucket(rec)
            if self.spans is not None and rec.span_root is not None:
                self.spans.end(rec.span_root, status="error")
            raise
        if outcome == "full":
            # Real backpressure: admitting replicas exist and ALL shed.
            del self.requests[fid]
            self.rejected += 1
            self._refund_bucket(rec)
            if self.spans is not None and rec.span_root is not None:
                self.spans.end(rec.span_root, status="rejected")
            return None
        if outcome == "none_admitting":
            # Transient chaos hole: park; the tick loop resubmits.
            rec.retry_due = self.tick
        return fid

    def _refund_bucket(self, rec: _FleetRequest) -> None:
        """Return a bucket spend for a submission the fleet REJECTED
        after the quota check passed — a rejection does no work, so it
        must not drain the tenant's budget."""
        if self._buckets is not None and rec.tenant is not None:
            self._buckets.refund(rec.tenant, rec.cost, self.tick)
        if self._adapter_buckets is not None and rec.adapter is not None:
            self._adapter_buckets.refund(rec.adapter, rec.cost, self.tick)

    def _pick_replicas(self, rec: _FleetRequest,
                       exclude: Set[int] = frozenset()) -> List[_Replica]:
        """Trust-aware routing order: admitting replicas only (healthy
        before degraded), least-loaded first.  ``exclude`` avoids
        replicas that already failed this request (ignored when it
        would leave no candidates — availability beats affinity; a
        replica already running an attempt of this request is never a
        candidate)."""
        live_on = set(rec.live)
        avoid = set(exclude) | rec.excluded | live_on
        candidates = [r for r in self.replicas
                      if r.state in ADMITTING and r.engine is not None]
        picked = [r for r in candidates if r.index not in avoid]
        if not picked:
            picked = [r for r in candidates if r.index not in live_on]
        # Disaggregated pools: submissions (and resubmissions — every
        # resubmission replays from the prompt) PREFER prefill
        # specialists; decode replicas stay in the order as a fallback
        # because availability beats specialization.
        roles = self._roles_active
        return sorted(picked,
                      key=lambda r: (roles and r.role == "decode",
                                     r.state is not ReplicaState.HEALTHY,
                                     r.engine.load, r.index))

    def _try_submit(self, rec: _FleetRequest,
                    exclude: Set[int] = frozenset()) -> str:
        """Returns ``"submitted"``, ``"full"`` (admitting replicas
        existed but EVERY one's queue shed the request — backpressure)
        or ``"none_admitting"`` (no replica can take work right now)."""
        reps = self._pick_replicas(rec, exclude)
        if not reps:
            return "none_admitting"
        for rep in reps:
            if self._submit_to(rec, rep):
                return "submitted"
        return "full"

    def _submit_to(self, rec: _FleetRequest, rep: _Replica) -> bool:
        now = time.perf_counter()
        deadline_s = None
        if rec.deadline_at is not None:
            deadline_s = max(rec.deadline_at - now, 0.0)
        span = None
        if self.spans is not None:
            span = self.spans.start(
                "fleet.attempt", kind="serve", parent_id=rec.span_root,
                request_id=rec.fid, replica=rep.index,
                attempt=rec.submissions + 1)
        local = rep.engine.submit(ServeRequest(
            prompt=rec.prompt, max_new_tokens=rec.max_new_tokens,
            temperature=rec.temperature, eos_id=rec.eos_id,
            deadline_s=deadline_s, rng=rec.rng,
            on_token=self._token_forwarder(rec, rep.index),
            priority=rec.priority, first_submit_id=rec.fid,
            span_parent=span, tenant=rec.tenant, adapter=rec.adapter,
        ))
        if local is None:
            if span is not None:
                self.spans.end(span, outcome="queue_full")
            return False
        rec.submissions += 1
        rec.retry_due = None
        rec.live[rep.index] = _Attempt(
            replica=rep.index, gen=rep.gen, local_id=local,
            submit_t=now, span=span,
        )
        self._local2fleet[(rep.index, local)] = rec.fid
        return True

    def _token_forwarder(self, rec: _FleetRequest, replica: int
                         ) -> Optional[Callable[[int, int], None]]:
        if rec.on_token is None:
            return None

        def forward(_local_rid: int, token: int) -> None:
            # Primary-attempt streaming: the earliest-submitted live
            # attempt owns the stream (hedges stream only if promoted
            # by the primary's failure) — and nothing streams after the
            # record closed.
            att = rec.live.get(replica)
            if rec.done or att is None or att.loser:
                return
            primary = min(rec.live.values(), key=lambda a: a.submit_t)
            if primary.replica == replica:
                rec.on_token(rec.fid, token)

        return forward

    # -- the fleet tick ----------------------------------------------------

    def step(self) -> int:
        """One fleet tick: chaos → step live replicas → process
        retirements → supervise lifecycles → due retries + hedges.
        Returns tokens emitted across the fleet this tick."""
        self.tick += 1
        self._apply_chaos()
        self._dispatch_classes()
        emitted = 0
        for rep in self.replicas:
            if rep.engine is None or rep.state is ReplicaState.QUARANTINED:
                continue
            if self.tick < rep.stalled_until:
                continue  # chaos wedge: no progress, heartbeat will see
            emitted += rep.engine.step()
            rep.last_progress_tick = self.tick
        self._rebalance_pools()
        self._process_terminals()
        self._supervise()
        self._autoscale()
        self._run_retries_and_hedges()
        self._set_state_gauge()
        # Done records with every attempt settled leave the working set
        # (their FleetResult stays in ``results`` until drained) — the
        # tick loop stays O(live), not O(history).
        for fid in [f for f, r in self.requests.items()
                    if r.done and not r.live]:
            del self.requests[fid]
        return emitted

    def run_until_idle(self, max_ticks: int = 100_000
                       ) -> Dict[int, FleetResult]:
        """Drive ``step()`` until every submitted request is terminal
        AND every verdict-vote ballot has resolved (or ``max_ticks``
        trips — the liveness backstop)."""
        ticks = 0
        while self.busy:
            self.step()
            ticks += 1
            if ticks >= max_ticks:
                raise RuntimeError(
                    f"fleet did not drain in {max_ticks} ticks "
                    f"(states: {[r.state.value for r in self.replicas]})"
                )
        return self.results

    # -- chaos mechanics ---------------------------------------------------

    def _apply_chaos(self) -> None:
        if self.chaos is None or not hasattr(self.chaos, "on_fleet_tick"):
            return
        from trustworthy_dl_tpu.chaos.plan import FaultKind

        for event in self.chaos.on_fleet_tick(self.tick):
            if event.kind is FaultKind.TENANT_FLOOD:
                self.counters["tenant_floods"] += 1
                self._run_flood(event)
                continue
            if event.kind is FaultKind.ADAPTER_POISON:
                # The injector keeps the persistent per-adapter signal
                # overwrite (the adapter id rides the event's ``tenant``
                # field — there is no replica target: a poisoned
                # artifact is everywhere its page is resident).  The
                # per-adapter flag ladder does the catching.
                self.counters["adapter_poisons"] += 1
                continue
            target = event.target
            if not 0 <= target < len(self.replicas):
                logger.warning("chaos: fleet event %s targets unknown "
                               "replica %d", event.kind.value, target)
                continue
            rep = self.replicas[target]
            if event.kind is FaultKind.REPLICA_CRASH:
                self._crash_replica(rep)
            elif event.kind is FaultKind.REPLICA_STALL:
                self.counters["stalls"] += 1
                rep.stalled_until = self.tick + max(int(event.severity), 1)
            elif event.kind is FaultKind.REPLICA_POISON:
                # The injector keeps the persistent signal overwrite;
                # the monitor flag-rate ladder does the rest.
                self.counters["poisons"] += 1
            elif event.kind is FaultKind.REPLICA_ADAPTIVE_POISON:
                # The injector's attached adversary owns the corruption
                # and its strength controller; the suspicion tier +
                # verdict voting do the catching (the flag-rate ladder
                # never trips by the attacker's design).
                self.counters["adaptive_poisons"] += 1
            elif event.kind is FaultKind.REPLICA_SLOWSTART:
                # Warm-up only makes sense for a replica IN service: a
                # quarantined/draining replica must keep its ladder
                # state (a slow-start must never cancel a pending
                # quarantine or skip a cool-off); an already-restarting
                # one just warms longer.
                self.counters["slowstarts"] += 1
                warm = self.tick + max(int(event.severity), 1)
                if rep.state in ADMITTING:
                    rep.warm_until = warm
                    self._transition(rep, ReplicaState.RESTARTING,
                                     "slowstart")
                elif rep.state is ReplicaState.RESTARTING:
                    rep.warm_until = max(rep.warm_until, warm)
                else:
                    logger.warning(
                        "chaos: slowstart on replica %d ignored in "
                        "state %s (ladder state preserved)",
                        rep.index, rep.state.value)
            elif event.kind is FaultKind.REPLICA_PREEMPT:
                self._preempt_replica(rep)

    def _crash_replica(self, rep: _Replica) -> None:
        """Kill the engine outright: every fleet request it held fails
        over (ONE episode), the replica restarts after
        ``restart_ticks``.  The dead generation's allocator journal
        stays in ``self.journals`` — its blocks must keep reconciling.
        A crash must never LAUNDER trust state: a quarantined replica
        stays quarantined (the cool-off probe path rebuilds the engine
        when it fires), and a trust-drain in progress completes as a
        quarantine — dying mid-drain is not an exit from the ladder."""
        if rep.state is ReplicaState.RETIRED:
            # Scaled-in replica: no engine exists to crash — the event
            # is a no-op (and must not resurrect retired capacity).
            logger.warning("chaos: crash on retired replica %d ignored",
                           rep.index)
            return
        self.counters["crashes"] += 1
        if rep.state is ReplicaState.QUARANTINED:
            rep.engine = None   # probe exit rebuilds; cool-off intact
            return
        self.counters["failover_episodes"] += 1
        victims = [(key, fid) for key, fid in self._local2fleet.items()
                   if key[0] == rep.index]
        for (replica, local), fid in victims:
            del self._local2fleet[(replica, local)]
            rec = self.requests[fid]
            att = rec.live.pop(replica, None)
            if att is not None:
                self._close_attempt_span(att, "crashed")
                rec.closed.append({
                    "replica": replica, "gen": att.gen,
                    "journal": f"{replica}:{att.gen}", "outcome": "crashed",
                    "layout": None, "slot": -1, "block_ids": [],
                    "prefix_block_ids": [], "prefix_publishers": {},
                })
            self._schedule_failover(rec, from_replica=rep.index,
                                    reason="crash")
        # Vote ballots the dead engine held abstain (the vote must not
        # wait forever on a replica that no longer exists)...
        for key in [k for k in self._vote_ballots if k[0] == rep.index]:
            vote = self._vote_ballots.pop(key)
            vote.pending.discard(rep.index)
            vote.ballots[rep.index] = None
            if not vote.pending:
                self._resolve_vote(vote)
        # ...and votes TARGETING the dead replica are abandoned: the
        # generation (and the stream under audit) is gone, so a stale
        # verdict must never convict the successor — nor leak a second
        # concurrent vote once the rebuild resets ``vote_open``.
        self._abandon_votes_targeting(rep.index)
        rep.engine = None
        # A crash voids a pending scale-in: the capacity decision is
        # re-made by the autoscaler against post-crash reality, not
        # carried as a stale flag into an unrelated future drain.
        rep.retire_pending = False
        if rep.quarantine_pending:
            # The suspect replica died mid-drain: impound it — the
            # quarantine the flag-rate earned still happens, cool-off
            # ladder intact (no crash-as-quarantine-escape).
            rep.quarantine_pending = False
            rep.cooloff_ticks = max(rep.cooloff_ticks * 2,
                                    self.config.quarantine_cooloff_ticks)
            rep.cooloff_until = self.tick + rep.cooloff_ticks
            self._transition(rep, ReplicaState.QUARANTINED, "crash")
        else:
            rep.warm_until = self.tick + self.config.restart_ticks
            self._transition(rep, ReplicaState.RESTARTING, "crash")

    def _preempt_replica(self, rep: _Replica) -> None:
        """Preemptible capacity loss WITH notice — the serving twin of
        the training-side PREEMPT.  Unlike a crash the fleet gets to
        move the replica's state before the instance disappears: the
        queue re-queues elsewhere (no device state to move) and every
        in-flight request LIVE-migrates as a KV block copy
        (serve/migrate.py); only what cannot move (no capacity, no
        migration surface) falls back to the replay fail-over.  A
        preemption that migrates everything is therefore NOT a failover
        episode and NOT a drain — the capacity leaves, the work does
        not — and the replica warms back through RESTARTING exactly
        like a crash restart (``predict_fleet``: 1 preempt +
        1 restart)."""
        if rep.state is ReplicaState.RETIRED or rep.engine is None:
            logger.warning("chaos: preempt on replica %d ignored "
                           "(no engine)", rep.index)
            return
        self.counters["preempts"] += 1
        if rep.state is ReplicaState.QUARANTINED:
            # Quarantined = already drained empty: nothing to move, and
            # preemption must not launder the cool-off (crash parity).
            rep.engine = None
            self._forensic_incident("replica_preempt", rep=rep,
                                    trigger_type="replica_transition")
            return
        self._migrate(rep, rep.engine.queued_ids,
                      status="migrated", reason="preempt")
        for local in list(rep.engine.inflight_ids):
            fid = self._local2fleet.get((rep.index, local))
            if fid is None or not self._live_migrate(rep, fid, "preempt"):
                self._migrate(rep, [local],
                              status="failover", reason="preempt")
        # Settle the cancels NOW: ballots seated here abstain, and the
        # moved attempts close before the engine is torn down.
        self._process_terminals()
        self._abandon_votes_targeting(rep.index)
        rep.retire_pending = False
        if rep.quarantine_pending:
            # Preempted mid-trust-drain: impound — same
            # no-escape-from-the-ladder rule as a crash.
            rep.quarantine_pending = False
            rep.cooloff_ticks = max(rep.cooloff_ticks * 2,
                                    self.config.quarantine_cooloff_ticks)
            rep.cooloff_until = self.tick + rep.cooloff_ticks
            rep.engine = None
            self._transition(rep, ReplicaState.QUARANTINED, "preempt")
            self._forensic_incident("replica_preempt", rep=rep,
                                    trigger_type="replica_transition")
            return
        rep.engine = None
        rep.warm_until = self.tick + self.config.restart_ticks
        self._transition(rep, ReplicaState.RESTARTING, "preempt")
        # Assembled AFTER the transition so the incident's counters
        # snapshot carries the full episode (preempt + migrations) and
        # its actions include every kv_migration just emitted.
        self._forensic_incident("replica_preempt", rep=rep,
                                trigger_type="replica_transition")

    # -- control plane: floods, class dispatch, autoscaling ----------------

    def _run_flood(self, event: Any) -> None:
        """Execute a TENANT_FLOOD: burst ``severity`` requests from the
        flooding tenant through the NORMAL admission path in one tick —
        the token bucket throttles what the tenant cannot pay for, the
        class queues schedule the rest, and the admitted burst drives
        the autoscaler like any real overload.  Admitted flood requests
        are accepted work: they serve to completion like any other."""
        n = max(int(event.severity), 1)
        tenant = event.tenant or "flood"
        cfgc = self.config
        admitted = 0
        for _ in range(n):
            fid = self.submit(ServeRequest(
                prompt=[0] * cfgc.flood_prompt_len,
                max_new_tokens=cfgc.flood_new_tokens,
                temperature=0.0, tenant=tenant, priority=0,
            ))
            if fid is not None:
                admitted += 1
        logger.warning("fleet: tenant flood from %r — %d/%d admitted at "
                       "tick %d", tenant, admitted, n, self.tick)

    def _classq_alive(self, fid: int) -> bool:
        rec = self.requests.get(fid)
        return rec is not None and not rec.done and not rec.live \
            and rec.retry_due is None

    def _free_engine_queue_slots(self) -> int:
        free = 0
        for rep in self.replicas:
            if rep.state in ADMITTING and rep.engine is not None:
                free += max(int(rep.engine.queue_limit)
                            - len(rep.engine.queued_ids), 0)
        return free

    def _dispatch_classes(self) -> None:
        """One dispatch pass per tick (no-op without SLO classes): shed
        the lowest class first while any class's latency target is
        breached and the backlog exceeds free capacity — replacing the
        raw lowest-priority shed — then release queued requests to the
        engines by token-cost deficit round robin."""
        if self._classq is None:
            return
        free = self._free_engine_queue_slots()
        if (self._class_latency.any_breached()
                and self._classq.depth() > free):
            # At most one shed per tick (pressure is re-evaluated every
            # tick), from the NEWEST entry of the LOWEST class.
            cand = self._classq.shed_candidate(self._classq_alive)
            if cand is not None:
                name, fid = cand
                rec = self.requests.get(fid)
                if rec is not None and not rec.done:
                    self._class_stats[name]["shed"] += 1
                    self._finalize_unserved(rec, "shed_slo")
        batch = self._classq.take(free, self._classq_alive)
        for i, (name, fid, cost) in enumerate(batch):
            rec = self.requests.get(fid)
            if rec is None or rec.done:
                continue
            try:
                outcome = self._try_submit(rec)
            except BaseException:
                # An engine-side RAISE mid-batch must not orphan the
                # already-dequeued tail either: re-queue everything
                # from this entry on (the raising entry keeps its
                # record and stays queued), then let the caller see
                # the error.
                for name2, fid2, cost2 in reversed(batch[i:]):
                    self._classq.push_front(name2, fid2, cost2)
                raise
            if outcome != "submitted":
                # Engine backpressure mid-batch: EVERY not-yet-placed
                # entry goes back (reversed push_front restores order)
                # — dropping the tail would orphan requests with no
                # live attempt, no retry and no queue entry, wedging
                # ``busy`` forever.
                for name2, fid2, cost2 in reversed(batch[i:]):
                    self._classq.push_front(name2, fid2, cost2)
                break

    def _rebalance_pools(self) -> None:
        """Disaggregated-pool sweep (no-op without ``pool_roles``): a
        request that just produced its first decode token on a
        prefill-specialist replica moves to a decode specialist as a
        live block copy — the hand-off the split exists for.  A refusal
        (full decode pool) leaves it decoding where it is; the sweep
        retries next tick, because availability beats specialization."""
        if not self._roles_active:
            return
        moved = 0
        for rep in self.replicas:
            if (rep.role != "prefill" or rep.engine is None
                    or rep.state not in ADMITTING):
                continue
            for local in list(getattr(rep.engine, "decode_ready_ids",
                                      ())):
                fid = self._local2fleet.get((rep.index, local))
                if fid is None:
                    continue  # vote replay: audits never rebalance
                if self._live_migrate(rep, fid, "disagg"):
                    moved += 1
        if moved and self.trace is not None:
            self.trace.emit(
                EventType.POOL_REBALANCE, role="prefill", moved=moved,
                replicas=sum(1 for r in self.replicas
                             if r.role == "decode"
                             and r.engine is not None))

    def _in_service(self) -> List[_Replica]:
        """Replicas that exist as capacity (everything but RETIRED) —
        the count the autoscaler's [min, max] bounds govern."""
        return [r for r in self.replicas
                if r.state is not ReplicaState.RETIRED]

    def _note_replica_trace(self) -> None:
        n = len(self._in_service())
        if len(self.replica_trace) < 4096 and (
                not self.replica_trace
                or self.replica_trace[-1][1] != n):
            self.replica_trace.append((self.tick, n))

    def _autoscale(self) -> None:
        """One control decision per tick (no-op without an autoscaler):
        gather the tick's signals, run the shared pure predicate
        through the hysteresis state, and execute at most one scale
        action."""
        if self.autoscaler is None:
            return
        if self._pool_scalers:
            # Disaggregated pools scale INDEPENDENTLY: each pool reads
            # only its own replicas' signals and holds its own
            # hysteresis/cool-down state, so decode-pool pressure (long
            # generations) grows decode capacity without touching the
            # prefill pool and vice versa.  The [min, max] bounds apply
            # per pool.
            for role in ("prefill", "decode"):
                sig = self._scale_signals(role)
                decision = self._pool_scalers[role].observe(sig)
                if decision > 0:
                    self._scale_up(sig, role=role)
                elif decision < 0:
                    self._scale_down(sig, role=role)
            return
        sig = self._scale_signals(None)
        decision = self.autoscaler.observe(sig)
        if decision > 0:
            self._scale_up(sig)
        elif decision < 0:
            self._scale_down(sig)

    def _scale_signals(self, role: Optional[str]) -> Any:
        """One tick's autoscaler inputs, fleet-wide (``role=None``) or
        restricted to one disaggregated pool."""
        from trustworthy_dl_tpu.serve.control import ScaleSignals, \
            predicted_replicas

        # Capacity-planning view: a replica already draining toward
        # RETIRED is LEAVING — counting it against the [min, max]
        # bounds would let repeated scale-downs (one per cool-down,
        # while a long drain holds the count up) walk the fleet below
        # min_replicas, to zero in the worst case.  Excluding it also
        # lets a scale-up REPLACE leaving capacity under fresh load.
        # QUARANTINED replicas are excluded the same way: they serve
        # nothing for an indefinite cool-off, so counting them would
        # BOTH dilute queue-per-replica (12 requests on the one live
        # engine of a 3-replica fleet reading as 4/replica) AND block
        # scale-ups at the max bound exactly when chaos removed the
        # capacity.  RESTARTING stays counted — it is warming capacity,
        # and forgetting it would re-fire a scale-up every tick of the
        # warmup.
        staying = [r for r in self._in_service()
                   if r.state is not ReplicaState.QUARANTINED
                   and not (r.state is ReplicaState.DRAINING
                            and r.retire_pending)
                   and (role is None or r.role == role)]
        engines = [r.engine for r in staying if r.engine is not None]
        queue = sum(e.load for e in engines)
        if self._classq is not None and role in (None, "prefill"):
            # Class-queued work dispatches to the PREFILL pool when the
            # split is on (routing prefers prefill specialists), so the
            # backlog is that pool's pressure, counted once.
            queue += self._classq.depth()
        occ = 0.0
        pools = [getattr(e, "scheduler", None) for e in engines]
        pools = [s for s in pools if s is not None]
        if pools:
            occ = sum(s.occupancy for s in pools) / len(pools)
        burning = any(
            getattr(e, "slo", None) is not None and e.slo.breached
            for e in engines)
        itl = (self._itl_est.quantile(0.99)
               if self._itl_est.count else None)
        cfg = self.autoscaler.cfg
        # The predictive arm models FLEET-wide demand.  A pool scaler
        # may consume it only when the config DECLARES that pool's
        # demand share (PredictiveArmConfig.role_share) — the shares
        # partition the envelope, so per-pool predictions cannot
        # jointly exceed the fleet-wide ask (the double-provisioning
        # hazard that used to force pool mode to run reactive-only).
        pred = None
        if cfg.predictive is not None:
            if role is None:
                pred = predicted_replicas(cfg.predictive, self.tick)
            elif role in dict(cfg.predictive.role_share or ()):
                pred = predicted_replicas(cfg.predictive, self.tick,
                                          role=role)
        return ScaleSignals(
            tick=self.tick, in_service=len(staying),
            queue_per_replica=queue / max(len(staying), 1),
            occupancy=occ, itl_p99=itl, slo_burning=burning,
            predicted_replicas=pred,
            down_candidates=any(r.state in ADMITTING
                                and r.engine is not None
                                and (role is None or r.role == role)
                                for r in self.replicas),
        )

    def _emit_scale(self, direction: str, frm: int, to: int,
                    reason: str) -> None:
        self.counters[f"scale_{direction}s"] += 1
        self._scale_counter.inc(direction=direction)
        self._note_replica_trace()
        if self.trace is not None:
            self.trace.emit(EventType.FLEET_SCALE, direction=direction,
                            from_replicas=frm, to_replicas=to,
                            reason=reason, tick=self.tick)

    def _scale_up(self, sig: Any, role: Optional[str] = None) -> None:
        """Add capacity: revive a RETIRED index (fresh generation —
        journals retained) or append a new replica.  Either way the
        engine build goes through the existing HBM headroom gate
        (``hbm`` rides engine_kwargs), and the replica warms up through
        RESTARTING like any rebuild — scale-up is never instant
        admission.  ``role`` pins the new capacity to one disaggregated
        pool: the revived/appended replica joins THAT pool (a decode
        scale-up must never come back as a prefill specialist).

        With TP headroom configured (``tp_max > tp_size``) the pure
        shape predicate (control.choose_scale_action) picks scale-OUT
        (another replica of the current width) vs scale-UP (the new
        capacity arrives with a DOUBLED TP group): occupancy pressure
        with a quiet queue means per-replica HBM is the bottleneck and
        a wider shard group buys pool blocks, while queue pressure
        means aggregate service rate is — more engines beat bigger
        ones.  Existing replicas are never rebuilt in place (that would
        kill their in-flight work); the fleet upgrades through churn."""
        from trustworthy_dl_tpu.serve.control import choose_scale_action

        frm = len(self._in_service())
        cfgc = self.config
        cur_tp = max((r.tp for r in self._in_service()
                      if r.engine is not None), default=self._base_tp)
        tp_max = cfgc.tp_max or max(cfgc.tp_size, self._base_tp)
        action = choose_scale_action(self.autoscaler.cfg, sig,
                                     cur_tp, tp_max)
        tp_new = min(cur_tp * 2, tp_max) if action == "up" else None
        rep = next((r for r in self.replicas
                    if r.state is ReplicaState.RETIRED
                    and (role is None or r.role == role)), None)
        if rep is None and role is not None:
            # No retired index from this pool — a retired replica from
            # the OTHER pool is still cheaper than a fresh index (its
            # journal survives); it changes pools on revival.
            rep = next((r for r in self.replicas
                        if r.state is ReplicaState.RETIRED), None)
        if rep is not None:
            rep.gen += 1
            self._build_replica(rep.index, prev=rep, role=role, tp=tp_new)
        else:
            rep = self._build_replica(len(self.replicas), role=role,
                                      tp=tp_new)
            self.replicas.append(rep)
        rep.warm_until = self.tick + cfgc.restart_ticks
        rep.last_progress_tick = self.tick
        self._transition(rep, ReplicaState.RESTARTING, "scale_up")
        if action == "up":
            self.counters["tp_scale_ups"] += 1
        logger.warning("fleet: scale-%s -> replica %d tp=%d "
                       "(queue/replica %.1f, occupancy %.2f)", action,
                       rep.index, rep.tp,
                       sig.queue_per_replica, sig.occupancy)
        self._emit_scale("up", frm, len(self._in_service()), "scale_up")

    def _scale_down(self, sig: Any, role: Optional[str] = None) -> None:
        """Shed capacity WITHOUT shedding work: pick the least-loaded
        admitting replica (ties: newest index), migrate its queue now,
        and let in-flight run out — a scale-down drain never
        force-migrates at the grace deadline and never kills accepted
        requests.  The drain completes into RETIRED: pool released,
        journal retained, index reusable by the next scale-up.
        ``role`` restricts the pick to one disaggregated pool so the
        decode scaler can never drain a prefill specialist."""
        cands = [r for r in self.replicas
                 if r.state in ADMITTING and r.engine is not None
                 and (role is None or r.role == role)]
        if not cands:
            return  # nothing safely removable this tick
        frm = len(self._in_service())
        rep = min(cands, key=lambda r: (r.engine.load, -r.index))
        rep.retire_pending = True
        rep.quarantine_pending = False
        self._transition(rep, ReplicaState.DRAINING, "scale_down")
        self._migrate(rep, rep.engine.queued_ids,
                      status="migrated", reason="scale_down")
        # In-flight moves immediately as live block copies (the retiring
        # pool's capacity frees NOW, not after the longest decode); what
        # cannot move keeps the pre-existing run-out — a scale-in drain
        # still never kills accepted work.
        for local in list(rep.engine.inflight_ids):
            fid = self._local2fleet.get((rep.index, local))
            if fid is not None:
                self._live_migrate(rep, fid, "scale_down")
        logger.warning("fleet: scale-down draining replica %d "
                       "(queue/replica %.1f, occupancy %.2f)",
                       rep.index, sig.queue_per_replica, sig.occupancy)
        self._emit_scale("down", frm, frm - 1, "scale_down")

    # -- terminal processing -----------------------------------------------

    def _process_terminals(self) -> None:
        while self._terminal:
            replica, result, placement = self._terminal.popleft()
            self._on_terminal(replica, result, placement)

    def _attempt_record(self, att: _Attempt, result: ServeResult,
                        placement: Optional[dict], outcome: str
                        ) -> Dict[str, Any]:
        placement = placement or {"layout": None, "slot": -1,
                                  "block_ids": [], "prefix_block_ids": [],
                                  "prefix_publishers": {}}
        return {"replica": att.replica, "gen": att.gen,
                "journal": f"{att.replica}:{att.gen}",
                "local_id": att.local_id, "outcome": outcome,
                **placement}

    def _on_terminal(self, replica: int, result: ServeResult,
                     placement: Optional[dict]) -> None:
        vote = self._vote_ballots.pop((replica, result.request_id), None)
        if vote is not None:
            # A verdict-vote replay, not a fleet request: record the
            # ballot (abstain unless it completed) and resolve once the
            # last voter reports.  Replays never feed the voter's flag
            # window — they are audit traffic, and a poisoned VOTER is
            # caught by its dissent, not by double-scoring.
            self._on_vote_ballot(vote, replica, result)
            return
        fid = self._local2fleet.pop((replica, result.request_id), None)
        if fid is None:
            return  # already accounted (crash bookkeeping ran first)
        rec = self.requests.get(fid)
        if rec is None:
            return
        att = rec.live.pop(replica, None)
        if att is None:
            return
        status = result.status
        if (status in ("completed", "deadline_exceeded")
                and placement is not None):
            # The monitor scored this retirement (it held a slot — a
            # queue-side deadline expiry has placement None and never
            # ran, so feeding it would dilute the flag rate and let a
            # poisoned replica hide behind tight-deadline sheds).
            adapter = getattr(result, "adapter", None)
            if adapter is not None:
                # Adapter-attributed stream: the flag indicts the
                # ARTIFACT, not the replica that hosted it — the verdict
                # pools into the fleet-wide per-adapter window and the
                # replica's own window records a clean retirement (its
                # base-model behaviour is not in evidence here).
                if result.flagged:
                    self._note_adapter_impound(adapter, replica, placement)
                # This observation may CONVICT the adapter, and the
                # conviction sweep fails every open request riding it —
                # but this fid's real result is in hand, mid-flight:
                # mark it so the sweep leaves it to finalize below.
                self._terminal_fid = fid
                try:
                    self._observe_adapter_retirement(adapter,
                                                     result.flagged)
                finally:
                    self._terminal_fid = None
                self.observe_retirement(replica, False)
            else:
                self.observe_retirement(replica, result.flagged)
        if att.loser or (rec.done and status != "hedge_lost"):
            # A dedup loser we cancelled — or the race variant: both
            # attempts completed inside one tick and this one lost.
            status = "hedge_lost"
        self._close_attempt_span(att, status)
        rec.closed.append(self._attempt_record(att, result, placement,
                                               status))
        if status == "hedge_lost":
            self.counters["hedge_lost"] += 1
            self._ledger_loser(rec, att)
            return
        if status == "completed":
            self._finalize(rec, result, att)
            return
        if status == "deadline_exceeded":
            # Absolute deadline: every sibling attempt is as dead.
            self._cancel_siblings(rec, status="hedge_lost")
            self._finalize(rec, result, att)
            return
        if status in ("migrated", "failover"):
            # We cancelled it ourselves to move it; the resubmission is
            # already scheduled by the drain/crash path.
            return
        if status in ("no_capacity", "shed_slo"):
            # Engine-side shed: retry elsewhere while budget remains.
            self._schedule_failover(rec, from_replica=replica,
                                    reason=status)
            return
        # Unknown terminal: finalize loudly rather than lose the request.
        logger.warning("fleet: request %d terminal status %r taken as "
                       "final", fid, status)
        self._finalize(rec, result, att)

    def _cancel_siblings(self, rec: _FleetRequest, status: str) -> None:
        for replica, att in list(rec.live.items()):
            rep = self.replicas[replica]
            att.loser = True
            if rep.engine is not None:
                rep.engine.cancel(att.local_id, status=status)

    def _schedule_failover(self, rec: _FleetRequest, from_replica: int,
                           reason: str) -> None:
        if rec.done or rec.live or rec.retry_due is not None:
            return
        now = time.perf_counter()
        if rec.deadline_at is not None and now > rec.deadline_at:
            self._finalize_unserved(rec, "deadline_exceeded")
            return
        if rec.submissions > self.config.max_retries:
            self._finalize_unserved(rec, "failover_exhausted")
            return
        rec.excluded.add(from_replica)
        rec.retry_due = self.tick + backoff_ticks(self.config,
                                                  max(rec.submissions, 1))
        self.counters["failovers"] += 1
        self._failover_counter.inc()
        if self.trace is not None:
            self.trace.emit(EventType.FLEET_FAILOVER, request_id=rec.fid,
                            from_replica=from_replica, to_replica=None,
                            attempt=rec.submissions + 1, reason=reason,
                            due_tick=rec.retry_due)

    # -- finalization ------------------------------------------------------

    def _finalize(self, rec: _FleetRequest, result: ServeResult,
                  att: _Attempt) -> None:
        if rec.done:
            return
        rec.done = True
        rec.retry_due = None
        self._cancel_siblings(rec, status="hedge_lost")
        ttft = None
        if result.ttft_s is not None:
            ttft = (att.submit_t - rec.submit_t) + result.ttft_s
        self.results[rec.fid] = FleetResult(
            request_id=rec.fid, tokens=list(result.tokens),
            status=result.status, replica=att.replica,
            attempts=rec.submissions, ttft_s=ttft,
            flagged=result.flagged, monitor_z=result.monitor_z,
            tenant=rec.tenant, slo_class=rec.slo_class,
            adapter=rec.adapter,
        )
        if result.status == "completed":
            for dt in result.itl_s:
                self._itl_est.observe(dt)
            if rec.slo_class is not None:
                stats = self._class_stats[rec.slo_class]
                stats["completed"] += 1
                stats["tokens"] += len(result.tokens)
                self._class_latency.observe(rec.slo_class, ttft_s=ttft,
                                            itl_s=result.itl_s)
        self._ledger_canonical(rec, result, att, ttft)
        if self.spans is not None and rec.span_root is not None:
            self.spans.end(rec.span_root, status=result.status,
                           replica=att.replica, attempts=rec.submissions,
                           tokens=len(result.tokens))
        self._maybe_vote(rec, result, att)

    def _finalize_unserved(self, rec: _FleetRequest, status: str) -> None:
        """Terminal without a serving attempt left: deadline ran out
        between attempts, retry budget exhausted, or fleet-wide
        starvation.  NEVER silent: the request gets a result, a ledger
        record and a closed span like every other."""
        if rec.done:
            return
        rec.done = True
        rec.retry_due = None
        # Token-bucket reconciliation: the spend landed ONCE at submit()
        # and rode through every drain→migrate→resubmit hop without a
        # re-charge; a request that dies UNSERVED (deadline between
        # attempts, retry budget, starvation) produced no tokens, so the
        # tenant gets that one spend back — never refunded twice
        # (rec.done guards above) and never refunded for served work.
        self._refund_bucket(rec)
        self._cancel_siblings(rec, status="hedge_lost")
        self.results[rec.fid] = FleetResult(
            request_id=rec.fid, tokens=[], status=status, replica=None,
            attempts=rec.submissions, ttft_s=None,
            tenant=rec.tenant, slo_class=rec.slo_class,
            adapter=rec.adapter,
        )
        if self.ledger is not None:
            self.ledger.append({
                "request_id": rec.fid, "status": status,
                "admitted": bool(rec.closed),
                "replica": None, "attempts": list(rec.closed),
                "flagged": False, "monitor_z": 0.0, "tokens": 0,
                "token_hash": attribution.token_hash([]),
                "ttft_s": None, "submissions": rec.submissions,
                "tenant": rec.tenant, "slo_class": rec.slo_class,
            })
        if self.trace is not None:
            self.trace.emit(EventType.SERVE_RETIRE, request_id=rec.fid,
                            status=status, tokens=0, fleet=True)
        if self.spans is not None and rec.span_root is not None:
            self.spans.end(rec.span_root, status=status,
                           attempts=rec.submissions)

    def _ledger_canonical(self, rec: _FleetRequest, result: ServeResult,
                          att: _Attempt, ttft: Optional[float]) -> None:
        if self.ledger is None:
            return
        winner = rec.closed[-1] if rec.closed else {}
        engine = self.replicas[att.replica].engine
        self.ledger.append({
            "request_id": rec.fid, "status": result.status,
            "admitted": True, "replica": att.replica,
            "journal": f"{att.replica}:{att.gen}",
            "layout": winner.get("layout"), "slot": winner.get("slot", -1),
            "block_ids": list(winner.get("block_ids") or []),
            "prefix_block_ids": list(winner.get("prefix_block_ids") or []),
            "prefix_publishers": dict(winner.get("prefix_publishers") or {}),
            "attempts": list(rec.closed),
            "kv_dtype": getattr(engine, "kv_dtype", None),
            "weight_dtype": getattr(engine, "weight_dtype", None),
            "kv_fallback_reason": getattr(engine, "kv_fallback_reason",
                                          None),
            "flagged": bool(result.flagged),
            "monitor_z": float(result.monitor_z),
            "tokens": len(result.tokens),
            "token_hash": attribution.token_hash(result.tokens),
            "ttft_s": ttft, "submissions": rec.submissions,
            "tenant": rec.tenant, "slo_class": rec.slo_class,
            "adapter": rec.adapter,
            "adapter_page": winner.get("adapter_page", 0),
        })

    def _ledger_loser(self, rec: _FleetRequest, att: _Attempt) -> None:
        if self.ledger is None:
            return
        self.ledger.append({
            "request_id": rec.fid, "status": "hedge_lost",
            "admitted": False, "replica": att.replica,
            "journal": f"{att.replica}:{att.gen}",
            "tokens": 0, "token_hash": attribution.token_hash([]),
        })

    def _close_attempt_span(self, att: _Attempt, outcome: str) -> None:
        if self.spans is not None and att.span is not None:
            self.spans.end(att.span, outcome=outcome)

    # -- supervision -------------------------------------------------------

    def _forensic_incident(self, reason: str, *,
                           rep: Optional[_Replica] = None,
                           adapter: Optional[str] = None,
                           tenant: Optional[str] = None,
                           trigger_type: Optional[str] = None,
                           refusals: Optional[List[Dict[str, Any]]] = None,
                           extra: Optional[Dict[str, Any]] = None) -> None:
        """Assemble one forensic incident for a fleet episode (no-op
        without an attached assembler).  The counters snapshot is taken
        HERE — after every counter the episode bumped — so drill
        assertions can reconcile the incident against
        ``predict_fleet()`` exactly."""
        if self.forensics is None:
            return
        records = list(self.ledger.records()) \
            if self.ledger is not None else []
        # Ledger records land at RETIREMENT — a mid-episode blast
        # radius must also see the requests still in flight (a
        # preemption's migrated streams, a drain's survivors), so open
        # requests contribute a provisional record built from their
        # closed-attempt history.  The journal/block placements in
        # ``rec.closed`` are the same dicts the final ledger record
        # will carry.
        for fid, rec in self.requests.items():
            if not rec.done and rec.closed:
                records.append({"request_id": fid, "admitted": True,
                                "status": "in_flight",
                                "attempts": list(rec.closed),
                                "provisional": True})
        self.forensics.assemble(
            reason, tick=self.tick,
            suspects=[rep.index] if rep is not None else None,
            suspect_journals=[rep.journal_key] if rep is not None else (),
            adapter=adapter, tenant=tenant, trigger_type=trigger_type,
            counters=dict(self.counters),
            records=records,
            refusals=refusals, extra=extra,
        )

    def _transition(self, rep: _Replica, to: ReplicaState,
                    reason: str) -> None:
        if rep.state is to:
            return
        frm = rep.state
        rep.state = to
        rep.reason = reason
        self._transition_counter.inc(to_state=to.value)
        if to is ReplicaState.DRAINING:
            self.counters["drains"] += 1
            rep.drain_deadline = self.tick + self.config.drain_grace_ticks
        elif to is ReplicaState.QUARANTINED:
            self.counters["quarantines"] += 1
        logger.warning("fleet: replica %d %s -> %s (%s)", rep.index,
                       frm.value, to.value, reason)
        if self.trace is not None:
            self.trace.emit(EventType.REPLICA_TRANSITION,
                            replica=rep.index, from_state=frm.value,
                            to_state=to.value, reason=reason,
                            tick=self.tick)
        if to is ReplicaState.QUARANTINED:
            # The quarantine is the flight-dump-grade verdict: durable
            # history row + full forensic incident (trigger = the
            # transition just emitted; blast radius = every request
            # that decoded off this generation's blocks).
            if self.verdicts is not None:
                self.verdicts.append("quarantine", "quarantined",
                                     replica=rep.index, reason=reason,
                                     tick=self.tick)
            self._forensic_incident("replica_quarantine", rep=rep,
                                    trigger_type="replica_transition",
                                    extra={"transition_reason": reason})

    def _migrate(self, rep: _Replica, ids: List[int], status: str,
                 reason: str) -> None:
        """Cancel the given local requests on ``rep`` and schedule their
        resubmission elsewhere (the cancel's retire_hook lands them in
        the terminal queue; the 'migrated'/'failover' status routes them
        back through ``_schedule_failover``)."""
        for local in ids:
            fid = self._local2fleet.get((rep.index, local))
            rep.engine.cancel(local, status=status)
            if fid is None:
                continue
            rec = self.requests.get(fid)
            if rec is not None and not rec.done:
                # The cancel fired the hook synchronously; the terminal
                # record is queued.  Schedule the move NOW so the
                # resubmission carries the drain reason.
                self._drain_moves.append((fid, rep.index, reason))

    def _live_migrate(self, rep: _Replica, fid: int, reason: str) -> bool:
        """Move fleet request ``fid`` off ``rep`` as a LIVE KV
        block-table migration (serve/migrate.py): the destination's
        admission rides the normal allocator path, the fleet re-points
        its attempt table in the commit hook BEFORE the source attempt
        closes, and the source's blocks release — or impound, when the
        source is bound for quarantine — only after that.  Returns False
        (source untouched, caller falls back to the replay path or the
        drain grace window) when no destination can take the copy:
        structural gate failure, full pools, or a mid-prefill request
        with nothing migratable yet."""
        from trustworthy_dl_tpu.serve.migrate import can_migrate, \
            migrate_request

        if not self.config.live_migration:
            return False
        rec = self.requests.get(fid)
        if rec is None or rec.done:
            return False
        att = rec.live.get(rep.index)
        if att is None:
            return False
        cands = [r for r in self.replicas
                 if r.index != rep.index and r.state in ADMITTING
                 and r.engine is not None and r.index not in rec.live]
        if self._roles_active:
            decode = [r for r in cands if r.role == "decode"]
            if decode:
                cands = decode
        cands.sort(key=lambda r: (r.state is not ReplicaState.HEALTHY,
                                  r.engine.load, r.index))
        refusals: List[Dict[str, Any]] = []
        for dst in cands:
            if not can_migrate(rep.engine, dst.engine):
                refusals.append({"replica": dst.index,
                                 "reason": "structural_gate"})
                continue

            def commit(new_local: int, _dst: _Replica = dst) -> None:
                # The destination attempt inherits the SOURCE attempt's
                # submit_t: the fleet's TTFT math must read the stream
                # as one request, not restart the clock mid-flight.
                rec.live[_dst.index] = _Attempt(
                    replica=_dst.index, gen=_dst.gen,
                    local_id=new_local, submit_t=att.submit_t)
                self._local2fleet[(_dst.index, new_local)] = rec.fid

            moved = migrate_request(
                rep.engine, dst.engine, att.local_id,
                quarantine_src=rep.quarantine_pending,
                on_token=self._token_forwarder(rec, dst.index),
                src_journal=f"{rep.index}:{att.gen}",
                on_commit=commit,
                on_refuse=lambda why, _d=dst: refusals.append(
                    {"replica": _d.index, "reason": why}),
            )
            if moved is None:
                continue
            self.counters["migrations"] += 1
            self._migration_counter.inc(reason=reason)
            if self.trace is not None:
                self.trace.emit(EventType.KV_MIGRATION, request_id=fid,
                                from_replica=rep.index,
                                to_replica=dst.index,
                                blocks=moved["blocks"], reason=reason)
            # Settle the source cancel NOW: until its terminal record
            # pops the source attempt from rec.live, both attempts
            # share a submit_t and the streaming tie-break would
            # suppress the destination's next token.
            self._process_terminals()
            return True
        # Full walk refused: every ranked destination either failed the
        # structural gate or refused the claim (or the source had
        # nothing migratable).  The caller falls back to replay; the
        # incident records WHO refused and WHY, per destination.
        self._last_migration_refusals = refusals
        if refusals:
            self._forensic_incident(
                "migration_refused", rep=rep, refusals=refusals,
                trigger_type="replica_transition",
                extra={"request_id": fid, "migrate_reason": reason})
        return False

    def _start_trust_drain(self, rep: _Replica, reason: str) -> None:
        """ONE spelling of the trust-driven drain entry (flag-rate trip
        AND verdict outvote): transition, arm the quarantine, migrate
        the queue now — and move in-flight work IMMEDIATELY as live
        block copies with the source blocks impounded (the suspect's
        bytes leave its pool with the evidence held, instead of the
        suspect serving user tokens for a whole grace window).  What
        cannot move keeps the pre-existing grace-window run-out."""
        self._transition(rep, ReplicaState.DRAINING, reason)
        rep.quarantine_pending = True
        self._migrate(rep, rep.engine.queued_ids,
                      status="migrated", reason="drain")
        for local in list(rep.engine.inflight_ids):
            fid = self._local2fleet.get((rep.index, local))
            if fid is not None:
                self._live_migrate(rep, fid, "drain")

    def _supervise(self) -> None:
        cfg = self.config
        # NOTE: _drain_moves is NOT reset here — a vote-triggered drain
        # queues moves from terminal processing before this pass runs.
        for rep in self.replicas:
            if rep.state is ReplicaState.RETIRED:
                continue  # scaled in: no engine, no signals, no ladder
            if rep.state is ReplicaState.RESTARTING:
                if self.tick >= rep.warm_until:
                    if rep.engine is None:
                        rep.gen += 1
                        self._build_replica(rep.index, prev=rep)
                        self.counters["restarts"] += 1
                    # Fresh heartbeat epoch: the warmup gap must not
                    # read as missed ticks the instant service resumes.
                    rep.last_progress_tick = self.tick
                    self._transition(rep, ReplicaState.HEALTHY,
                                     "warmup_complete")
                continue
            if rep.state is ReplicaState.QUARANTINED:
                if self.tick >= rep.cooloff_until:
                    # Cool-off over: readmission PROBE — the replica
                    # re-enters through RESTARTING and must serve clean;
                    # a still-poisoned replica re-flags and goes back
                    # with a doubled cool-off.
                    self.counters["readmissions"] += 1
                    if self.verdicts is not None:
                        self.verdicts.append(
                            "quarantine", "readmitted",
                            replica=rep.index,
                            reason="readmission_probe", tick=self.tick)
                    # Any vote straggler from the PRE-quarantine
                    # generation dies with the evidence window: the
                    # probe must be judged on fresh behaviour only.
                    self._abandon_votes_targeting(rep.index)
                    rep.reset_trust_window()
                    rep.warm_until = self.tick + cfg.restart_ticks
                    self._transition(rep, ReplicaState.RESTARTING,
                                     "readmission_probe")
                continue
            if rep.engine is None:
                continue
            missed = self.tick - rep.last_progress_tick
            trip = rep.ladder_tripped(cfg)
            watcher_bad = (
                (rep.engine.slo is not None and rep.engine.slo.breached)
                or (rep.engine.anomaly is not None
                    and rep.engine.anomaly.any_active))
            if watcher_bad and rep.state in (ReplicaState.HEALTHY,
                                             ReplicaState.DEGRADED):
                # Anomaly/SLO-watcher episodes feed the suspicion tier
                # too: a replica can be suspected (and vote-audited)
                # without a single monitor flag.
                self.note_suspicion(rep.index, "watcher")
            if rep.state in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
                if trip:
                    self._start_trust_drain(rep, "monitor_flag_rate")
                elif (getattr(rep.engine, "in_service_capacity", None)
                        == 0 and rep.engine.load):
                    # Every slot impounded by per-request monitor
                    # quarantines: the replica cannot serve its queue
                    # and the flag evidence is already decisive at
                    # engine granularity.  Without this a SUB-threshold
                    # attacker (window rate below the ladder trip, but
                    # flags trickling in) starves its replica's queue
                    # forever — the fleet drives engine.step() directly
                    # and never hits the engine's own run_until_idle
                    # starvation shed.
                    self._start_trust_drain(rep,
                                            "slot_quarantine_exhausted")
                elif missed >= cfg.heartbeat_miss_limit:
                    self._transition(rep, ReplicaState.DRAINING,
                                     "heartbeat")
                    rep.quarantine_pending = False
                    self.counters["failover_episodes"] += 1
                    # No progress = nothing to wait for: migrate queue
                    # AND in-flight immediately.  A wedged engine's
                    # pool is still readable, so in-flight state moves
                    # as a live block copy — every accepted token
                    # travels — and only what cannot move replays.
                    self._migrate(rep, rep.engine.queued_ids,
                                  status="migrated", reason="drain")
                    for local in list(rep.engine.inflight_ids):
                        fid = self._local2fleet.get((rep.index, local))
                        if fid is None or not self._live_migrate(
                                rep, fid, "heartbeat"):
                            self._migrate(rep, [local],
                                          status="failover",
                                          reason="heartbeat")
                elif rep.state is ReplicaState.HEALTHY and (
                        rep.flag_count >= 1
                        or missed >= cfg.heartbeat_miss_degraded
                        or watcher_bad):
                    self._transition(rep, ReplicaState.DEGRADED,
                                     "early_warning")
                elif rep.state is ReplicaState.DEGRADED and (
                        rep.flag_count == 0
                        and missed < cfg.heartbeat_miss_degraded
                        and not watcher_bad):
                    self._transition(rep, ReplicaState.HEALTHY,
                                     "recovered")
            if rep.state is ReplicaState.DRAINING:
                # Scale-down drains are exempt from the grace-deadline
                # force-migration — a scale-in drain's in-flight work
                # RUNS OUT where it is, bounded by max_new_tokens.  But
                # that bound assumes the engine keeps TICKING: a
                # replica that stops making progress mid-retire-drain
                # (chaos stall, wedge) would strand its in-flight work
                # forever, so a stalled retire-drain falls back to the
                # force-migration after heartbeat_miss_limit silent
                # ticks — the capacity was leaving anyway, the work
                # must not leave with it.
                stalled_retire = (
                    rep.retire_pending and rep.engine.load
                    and self.tick - rep.last_progress_tick
                    >= cfg.heartbeat_miss_limit)
                if stalled_retire or (
                        not rep.retire_pending and rep.engine.load
                        and self.tick >= rep.drain_deadline):
                    why = ("scale_down_stall" if stalled_retire
                           else "drain_grace")
                    self._migrate(rep, rep.engine.queued_ids,
                                  status="migrated", reason="drain")
                    for local in list(rep.engine.inflight_ids):
                        fid = self._local2fleet.get((rep.index, local))
                        if fid is None or not self._live_migrate(
                                rep, fid, why):
                            self._migrate(rep, [local],
                                          status="failover", reason=why)
                if rep.engine.load == 0:
                    if rep.retire_pending:
                        # Scale-in complete: release the pool, keep the
                        # journal (records naming its blocks must still
                        # reconcile), leave the index reusable.
                        rep.retire_pending = False
                        rep.engine = None
                        self._transition(rep, ReplicaState.RETIRED,
                                         "scale_down_complete")
                        self._note_replica_trace()
                    elif rep.quarantine_pending:
                        rep.quarantine_pending = False
                        rep.cooloff_ticks = max(
                            rep.cooloff_ticks * 2,
                            cfg.quarantine_cooloff_ticks)
                        rep.cooloff_until = self.tick + rep.cooloff_ticks
                        self._transition(rep, ReplicaState.QUARANTINED,
                                         rep.reason)
                    else:
                        rep.warm_until = max(rep.stalled_until,
                                             self.tick + cfg.restart_ticks)
                        self._transition(rep, ReplicaState.RESTARTING,
                                         "drain_complete")
        # Cancel hooks queued terminal records; drain them, then arm the
        # scheduled moves (the terminal handler skips migrated/failover
        # statuses precisely so this path owns their resubmission).
        self._process_terminals()
        for fid, from_replica, reason in self._drain_moves:
            rec = self.requests.get(fid)
            if rec is not None and not rec.done:
                self._schedule_failover(rec, from_replica, reason)
        self._drain_moves = []

    def observe_retirement(self, replica: int, flagged: bool) -> None:
        """Feed one retirement's monitor verdict into the replica's
        flag-rate window AND the EWMA suspicion score (called from the
        terminal processing path).  The post-observation flag rate is
        public (gauges) — it is also what an adaptive adversary steers
        by, so the chaos feedback hook gets exactly the same number."""
        if not 0 <= replica < len(self.replicas):
            return
        rep = self.replicas[replica]
        rep.flags.append(1 if flagged else 0)
        if flagged:
            rep.total_flags += 1
        a = self.config.suspicion_ewma_alpha
        rep.suspicion = (1.0 - a) * rep.suspicion + a * (
            1.0 if flagged else 0.0)
        self._suspicion_gauge.set(rep.suspicion, replica=str(rep.index))
        self._update_suspicion_episode(rep, reason="flag_rate")
        if self.chaos is not None and hasattr(self.chaos,
                                              "on_flag_observed"):
            self.chaos.on_flag_observed(replica, flagged, rep.flag_rate)

    # -- adapter trust plane ----------------------------------------------

    def _observe_adapter_retirement(self, adapter: str,
                                    flagged: bool) -> None:
        """Feed one adapter-attributed retirement's monitor verdict into
        the ADAPTER's fleet-wide flag window.  Same window length and
        trip predicate as the replica ladder (flag_min_count /
        flag_rate_quarantine over flag_window) — but the evidence pools
        across every replica serving the adapter, and the trip
        quarantines the adapter EVERYWHERE in one step."""
        cfg = self.config
        win = self._adapter_flags.get(adapter)
        if win is None:
            win = self._adapter_flags[adapter] = deque(
                maxlen=cfg.flag_window)
        win.append(1 if flagged else 0)
        if adapter in self.quarantined_adapters:
            return  # already impounded; late stragglers add no verdict
        count = sum(win)
        rate = count / len(win)
        if count >= cfg.flag_min_count and rate >= cfg.flag_rate_quarantine:
            self._quarantine_adapter(adapter, "monitor_flag_rate", rate)

    def _note_adapter_impound(self, adapter: str, replica: int,
                              placement: Optional[dict]) -> None:
        """Remember an engine-side slot impound whose flag was
        ADAPTER-attributed.  The engine quarantines the slot at retire
        time (defence in depth — it cannot know fleet policy); once the
        fleet convicts the adapter the evidence belongs to the artifact
        and the slot is released (an already-convicted adapter's
        straggler releases immediately)."""
        slot = (placement or {}).get("slot", -1)
        if slot is None or slot < 0:
            return
        rep = self.replicas[replica]
        if adapter in self.quarantined_adapters:
            self._release_impound(rep, rep.gen, int(slot))
        else:
            self._adapter_impounds.setdefault(adapter, []).append(
                (replica, rep.gen, int(slot)))

    def _release_impound(self, rep: "_Replica", gen: int,
                         slot: int) -> None:
        if (rep.engine is not None and rep.gen == gen
                and slot in rep.engine.quarantined_slots):
            rep.engine.release_quarantine(slot)

    def _quarantine_adapter(self, adapter: str, reason: str,
                            flag_rate: float = 0.0) -> None:
        """Fleet-wide adapter quarantine: refuse new submissions naming
        the adapter, impound its pool page on EVERY replica (in-flight
        requests finish; the page frees at the last release), emit the
        typed event, bump the drill counter.  Replicas stay in service —
        the artifact is the convict, not the host."""
        if adapter in self.quarantined_adapters:
            return
        self.quarantined_adapters.add(adapter)
        self.counters["adapter_quarantines"] += 1
        for rep in self.replicas:
            if rep.engine is not None and hasattr(rep.engine,
                                                  "quarantine_adapter"):
                rep.engine.quarantine_adapter(adapter)
        # Conviction transfers the evidence: the slots the engines
        # impounded for THIS adapter's flags go back in service (the
        # replicas were never the suspects).
        for replica, gen, slot in self._adapter_impounds.pop(adapter, []):
            self._release_impound(self.replicas[replica], gen, slot)
        # The verdict is fleet-wide and permanent until an operator
        # readmits: every open request riding the adapter would sit in
        # an engine queue forever (admission refuses a quarantined
        # page's resolution) or keep streaming through the convicted
        # artifact.  Fail them NOW, loudly, with their own terminal
        # status — the fleet owns the verdict, so the fleet retires
        # them.
        for rec in list(self.requests.values()):
            if (rec.adapter == adapter and not rec.done
                    and rec.fid != self._terminal_fid):
                self._finalize_unserved(rec, "adapter_quarantined")
        logger.warning("fleet: adapter %r QUARANTINED fleet-wide "
                       "(%s, flag rate %.3f)", adapter, reason, flag_rate)
        if self.trace is not None:
            self.trace.emit(EventType.ADAPTER_QUARANTINE, adapter=adapter,
                            reason=reason,
                            flag_rate=round(flag_rate, 4),
                            tick=self.tick)
        if self.verdicts is not None:
            self.verdicts.append("adapter_quarantine", "quarantined",
                                 adapter=adapter, reason=reason,
                                 tick=self.tick)
        # The blast radius is adapter-keyed: every request that decoded
        # through the convicted artifact's page, on any replica.
        self._forensic_incident("adapter_quarantine", adapter=adapter,
                                trigger_type="adapter_quarantine",
                                extra={"flag_rate": round(flag_rate, 4)})

    def release_adapter_quarantine(self, adapter: str) -> None:
        """Operator-driven readmission of a quarantined adapter: clears
        the fleet verdict AND the stale evidence window (re-conviction
        must come from fresh behaviour), and lifts the refusal on every
        live replica."""
        self.quarantined_adapters.discard(adapter)
        self._adapter_flags.pop(adapter, None)
        for rep in self.replicas:
            if rep.engine is not None and hasattr(rep.engine,
                                                  "unquarantine_adapter"):
                rep.engine.unquarantine_adapter(adapter)

    def adapter_flag_rate(self, adapter: str) -> float:
        win = self._adapter_flags.get(adapter)
        return sum(win) / len(win) if win else 0.0

    def note_suspicion(self, replica: int, reason: str,
                       weight: float = 1.0) -> None:
        """Raise a replica's suspicion from a NON-flag signal — an
        anomaly-watcher episode (wired in ``_supervise``) or an
        attribution irregularity a reconciliation job attributes to the
        replica.  Folded into the same EWMA the flag verdicts feed, and
        marks the replica eligible for suspicion without
        ``suspicion_min_flags`` flag evidence."""
        if not 0 <= replica < len(self.replicas):
            return
        rep = self.replicas[replica]
        a = self.config.suspicion_ewma_alpha
        rep.suspicion = min(1.0,
                            (1.0 - a) * rep.suspicion + a * float(weight))
        rep.suspicion_noted = True
        self._suspicion_gauge.set(rep.suspicion, replica=str(rep.index))
        self._update_suspicion_episode(rep, reason=reason)

    def _update_suspicion_episode(self, rep: _Replica,
                                  reason: str) -> None:
        cfg = self.config
        suspected = (rep.suspicion >= cfg.suspicion_threshold
                     and (rep.total_flags >= cfg.suspicion_min_flags
                          or rep.suspicion_noted))
        if suspected and not rep.suspicion_episode:
            rep.suspicion_episode = True
            self.counters["suspicions"] += 1
            self._suspicion_counter.inc()
            logger.warning("fleet: replica %d SUSPECTED (score %.3f, "
                           "flag rate %.3f, %s)", rep.index,
                           rep.suspicion, rep.flag_rate, reason)
            if self.trace is not None:
                self.trace.emit(EventType.FLEET_SUSPICION,
                                replica=rep.index,
                                score=round(rep.suspicion, 4),
                                reason=reason,
                                flag_rate=round(rep.flag_rate, 4),
                                tick=self.tick)
            if self.verdicts is not None:
                self.verdicts.append("suspicion", "opened",
                                     replica=rep.index, reason=reason,
                                     tick=self.tick)
        elif (rep.suspicion_episode
              and rep.suspicion < cfg.suspicion_threshold / 2.0
              and rep.outvotes == 0):
            # Hysteresis: the episode closes only once the score decays
            # well below the threshold, so a borderline replica doesn't
            # open a fresh episode (and counter tick) per retirement.
            # An outvote on record PINS the episode open: a replica a
            # verdict has already gone against must stay under audit
            # until the ladder resolves (or a fresh generation resets
            # it) — otherwise an attacker could take one outvote, go
            # signal-quiet while still corrupting tokens, wait out the
            # EWMA decay, and never face the deciding vote.
            rep.suspicion_episode = False
            if self.verdicts is not None:
                self.verdicts.append("suspicion", "closed",
                                     replica=rep.index, reason=reason,
                                     tick=self.tick)

    # -- cross-replica verdict voting --------------------------------------

    def _maybe_vote(self, rec: _FleetRequest, result: ServeResult,
                    att: _Attempt) -> None:
        """Launch a verdict vote for a completed request that retired on
        a SUSPECTED (but still admitting — i.e. sub-threshold) replica:
        replay it on up to ``vote_k`` other admitting replicas with the
        request's own rng key.  One vote in flight per suspect keeps
        audit cost bounded and drill counts exact."""
        cfg = self.config
        if cfg.vote_k < 1 or result.status != "completed":
            return
        rep = self.replicas[att.replica]
        if (not rep.suspicion_episode or rep.vote_open
                or rep.state not in ADMITTING or rep.engine is None):
            return
        if rep.ladder_tripped(cfg):
            return  # the flag-rate ladder owns it this tick
        voters = sorted(
            (r for r in self.replicas
             if r.index != rep.index and r.state in ADMITTING
             and r.engine is not None),
            key=lambda r: (r.engine.load, r.index),
        )[:cfg.vote_k]
        if not voters:
            return
        accepted: List[Tuple[_Replica, int]] = []
        for voter in voters:
            local = voter.engine.submit(ServeRequest(
                prompt=rec.prompt, max_new_tokens=rec.max_new_tokens,
                temperature=rec.temperature, eos_id=rec.eos_id,
                rng=rec.rng, priority=rec.priority, tenant=rec.tenant,
                # Audit semantics: no user stream, no deadline, and the
                # replay's prompt blocks never enter the PrefixCache.
                publish_prefix=False,
            ))
            if local is not None:
                accepted.append((voter, local))
        if len(accepted) < min(cfg.vote_k, 2):
            # Quorum-or-nothing launch: a vote that cannot seat at
            # least two ballots (one at vote_k=1) could never convict
            # and would punish whoever dissented alone — abandon the
            # partial launch (backpressure) and retry at the suspect's
            # next retirement.
            for voter, local in accepted:
                voter.engine.cancel(local, status="vote_abandoned")
            return
        vote = _Vote(fid=rec.fid, target=rep.index,
                     original_hash=attribution.token_hash(result.tokens))
        for voter, local in accepted:
            vote.pending.add(voter.index)
            self._vote_ballots[(voter.index, local)] = vote
        rep.vote_open = True
        self.counters["votes"] += 1

    def _abandon_votes_targeting(self, index: int) -> None:
        """Drop every outstanding verdict vote whose TARGET generation
        is being torn down (crash rebuild, readmission probe): cancel
        the replay ballots and forget the vote — no counters, no
        outcome.  Without this, ``reset_trust_window`` clearing
        ``vote_open`` would let a fresh generation open a SECOND
        concurrent vote while the stale one still resolves against
        evidence from a pool that no longer exists."""
        stale = [(key, vote) for key, vote in self._vote_ballots.items()
                 if vote.target == index]
        for (voter, local), _vote in stale:
            self._vote_ballots.pop((voter, local), None)
            rep = self.replicas[voter]
            if rep.engine is not None:
                rep.engine.cancel(local, status="vote_abandoned")

    def _on_vote_ballot(self, vote: _Vote, replica: int,
                        result: ServeResult) -> None:
        vote.pending.discard(replica)
        completed = result.status == "completed"
        replay_hash = attribution.token_hash(result.tokens)
        vote.ballots[replica] = replay_hash if completed else None
        if self.ledger is not None:
            # The replay is evidence, not service: admitted False keeps
            # the one-admitted-record-per-fleet-id invariant, and the
            # hash is all the vote retains of the stream.
            self.ledger.append({
                "request_id": vote.fid, "status": "vote_replay",
                "admitted": False, "replica": replica,
                "vote_target": vote.target,
                "tokens": len(result.tokens),
                "token_hash": replay_hash,
            })
        if not vote.pending:
            self._resolve_vote(vote)

    def _resolve_vote(self, vote: _Vote) -> None:
        """Majority-vote the streams token-for-token (by token_hash —
        exact equality, no retained streams).  Outvoted = a dissenting
        hash shared by >= 2 replays that also outnumbers the agreeing
        ballots: a clean original beats any LONE faulty voter by
        construction, and split dissent convicts nobody."""
        cfg = self.config
        rep = self.replicas[vote.target]
        rep.vote_open = False
        counted = {r: h for r, h in vote.ballots.items() if h is not None}
        agree = [r for r, h in counted.items()
                 if h == vote.original_hash]
        dissent_by_hash: Dict[str, List[int]] = {}
        for r, h in counted.items():
            if h != vote.original_hash:
                dissent_by_hash.setdefault(h, []).append(r)
        top_dissent: List[int] = max(dissent_by_hash.values(),
                                     key=len, default=[])
        if len(counted) < 2:
            # Below quorum (abstentions shrank the ballot set): nobody
            # is convicted and nobody is suspected — one surviving
            # voter's word alone is evidence of nothing.
            outcome = "inconclusive"
        elif len(top_dissent) >= 2 and len(top_dissent) > len(agree):
            outcome = "outvoted"
            self.counters["outvotes"] += 1
            rep.outvotes += 1
            self.note_suspicion(vote.target, "outvoted")
            if (rep.outvotes >= cfg.vote_outvote_limit
                    and rep.state in ADMITTING and rep.engine is not None):
                # The suspect lost its Mth vote: same drain → quarantine
                # ladder the flag-rate trip takes — disagreement is the
                # verdict the sub-threshold attacker cannot tune away.
                self._start_trust_drain(rep, "verdict_outvoted")
        else:
            outcome = "confirmed"
            for h, voters in dissent_by_hash.items():
                for voter in voters:
                    # A minority dissenter disagreed with a confirmed
                    # stream: that VOTER is now suspect (symmetric
                    # catch for a lying replay replica).
                    self.note_suspicion(voter, "vote_dissent")
        self._vote_counter.inc(outcome=outcome)
        logger.warning("fleet: verdict vote on request %d (replica %d): "
                       "%s (agree %d, dissent %d)", vote.fid, vote.target,
                       outcome, len(agree), len(top_dissent))
        if self.trace is not None:
            self.trace.emit(EventType.VERDICT_VOTE, request_id=vote.fid,
                            replica=vote.target, outcome=outcome,
                            agree=len(agree), dissent=len(top_dissent),
                            outvotes=rep.outvotes, tick=self.tick)
        if self.verdicts is not None:
            self.verdicts.append("vote", outcome, replica=vote.target,
                                 request_id=vote.fid, tick=self.tick)

    # -- retries + hedges --------------------------------------------------

    def _run_retries_and_hedges(self) -> None:
        now = time.perf_counter()
        for rec in list(self.requests.values()):
            if rec.done:
                continue
            if (rec.deadline_at is not None and now > rec.deadline_at
                    and not rec.live):
                self._finalize_unserved(rec, "deadline_exceeded")
                continue
            if rec.retry_due is not None and self.tick >= rec.retry_due:
                # ONE FLEET_FAILOVER event per failover — emitted by
                # _schedule_failover with the replica the request
                # actually left; the destination rides the new
                # fleet.attempt span.  (A second emit here would double
                # the event-vs-counter reconciliation.)
                self._try_submit(rec, exclude=rec.excluded)
                # On failure: stay parked; deadline/liveness guards
                # bound it.
                continue
            if (self.config.hedge_deadline_s is not None
                    and rec.deadline_at is not None and not rec.hedged
                    and len(rec.live) == 1
                    and len(self.replicas) > 1
                    and rec.deadline_at - now
                    < self.config.hedge_deadline_s):
                primary = next(iter(rec.live.values()))
                if self._try_submit(rec,
                                    exclude={primary.replica}
                                    | rec.excluded) == "submitted":
                    rec.hedged = True
                    self.counters["hedges"] += 1
                    self._hedge_counter.inc()
                    if self.trace is not None:
                        att = max(rec.live.values(),
                                  key=lambda a: a.submit_t)
                        self.trace.emit(EventType.FLEET_HEDGE,
                                        request_id=rec.fid,
                                        replica=att.replica,
                                        primary=primary.replica)
        # Cancels issued while finalizing (hedge losers) queued terminal
        # records — settle them inside the same tick so a pruned record
        # is never looked up by a straggler.
        self._process_terminals()

    # -- reporting ---------------------------------------------------------

    def _set_state_gauge(self) -> None:
        by_state = {s: 0 for s in ReplicaState}
        tif = 0
        load = 0
        for rep in self.replicas:
            by_state[rep.state] += 1
            self._suspicion_gauge.set(rep.suspicion,
                                      replica=str(rep.index))
            if rep.engine is not None:
                load += rep.engine.load
                sched = getattr(rep.engine, "scheduler", None)
                if sched is not None:
                    tif += sched.tokens_in_flight
        for state, n in by_state.items():
            self._replicas_gauge.set(float(n), state=state.value)
        if self._roles_active:
            for role in ("prefill", "decode"):
                n = sum(1 for r in self.replicas if r.role == role
                        and r.state is not ReplicaState.RETIRED)
                self._pool_gauge.set(float(n), role=role)
        self._tif_gauge.set(float(tif))
        self._queue_gauge.set(float(load))
        self._chips_gauge.set(float(self.chips_in_service()))
        if self._classq is not None:
            for name, depth in self._classq.depth_by_class().items():
                self._classq_gauge.set(float(depth), slo_class=name)

    def chips_in_service(self) -> int:
        """Devices the fleet occupies: the replicas × model-shards grid
        summed (each replica counts its TP group width) — the capacity
        dimension a scale-OUT and a scale-UP both grow, each along its
        own axis."""
        return sum(r.tp for r in self._in_service())

    @property
    def open_requests(self) -> int:
        """Accepted-but-unfinished fleet requests (class-queued, live
        or between retries) — the closed-loop driver's in-flight
        count."""
        return sum(1 for r in self.requests.values() if not r.done)

    @property
    def busy(self) -> bool:
        # Outstanding vote ballots keep the loop live: a vote's replays
        # must resolve (and their quarantine verdict land) even after
        # the last user request retired.
        return (any(not r.done for r in self.requests.values())
                or bool(self._vote_ballots))

    def drain_results(self) -> Dict[int, FleetResult]:
        """Return finished results and clear them — the bounded-memory
        retrieval API for long-lived fleet loops (engine parity)."""
        out = self.results
        self.results = {}
        return out

    def states(self) -> Dict[int, str]:
        return {r.index: r.state.value for r in self.replicas}

    def verify_attribution(self) -> Tuple[bool, List[str]]:
        """Reconcile the fleet ledger against every replica
        GENERATION's allocator journal (retained across restarts)."""
        if self.ledger is None:
            raise ValueError("fleet has no attribution ledger attached")
        return attribution.verify_attribution(self.ledger.records(),
                                              self.journals)

    def metrics_summary(self) -> Dict[str, Any]:
        """Fleet rollup: terminal statuses, recovery counters, replica
        states, canonical-stream goodput."""
        statuses: Dict[str, int] = {}
        tokens = 0
        for res in self.results.values():
            statuses[res.status] = statuses.get(res.status, 0) + 1
            if res.status == "completed":
                tokens += len(res.tokens)
        out = {
            "requests": len(self.requests),
            "statuses": statuses,
            "completed_tokens": tokens,
            "replica_states": self.states(),
            "replica_suspicion": {r.index: round(r.suspicion, 4)
                                  for r in self.replicas},
            "ticks": self.tick,
            **{f"fleet_{k}": v for k, v in self.counters.items()},
        }
        slo_active = {
            rep.index: rep.engine.slo.active
            for rep in self.replicas
            if rep.engine is not None
            and getattr(rep.engine, "slo", None) is not None
        }
        if slo_active:
            out["replica_slo_active"] = slo_active
        if self._classes:
            out["per_class"] = {
                c.name: {**self._class_stats[c.name],
                         **self._class_latency.summary(c.name)}
                for c in self._classes
            }
            out["class_queue_depth"] = self._classq.depth_by_class()
        if self.autoscaler is not None:
            out["replicas_in_service"] = len(self._in_service())
            out["replica_trace"] = list(self.replica_trace)
        if self._adapter_flags or self.quarantined_adapters:
            out["adapter_flag_rates"] = {
                name: round(self.adapter_flag_rate(name), 4)
                for name in sorted(self._adapter_flags)}
            out["quarantined_adapters"] = sorted(self.quarantined_adapters)
        return out

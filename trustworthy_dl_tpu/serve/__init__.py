"""Trust-aware TPU-native inference serving (beyond-reference).

The framework trains and batch-samples (models/generate.py) but the ROADMAP
north star — heavy traffic from millions of users — needs a *serving* path:
concurrent requests with heterogeneous prompt/output lengths, admitted and
retired mid-flight without recompiles.  This package is the Orca/vLLM-style
answer, shaped for XLA's static-shape world:

* ``kv_slots``  — KV memory pools: the PAGED block pool (default —
  fixed-size token blocks [L, NB+1, H, BLOCK, Dh] + host-side block
  tables/refcounts + radix prefix cache, vLLM/RadixAttention-style, so
  occupancy is bounded by tokens in flight, not requests) and the legacy
  slotted stripe cache [L, MAX_SLOTS, H, S, Dh]; no dynamic shapes
  anywhere — block tables are traced gather indices.
* ``scheduler`` — continuous (iteration-level) batching: chunked prefill
  interleaved with ONE fused decode step for all active slots (paged),
  or bucketed synchronous prefill (stripe), mid-flight retirement and
  slot/block reuse.
* ``engine``    — request lifecycle (queue → prefill → decode → stream),
  deadlines, backpressure, serving metrics (TTFT / ITL / tokens/s / slot
  occupancy), and trust-aware output monitoring: per-request logit
  entropy / top-1 margin z-scored against a rolling baseline, with
  anomalous generations quarantining the issuing slot — the inference
  mirror of the training-side trust state machine.

* ``fleet``     — the robustness layer (README §Fleet): N engine
  replicas behind one ``submit()`` with a per-replica lifecycle state
  machine (healthy → degraded → draining → quarantined → restarting)
  driven by the obs signals, request fail-over with bounded retries +
  hedged duplicates (dedup-at-retire), trust-aware routing/drain, and
  seeded REPLICA_* chaos drills with ``predict_fleet()``-pinned
  outcomes.
* ``workload``  — seeded traffic generator (bursty arrivals,
  heavy-tailed prompt/output lengths, tenant priority skew) for the
  scenario battery and the ``TDDL_BENCH_FLEET`` sweep.

The int8 quantization tier (``quant/``, ``ServeConfig.kv_dtype`` /
``weight_dtype``) roughly halves KV bytes per slot (per-(head, position)
scaled int8 K/V — ~2x the slot pool at fixed HBM) and the decode weight
stream (weight-only int8); the KV swap is parity-gated at engine
construction with automatic fallback to the model-dtype pool (README
§Serving/Quantization).
"""

from trustworthy_dl_tpu.core.config import ServeConfig
from trustworthy_dl_tpu.serve.control import (
    DEFAULT_SLO_CLASSES,
    AutoscalerConfig,
    PredictiveArmConfig,
    SLOClass,
    TenantQuotaConfig,
)
from trustworthy_dl_tpu.serve.engine import (
    OutputMonitor,
    ServeRequest,
    ServeResult,
    ServingEngine,
)
from trustworthy_dl_tpu.serve.fleet import (
    FleetConfig,
    FleetResult,
    ReplicaState,
    ServingFleet,
    backoff_ticks,
)
from trustworthy_dl_tpu.serve.workload import (
    Tenant,
    WorkloadConfig,
    WorkloadItem,
    drive_closed_loop,
    generate_workload,
    replay_workload,
)
from trustworthy_dl_tpu.serve.kv_slots import (
    BlockAllocator,
    PagedKV,
    PrefixCache,
    SlotAllocator,
    SlotKV,
    init_paged_pool,
    init_slots,
    kv_bytes_per_slot,
    kv_bytes_per_token,
    paged_pool_blocks,
)
from trustworthy_dl_tpu.serve.scheduler import (
    ContinuousBatchingScheduler,
    PagedBatchingScheduler,
    choose_bucket,
    default_buckets,
)

__all__ = [
    "AutoscalerConfig",
    "BlockAllocator",
    "ContinuousBatchingScheduler",
    "DEFAULT_SLO_CLASSES",
    "FleetConfig",
    "FleetResult",
    "OutputMonitor",
    "PagedBatchingScheduler",
    "PagedKV",
    "PredictiveArmConfig",
    "PrefixCache",
    "ReplicaState",
    "SLOClass",
    "ServeConfig",
    "ServeRequest",
    "ServeResult",
    "ServingEngine",
    "ServingFleet",
    "SlotAllocator",
    "SlotKV",
    "Tenant",
    "TenantQuotaConfig",
    "WorkloadConfig",
    "WorkloadItem",
    "backoff_ticks",
    "choose_bucket",
    "default_buckets",
    "drive_closed_loop",
    "generate_workload",
    "init_paged_pool",
    "init_slots",
    "kv_bytes_per_slot",
    "kv_bytes_per_token",
    "paged_pool_blocks",
    "replay_workload",
]

"""Trust-aware TPU-native inference serving (beyond-reference).

The framework trains and batch-samples (models/generate.py) but the ROADMAP
north star — heavy traffic from millions of users — needs a *serving* path:
concurrent requests with heterogeneous prompt/output lengths, admitted and
retired mid-flight without recompiles.  This package is the Orca/vLLM-style
answer, shaped for XLA's static-shape world:

* ``kv_slots``  — slotted KV cache [L, MAX_SLOTS, H, S, Dh] + host-side
  slot allocator (alloc/free/quarantine); no dynamic shapes anywhere.
* ``scheduler`` — continuous (iteration-level) batching: bucketed prefill
  for newly admitted slots, ONE fused decode step for all active slots,
  mid-flight retirement and slot reuse.
* ``engine``    — request lifecycle (queue → prefill → decode → stream),
  deadlines, backpressure, serving metrics (TTFT / ITL / tokens/s / slot
  occupancy), and trust-aware output monitoring: per-request logit
  entropy / top-1 margin z-scored against a rolling baseline, with
  anomalous generations quarantining the issuing slot — the inference
  mirror of the training-side trust state machine.
"""

from trustworthy_dl_tpu.serve.engine import (
    OutputMonitor,
    ServeRequest,
    ServeResult,
    ServingEngine,
)
from trustworthy_dl_tpu.serve.kv_slots import SlotAllocator, SlotKV, init_slots
from trustworthy_dl_tpu.serve.scheduler import (
    ContinuousBatchingScheduler,
    choose_bucket,
    default_buckets,
)

__all__ = [
    "ContinuousBatchingScheduler",
    "OutputMonitor",
    "ServeRequest",
    "ServeResult",
    "ServingEngine",
    "SlotAllocator",
    "SlotKV",
    "choose_bucket",
    "default_buckets",
    "init_slots",
]

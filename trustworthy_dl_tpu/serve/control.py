"""Fleet control plane primitives: autoscaling, tenant quotas, SLO-class
scheduling (ROADMAP item 4's closed loop).

PR 8 built the robustness substrate (replica lifecycle, fail-over,
drain/quarantine) and PR 10 built every signal a control plane needs
(fleet-aggregate gauges, SLO burn rates, HBM headroom) — but nothing
CLOSED the loop: replica count was static, any tenant could starve the
rest, and overload shed by raw priority.  This module is the decision
layer ``serve.fleet.ServingFleet`` wires in (all opt-in via
``FleetConfig``); everything here is host-only, jax-free, and
deterministic in fleet TICKS so drills can pin exact scale/throttle
counts (``FaultPlan.predict_fleet``):

* **SLO classes + deficit-round-robin scheduling** — requests map to a
  small set of :class:`SLOClass`es (per-class TTFT/ITL targets, a
  shed-order priority and a DRR weight).  :class:`ClassQueues` is a
  token-cost deficit-round-robin dequeuer: each round a class earns
  ``quantum * weight`` deficit and releases requests while it can pay
  their token cost (prompt + max_new), so a heavy class cannot starve a
  light one and fairness is measured in TOKENS, not request counts.
  Under a per-class latency breach (:class:`ClassLatencyTracker`) the
  fleet sheds from the LOWEST class first — replacing the raw
  lowest-priority shed.
* **Per-tenant token buckets** — :class:`TenantBuckets` admission:
  a submission spends ``prompt + max_new`` tokens from its tenant's
  bucket (refilled per tick, lazily).  A flooding tenant exhausts its
  own bucket and backpressures ITSELF — loudly (``tenant_throttle``
  events + ``tddl_fleet_tenant_throttled_total{tenant=}``) — while the
  rest of the fleet keeps serving.
* **Autoscaler** — :func:`autoscale_pressure` is the ONE pure decision
  predicate (queue depth per replica, pool occupancy, ITL-p99, SLO
  burn, and the predictive arm's demand estimate); :class:`Autoscaler`
  adds the stateful hysteresis around it: separate up/down thresholds,
  per-direction cool-down ticks, and a sustained-idle streak before any
  scale-down.  Scale-down always DRAINS (the fleet migrates the queue
  and lets in-flight run out) — the controller decides, the fleet's
  existing drain machinery executes, and accepted work is never killed.
* **Predictive arm** — :func:`diurnal_rate` is the SAME envelope
  formula ``serve.workload.generate_workload`` modulates its Poisson
  arrivals with, so :func:`predicted_replicas` can anticipate a seeded
  diurnal burst ``lead_s`` ahead of it instead of reacting a queue
  spike late.  Pure function of the tick — drills stay deterministic.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Callable, Deque, Dict, List, Mapping, Optional, \
    Sequence, Tuple


# --------------------------------------------------------------------------
# SLO classes
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One serving class.  ``priority`` orders shedding (HIGHER sheds
    last — the same convention as ``ServeRequest.priority``, which is
    how requests map to classes); ``weight`` scales the class's
    deficit-round-robin quantum; the latency targets (None = untracked)
    feed :class:`ClassLatencyTracker`'s breach predicate."""

    name: str
    priority: int
    weight: float = 1.0
    ttft_target_s: Optional[float] = None
    itl_target_s: Optional[float] = None

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("SLOClass needs a name")
        if self.weight <= 0.0:
            raise ValueError("SLOClass weight must be > 0")
        for field in ("ttft_target_s", "itl_target_s"):
            val = getattr(self, field)
            if val is not None and val <= 0.0:
                raise ValueError(f"SLOClass {field} must be > 0 or None")


#: Default three-class ladder, matching ``workload.DEFAULT_TENANTS``'s
#: priorities: bulk traffic (no latency contract, sheds first), an
#: interactive tier, and a premium tier that sheds last and earns the
#: largest DRR share.
DEFAULT_SLO_CLASSES: Tuple[SLOClass, ...] = (
    SLOClass("batch", priority=0, weight=1.0),
    SLOClass("standard", priority=1, weight=2.0,
             ttft_target_s=5.0, itl_target_s=0.5),
    SLOClass("premium", priority=2, weight=4.0,
             ttft_target_s=2.0, itl_target_s=0.25),
)


def class_for_priority(classes: Sequence[SLOClass],
                       priority: int) -> SLOClass:
    """Map a request priority onto a class: the highest class whose
    priority does not exceed the request's (so priority 7 traffic rides
    the top class of a 0/1/2 ladder, and anything below the ladder's
    floor rides the floor)."""
    ordered = sorted(classes, key=lambda c: c.priority)
    chosen = ordered[0]
    for cls in ordered:
        if cls.priority <= priority:
            chosen = cls
    return chosen


class ClassQueues:
    """Deficit-round-robin admission queues, one per SLO class.

    DRR in token cost: each round a non-empty class earns
    ``quantum_tokens * weight`` deficit and releases queued requests
    while the head's cost fits; an empty class's deficit resets (the
    classic DRR rule — idle classes bank nothing).  Entries are
    ``(fid, cost)``; stale entries (the fleet finalized the request
    while it queued — deadline expiry, shed) are skipped lazily via the
    ``alive`` predicate, so the fleet never has to search a queue."""

    def __init__(self, classes: Sequence[SLOClass],
                 quantum_tokens: int = 32,
                 per_class_limit: int = 256):
        if quantum_tokens < 1 or per_class_limit < 1:
            raise ValueError(
                "quantum_tokens and per_class_limit must be >= 1")
        # Dequeue order: highest priority first (premium drains ahead
        # of batch inside one round; the deficit weights keep it fair
        # across rounds).
        self._order = [c.name for c in
                       sorted(classes, key=lambda c: -c.priority)]
        self._weight = {c.name: float(c.weight) for c in classes}
        self._shed_order = [c.name for c in
                            sorted(classes, key=lambda c: c.priority)]
        self.quantum = int(quantum_tokens)
        self.limit = int(per_class_limit)
        self._q: Dict[str, Deque[Tuple[int, int]]] = {
            c.name: deque() for c in classes}
        self._deficit: Dict[str, float] = {c.name: 0.0 for c in classes}

    def push(self, name: str, fid: int, cost: int) -> bool:
        """Enqueue; False = that class's queue is full (backpressure —
        the CLASS is full, so a flooding class rejects its own tail)."""
        q = self._q[name]
        if len(q) >= self.limit:
            return False
        q.append((fid, int(cost)))
        return True

    def push_front(self, name: str, fid: int, cost: int) -> None:
        """Return an entry the fleet could not place (engine
        backpressure) to the head of its queue — it keeps its turn."""
        self._q[name].appendleft((fid, int(cost)))

    def _drop_stale(self, q: Deque[Tuple[int, int]],
                    alive: Callable[[int], bool]) -> None:
        while q and not alive(q[0][0]):
            q.popleft()

    def take(self, max_n: int, alive: Callable[[int], bool]
             ) -> List[Tuple[str, int, int]]:
        """Dequeue up to ``max_n`` requests by DRR; returns
        ``(class, fid, cost)`` tuples in release order."""
        out: List[Tuple[str, int, int]] = []
        if max_n <= 0:
            return out
        # Round bound: a head costing C needs at most
        # ceil(C / (quantum * min_weight)) rounds of deficit to clear;
        # request cost is bounded by the serve geometry, so a generous
        # constant keeps this loop provably terminating.
        for _ in range(256):
            if len(out) >= max_n or not any(self._q.values()):
                break
            for name in self._order:
                q = self._q[name]
                self._drop_stale(q, alive)
                if not q:
                    self._deficit[name] = 0.0
                    continue
                self._deficit[name] += self.quantum * self._weight[name]
                while q and len(out) < max_n \
                        and q[0][1] <= self._deficit[name]:
                    fid, cost = q.popleft()
                    if not alive(fid):
                        self._drop_stale(q, alive)
                        continue
                    self._deficit[name] -= cost
                    out.append((name, fid, cost))
                    self._drop_stale(q, alive)
        return out

    def shed_candidate(self, alive: Callable[[int], bool]
                       ) -> Optional[Tuple[str, int]]:
        """The request an over-pressure shed should drop: the NEWEST
        entry of the LOWEST-priority non-empty class — the tail of the
        least-protected class, mirroring the engine's ties-newest
        rule."""
        for name in self._shed_order:
            q = self._q[name]
            while q and not alive(q[-1][0]):
                q.pop()
            if q:
                fid, _cost = q.pop()
                return name, fid
        return None

    def depth(self) -> int:
        return sum(len(q) for q in self._q.values())

    def depth_by_class(self) -> Dict[str, int]:
        return {name: len(q) for name, q in self._q.items()}


class ClassLatencyTracker:
    """Per-class streaming TTFT/ITL percentiles + the breach predicate
    the lowest-class-first shed keys on: a class is BREACHED while its
    p99 exceeds its target (after ``min_count`` observations — one slow
    request is noise, a pattern is a breach).  Built on the same P²
    estimators as the SLO watcher (``obs.slo.StreamingPercentiles``),
    so tracking a million retirements is O(classes), not O(requests)."""

    def __init__(self, classes: Sequence[SLOClass], min_count: int = 8):
        from trustworthy_dl_tpu.obs.slo import StreamingPercentiles

        if min_count < 1:
            raise ValueError("min_count must be >= 1")
        self.min_count = int(min_count)
        self._cls = {c.name: c for c in classes}
        self._ttft = {c.name: StreamingPercentiles() for c in classes}
        self._itl = {c.name: StreamingPercentiles() for c in classes}

    def observe(self, name: str, ttft_s: Optional[float] = None,
                itl_s: Sequence[float] = ()) -> None:
        if name not in self._cls:
            return
        if ttft_s is not None:
            self._ttft[name].observe(float(ttft_s))
        for dt in itl_s:
            self._itl[name].observe(float(dt))

    def _over(self, est, target: Optional[float]) -> bool:
        if target is None or est.count < self.min_count:
            return False
        p99 = est.quantile(0.99)
        return p99 is not None and p99 > target

    def breached(self, name: str) -> bool:
        cls = self._cls[name]
        return (self._over(self._ttft[name], cls.ttft_target_s)
                or self._over(self._itl[name], cls.itl_target_s))

    def any_breached(self) -> bool:
        return any(self.breached(name) for name in self._cls)

    def summary(self, name: str) -> Dict[str, object]:
        cls = self._cls[name]
        out: Dict[str, object] = {"breached": self.breached(name)}
        for label, est, target in (
                ("ttft", self._ttft[name], cls.ttft_target_s),
                ("itl", self._itl[name], cls.itl_target_s)):
            out[f"{label}_count"] = est.count
            out[f"{label}_target_ms"] = (target * 1e3
                                         if target is not None else None)
            p99 = est.quantile(0.99) if est.count else None
            out[f"{label}_p99_ms"] = (float(p99 * 1e3)
                                      if p99 is not None else None)
        return out


# --------------------------------------------------------------------------
# Per-tenant token buckets
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TenantQuotaConfig:
    """Token-bucket admission: a submission costs ``prompt + max_new``
    tokens against its tenant's bucket.  ``capacity_tokens`` is the
    burst allowance, ``refill_per_tick`` the sustained rate (fleet
    TICKS, never wall time — drills must pin throttle counts).
    ``per_tenant`` overrides (capacity, refill) for named tenants —
    production quotas are never one-size-fits-all."""

    capacity_tokens: float
    refill_per_tick: float = 0.0
    per_tenant: Mapping[str, Tuple[float, float]] = \
        dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.capacity_tokens <= 0:
            raise ValueError("capacity_tokens must be > 0")
        if self.refill_per_tick < 0:
            raise ValueError("refill_per_tick must be >= 0")
        for tenant, (cap, refill) in self.per_tenant.items():
            if cap <= 0 or refill < 0:
                raise ValueError(
                    f"per_tenant[{tenant!r}] needs capacity > 0 "
                    "and refill >= 0")

    def limits(self, tenant: str) -> Tuple[float, float]:
        return tuple(self.per_tenant.get(
            tenant, (self.capacity_tokens, self.refill_per_tick)))


class TenantBuckets:
    """Lazily-refilled per-tenant buckets.  A bucket materialises at
    capacity on first sight and refills ``refill_per_tick * elapsed``
    on each touch — O(1) per submission, O(tenants) memory, and exactly
    reproducible from the tick sequence alone."""

    def __init__(self, cfg: TenantQuotaConfig):
        self.cfg = cfg
        #: tenant -> (level, last_refill_tick)
        self._b: Dict[str, Tuple[float, int]] = {}

    def level(self, tenant: str, tick: int) -> float:
        cap, refill = self.cfg.limits(tenant)
        lvl, last = self._b.get(tenant, (cap, tick))
        lvl = min(cap, lvl + refill * max(tick - last, 0))
        self._b[tenant] = (lvl, tick)
        return lvl

    def try_spend(self, tenant: str, tokens: float, tick: int) -> bool:
        lvl = self.level(tenant, tick)
        if lvl < tokens:
            return False
        self._b[tenant] = (lvl - tokens, tick)
        return True

    def refund(self, tenant: str, tokens: float, tick: int) -> None:
        """Return a spend whose submission was subsequently REJECTED
        (class queue full, fleet-wide backpressure): the fleet did no
        work for it, so the tenant's budget must not shrink — rejected
        bursts would otherwise silently throttle the tenant's next
        legitimate requests."""
        cap, _refill = self.cfg.limits(tenant)
        lvl = self.level(tenant, tick)
        self._b[tenant] = (min(cap, lvl + tokens), tick)


# --------------------------------------------------------------------------
# Autoscaler
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PredictiveArmConfig:
    """The predictive arm's knowledge of the diurnal envelope — the
    SAME three numbers ``serve.workload.WorkloadConfig`` modulates its
    Poisson arrivals with — plus the deployment's service capacity and
    how far ahead to look.  ``tick_duration_s`` maps fleet ticks onto
    the workload's clock (drills pin it; production estimates it)."""

    mean_rps: float
    burstiness: float
    burst_period_s: float
    per_replica_rps: float
    lead_s: float = 0.0
    tick_duration_s: float = 0.05
    #: Per-role demand envelopes for the disaggregated prefill/decode
    #: split: role -> the FRACTION of fleet-wide demand that pool
    #: serves (e.g. ``{"prefill": 0.4, "decode": 0.6}``).  None keeps
    #: the predictive arm fleet-wide only (pool scalers run reactive) —
    #: the pre-split behaviour.  Shares must sum to <= 1.0: the roles
    #: PARTITION the demand, which is exactly what makes per-pool
    #: prediction safe from double-provisioning.
    role_share: Optional[Mapping[str, float]] = None

    def __post_init__(self) -> None:
        if not 0.0 <= self.burstiness < 1.0:
            raise ValueError("burstiness must be in [0, 1)")
        if self.mean_rps <= 0 or self.burst_period_s <= 0 \
                or self.per_replica_rps <= 0:
            raise ValueError("mean_rps, burst_period_s and "
                             "per_replica_rps must be > 0")
        if self.lead_s < 0 or self.tick_duration_s <= 0:
            raise ValueError("lead_s must be >= 0 and tick_duration_s "
                             "> 0")
        if self.role_share is not None:
            shares = dict(self.role_share)
            if not shares or any(not 0.0 < s <= 1.0
                                 for s in shares.values()):
                raise ValueError("role_share fractions must be in (0, 1]")
            if sum(shares.values()) > 1.0 + 1e-9:
                raise ValueError(
                    "role_share fractions must sum to <= 1.0 (the roles "
                    f"partition fleet demand), got {shares}")
            # Freeze for hashability of the frozen dataclass.
            object.__setattr__(self, "role_share",
                               tuple(sorted(shares.items())))


def diurnal_rate(mean_rps: float, burstiness: float,
                 burst_period_s: float, t_s: float) -> float:
    """The workload generator's arrival-rate envelope at time ``t_s``
    (one spelling — ``generate_workload`` modulates with exactly this,
    so anticipating it is anticipating the seeded traffic)."""
    rate = mean_rps * (1.0 + burstiness * math.sin(
        2.0 * math.pi * t_s / burst_period_s))
    return max(rate, mean_rps * (1.0 - burstiness), 1e-6)


def predicted_replicas(cfg: PredictiveArmConfig, tick: int,
                       role: Optional[str] = None) -> int:
    """Replicas the diurnal envelope will demand ``lead_s`` from now:
    the predictive arm's scale-ahead estimate, a pure function of the
    tick (deterministic drills).

    ``role`` asks for ONE disaggregated pool's slice of that demand:
    the fleet-wide rate is scaled by the pool's declared
    ``role_share`` fraction before dividing by per-replica capacity.
    Because the shares partition the demand (they sum to <= 1), the
    pools' predictions can never jointly exceed what the fleet-wide
    arm would have asked for — the double-provisioning hazard that
    used to force pool-mode scalers to run reactive-only.  Returns
    a role estimate only when the config declares a share for it;
    asking for an undeclared role raises (a silently-fleet-wide
    number would quietly double-provision)."""
    t_s = tick * cfg.tick_duration_s + cfg.lead_s
    rate = diurnal_rate(cfg.mean_rps, cfg.burstiness,
                        cfg.burst_period_s, t_s)
    if role is not None:
        shares = dict(cfg.role_share or ())
        if role not in shares:
            raise ValueError(
                f"predictive role_share declares no share for role "
                f"{role!r} (declared: {sorted(shares)})")
        rate *= shares[role]
    return max(int(math.ceil(rate / cfg.per_replica_rps)), 1)


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Scale bounds + the hysteresis band.  The up thresholds must sit
    strictly above the down thresholds (the band IS the hysteresis —
    without it a fleet at the boundary flaps every tick), the
    per-direction cool-downs bound action frequency, and a scale-down
    additionally requires ``scale_down_idle_ticks`` CONSECUTIVE
    low-pressure ticks — one quiet tick between bursts must not shed
    capacity the next burst needs."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_queue_per_replica: float = 4.0
    scale_up_occupancy: float = 0.85
    scale_down_queue_per_replica: float = 0.5
    scale_down_occupancy: float = 0.30
    itl_p99_target_s: Optional[float] = None
    scale_up_cooldown_ticks: int = 16
    scale_down_cooldown_ticks: int = 32
    scale_down_idle_ticks: int = 16
    predictive: Optional[PredictiveArmConfig] = None

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.scale_down_queue_per_replica >= \
                self.scale_up_queue_per_replica:
            raise ValueError(
                "scale_down_queue_per_replica must be < "
                "scale_up_queue_per_replica (the gap is the hysteresis)")
        if self.scale_down_occupancy >= self.scale_up_occupancy:
            raise ValueError(
                "scale_down_occupancy must be < scale_up_occupancy "
                "(the gap is the hysteresis)")
        if self.itl_p99_target_s is not None \
                and self.itl_p99_target_s <= 0:
            raise ValueError("itl_p99_target_s must be > 0 or None")
        if min(self.scale_up_cooldown_ticks,
               self.scale_down_cooldown_ticks,
               self.scale_down_idle_ticks) < 1:
            raise ValueError("cooldown/idle tick counts must be >= 1")


@dataclasses.dataclass(frozen=True)
class ScaleSignals:
    """One tick's control inputs, as the fleet gathers them: queue
    depth per in-service replica (class queues + engine queues),
    KV-pool occupancy, the fleet-wide ITL p99, whether any replica's
    SLO watcher is burning budget, and the predictive arm's demand
    estimate (None = reactive only)."""

    tick: int
    in_service: int
    queue_per_replica: float
    occupancy: float
    itl_p99: Optional[float] = None
    slo_burning: bool = False
    predicted_replicas: Optional[int] = None
    #: False while no replica can safely be drained (everything mid-
    #: chaos: draining/restarting/quarantined) — a down DECISION must
    #: not be consumed (cool-down armed, streak reset) by a no-op.
    down_candidates: bool = True


def autoscale_pressure(cfg: AutoscalerConfig, sig: ScaleSignals) -> int:
    """The ONE pure decision predicate: +1 (demand exceeds capacity),
    -1 (capacity comfortably exceeds demand), 0 (inside the hysteresis
    band).  Stateless — cool-downs, idle streaks and the replica bounds
    live in :class:`Autoscaler`; sharing this function is what lets a
    drill replay recorded signals and pin the controller exactly."""
    up = (sig.queue_per_replica >= cfg.scale_up_queue_per_replica
          or sig.occupancy >= cfg.scale_up_occupancy
          or (cfg.itl_p99_target_s is not None
              and sig.itl_p99 is not None
              and sig.itl_p99 > cfg.itl_p99_target_s)
          or sig.slo_burning
          or (sig.predicted_replicas is not None
              and sig.predicted_replicas > sig.in_service))
    if up:
        return 1
    down = (sig.queue_per_replica <= cfg.scale_down_queue_per_replica
            and sig.occupancy <= cfg.scale_down_occupancy
            and not sig.slo_burning
            and (sig.predicted_replicas is None
                 or sig.predicted_replicas < sig.in_service))
    return -1 if down else 0


def choose_scale_action(cfg: AutoscalerConfig, sig: ScaleSignals,
                        tp_size: int, tp_max: int) -> str:
    """Scale-OUT vs scale-UP: once the autoscaler has decided to add
    capacity, choose its SHAPE.  Pure, like :func:`autoscale_pressure`,
    and sharing its thresholds so the two predicates cannot drift.

    * ``"up"`` — grow the model-shard dimension: the next replica is
      built with a DOUBLED tensor-parallel group (bounded by
      ``tp_max``).  Chosen when the pressure is occupancy-driven while
      the queue stays quiet: each replica's KV pool is the bottleneck,
      and a larger TP group shards the per-token KV bytes across more
      chips, so the same per-device HBM budget holds proportionally
      more blocks (the headroom gate sizes per SHARD —
      serve/engine.py).
    * ``"out"`` — add another replica of the current shape.  Chosen
      for queue-driven pressure (demand exceeds aggregate service
      rate: more independent engines beat bigger ones) and whenever
      the TP dimension is already at ``tp_max``.
    """
    if (tp_size < tp_max
            and sig.occupancy >= cfg.scale_up_occupancy
            and sig.queue_per_replica < cfg.scale_up_queue_per_replica):
        return "up"
    return "out"


class Autoscaler:
    """Stateful hysteresis around :func:`autoscale_pressure`: one
    decision per ``observe`` (the fleet calls it once per tick), bounded
    by [min, max] replicas, per-direction cool-downs, and the sustained
    low-pressure streak a scale-down requires."""

    def __init__(self, cfg: AutoscalerConfig):
        self.cfg = cfg
        self._last_up = -(10 ** 9)
        self._last_down = -(10 ** 9)
        self._low_streak = 0
        self.decisions = {"up": 0, "down": 0}

    def observe(self, sig: ScaleSignals) -> int:
        """Returns +1 (scale up now), -1 (scale down now) or 0."""
        cfg = self.cfg
        pressure = autoscale_pressure(cfg, sig)
        if pressure > 0:
            self._low_streak = 0
            if (sig.in_service < cfg.max_replicas
                    and sig.tick - self._last_up
                    >= cfg.scale_up_cooldown_ticks):
                self._last_up = sig.tick
                self.decisions["up"] += 1
                return 1
            return 0
        if pressure < 0:
            self._low_streak += 1
            if (sig.down_candidates
                    and sig.in_service > cfg.min_replicas
                    and self._low_streak >= cfg.scale_down_idle_ticks
                    and sig.tick - self._last_down
                    >= cfg.scale_down_cooldown_ticks):
                self._last_down = sig.tick
                self._low_streak = 0
                self.decisions["down"] += 1
                return -1
            return 0
        self._low_streak = 0
        return 0

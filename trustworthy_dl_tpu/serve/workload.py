"""Seeded serving-workload generator: what "heavy traffic from millions
of users" actually looks like, reduced to its three awkward properties —

* **bursty arrivals**: a Poisson process whose rate is modulated by a
  sinusoid (the diurnal/burst envelope), so offered load swings between
  ``mean * (1 - burstiness)`` and ``mean * (1 + burstiness)`` instead of
  arriving politely uniform;
* **heavy-tailed lengths**: prompt and output lengths drawn lognormal
  (median + sigma), clipped to the engine's geometry — most requests are
  short, a few drag whole blocks of KV for a long time (exactly the mix
  that separates token-bounded from request-bounded admission);
* **tenant skew**: tenants drawn by weight (Zipf-ish when you pass such
  weights), each with its own priority class — what SLO-breach shedding
  and the fleet router's priority handling are actually for.

Everything is driven by one ``numpy`` generator seeded from the config,
so a workload is reproducible from its config alone (the same contract
as ``chaos.FaultPlan``): drills and the ``TDDL_BENCH_FLEET`` sweep
replay identical traffic on every arm.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One traffic class: relative arrival weight + priority (higher
    survives shedding longer) + optional per-request deadline."""

    name: str
    weight: float = 1.0
    priority: int = 0
    deadline_s: Optional[float] = None


#: Default three-class mix: a dominant bulk tenant, a latency-sensitive
#: interactive tenant, and a trickle of high-priority traffic.
DEFAULT_TENANTS = (
    Tenant("bulk", weight=6.0, priority=0),
    Tenant("interactive", weight=3.0, priority=1, deadline_s=30.0),
    Tenant("premium", weight=1.0, priority=2, deadline_s=30.0),
)


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    seed: int = 0
    num_requests: int = 64
    mean_rps: float = 16.0          # long-run offered rate
    burstiness: float = 0.6         # rate swing fraction, in [0, 1)
    burst_period_s: float = 2.0     # one burst cycle
    prompt_median: int = 12         # lognormal median prompt length
    prompt_sigma: float = 0.6       # lognormal sigma (tail heaviness)
    output_median: int = 8
    output_sigma: float = 0.7
    min_prompt: int = 2
    min_output: int = 1
    #: Hard cap on max_new_tokens (None = max_seq // 2) — the CLI pins
    #: this to --max-new-tokens so the heavy tail cannot exceed the
    #: operator's stated per-request budget.
    max_output: Optional[int] = None
    tenants: Sequence[Tenant] = DEFAULT_TENANTS
    #: Per-tenant adapter fleet (0 = base model only): tenants are
    #: assigned to ``adapter-<k>`` ids Zipf-style — a few hot adapters
    #: serve most tenants, a long tail serves one each.  This is the
    #: population shape that makes an adapter POOL interesting: pool
    #: pages << adapters forces real eviction traffic, while the hot
    #: head keeps the hit rate meaningful.  Assignment is part of the
    #: seeded workload contract (same config -> same tenant->adapter
    #: map), so A/B bench arms replay identical adapter churn.
    num_adapters: int = 0
    adapter_zipf: float = 1.1       # Zipf exponent over adapter ranks
    #: Bimodal prompt mixture (0 = off, the lognormal above unchanged):
    #: with this probability a request's prompt is drawn from a SECOND
    #: lognormal mode at ``prompt_long_median`` — the RAG/summarise mix
    #: (short chat prompts + occasional huge contexts) whose prefill
    #: cost variance is what disaggregated prefill/decode pools exist
    #: to absorb.  Off means zero extra RNG draws, so every pre-existing
    #: workload config replays a byte-identical schedule.
    prompt_bimodal_frac: float = 0.0
    prompt_long_median: int = 0

    def __post_init__(self) -> None:
        if not 0.0 <= self.burstiness < 1.0:
            raise ValueError("burstiness must be in [0, 1)")
        if self.mean_rps <= 0 or self.burst_period_s <= 0:
            raise ValueError("mean_rps and burst_period_s must be > 0")
        if not self.tenants:
            raise ValueError("need at least one tenant")
        if self.num_adapters < 0:
            raise ValueError("num_adapters must be >= 0")
        if self.adapter_zipf <= 1.0:
            raise ValueError("adapter_zipf must be > 1 (Zipf exponent)")
        if not 0.0 <= self.prompt_bimodal_frac <= 1.0:
            raise ValueError("prompt_bimodal_frac must be in [0, 1]")
        if self.prompt_bimodal_frac > 0.0 and self.prompt_long_median < 1:
            raise ValueError("prompt_long_median must be >= 1 when the "
                             "bimodal mix is on")


@dataclasses.dataclass(frozen=True)
class WorkloadItem:
    """One arrival: submit at ``t_arrive`` (seconds from workload
    start) as tenant ``tenant`` with the given shape."""

    t_arrive: float
    prompt: Tuple[int, ...]
    max_new_tokens: int
    priority: int
    tenant: str
    deadline_s: Optional[float]
    adapter: Optional[str] = None  # tenant's assigned adapter (None = base)


def _lognormal_len(rng: np.random.Generator, median: int, sigma: float,
                   lo: int, hi: int) -> int:
    val = int(round(float(rng.lognormal(math.log(max(median, 1)), sigma))))
    return int(np.clip(val, lo, hi))


def zipf_adapter_assignments(tenant_names: Sequence[str],
                             num_adapters: int,
                             exponent: float = 1.1,
                             seed: int = 0) -> dict:
    """Seeded Zipf tenant -> adapter map: adapter ``adapter-<k>`` gets
    probability ``∝ 1/(k+1)^exponent``, so a hot head of adapters serves
    most tenants while the tail serves one each — the population shape
    that exercises an adapter pool's LRU (pages << adapters) without
    killing its hit rate.  The draw stream is its OWN generator (seeded
    off ``seed``), so adding adapters to a workload config never
    perturbs the arrival/length draws of the base traffic — the
    adapter-off and adapter-on bench arms replay IDENTICAL request
    schedules.  This is the one spelling of the assignment; the engine's
    ``adapter_map`` kwarg consumes it verbatim."""
    if num_adapters < 1:
        return {}
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xADA]))
    ranks = np.arange(1, num_adapters + 1, dtype=np.float64)
    probs = ranks ** -float(exponent)
    probs /= probs.sum()
    return {name: f"adapter-{int(rng.choice(num_adapters, p=probs))}"
            for name in tenant_names}


def make_tenant_population(n: int, base: str = "tenant",
                           zipf: float = 1.2) -> Tuple[Tenant, ...]:
    """``n`` tenants with Zipf arrival weights (rank-1 heaviest) — the
    many-tenant population the adapter-pool bench arms drive, where
    DEFAULT_TENANTS' three classes are too few to churn a pool."""
    if n < 1:
        raise ValueError("need n >= 1 tenants")
    return tuple(Tenant(f"{base}-{k}", weight=float((k + 1) ** -zipf))
                 for k in range(n))


def generate_workload(cfg: WorkloadConfig, vocab_size: int, max_seq: int
                      ) -> List[WorkloadItem]:
    """Materialise the full arrival schedule.  Lengths are clipped so
    ``prompt + new <= max_seq`` always holds — a generated workload is
    submittable against any engine with that geometry."""
    # The envelope is control.diurnal_rate — the ONE spelling the
    # autoscaler's predictive arm also reads, so anticipating the
    # envelope IS anticipating this generator's traffic.
    from trustworthy_dl_tpu.serve.control import diurnal_rate

    rng = np.random.default_rng(cfg.seed)
    weights = np.asarray([t.weight for t in cfg.tenants], np.float64)
    weights = weights / weights.sum()
    adapter_of = zipf_adapter_assignments(
        [t.name for t in cfg.tenants], cfg.num_adapters,
        exponent=cfg.adapter_zipf, seed=cfg.seed)
    items: List[WorkloadItem] = []
    t = 0.0
    for _ in range(cfg.num_requests):
        # Non-homogeneous Poisson via rate modulation: the gap at time t
        # is exponential at the CURRENT envelope rate — bursts pack
        # arrivals, troughs stretch them.
        rate = diurnal_rate(cfg.mean_rps, cfg.burstiness,
                            cfg.burst_period_s, t)
        t += float(rng.exponential(1.0 / rate))
        tenant = cfg.tenants[int(rng.choice(len(cfg.tenants), p=weights))]
        out_hi = max(max_seq // 2, 1)
        if cfg.max_output is not None:
            out_hi = max(min(out_hi, cfg.max_output), 1)
        new = _lognormal_len(rng, cfg.output_median, cfg.output_sigma,
                             cfg.min_output, out_hi)
        # The bimodal mode-pick draw happens ONLY when the mix is on, so
        # frac=0 configs replay their pre-existing schedules unchanged.
        p_median = cfg.prompt_median
        if (cfg.prompt_bimodal_frac > 0.0
                and float(rng.random()) < cfg.prompt_bimodal_frac):
            p_median = cfg.prompt_long_median
        plen = _lognormal_len(rng, p_median, cfg.prompt_sigma,
                              cfg.min_prompt, max(max_seq - new - 1, 1))
        items.append(WorkloadItem(
            t_arrive=t,
            prompt=tuple(int(x) for x in
                         rng.integers(0, vocab_size, plen)),
            max_new_tokens=new,
            priority=tenant.priority,
            tenant=tenant.name,
            deadline_s=tenant.deadline_s,
            adapter=adapter_of.get(tenant.name),
        ))
    return items


def replay_workload(target: Any, items: Sequence[WorkloadItem],
                    make_request: Callable[[WorkloadItem], Any],
                    idle_sleep_s: float = 0.05) -> int:
    """Open-loop replay against anything with the serving surface
    (``submit``/``step``/``busy`` — a ServingFleet or a ServingEngine):
    each item is submitted when the wall clock passes its arrival time,
    the target is stepped while busy, and idle gaps before the next
    arrival sleep instead of spinning empty ticks.  ONE spelling of the
    driver loop for the bench sweep and the CLI.  Returns how many
    submissions were accepted (backpressure sheds return None)."""
    t0 = time.perf_counter()
    pending = list(items)
    accepted = 0
    while pending or target.busy:
        now = time.perf_counter() - t0
        while pending and pending[0].t_arrive <= now:
            item = pending.pop(0)
            if target.submit(make_request(item)) is not None:
                accepted += 1
        if not target.busy and pending:
            time.sleep(min(max(pending[0].t_arrive - now, 0.0),
                           idle_sleep_s))
            continue
        target.step()
    return accepted


def drive_closed_loop(target: Any, items: Sequence[WorkloadItem],
                      make_request: Callable[[WorkloadItem], Any],
                      inflight_target: int,
                      max_ticks: int = 200_000,
                      max_refused_ticks: int = 2_000) -> int:
    """CLOSED-loop bounded-queue driver: hold ``inflight_target``
    accepted-but-unfinished requests against the target (anything with
    the serving surface plus ``open_requests`` — a ServingFleet or a
    ServingEngine), submitting from ``items`` in order as capacity
    frees and ticking the target every iteration.

    This is the saturating driver the adversary bench introduced (PR
    12) and the autoscale/overload drills need: an open-loop wall-clock
    replay only loads a degraded/scaling fleet on a machine-specific
    service-rate knife edge, while a closed loop keeps backpressure —
    and therefore routing, throttling and scaling decisions — engaged
    deterministically, tick for tick.  ONE spelling shared by
    ``bench.py``, the drills and the CLI.  A submission the target
    refuses (engine backpressure or a tenant-bucket throttle) is
    retried on a later tick; a head item the target refuses for
    ``max_refused_ticks`` CONSECUTIVE ticks is dropped (logged, not
    counted accepted) — a permanently-throttled item (cost above its
    tenant's bucket capacity, zero refill) must not head-of-line-block
    every other tenant behind it until the ``max_ticks`` liveness
    backstop kills the whole drive.  Returns how many submissions were
    accepted."""
    pending = list(items)
    accepted = 0
    ticks = 0
    refused_streak = 0
    while pending or target.busy:
        while pending and target.open_requests < inflight_target:
            fid = target.submit(make_request(pending[0]))
            if fid is None:
                # Backpressure/throttle: retry next tick — but give up
                # on a head nothing will ever admit.
                refused_streak += 1
                if refused_streak >= max_refused_ticks:
                    logger.warning(
                        "drive_closed_loop: dropping head item after "
                        "%d consecutive refused ticks (permanently "
                        "throttled?)", refused_streak)
                    pending.pop(0)
                    refused_streak = 0
                break
            pending.pop(0)
            accepted += 1
            refused_streak = 0
        target.step()
        ticks += 1
        if ticks > max_ticks:
            raise RuntimeError(
                f"closed-loop drive did not drain in {max_ticks} ticks "
                f"({len(pending)} submissions still pending)")
    return accepted

"""Slotted KV cache — the serving engine's static-shape memory pool.

vLLM pages the KV cache at block granularity (PagedAttention, Kwon et al.,
SOSP '23) because CUDA kernels can chase block tables.  Under XLA the
equivalent that keeps the decode step a single never-recompiled program is
coarser: one cache SLOT per in-flight sequence,

    k, v: [L, MAX_SLOTS, H, MAX_SEQ, Dh]

with per-slot valid lengths.  The decode step is then exactly the batch
generate decode (models/generate._block_with_cache) with a *vector* of
per-row write offsets — same numerics source, same static shapes, so it
jits once for the engine's lifetime.

THE STATIC-SHAPE INVARIANT: nothing in the device programs depends on how
many requests are live.  Admission/retirement only change the host-side
``lengths``/active arrays fed in as (traced) *values*; slot allocation and
free-list bookkeeping are pure host work (SlotAllocator below).

Slot hygiene: a freed slot's cache rows are NOT scrubbed — the decode step
keeps writing garbage K/V at the freed slot's stale position (static shapes
mean inactive rows still compute).  That is safe by construction: a slot is
only re-used after prefill overwrites positions [0, prompt_len), the decode
mask admits k_pos <= current position only, and every position a new
request ever attends to is (re)written before it first becomes visible.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Set

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import gpt2


class SlotKV(NamedTuple):
    """Slot-pooled KV arrays; lengths live host-side (scheduler).

    int8 tier (quant/int8.py): ``k``/``v`` store int8 and the
    per-(head, position) f32 scales ride in ``k_scale``/``v_scale``
    ``[L, MAX_SLOTS, H, MAX_SEQ]``.  None scales = full-precision pool
    (the pre-quantization layout, byte-for-byte)."""

    k: jax.Array  # [L, MAX_SLOTS, H, MAX_SEQ, Dh]
    v: jax.Array  # [L, MAX_SLOTS, H, MAX_SEQ, Dh]
    k_scale: Optional[jax.Array] = None  # [L, MAX_SLOTS, H, MAX_SEQ]
    v_scale: Optional[jax.Array] = None

    @property
    def max_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_seq(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def pool_bytes(self) -> int:
        """Total HBM the pool holds (values + scales) — the number the
        ``tddl_serve_kv_bytes`` gauge reports."""
        total = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return total

    @property
    def bytes_per_slot(self) -> int:
        return self.pool_bytes // self.max_slots


def kv_bytes_per_slot(cfg: gpt2.GPT2Config, max_seq: int,
                      kv_dtype: Optional[Any] = None) -> int:
    """Bytes one slot costs under ``kv_dtype`` WITHOUT allocating — the
    bench A/B sizes its equal-HBM-budget arms with this.  int8 counts
    1 byte/element plus the 4-byte per-(head, position) scales."""
    kv_dtype = cfg.dtype if kv_dtype is None else kv_dtype
    positions = cfg.n_layer * cfg.n_head * max_seq
    dh = cfg.n_embd // cfg.n_head
    if kv_dtype == jnp.int8:
        return 2 * positions * (dh + 4)
    itemsize = jnp.zeros((), kv_dtype).dtype.itemsize
    return 2 * positions * dh * itemsize


def init_slots(cfg: gpt2.GPT2Config, max_slots: int, max_seq: int,
               kv_dtype: Optional[Any] = None) -> SlotKV:
    """``kv_dtype=None`` keeps the model compute dtype; ``jnp.int8``
    allocates the quantized pool (int8 values + f32 scales, zeros — an
    untouched row dequantises to exact zeros, same as the dense pool)."""
    if max_seq > cfg.n_positions:
        raise ValueError(
            f"max_seq={max_seq} exceeds the model's position table "
            f"(n_positions={cfg.n_positions})"
        )
    kv_dtype = cfg.dtype if kv_dtype is None else kv_dtype
    shape = (cfg.n_layer, max_slots, cfg.n_head, max_seq,
             cfg.n_embd // cfg.n_head)
    if kv_dtype == jnp.int8:
        scales = jnp.zeros(shape[:-1], jnp.float32)
        return SlotKV(k=jnp.zeros(shape, jnp.int8),
                      v=jnp.zeros(shape, jnp.int8),
                      k_scale=scales, v_scale=scales)
    return SlotKV(k=jnp.zeros(shape, kv_dtype),
                  v=jnp.zeros(shape, kv_dtype))


class SlotAllocator:
    """Host-side slot lifecycle: free list + quarantine set.

    Quarantine is the serving mirror of the training trust gate: a slot
    whose request was flagged anomalous leaves the pool (capacity shrinks,
    visible in the occupancy metric) until an operator releases it —
    matching the training-side COMPROMISED → probation → readmission
    ladder, where re-entry is also an explicit decision, not automatic."""

    def __init__(self, max_slots: int):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = max_slots
        # LIFO free list: the most recently freed slot is re-used first,
        # keeping the working set of cache rows small (cache-friendly).
        self._free: List[int] = list(range(max_slots - 1, -1, -1))
        self._quarantined: Set[int] = set()

    def alloc(self) -> Optional[int]:
        """Claim a free slot, or None when the pool is exhausted."""
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        if slot in self._quarantined:
            return  # quarantined slots never re-enter the pool via free()
        if slot in self._free or not 0 <= slot < self.max_slots:
            raise ValueError(f"double free / bad slot {slot}")
        self._free.append(slot)

    def quarantine(self, slot: int) -> None:
        """Remove a slot from service (flagged-anomalous request)."""
        self._quarantined.add(slot)
        if slot in self._free:
            self._free.remove(slot)

    def release(self, slot: int) -> None:
        """Operator action: return a quarantined slot to the pool."""
        if slot in self._quarantined:
            self._quarantined.discard(slot)
            self._free.append(slot)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def quarantined(self) -> Set[int]:
        return set(self._quarantined)

    @property
    def capacity(self) -> int:
        """Slots currently in service (total minus quarantined)."""
        return self.max_slots - len(self._quarantined)

"""KV cache memory pools for the serving engine — slotted (legacy) and
paged (default).

The original layout (PR 1) is the slotted stripe pool: one cache SLOT per
in-flight sequence,

    k, v: [L, MAX_SLOTS, H, MAX_SEQ, Dh]

with per-slot valid lengths.  A short request strands almost its whole
MAX_SEQ stripe, so concurrency is capped by *request count* rather than
by tokens in flight.

The paged pool (this PR) is the vLLM answer (PagedAttention, Kwon et al.,
SOSP '23) shaped for XLA's static-shape world: fixed-size token BLOCKS in
a global pool,

    k, v: [L, NUM_BLOCKS + 1, H, BLOCK, Dh]      (physical block 0 = trash)

plus per-slot block tables (host-side lists of physical block ids).  The
decode step gathers each slot's logical view through its block table —
the tables are plain i32 *values*, structurally stable, so block churn
never recompiles the fused decode program — and occupancy is bounded by
tokens (rounded up to blocks), not by requests.  Physical block 0 is a
reserved trash row: inactive decode rows and padded prefill tails scatter
their garbage writes there, so a freed-and-reused block can never be
corrupted by a stale slot's static-shape write.

On top of the pool, the radix ``PrefixCache`` keeps *full* prompt blocks
resident after retirement with reference-counted sharing (RadixAttention,
Zheng et al. 2024): requests whose prompt shares a cached full-block
prefix reuse those blocks and prefill only the unshared suffix.  Writes
only ever target exclusively-owned blocks (a request's suffix and
generated tokens land in privately allocated blocks by construction), so
the copy-on-write discipline never actually needs a copy.

THE STATIC-SHAPE INVARIANT: nothing in the device programs depends on how
many requests are live.  Admission/retirement only change the host-side
``lengths``/table arrays fed in as (traced) *values*; slot, block and
refcount bookkeeping are pure host work (SlotAllocator / BlockAllocator
below).

Slot hygiene (stripe pool): a freed slot's cache rows are NOT scrubbed —
the decode step keeps writing garbage K/V at the freed slot's stale
position (static shapes mean inactive rows still compute).  That is safe
by construction: a slot is only re-used after prefill overwrites
positions [0, prompt_len), the decode mask admits k_pos <= current
position only, and every position a new request ever attends to is
(re)written before it first becomes visible.  The paged pool gets the
same property from the trash block instead (stale tables are never handed
to the device; inactive rows are pointed at block 0).
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import gpt2


class SlotKV(NamedTuple):
    """Slot-pooled KV arrays; lengths live host-side (scheduler).

    int8 tier (quant/int8.py): ``k``/``v`` store int8 and the
    per-(head, position) f32 scales ride in ``k_scale``/``v_scale``
    ``[L, MAX_SLOTS, H, MAX_SEQ]``.  None scales = full-precision pool
    (the pre-quantization layout, byte-for-byte)."""

    k: jax.Array  # [L, MAX_SLOTS, H, MAX_SEQ, Dh]
    v: jax.Array  # [L, MAX_SLOTS, H, MAX_SEQ, Dh]
    k_scale: Optional[jax.Array] = None  # [L, MAX_SLOTS, H, MAX_SEQ]
    v_scale: Optional[jax.Array] = None

    @property
    def max_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_seq(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def pool_bytes(self) -> int:
        """Total HBM the pool holds (values + scales) — the number the
        ``tddl_serve_kv_bytes`` gauge reports."""
        total = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return total

    @property
    def bytes_per_slot(self) -> int:
        return self.pool_bytes // self.max_slots


def kv_bytes_per_token(cfg: gpt2.GPT2Config,
                       kv_dtype: Optional[Any] = None) -> int:
    """Bytes ONE cached token position costs under ``kv_dtype`` WITHOUT
    allocating — the HBM-budget primitive both pool layouts share (a
    stripe slot costs ``max_seq`` of these, a paged block ``block_size``).
    int8 counts 1 byte/element plus the 4-byte per-(head, position)
    scale, K and V each."""
    kv_dtype = cfg.dtype if kv_dtype is None else kv_dtype
    heads = cfg.n_layer * cfg.n_head
    dh = cfg.n_embd // cfg.n_head
    if kv_dtype == jnp.int8:
        return 2 * heads * (dh + 4)
    itemsize = jnp.zeros((), kv_dtype).dtype.itemsize
    return 2 * heads * dh * itemsize


def kv_bytes_per_slot(cfg: gpt2.GPT2Config, max_seq: int,
                      kv_dtype: Optional[Any] = None) -> int:
    """Deprecated thin wrapper: ``max_seq * kv_bytes_per_token(...)``.

    Kept for the stripe pool's callers; new HBM budgeting should compute
    from :func:`kv_bytes_per_token` (and :func:`paged_pool_blocks` for
    block-count sizing) so the math works for both layouts."""
    return max_seq * kv_bytes_per_token(cfg, kv_dtype)


def paged_pool_blocks(cfg: gpt2.GPT2Config, hbm_bytes: int, block_size: int,
                      kv_dtype: Optional[Any] = None) -> int:
    """Largest USABLE block count whose paged pool (including the +1
    trash block the layout always carries) fits in ``hbm_bytes`` — the
    pool-sizing helper the bench's equal-HBM paged arm uses."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    per_block = block_size * kv_bytes_per_token(cfg, kv_dtype)
    return max(int(hbm_bytes // per_block) - 1, 0)


def validate_paged_geometry(max_seq: int, block_size: int,
                            num_blocks: Optional[int],
                            prefill_chunk: Optional[int]) -> None:
    """Loud construction-time validation of the paged-pool knobs —
    shared by ``core.config.ServeConfig`` and the paged scheduler so a
    bad geometry fails where the operator typed it."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if max_seq % block_size != 0:
        raise ValueError(
            f"max_seq={max_seq} must be a multiple of block_size="
            f"{block_size} (the paged pool addresses whole blocks)"
        )
    if num_blocks is not None and num_blocks < max_seq // block_size:
        raise ValueError(
            f"num_blocks={num_blocks} cannot hold even one full "
            f"sequence (max_seq={max_seq} needs "
            f"{max_seq // block_size} blocks of {block_size})"
        )
    if prefill_chunk is not None:
        if (prefill_chunk % block_size != 0
                or not block_size <= prefill_chunk <= max_seq):
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be a multiple of "
                f"block_size={block_size} in [{block_size}, {max_seq}]"
            )


def resolve_prefill_chunk(max_seq: int, block_size: int,
                          prefill_chunk: Optional[int]) -> int:
    """``None`` -> the auto chunk: 64 positions (rounded down to a block
    multiple), clamped to ``max_seq``.  Explicit values were already
    validated by :func:`validate_paged_geometry`."""
    if prefill_chunk is not None:
        return prefill_chunk
    return max(block_size, (min(64, max_seq) // block_size) * block_size)


def init_slots(cfg: gpt2.GPT2Config, max_slots: int, max_seq: int,
               kv_dtype: Optional[Any] = None) -> SlotKV:
    """``kv_dtype=None`` keeps the model compute dtype; ``jnp.int8``
    allocates the quantized pool (int8 values + f32 scales, zeros — an
    untouched row dequantises to exact zeros, same as the dense pool)."""
    if max_seq > cfg.n_positions:
        raise ValueError(
            f"max_seq={max_seq} exceeds the model's position table "
            f"(n_positions={cfg.n_positions})"
        )
    kv_dtype = cfg.dtype if kv_dtype is None else kv_dtype
    shape = (cfg.n_layer, max_slots, cfg.n_head, max_seq,
             cfg.n_embd // cfg.n_head)
    if kv_dtype == jnp.int8:
        scales = jnp.zeros(shape[:-1], jnp.float32)
        return SlotKV(k=jnp.zeros(shape, jnp.int8),
                      v=jnp.zeros(shape, jnp.int8),
                      k_scale=scales, v_scale=scales)
    return SlotKV(k=jnp.zeros(shape, kv_dtype),
                  v=jnp.zeros(shape, kv_dtype))


class SlotAllocator:
    """Host-side slot lifecycle: free list + quarantine set.

    Quarantine is the serving mirror of the training trust gate: a slot
    whose request was flagged anomalous leaves the pool (capacity shrinks,
    visible in the occupancy metric) until an operator releases it —
    matching the training-side COMPROMISED → probation → readmission
    ladder, where re-entry is also an explicit decision, not automatic."""

    def __init__(self, max_slots: int):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = max_slots
        # LIFO free list: the most recently freed slot is re-used first,
        # keeping the working set of cache rows small (cache-friendly).
        self._free: List[int] = list(range(max_slots - 1, -1, -1))
        self._quarantined: Set[int] = set()

    def alloc(self) -> Optional[int]:
        """Claim a free slot, or None when the pool is exhausted."""
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        if slot in self._quarantined:
            return  # quarantined slots never re-enter the pool via free()
        if slot in self._free or not 0 <= slot < self.max_slots:
            raise ValueError(f"double free / bad slot {slot}")
        self._free.append(slot)

    def quarantine(self, slot: int) -> None:
        """Remove a slot from service (flagged-anomalous request)."""
        self._quarantined.add(slot)
        if slot in self._free:
            self._free.remove(slot)

    def release(self, slot: int) -> None:
        """Operator action: return a quarantined slot to the pool."""
        if slot in self._quarantined:
            self._quarantined.discard(slot)
            self._free.append(slot)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def quarantined(self) -> Set[int]:
        return set(self._quarantined)

    @property
    def capacity(self) -> int:
        """Slots currently in service (total minus quarantined)."""
        return self.max_slots - len(self._quarantined)


# ---------------------------------------------------------------------------
# Paged pool (the default serve data path since the paged-KV PR)
# ---------------------------------------------------------------------------

#: Physical block index reserved as the write sink for garbage: inactive
#: decode rows and padded prefill tails scatter here, never into a block
#: another request could own.  The allocator never hands it out.
TRASH_BLOCK = 0


class PagedKV(NamedTuple):
    """Block-pooled KV arrays; block tables and refcounts live host-side.

    Layout ``[L, NUM_BLOCKS + 1, H, BLOCK, Dh]`` — the +1 is the reserved
    trash block (index 0).  int8 tier: ``k``/``v`` store int8 and the
    per-(head, position) f32 scales ride in ``k_scale``/``v_scale``
    ``[L, NUM_BLOCKS + 1, H, BLOCK]`` — the pool pages values and scales
    identically, so the equal-HBM ~1.9x capacity win of the int8 tier
    compounds with paging."""

    k: jax.Array  # [L, NUM_BLOCKS + 1, H, BLOCK, Dh]
    v: jax.Array
    k_scale: Optional[jax.Array] = None  # [L, NUM_BLOCKS + 1, H, BLOCK]
    v_scale: Optional[jax.Array] = None

    @property
    def num_blocks(self) -> int:
        """USABLE blocks (the trash block is excluded)."""
        return self.k.shape[1] - 1

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def pool_bytes(self) -> int:
        """Total HBM the pool holds (values + scales, INCLUDING the trash
        block) — the honest number ``tddl_serve_kv_bytes`` reports."""
        total = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return total

    @property
    def bytes_per_block(self) -> int:
        return self.pool_bytes // (self.num_blocks + 1)


def init_paged_pool(cfg: gpt2.GPT2Config, num_blocks: int, block_size: int,
                    kv_dtype: Optional[Any] = None) -> PagedKV:
    """Allocate ``num_blocks`` usable blocks (+1 trash).  ``kv_dtype``
    semantics match :func:`init_slots`: None follows the model compute
    dtype, ``jnp.int8`` allocates the quantized pool (int8 values + f32
    per-(head, position) scales, zeros — an untouched block dequantises
    to exact zeros)."""
    if num_blocks < 1:
        raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
    if block_size > cfg.n_positions:
        raise ValueError(
            f"block_size={block_size} exceeds the model's position table "
            f"(n_positions={cfg.n_positions})"
        )
    kv_dtype = cfg.dtype if kv_dtype is None else kv_dtype
    shape = (cfg.n_layer, num_blocks + 1, cfg.n_head, block_size,
             cfg.n_embd // cfg.n_head)
    if kv_dtype == jnp.int8:
        scales = jnp.zeros(shape[:-1], jnp.float32)
        return PagedKV(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       k_scale=scales, v_scale=scales)
    return PagedKV(k=jnp.zeros(shape, kv_dtype),
                   v=jnp.zeros(shape, kv_dtype))


class BlockAllocator:
    """Host-side block lifecycle: free list + reference counts +
    quarantine set.

    Refcounts carry the prefix-sharing discipline: a block referenced by
    N requests (and/or the prefix cache) frees only when the LAST holder
    releases it.  ``release(quarantine=True)`` is the trust hook — a
    block whose last holder was a flagged request leaves the pool
    instead of returning to the free list, while blocks still shared
    with clean holders merely decref (quarantining a slot releases only
    its unshared blocks)."""

    def __init__(self, num_blocks: int, journal_capacity: int = 65536):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list over physical ids [1, num_blocks]; id 0 is the
        # reserved trash block and is never handed out.
        self._free: List[int] = list(range(num_blocks, 0, -1))
        self._ref: Dict[int, int] = {}
        self._quarantined: Set[int] = set()
        # Lifecycle evidence for obs.attribution.verify_attribution, two
        # granularities: ``journal`` is a bounded ring of (op, block,
        # seq[, outcome]) tuples for event-level debugging; ``lifetime``
        # is EXACT cumulative per-block op counts — keyed by block id so
        # it is bounded by the pool size, never by run length (the ring
        # alone would false-positive "never allocated" once a pinned
        # block's alloc entry rotated out).
        import collections as _collections

        self.journal: Any = _collections.deque(maxlen=journal_capacity)
        self._journal_seq = 0
        self.lifetime: Dict[int, Dict[str, int]] = {}

    def _journal_add(self, op: str, block: int, *extra: Any) -> None:
        self._journal_seq += 1
        self.journal.append((op, block, self._journal_seq, *extra))
        counts = self.lifetime.setdefault(
            block, {"alloc": 0, "incref": 0, "release": 0,
                    "unquarantine": 0})
        counts[op] += 1

    def alloc(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` blocks at refcount 1, or None when the pool cannot
        satisfy the request (backpressure, not an error)."""
        if n < 0:
            raise ValueError(f"cannot alloc {n} blocks")
        if len(self._free) < n:
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
            self._journal_add("alloc", b)
        return out

    def incref(self, block: int) -> None:
        if block not in self._ref:
            raise ValueError(f"incref of unallocated block {block}")
        self._ref[block] += 1
        self._journal_add("incref", block)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def release(self, block: int, quarantine: bool = False) -> str:
        """Drop one reference.  Returns what happened: ``"shared"``
        (other holders remain), ``"freed"``, or ``"quarantined"`` (hit
        refcount 0 under a trust flag — the block leaves the pool until
        :meth:`unquarantine`)."""
        if self._ref.get(block, 0) <= 0:
            raise ValueError(f"double free / bad block {block}")
        self._ref[block] -= 1
        if self._ref[block] > 0:
            self._journal_add("release", block, "shared")
            return "shared"
        del self._ref[block]
        if quarantine:
            self._quarantined.add(block)
            self._journal_add("release", block, "quarantined")
            return "quarantined"
        self._free.append(block)
        self._journal_add("release", block, "freed")
        return "freed"

    def unquarantine(self, block: int) -> None:
        """Operator action: return a quarantined block to the free pool."""
        if block in self._quarantined:
            self._quarantined.discard(block)
            self._free.append(block)
            self._journal_add("unquarantine", block)

    # -- speculative claims (speculative decoding's COW discipline) -------

    def claim_speculative(self, blocks: Sequence[int]) -> None:
        """Pin the blocks a speculative draft window is about to write:
        one extra reference each (journaled as ordinary increfs, so
        ``verify_attribution``'s per-block ref/release balance covers
        speculative traffic like any other sharing).  While claimed, no
        host-side actor (prefix-cache LRU eviction, admission-pressure
        eviction) can see the block as single-holder-free — un-verified
        draft KV is visibly referenced for exactly the tick it exists."""
        for b in blocks:
            self.incref(b)

    def release_speculative(self, blocks: Sequence[int]) -> None:
        """Drop the speculative claims after the verify pass: THE
        rollback.  Rejected draft tokens cost exactly this refcount
        decrement — no device copy, no scrub; the rejected positions'
        K/V are causally invisible (beyond the accepted length) and are
        overwritten by the next tick's writes before they could ever be
        attended.  Accepted tokens cost the same decrement (the claim
        commits into the slot's own table reference, which already
        holds the block)."""
        for b in blocks:
            self.release(b)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        """Blocks currently referenced (requests and/or prefix cache)."""
        return len(self._ref)

    @property
    def quarantined(self) -> Set[int]:
        return set(self._quarantined)


def blocks_for_span(table: Sequence[int], block_size: int,
                    start: int, end: int) -> List[int]:
    """Distinct physical blocks backing logical positions ``[start,
    end)`` of a slot's block table — the speculative draft window's
    claim set.  Positions past the table's allocation are nobody's
    storage (their static-shape writes land in the trash block) and
    contribute nothing; the trash block itself is never claimable."""
    out: List[int] = []
    for lb in range(start // block_size, -(-end // block_size)):
        if lb < len(table) and table[lb] != TRASH_BLOCK \
                and table[lb] not in out:
            out.append(table[lb])
    return out


class PrefixCache:
    """Host-side radix cache over FULL prompt blocks (RadixAttention-lite).

    Nodes form a block-granular radix tree — each keyed by (parent, its
    one-block token segment) and holding one physical block id on which
    the cache itself keeps a reference — so a retired request's prompt
    blocks stay resident and a later request with the same prefix reuses
    them without prefill.
    Lookups incref every matched block on behalf of the caller (atomic
    with the match, so a concurrent eviction can never free a block the
    caller is about to table).  Eviction is LRU over LEAF nodes whose
    block has no other holder — an interior node is pinned by its cached
    extensions, a shared block by its live requests."""

    def __init__(self, block_size: int, blocks: BlockAllocator):
        self.block_size = block_size
        self._blocks = blocks
        # True radix layout: a node is keyed by (parent node id, the ONE
        # block_size-token segment extending it), so memory and hashing
        # stay LINEAR in cached tokens — keying by cumulative prefix
        # tuples would make a p-token prompt cost O(p^2/block) ints.
        # Record: [physical block id, last-used tick, node id,
        # cached-extension count].  Node id 0 is the implicit root.
        self._nodes: Dict[Tuple[int, Tuple[int, ...]], List[Any]] = {}
        self._by_id: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        # block id -> request id that PUBLISHED it (attribution: a
        # prefix-cache hit records whose prefill it is trusting).
        self._publisher: Dict[int, int] = {}
        self._next_id = 1
        self._clock = 0

    def _bump(self) -> int:
        self._clock += 1
        return self._clock

    def _segment(self, tokens: Sequence[int], i: int) -> Tuple[int, ...]:
        return tuple(tokens[i * self.block_size:(i + 1) * self.block_size])

    def __len__(self) -> int:
        return len(self._nodes)

    def lookup(self, tokens: Sequence[int], max_blocks: int) -> List[int]:
        """Longest cached full-block prefix of ``tokens`` (at most
        ``max_blocks`` blocks), each matched block increffed for the
        caller.  Callers cap ``max_blocks`` at ``(len(prompt)-1) //
        block_size`` so at least one prompt token always prefills (the
        first sampled token needs fresh logits)."""
        out: List[int] = []
        parent = 0
        for i in range(max_blocks):
            node = self._nodes.get((parent, self._segment(tokens, i)))
            if node is None:
                break
            node[1] = self._bump()
            out.append(node[0])
            parent = node[2]
        for b in out:
            self._blocks.incref(b)
        return out

    def insert(self, tokens: Sequence[int], block_ids: Sequence[int],
               publisher: Optional[int] = None) -> List[int]:
        """Register ``tokens``' full blocks (backed by ``block_ids``, the
        owning request's table) — the cache increfs each newly cached
        block.  A prefix already cached (possibly under a different
        physical block holding identical content) is refreshed, not
        duplicated.  ``publisher`` (the owning request id) is remembered
        per newly cached block for attribution.  Returns the NEWLY
        cached block ids (the caller's publication record — what a later
        quarantine must purge)."""
        n = min(len(tokens) // self.block_size, len(block_ids))
        added: List[int] = []
        parent = 0
        for i in range(n):
            key = (parent, self._segment(tokens, i))
            node = self._nodes.get(key)
            if node is not None:
                node[1] = self._bump()
                parent = node[2]
                continue
            nid = self._next_id
            self._next_id += 1
            self._nodes[key] = [block_ids[i], self._bump(), nid, 0]
            self._by_id[nid] = key
            self._blocks.incref(block_ids[i])
            if publisher is not None:
                self._publisher[block_ids[i]] = publisher
            if parent:
                self._nodes[self._by_id[parent]][3] += 1
            added.append(block_ids[i])
            parent = nid
        return added

    def publishers(self, block_ids: Sequence[int]) -> Dict[int, int]:
        """Publisher request id per cached block (blocks with no
        recorded publisher are omitted)."""
        return {b: self._publisher[b] for b in block_ids
                if b in self._publisher}

    def _remove(self, key: Tuple[int, Tuple[int, ...]]) -> List[int]:
        """Drop one node; returns [block id, node id]."""
        block, _, nid, _ = self._nodes.pop(key)
        self._publisher.pop(block, None)
        del self._by_id[nid]
        if key[0] and key[0] in self._by_id:
            self._nodes[self._by_id[key[0]]][3] -= 1
        return [block, nid]

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` cached blocks, LRU leaves first,
        skipping any block a live request still references.  Returns how
        many were actually freed.  One heap pass — parents exposed by a
        child's eviction are pushed as they become leaves, so evicting k
        blocks from n nodes is O(n + k log n), not O(n*k) (this runs on
        the admission path whenever the pool is tight)."""
        heap = [(node[1], key) for key, node in self._nodes.items()
                if node[3] == 0]
        heapq.heapify(heap)
        freed = 0
        while heap and freed < n_blocks:
            _, key = heapq.heappop(heap)
            node = self._nodes.get(key)
            if node is None or node[3] != 0:
                continue                    # removed or re-grew a child
            if self._blocks.refcount(node[0]) != 1:
                continue                    # a live request pins it
            block, _ = self._remove(key)
            if key[0] and key[0] in self._by_id:
                parent_key = self._by_id[key[0]]
                parent = self._nodes[parent_key]
                if parent[3] == 0:
                    heapq.heappush(heap, (parent[1], parent_key))
            self._blocks.release(block)
            freed += 1
        return freed

    def purge(self, block_ids: Set[int]) -> int:
        """Drop every node backed by one of ``block_ids`` AND the
        subtrees hanging off them (unreachable once their parent is
        gone), releasing the cache's reference on each removed node's
        block.  The quarantine hook: a flagged request's PUBLISHED
        prompt blocks must leave the cache — without this their cache
        ref keeps them 'shared' at quarantine-retire and a later
        same-prefix request would decode straight off suspect KV.
        Returns the number of nodes removed."""
        doomed = [key for key, node in self._nodes.items()
                  if node[0] in block_ids]
        removed = 0
        while doomed:
            key = doomed.pop()
            if key not in self._nodes:
                continue
            block, nid = self._remove(key)
            doomed.extend(k for k in self._nodes if k[0] == nid)
            self._blocks.release(block)
            removed += 1
        return removed

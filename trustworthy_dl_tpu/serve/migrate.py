"""Live KV block-table migration: move an in-flight request between
replicas as a BLOCK COPY, not a recompute.

Every capacity-loss path in the fleet (trust drain, scale-in, heartbeat
fail-over, preemption, disaggregated prefill→decode hand-off) used to
end the same way: cancel on the source and replay the whole prompt —
and every already-accepted token — on a fresh replica.  This module
turns that into a two-phase hand-off of the request's PHYSICAL state:

1. **export** — the source engine snapshots the decode-phase request
   (block table, int8 scales ride in the same pool, emitted stream,
   trust signals, the WHOLE sampling key stream, timing).  Read-only;
   the source keeps serving.  Mid-prefill requests refuse (their state
   is a half-written table — replay is the honest path for those).
2. **claim** — the destination reserves a slot + fresh blocks + the
   adapter page through its NORMAL allocator paths (prefix-evict
   retry, adapter acquire, full unwind on any shortage).  A refusal
   here returns ``None`` and the source is left byte-identical —
   admission control is never bypassed by arriving as a migration.
3. **copy** — one jitted gather/scatter per pool leaf moves the
   KV blocks (and their scales — the int8 tier's values and scales
   page identically) from the source pool into the claimed blocks.
   Id vectors are padded to the fixed blocks-per-sequence width with
   ``TRASH_BLOCK`` so the program compiles ONCE per pool geometry; the
   reserved trash block absorbs the pad reads/writes by construction.
4. **commit** — the destination registers the continuation under a
   fresh local id (rng position travels because the key-stream index
   IS ``len(emitted)``), the caller's ``on_commit`` hook runs (the
   fleet re-points its attempt table here), and only THEN does the
   source release — ``cancel(status="migrated")``, which impounds the
   source blocks instead of freeing them when the source is being
   quarantined (``quarantine_src=True``): a suspect replica's bytes
   never silently re-enter its pool even as it loses the request.

Streams are bit-identical to an unmigrated ``generate()`` because
nothing numeric is recomputed: the destination decodes from the copied
blocks with the same keys at the same positions, and both replicas run
the same compile-once programs.

The capability gate (:func:`can_migrate`) is deliberately structural —
paged scheduler on both ends, identical pool geometry/dtype, the
export/adopt surface present — so heterogeneous or stripe-pool fleets
(and the unit-test fake engines) fall back to the pre-existing
cancel-and-recompute path instead of failing.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.serve.kv_slots import TRASH_BLOCK, PagedKV

# Module-level program cache (the scheduler's ``_PROGRAMS`` idiom): one
# jitted copy program shared by every engine pair in the process, keyed
# by jax's own (shape, dtype) cache — fixed-width id vectors mean two
# compiles per pool geometry (values leaf + scales leaf), ever.
_PROGRAMS: Dict[str, Any] = {}


def _programs() -> Dict[str, Any]:
    if not _PROGRAMS:
        def _copy_blocks(dst_pool: jax.Array, src_pool: jax.Array,
                         dst_ids: jax.Array, src_ids: jax.Array
                         ) -> jax.Array:
            # Gather the source rows along the block axis and scatter
            # them into the destination pool.  Pad entries map trash →
            # trash; duplicate trash writes are harmless (the reserved
            # block's content is garbage by contract).  No donation:
            # the source pool stays live under the source scheduler.
            return dst_pool.at[:, dst_ids].set(src_pool[:, src_ids])

        _PROGRAMS["copy"] = jax.jit(_copy_blocks)
    return _PROGRAMS


def can_migrate(src_engine: Any, dst_engine: Any) -> bool:
    """True when a live block-copy between the two engines is possible.

    Structural, not declared: both ends expose the export/adopt surface,
    both schedulers are paged, and the pools share geometry and dtype
    (a copy between mismatched pools would be a silent corruption, and
    between int8 and f32 tiers a silent dequant).  Anything that fails
    the gate — stripe pools, fakes, heterogeneous fleets — keeps the
    old cancel-and-recompute behaviour.
    """
    if src_engine is dst_engine:
        return False
    if not (hasattr(src_engine, "export_request")
            and hasattr(dst_engine, "adopt_request")):
        return False
    ss = getattr(src_engine, "scheduler", None)
    ds = getattr(dst_engine, "scheduler", None)
    if getattr(ss, "export_migration", None) is None:
        return False
    if getattr(ds, "claim_migration", None) is None:
        return False
    skv = getattr(ss, "kv", None)
    dkv = getattr(ds, "kv", None)
    if not (isinstance(skv, PagedKV) and isinstance(dkv, PagedKV)):
        return False
    if skv.k.shape != dkv.k.shape or skv.k.dtype != dkv.k.dtype:
        return False
    if skv.quantized != dkv.quantized:
        return False
    if getattr(ss, "nbps", None) != getattr(ds, "nbps", None):
        return False
    return True


def _copy_pools(src_sched: Any, dst_sched: Any,
                src_ids: list, dst_ids: list) -> None:
    """Move the named blocks (values AND scales) src pool → dst pool."""
    width = int(dst_sched.nbps)
    s = np.full(width, TRASH_BLOCK, np.int32)
    d = np.full(width, TRASH_BLOCK, np.int32)
    s[:len(src_ids)] = src_ids
    d[:len(dst_ids)] = dst_ids
    si, di = jnp.asarray(s), jnp.asarray(d)
    copy = _programs()["copy"]
    skv, dkv = src_sched.kv, dst_sched.kv
    new_k = copy(dkv.k, skv.k, di, si)
    new_v = copy(dkv.v, skv.v, di, si)
    new_ks = new_vs = None
    if dkv.k_scale is not None:
        new_ks = copy(dkv.k_scale, skv.k_scale, di, si)
        new_vs = copy(dkv.v_scale, skv.v_scale, di, si)
    dst_sched.kv = PagedKV(k=new_k, v=new_v, k_scale=new_ks,
                           v_scale=new_vs)


def migrate_request(src_engine: Any, dst_engine: Any, local_id: int, *,
                    quarantine_src: bool = False,
                    on_token: Optional[Callable[[int, int], None]] = None,
                    src_journal: Optional[str] = None,
                    on_commit: Optional[Callable[[int], None]] = None,
                    on_refuse: Optional[Callable[[str], None]] = None,
                    ) -> Optional[Dict[str, Any]]:
    """Two-phase live migration of one in-flight request.

    Returns ``{"local_id": <new id on the destination>, "blocks":
    <KV blocks copied>}`` on success, or ``None``
    with the source byte-untouched when the request is not migratable
    (unknown id, still prefilling, no tokens yet) or the destination
    refuses the claim (slot/block/adapter shortage).  On success the
    source side is released via ``cancel(status="migrated", quarantine=
    quarantine_src)`` — AFTER the destination committed and after the
    caller's ``on_commit(new_local)`` ran, so a fleet can re-point its
    routing before the source attempt closes and no token is ever
    streamed by zero or two replicas.

    ``src_journal`` (the fleet's ``replica:gen`` allocator-journal key)
    is threaded into the destination's attribution record as
    ``migrated_from`` so ``verify_attribution`` can reconcile the
    source-side block provenance without flagging the release.

    ``on_refuse`` is invoked with the refusal class
    (``"src_not_migratable"`` / ``"claim_refused"``) just before each
    ``None`` return — the fleet's forensic incident records capture
    per-destination refusals through it.
    """
    snap = src_engine.export_request(local_id)
    if snap is None:
        if on_refuse is not None:
            on_refuse("src_not_migratable")
        return None
    task = snap["task"]
    src_ids = list(snap["block_ids"])
    claim = dst_engine.scheduler.claim_migration(len(src_ids),
                                                task.adapter)
    if claim is None:
        if on_refuse is not None:
            on_refuse("claim_refused")
        return None
    _copy_pools(src_engine.scheduler, dst_engine.scheduler,
                src_ids, claim["block_ids"])
    migrated_from = {"block_ids": src_ids,
                     "replica": snap.get("replica")}
    if src_journal is not None:
        migrated_from["journal"] = src_journal
    new_local = dst_engine.adopt_request(snap, claim, on_token=on_token,
                                         migrated_from=migrated_from)
    if on_commit is not None:
        on_commit(new_local)
    src_engine.cancel(local_id, status="migrated",
                      quarantine=quarantine_src)
    return {"local_id": new_local, "blocks": len(src_ids)}

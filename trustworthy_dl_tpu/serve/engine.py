"""Request lifecycle for the serving engine: queue → prefill → decode →
stream, with deadlines, backpressure, serving metrics, and trust-aware
output monitoring.

The engine is a synchronous iteration loop (``step()``): each iteration
admits queued requests into free slots, runs the scheduler's single fused
decode step, streams new tokens to per-request callbacks, and retires
finished/expired sequences.  Everything host-side is O(MAX_SLOTS) python;
the device work per iteration is exactly one decode program plus one
bucketed prefill per admission.

Trust-aware admission control (the inference mirror of the training trust
state machine): every emitted token's logit entropy and top-1 margin are
computed in-step (scheduler._logit_signals); at retirement the request's
mean signal vector is z-scored against a rolling baseline of past *clean*
requests (detect/baseline ring buffer — score-then-absorb-only-clean, the
same hardening the training detector uses so an attacker cannot drag its
own baseline).  A flagged generation marks the request and QUARANTINES the
slot it ran on — a compromised replica's capacity leaves the pool until an
operator releases it, mirroring COMPROMISED → probation on the training
side.

Serving metrics flow through ``utils.metrics.MetricsCollector``: per
iteration (slot occupancy, queue depth, tokens emitted) and per request
(TTFT, ITLs); ``metrics_summary()`` reports tokens/s and p50/p99
inter-token latency — the numbers the bench serve leg records.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.detect import baseline as bl
from trustworthy_dl_tpu.models import generate as gen
from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.obs import attribution
from trustworthy_dl_tpu.obs.events import EventType
from trustworthy_dl_tpu.obs.registry import get_registry
from trustworthy_dl_tpu.quant import int8 as q8
from trustworthy_dl_tpu.serve.kv_slots import (
    kv_bytes_per_token,
    resolve_prefill_chunk,
    validate_paged_geometry,
)
from trustworthy_dl_tpu.serve.scheduler import (
    ContinuousBatchingScheduler,
    PagedBatchingScheduler,
    SlotTask,
    request_key_stream,
)
from trustworthy_dl_tpu.utils.metrics import MetricsCollector

logger = logging.getLogger(__name__)


class _NullMetric:
    """No-op stand-in when a registry rejects a (re-)registration — the
    one case is a label-shape clash (an unlabelled standalone engine
    and a replica-labelled fleet engine sharing one registry).  The
    engine's own rollup counters stay exact; only this engine's export
    series is dropped, loudly at debug level."""

    def inc(self, *a: Any, **kw: Any) -> None:
        pass

    def set(self, *a: Any, **kw: Any) -> None:
        pass

    def observe(self, *a: Any, **kw: Any) -> None:
        pass

    def value(self, *a: Any, **kw: Any) -> None:
        return None


class _BoundMetric:
    """Binds an engine's fixed labels (replica=… in fleet mode) onto a
    registry metric so components that don't know about fleet labelling
    (the AdapterPool's gauge/counter handles) can call plain
    ``set(v)`` / ``inc(tenant=…)``."""

    def __init__(self, metric: Any, labels: Dict[str, str]):
        self._metric = metric
        self._labels = labels

    def set(self, *a: Any, **kw: Any) -> None:
        self._metric.set(*a, **{**kw, **self._labels})

    def inc(self, *a: Any, **kw: Any) -> None:
        self._metric.inc(*a, **{**kw, **self._labels})


@dataclasses.dataclass
class ServeRequest:
    """One generation request.  ``temperature<=0`` decodes greedily;
    ``deadline_s`` is a relative wall-clock budget from submit time (the
    request retires mid-flight with whatever it has when it expires);
    ``on_token`` streams each token as ``on_token(request_id, token)``;
    ``priority`` orders load shedding under an SLO breach — when the
    attached watcher is burning budget, the LOWEST-priority queued
    requests are shed first (ties: newest first).  ``first_submit_id``
    is the retry-age anchor: a resubmission of a previously shed/failed
    request carries its ORIGINAL submission's id so the shed tie-break
    treats it as old as it really is (without it a retry gets a fresh —
    newest — id and is shed again first under sustained pressure;
    fleet fail-over depends on this).  ``span_parent`` re-parents the
    request's ``serve.request`` span under an outer span (the fleet's
    per-attempt span, so one request's timeline survives fail-over).
    ``publish_prefix=False`` keeps the request's prompt blocks OUT of
    the shared PrefixCache — the fleet's verdict-vote replays are
    transient audits that must not perturb cache state.  ``tenant``
    is the end-to-end tenant identity: it rides the attribution-ledger
    record and the ``serve.request`` span, and the FLEET's per-tenant
    token buckets meter admission by it (None = untagged).  ``adapter``
    names the tenant's low-rank adapter (serve/adapters.py) — None
    falls back to the engine's ``adapter_map`` lookup by tenant, and
    the resolved id claims a pool page at admission."""

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    deadline_s: Optional[float] = None
    rng: Optional[jax.Array] = None
    on_token: Optional[Callable[[int, int], None]] = None
    priority: int = 0
    first_submit_id: Optional[int] = None
    span_parent: Optional[int] = None
    publish_prefix: bool = True
    tenant: Optional[str] = None
    adapter: Optional[str] = None


@dataclasses.dataclass
class ServeResult:
    request_id: int
    tokens: List[int]
    # completed | deadline_exceeded | shed_slo | no_capacity (shed
    # because every slot was quarantined — see run_until_idle) | any
    # caller-chosen status passed to cancel() (the fleet uses
    # "migrated" / "hedge_lost" / "failover")
    status: str
    ttft_s: Optional[float]        # submit -> first token
    itl_s: List[float]             # inter-token latencies
    flagged: bool = False          # output monitor verdict
    monitor_z: float = 0.0
    adapter: Optional[str] = None  # resolved adapter id (serve/adapters.py)


class OutputMonitor:
    """Rolling per-request output-anomaly baseline.

    Signal vector per finished request: [mean logit entropy, mean top-1
    margin].  Both are cheap in-step reductions of the decode logits, and
    together they see the two anomaly directions: a backdoored/looping
    generation collapses entropy and inflates margin; a corrupted replica
    emitting garbage logits does the reverse.  The baseline is the same
    ring-buffer machinery the training detector uses (detect/baseline),
    one fleet-wide row, and absorbs ONLY requests it did not flag."""

    NUM_SIGNALS = 2

    def __init__(self, window: int = 256, warmup: int = 16,
                 z_threshold: float = 4.0):
        self.warmup = warmup
        self.z_threshold = z_threshold
        self._state = bl.init_baseline_state(1, window, self.NUM_SIGNALS)

    def observe(self, entropies: Sequence[float],
                margins: Sequence[float]) -> tuple:
        """Score one finished request; absorb it iff clean.  Returns
        (flagged, max_z)."""
        vec = jnp.asarray(
            [[float(np.mean(entropies)), float(np.mean(margins))]],
            jnp.float32,
        )
        mean, std, valid = bl.baseline_moments(self._state)
        z = float(jnp.max(bl.zscores(vec, mean, std)))
        warm = int(valid[0]) >= self.warmup
        flagged = warm and z > self.z_threshold
        if not flagged:
            self._state = bl.push_stats(self._state, vec)
        return flagged, z

    @property
    def count(self) -> int:
        return int(self._state.count[0])


class ServingEngine:
    """Continuous-batching serving over a fixed slot pool.

    ``queue_limit`` is the backpressure bound: ``submit`` returns None
    (shed load) once the admission queue is full — slots exhausted is not
    an error, it is the steady state under heavy traffic.

    Long-lived servers: per-request bookkeeping is dropped at retirement;
    finished ``ServeResult``s accumulate in ``results`` until the caller
    reads them — use ``drain_results()`` on a production loop so host
    memory stays bounded."""

    def __init__(self, params: Any, cfg: gpt2.GPT2Config,
                 max_slots: int = 8, max_seq: int = 256,
                 queue_limit: int = 64,
                 buckets: Optional[Sequence[int]] = None,
                 rng: Optional[jax.Array] = None,
                 monitor: Optional[OutputMonitor] = None,
                 enable_monitor: bool = True,
                 metrics: Optional[MetricsCollector] = None,
                 chaos: Any = None, trace: Any = None,
                 registry: Any = None,
                 kv_dtype: str = "model", weight_dtype: str = "model",
                 kv_parity_check: bool = True,
                 paged: bool = True, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefill_chunk: Optional[int] = None,
                 spans: Any = None, ledger: Any = None,
                 slo: Any = None, anomaly: Any = None,
                 retain_results: int = 1024,
                 replica_id: Optional[int] = None,
                 retire_hook: Optional[Callable[..., None]] = None,
                 compilewatch: Any = None, hbm: Any = None,
                 spec_k: int = 0, attn_impl: str = "auto",
                 adapter_rank: int = 0,
                 adapter_pool_pages: Optional[int] = None,
                 adapter_dtype: str = "model",
                 adapter_map: Optional[Dict[str, str]] = None,
                 tp_size: int = 1,
                 tp_devices: Optional[Sequence[Any]] = None):
        # ``chaos``: an optional chaos.FaultInjector whose SERVE_POISON
        # events overwrite a retiring request's output signals — the
        # deterministic drill for the monitor→quarantine path (a poisoned
        # replica must lose its slot, not keep serving).
        self.chaos = chaos
        self.cfg = cfg
        # Tensor-parallel replica: the engine owns a TP submesh over the
        # 'model' axis and the params carry the model's registry-declared
        # TP layout (core/sharding.py:serve_tp_mesh/place_serve_tp — the
        # SAME rules training TP resolves, so one layout serves both
        # planes).  Every jitted serve program then runs GSPMD-partitioned
        # over the group; tp_size=1 is byte-for-byte the single-chip
        # engine.  ``tp_devices`` is the fleet's carved per-replica device
        # slice; None defaults to the first tp_size local devices.
        self.tp_size = int(tp_size)
        self.tp_mesh = None
        if self.tp_size > 1:
            from trustworthy_dl_tpu.core import sharding as shreg

            self.tp_mesh = shreg.serve_tp_mesh(self.tp_size, tp_devices)
            params = shreg.place_serve_tp(params, self.tp_mesh)
        # Paged pool geometry fails loudly HERE, before any model work
        # (kv_slots.validate_paged_geometry — the same check ServeConfig
        # runs, so engines built without a config stay just as safe).
        self.paged = paged
        if paged:
            validate_paged_geometry(max_seq, block_size, num_blocks,
                                    prefill_chunk)
            if num_blocks is None:
                # Default pool matches the stripe engine's token capacity
                # exactly (max_slots full stripes), so paged-by-default
                # is a strict superset before any knob is touched.
                num_blocks = max_slots * (max_seq // block_size)
        # HBM headroom gate (obs/hbm.py): the KV pool is the one
        # construction-time allocation an operator sizes to fill HBM —
        # consult the monitor BEFORE allocating and shrink to what the
        # live budget actually has room for (floor: one full stripe /
        # one slot), instead of discovering the OOM at device_put.  The
        # denial itself is attributable: ``hbm_pressure`` event +
        # ``tddl_hbm_pressure_total``.
        self.hbm = hbm
        if hbm is not None:
            bpt = kv_bytes_per_token(cfg, jnp.int8) \
                if kv_dtype == "int8" else kv_bytes_per_token(cfg)
            # TP replica: the KV heads shard over the group, so each
            # device holds 1/tp of the pool's bytes — the headroom gate
            # budgets per DEVICE, so it admits the per-shard cost.  This
            # is what lets a scale-UP (bigger TP group) fit more blocks
            # into the same per-chip budget.
            bpt = max(bpt // max(self.tp_size, 1), 1)
            if paged:
                requested = num_blocks * block_size * bpt
                if not hbm.admit(requested, what="serve_paged_pool"):
                    # Size the shrunk pool from the SAME sweep that made
                    # the deny decision (admit() stored it) — a second
                    # sweep could report headroom the gate never saw.
                    headroom = max(hbm.last_headroom or 0, 0)
                    floor = max_seq // block_size
                    allowed = max(int(headroom // (block_size * bpt)),
                                  floor)
                    logger.warning(
                        "HBM headroom gate: paged pool shrunk %d -> %d "
                        "blocks (requested %d bytes, headroom %d)",
                        num_blocks, allowed, requested, headroom,
                    )
                    num_blocks = allowed
            else:
                requested = max_slots * max_seq * bpt
                if not hbm.admit(requested, what="serve_stripe_pool"):
                    headroom = max(hbm.last_headroom or 0, 0)
                    allowed = max(int(headroom // (max_seq * bpt)), 1)
                    logger.warning(
                        "HBM headroom gate: stripe pool shrunk %d -> %d "
                        "slots (requested %d bytes, headroom %d)",
                        max_slots, allowed, requested, headroom,
                    )
                    max_slots = allowed
        # Quantization tier (quant/int8.py).  Unknown dtype strings fail
        # HERE; the int8 KV swap is additionally parity-gated: a short
        # eager greedy-token probe against the full-precision path, with
        # automatic fallback to the model-dtype pool on failure (the
        # same always-safe-swap pattern as flash_attention's non-tiling
        # fallback).  ``kv_parity_check=False`` skips the probe (bench
        # arms that construct many engines).
        q8.validate_dtypes(kv_dtype, weight_dtype)
        # Speculative decoding (README §Serving/"Speculative decoding"):
        # the same loud knob validation ServeConfig runs, so engines
        # built without a config fail identically (paged pool required,
        # weight_dtype must stay "model" — the int8 tier is the DRAFT).
        from trustworthy_dl_tpu.core.config import (validate_adapters,
                                                    validate_spec)

        validate_spec(spec_k, paged, weight_dtype)
        validate_adapters(adapter_rank, adapter_pool_pages, adapter_dtype,
                          paged, spec_k)
        self.spec_k = int(spec_k)
        self.kv_fallback_reason: Optional[str] = None
        # The decode view is built at most ONCE here and shared with the
        # parity probe, the scheduler (its ``view=`` kwarg) and the
        # weight-error histogram — quantize_decode_view walks every block
        # matrix, and bench arms construct engines in a loop.
        base_view = None
        view = None
        if weight_dtype == "int8" or (kv_dtype == "int8" and kv_parity_check):
            base_view = gen._decode_view(params, cfg)
            view = (q8.quantize_decode_view(params, cfg, view=base_view)
                    if weight_dtype == "int8" else base_view)
        # The int8 self-draft for speculative decoding: built ONCE here
        # (validate_spec already pinned weight_dtype == "model", so the
        # serve view is dense) reusing whatever dense view exists — one
        # weight walk total.  The dense view doubles as the scheduler's
        # serve/verify view so it is not rebuilt there either.
        draft_view = None
        if self.spec_k > 0:
            if base_view is None:
                base_view = gen._decode_view(params, cfg)
            draft_view = q8.draft_decode_view(params, cfg,
                                              dense_view=base_view)
            if view is None:
                view = base_view
        if kv_dtype == "int8" and kv_parity_check:
            if not q8.kv_parity_probe(view, cfg):
                self.kv_fallback_reason = "kv_parity_probe_failed"
                kv_dtype = "model"
                # Keep the HBM budget the int8 sizing planned for: an
                # operator who filled HBM at int8 bytes/token must not
                # have the fallback allocate 2-4x that in the model dtype
                # — on a budgeted deployment that is an OOM at
                # construction, the opposite of "always safe".  Shrink
                # the pool (blocks when paged, slots on the stripe path)
                # to what the int8 byte budget buys at model-dtype cost.
                int8_bpt = kv_bytes_per_token(cfg, jnp.int8)
                model_bpt = kv_bytes_per_token(cfg)
                if paged:
                    fallback_blocks = max(
                        max_seq // block_size,
                        (num_blocks * int8_bpt) // model_bpt,
                    )
                    logger.warning(
                        "int8 KV parity probe failed: falling back to "
                        "the model-dtype paged pool, shrinking %d -> %d "
                        "blocks to stay inside the int8 pool's HBM "
                        "budget (safety gate; see README "
                        "§Serving/Quantization)",
                        num_blocks, fallback_blocks,
                    )
                    num_blocks = fallback_blocks
                else:
                    fallback_slots = max(
                        1, (max_slots * int8_bpt) // model_bpt
                    )
                    logger.warning(
                        "int8 KV parity probe failed: falling back to "
                        "the model-dtype KV pool, shrinking %d -> %d "
                        "slots to stay inside the int8 pool's HBM "
                        "budget (safety gate; see README "
                        "§Serving/Quantization)",
                        max_slots, fallback_slots,
                    )
                    max_slots = fallback_slots
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        # Multi-tenant adapter tier (serve/adapters.py): the SECOND
        # paged HBM resource, sized through the SAME headroom gate as
        # the KV pool — and sized AFTER it, so the KV pool keeps its
        # claim and the adapter pool shrinks into what remains (floor:
        # one usable page).  ``adapter_map`` routes tenant → adapter id
        # for requests that don't name one explicitly.
        self.adapter_rank = int(adapter_rank)
        self.adapter_dtype = adapter_dtype
        self.adapter_map: Dict[str, str] = dict(adapter_map or {})
        self.adapter_pool: Any = None
        if adapter_rank > 0:
            from trustworthy_dl_tpu.serve.adapters import (
                AdapterPool,
                adapter_bytes_per_page,
                adapter_pool_bytes,
            )

            pages = (adapter_pool_pages if adapter_pool_pages is not None
                     else max_slots)
            if hbm is not None:
                bpp = adapter_bytes_per_page(cfg, adapter_rank,
                                             adapter_dtype)
                requested = adapter_pool_bytes(cfg, pages, adapter_rank,
                                               adapter_dtype)
                if not hbm.admit(requested, what="serve_adapter_pool"):
                    # Re-size from the SAME sweep that denied (the KV
                    # template above): headroom // bytes-per-page, minus
                    # the reserved zero page, floored at one usable page.
                    headroom = max(hbm.last_headroom or 0, 0)
                    allowed = max(int(headroom // bpp) - 1, 1)
                    logger.warning(
                        "HBM headroom gate: adapter pool shrunk %d -> %d "
                        "pages (requested %d bytes, headroom %d)",
                        pages, allowed, requested, headroom,
                    )
                    pages = allowed
            self.adapter_pool = AdapterPool(
                cfg, adapter_rank, pages, adapter_dtype=adapter_dtype,
                trace=trace,
            )
        if paged:
            # ``attn_impl`` selects the decode-attention read (README
            # §Serving/"Decode attention kernel"): "auto" resolves
            # through the shared Pallas gate to the ragged paged-
            # attention kernel (+ fused trust epilogue) on TPU and the
            # jnp gather fallback elsewhere; "pallas"/"jnp" force a
            # path.  Resolution happens once, in the scheduler, and is
            # baked into every compiled program as a static.
            self.scheduler: Any = PagedBatchingScheduler(
                params, cfg, max_slots, max_seq, buckets,
                kv_dtype=kv_dtype, weight_dtype=weight_dtype, view=view,
                block_size=block_size, num_blocks=num_blocks,
                prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
                spec_k=self.spec_k, draft_view=draft_view,
                attn_impl=attn_impl, adapters=self.adapter_pool,
            )
        else:
            if attn_impl not in ("auto", "jnp"):
                # The stripe pool has no paged-attention kernel: an
                # explicit kernel ask must fail where the operator typed
                # it, not silently serve the gather path (ServeConfig
                # additionally warns for any paged knob on paged=False).
                raise ValueError(
                    f"attn_impl={attn_impl!r} requires the paged pool "
                    "(paged=True); the stripe engine always runs the "
                    "jnp attention path"
                )
            self.scheduler = ContinuousBatchingScheduler(
                params, cfg, max_slots, max_seq, buckets,
                kv_dtype=kv_dtype, weight_dtype=weight_dtype, view=view,
            )
        self.queue_limit = queue_limit
        self.monitor = monitor if monitor is not None else (
            OutputMonitor() if enable_monitor else None
        )
        # ``trace``: optional obs TraceBus — the request lifecycle
        # (submit → admit → retire/quarantine) correlates on request_id.
        # Registry metrics are always on (per-iteration gauges ride the
        # collector's absorption; counters/latency histograms are the
        # serving SLO surface).
        self.trace = trace
        if registry is None:
            registry = get_registry()
        # Fleet-mode metric labelling: under a ServingFleet every engine
        # shares ONE registry, so the per-engine serve gauges would
        # last-writer-win each other (documented in PR 8 as "read only
        # the fleet aggregates").  With a ``replica_id`` the whole
        # tddl_serve_* surface gains a ``replica=`` label instead —
        # per-replica occupancy/blocks/tokens individually readable —
        # while standalone engines keep the unlabelled form.
        self.replica_id = replica_id
        self._rlabel_names = ("replica",) if replica_id is not None else ()
        self._rlabels = ({"replica": str(replica_id)}
                         if replica_id is not None else {})
        self.metrics = metrics or MetricsCollector(
            namespace="serve", registry=registry,
            labels=self._rlabels or None,
        )
        # A registry that ALREADY holds a metric under the other label
        # shape (a standalone engine registered the unlabelled form
        # before a fleet replica arrived, or vice versa) would raise on
        # re-registration; degrade that engine's series to a no-op
        # instead — the rollup dicts stay the source of truth, exactly
        # like MetricsCollector's export path.
        def _metric(register, name, help, labels=(), **kw):
            try:
                return register(name, help, labels=labels, **kw)
            except ValueError:
                logger.debug("serve metrics: registry rejected %s%s",
                             name, labels, exc_info=True)
                return _NullMetric()

        self._req_counter = _metric(
            registry.counter, "tddl_serve_requests_total",
            "Requests retired/shed, by terminal status",
            labels=("status",) + self._rlabel_names,
        )
        self._tok_counter = _metric(
            registry.counter, "tddl_serve_tokens_total", "Tokens emitted",
            labels=self._rlabel_names,
        )
        self._ttft_hist = _metric(
            registry.histogram, "tddl_serve_ttft_seconds",
            "Submit -> first token", labels=self._rlabel_names,
        )
        self._itl_hist = _metric(
            registry.histogram, "tddl_serve_itl_seconds",
            "Inter-token latency", labels=self._rlabel_names,
        )
        # KV-pool capacity surface: bytes resident (values + scales) and
        # slot count by storage dtype — the numbers the quantization
        # A/B moves (int8 ≈ halves bytes/slot → ~2x slots at fixed HBM).
        kv = self.scheduler.kv
        kv_dtype_label = str(kv.k.dtype)
        _metric(
            registry.gauge, "tddl_serve_kv_bytes",
            "KV slot-pool HBM footprint (values + quant scales)",
            labels=self._rlabel_names,
        ).set(float(kv.pool_bytes), **self._rlabels)
        _metric(
            registry.gauge, "tddl_serve_slots_total",
            "KV slots in the pool, by storage dtype",
            labels=("dtype",) + self._rlabel_names,
        ).set(float(max_slots), dtype=kv_dtype_label, **self._rlabels)
        # Quantization-error histogram: per-matrix weight roundtrip
        # relative errors (weight-only int8) — empty when nothing is
        # quantized.  Buckets span the int8 regime (~1e-3 rel err).
        self._quant_err_hist = _metric(
            registry.histogram, "tddl_serve_quant_error",
            "Relative quantization error (weight roundtrip, per matrix)",
            labels=self._rlabel_names,
            buckets=(1e-5, 1e-4, 1e-3, 3e-3, 1e-2, 3e-2, 1e-1, 1.0),
        )
        if weight_dtype == "int8":
            for err in q8.weight_roundtrip_errors(base_view, cfg,
                                                  qview=view):
                self._quant_err_hist.observe(err, **self._rlabels)
        # Paged-pool occupancy surface: blocks referenced (requests +
        # prefix cache), tokens in flight, and prefix-cache reuse.  The
        # gauges/counter are registered on BOTH pool layouts so every
        # serve snapshot carries them (stripe reports 0 blocks — it has
        # no block pool to occupy).
        self._blocks_gauge = _metric(
            registry.gauge, "tddl_serve_blocks_in_use",
            "Paged-KV blocks currently referenced (requests + prefix "
            "cache); 0 on the legacy stripe pool",
            labels=self._rlabel_names,
        )
        self._tif_gauge = _metric(
            registry.gauge, "tddl_serve_tokens_in_flight",
            "Cached tokens currently backing live sequences",
            labels=self._rlabel_names,
        )
        self._prefix_counter = _metric(
            registry.counter, "tddl_serve_prefix_hits_total",
            "Admissions that reused cached prefix blocks",
            labels=self._rlabel_names,
        )
        self._prefix_hits_seen = 0
        # Adapter-pool residency surface (serve/adapters.py): pages
        # resident (impounded included) and evictions by evicted tenant.
        # Registered on every engine so the snapshot shape is uniform;
        # an adapterless engine just exports 0.  The pool receives
        # label-bound handles — it doesn't know about fleet labelling.
        self._adapter_pages_gauge = _metric(
            registry.gauge, "tddl_serve_adapter_pages_in_use",
            "Adapter-pool pages resident (live + warm + impounded); 0 "
            "when the adapter tier is off",
            labels=self._rlabel_names,
        )
        self._adapter_pages_gauge.set(0.0, **self._rlabels)
        self._adapter_evictions_counter = _metric(
            registry.counter, "tddl_serve_adapter_evictions_total",
            "Cold adapters LRU-evicted from the pool, by evicted tenant",
            labels=("tenant",) + self._rlabel_names,
        )
        if self.adapter_pool is not None:
            self.adapter_pool._pages_gauge = _BoundMetric(
                self._adapter_pages_gauge, self._rlabels)
            self.adapter_pool._evictions_counter = _BoundMetric(
                self._adapter_evictions_counter, self._rlabels)
        # Serving-kernel path gauge: one series per (program, path),
        # the active path set to 1 for each of the tier's programs
        # (decode / prefill / verify / adapter) — a silent fallback of
        # ANY program to its slow jnp spelling (gate off, untileable
        # geometry, non-TPU backend) is visible in EVERY serve
        # snapshot, and pages alongside the sentinel's tick fractions
        # instead of hiding inside tokens/s.
        from trustworthy_dl_tpu.ops import paged_attention as pattn

        self._attn_gauge = _metric(
            registry.gauge, "tddl_serve_attn_kernel",
            "Active serving-kernel path per paged program (1 = in "
            "use): the Pallas kernel, its interpret-mode twin, or the "
            "jnp gather/materialise fallback",
            labels=("path", "program") + self._rlabel_names,
        )
        _paths = self.attn_kernel_paths
        for _program in pattn.PAGED_PROGRAMS:
            for _path in ("pallas", "interpret", "jnp"):
                self._attn_gauge.set(
                    1.0 if _path == _paths[_program] else 0.0,
                    path=_path, program=_program, **self._rlabels,
                )
        # Speculative-decode surface: drafted vs accepted tokens (their
        # ratio is the accepted_rate the bench A/B and the perf sentinel
        # track).  Registered on every engine — replica-labelled in
        # fleet mode like the rest of the tddl_serve_* gauges — and
        # incremented only when the spec tier runs.
        self._spec_proposed_counter = _metric(
            registry.counter, "tddl_serve_spec_proposed_total",
            "Draft tokens proposed by the speculative int8 self-draft",
            labels=self._rlabel_names,
        )
        self._spec_accepted_counter = _metric(
            registry.counter, "tddl_serve_spec_accepted_total",
            "Draft tokens accepted by the batched model-dtype verify",
            labels=self._rlabel_names,
        )
        self._spec_seen = (0, 0)   # (proposed, accepted) already counted
        self.peak_tokens_in_flight = 0
        self.peak_active = 0
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._queue: Deque[tuple] = deque()   # (task, request)
        self._inflight: Dict[int, tuple] = {}  # request_id -> (task, req, t)
        self._timing: Dict[int, List[float]] = {}  # request_id -> token times
        self._submit_t: Dict[int, float] = {}
        self.results: Dict[int, ServeResult] = {}
        self.rejected = 0
        self._next_id = 0
        self._iteration = 0
        self._tokens_emitted = 0
        self._t_start: Optional[float] = None
        # Host wall spent inside scheduler.decode_tick() (chunked
        # prefill + the fused decode step + its packed pull): the
        # decode-phase tick fraction of metrics_summary and the perf
        # sentinel fingerprint — where a silent attention-path fallback
        # shows up as time.
        self.decode_tick_s = 0.0
        # -- active observability plane (all optional, all host-only) --
        # ``spans``: obs.spans.SpanTracker — request/phase timeline.
        # ``ledger``: obs.attribution.AttributionLedger — one durable
        # record per retired request.  ``slo``/``anomaly``: the
        # streaming watchers; when the SLO watcher is burning budget
        # (or an anomaly is active) the admission path sheds the
        # lowest-priority queued requests.  None of these touch the
        # device programs — streams stay bit-identical with all four
        # attached (pinned by tests).
        self.spans = spans
        self.ledger = ledger
        self.slo = slo
        self.anomaly = anomaly
        # Fleet integration (serve/fleet.py): ``replica_id`` names this
        # engine in a ServingFleet — it gates replica-addressed chaos
        # (request ids are replica-local, so an unaddressed poison would
        # be ambiguous across N replicas) and rides trace/ledger rows.
        # ``retire_hook(result, placement)`` fires synchronously at
        # every terminal state — placement is the scheduler's
        # attribution snapshot for admitted requests (None otherwise) —
        # so the fleet sees failures the instant they happen instead of
        # polling ``results``.  (``self.replica_id`` itself is set up
        # top with the replica-labelled metric surface.)
        # Every engine trace event carries the replica index in fleet
        # mode: request ids are replica-LOCAL, so without the tag a
        # shared TraceBus cannot tell replica 0's request 3 from
        # replica 1's (the same ambiguity the replica-gated chaos hook
        # closes for SERVE_POISON).
        self._trace_tags = ({"replica": replica_id}
                            if replica_id is not None else {})
        self.retire_hook = retire_hook
        self.scheduler.spans = spans
        # Performance tier (obs/compilewatch.py): the fused decode
        # dispatch runs under the watcher's "serve_decode" guard — the
        # compile-once pin enforced at runtime.
        self.compilewatch = compilewatch
        self.scheduler.compilewatch = compilewatch
        self._req_spans: Dict[int, Dict[str, int]] = {}  # rid -> open ids
        # Bounded completed-request retention: ``results`` keeps at most
        # ``retain_results`` finished records (oldest evicted first);
        # the rollup counters + streaming percentile estimators below
        # keep ``metrics_summary`` exact over EVERY request ever
        # retired, evicted or not.
        if retain_results < 1:
            raise ValueError("retain_results must be >= 1")
        self.retain_results = retain_results
        self._status_counts: Dict[str, int] = {}
        self._flagged_total = 0
        # An attached SLO watcher already keeps P² sketches of the same
        # ttft_s/itl_s streams — own a second pair only when unwatched,
        # and read whichever exists in metrics_summary (one marker set
        # per signal, one p50 for both summary and slo_status.json).
        if slo is None:
            from trustworthy_dl_tpu.obs.slo import StreamingPercentiles

            self._ttft_est = StreamingPercentiles()
            self._itl_est = StreamingPercentiles()
        else:
            self._ttft_est = None
            self._itl_est = None
        self.shed_slo = 0

    @classmethod
    def from_config(cls, params: Any, cfg: gpt2.GPT2Config,
                    serve_config: Any, **kwargs: Any) -> "ServingEngine":
        """Build an engine from a ``core.config.ServeConfig`` (whose
        construction already validated the dtype knobs loudly);
        ``kwargs`` pass through for the non-config surfaces (rng,
        monitor, trace, registry, ...)."""
        return cls(
            params, cfg,
            max_slots=serve_config.max_slots,
            max_seq=serve_config.max_seq,
            queue_limit=serve_config.queue_limit,
            kv_dtype=serve_config.kv_dtype,
            weight_dtype=serve_config.weight_dtype,
            paged=serve_config.paged,
            block_size=serve_config.block_size,
            num_blocks=serve_config.num_blocks,
            prefix_cache=serve_config.prefix_cache,
            prefill_chunk=serve_config.prefill_chunk,
            spec_k=serve_config.spec_k,
            attn_impl=serve_config.attn_impl,
            adapter_rank=serve_config.adapter_rank,
            adapter_pool_pages=serve_config.adapter_pool_pages,
            adapter_dtype=serve_config.adapter_dtype,
            tp_size=serve_config.tp_size,
            **kwargs,
        )

    # -- submission --------------------------------------------------------

    def submit(self, request: ServeRequest) -> Optional[int]:
        """Enqueue a request; returns its request_id, or None when shed by
        backpressure (queue full).  Raises for requests that can never be
        served (longer than the cache)."""
        prompt = np.asarray(list(request.prompt), np.int32)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + request.max_new_tokens
        if total > self.scheduler.max_seq:
            raise ValueError(
                f"prompt+new = {total} exceeds max_seq="
                f"{self.scheduler.max_seq}"
            )
        largest_bucket = max(self.scheduler.buckets)
        if prompt.size > largest_bucket:
            # Reject at submission, not at admission — an engine built
            # with custom (sub-max_seq) buckets must fail the request up
            # front rather than crash the serving loop mid-flight.
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds the largest "
                f"prefill bucket {largest_bucket}"
            )
        # Tenant → adapter resolution: an explicit request.adapter wins,
        # else the engine's adapter_map by tenant.  Loud when the tier
        # is off — a silently dropped adapter would serve the BASE model
        # under the tenant's name, the exact trust failure the paged
        # adapter tier exists to prevent.
        adapter = request.adapter
        if adapter is None and request.tenant is not None:
            adapter = self.adapter_map.get(request.tenant)
        if adapter is not None and self.adapter_pool is None:
            raise ValueError(
                f"request names adapter {adapter!r} but the adapter tier "
                "is off (adapter_rank=0); serving it on the base model "
                "would silently misattribute the stream"
            )
        if len(self._queue) >= self.queue_limit:
            self.rejected += 1
            self._req_counter.inc(status="rejected", **self._rlabels)
            return None
        request_id = self._next_id
        self._next_id += 1
        rng = request.rng
        if rng is None:
            rng = jax.random.fold_in(self._rng, request_id)
        task = SlotTask(
            request_id=request_id,
            prompt=prompt,
            max_new_tokens=int(request.max_new_tokens),
            temperature=float(request.temperature),
            keys=request_key_stream(rng, int(request.max_new_tokens)),
            eos_id=request.eos_id,
            publish_prefix=bool(request.publish_prefix),
            adapter=adapter,
        )
        self._queue.append((task, request))
        self._submit_t[request_id] = time.perf_counter()
        if self.trace is not None:
            self.trace.emit(EventType.SERVE_SUBMIT, request_id=request_id,
                            prompt_len=int(prompt.size),
                            max_new_tokens=int(request.max_new_tokens), **self._trace_tags)
        if self.spans is not None:
            root = self.spans.start("serve.request", kind="serve",
                                    parent_id=request.span_parent,
                                    request_id=request_id,
                                    replica=self.replica_id,
                                    tenant=request.tenant,
                                    prompt_len=int(prompt.size),
                                    max_new_tokens=int(
                                        request.max_new_tokens))
            queued = self.spans.start("serve.queued", kind="serve",
                                      parent_id=root,
                                      request_id=request_id)
            self._req_spans[request_id] = {"root": root, "queued": queued}
        return request_id

    # -- terminal bookkeeping ----------------------------------------------

    def _record_result(self, result: ServeResult,
                       placement: Optional[Dict[str, Any]] = None) -> None:
        """The ONE rollup path every terminal state goes through: status
        counters (exact forever), bounded ``results`` retention (oldest
        evicted first), registry counter, and the fleet's
        ``retire_hook`` (placement = the scheduler's attribution
        snapshot for admitted requests, None for queue-side sheds)."""
        self._status_counts[result.status] = \
            self._status_counts.get(result.status, 0) + 1
        if result.flagged:
            self._flagged_total += 1
        self.results[result.request_id] = result
        while len(self.results) > self.retain_results:
            del self.results[next(iter(self.results))]
        self._req_counter.inc(status=result.status, **self._rlabels)
        if self.retire_hook is not None:
            self.retire_hook(result, placement)

    def _close_request_spans(self, rid: int, status: str,
                             **attrs: Any) -> None:
        handles = self._req_spans.pop(rid, None)
        if handles is None or self.spans is None:
            return
        for name in ("queued", "prefill", "decode", "monitor"):
            sid = handles.get(name)
            if sid is not None:
                self.spans.end(sid)
        self.spans.end(handles["root"], status=status, **attrs)

    def _span_first_token(self, rid: int) -> None:
        """prefill → decode span transition at the request's first
        emitted token."""
        handles = self._req_spans.get(rid)
        if handles is None or self.spans is None:
            return
        sid = handles.pop("prefill", None)
        if sid is not None:
            self.spans.end(sid)
        handles["decode"] = self.spans.start(
            "serve.decode", kind="serve", parent_id=handles["root"],
            request_id=rid,
        )

    def _ledger_unadmitted(self, rid: int, status: str,
                           tenant: Optional[str] = None) -> None:
        if self.ledger is None:
            return
        self.ledger.append({
            "request_id": rid, "status": status, "admitted": False,
            "slot": -1, "layout": "paged" if self.paged else "stripe",
            "block_ids": [], "prefix_block_ids": [],
            "prefix_publishers": {},
            "kv_dtype": self.kv_dtype, "weight_dtype": self.weight_dtype,
            "kv_fallback_reason": self.kv_fallback_reason,
            "flagged": False, "monitor_z": 0.0,
            "tokens": 0, "token_hash": attribution.token_hash([]),
            "tenant": tenant,
        })

    def _request_age_id(self, task: SlotTask, request: ServeRequest) -> int:
        """Submission-order age for shed tie-breaks: the ORIGINAL
        submission's id when the request is a retry
        (``first_submit_id``), its own id otherwise.  Without the
        anchor, a shed-and-resubmitted request gets a fresh (newest) id
        and is shed again first under sustained pressure — a retry
        starvation loop the fleet's fail-over path would otherwise
        inherit."""
        if request.first_submit_id is not None:
            return int(request.first_submit_id)
        return int(task.request_id)

    def _shed_for_slo(self) -> None:
        """The watcher's host-side shed hook: while an SLO rule is
        burning budget (or an anomaly is active), drop the
        LOWEST-priority queued request (ties: newest first, by ORIGINAL
        submission age — retries inherit theirs) — but only when the
        queue exceeds the currently free capacity, so shedding relieves
        real pressure instead of burning goodput.  At most one shed per
        iteration: pressure is re-evaluated every step."""
        breached = ((self.slo is not None and self.slo.breached)
                    or (self.anomaly is not None
                        and self.anomaly.any_active))
        if not breached or not self._queue:
            return
        if len(self._queue) <= self.scheduler.allocator.free_count:
            return
        idx = min(range(len(self._queue)),
                  key=lambda i: (self._queue[i][1].priority,
                                 -self._request_age_id(*self._queue[i])))
        task, _request = self._queue[idx]
        del self._queue[idx]
        rid = task.request_id
        self._submit_t.pop(rid, None)
        self.shed_slo += 1
        self._record_result(ServeResult(
            request_id=rid, tokens=[], status="shed_slo", ttft_s=None,
            itl_s=[],
        ))
        if self.trace is not None:
            self.trace.emit(EventType.SERVE_RETIRE, request_id=rid,
                            status="shed_slo", tokens=0, admitted=False, **self._trace_tags)
        self._close_request_spans(rid, "shed_slo")
        self._ledger_unadmitted(rid, "shed_slo", tenant=_request.tenant)

    # -- iteration loop ----------------------------------------------------

    def step(self) -> int:
        """One scheduler iteration: expire → admit → decode → retire.
        Returns the number of tokens emitted this iteration."""
        now = time.perf_counter()
        if self._t_start is None:
            self._t_start = now
        self._iteration += 1
        self._expire_queued(now)
        self._shed_for_slo()

        # Admit as many queued requests as there are free slots.  On the
        # stripe path each admission prefetches the first token
        # (synchronous bucketed prefill), so TTFT is the admission
        # latency itself; the paged path only books host-side state here
        # (block claim + prefix-cache lookup) and the chunked prefill
        # runs inside subsequent decode_ticks — the first token lands
        # when the final chunk completes.
        emitted = 0
        while self._queue and self.scheduler.has_free_slot:
            task, request = self._queue.popleft()
            if not self.scheduler.admit(task):
                self._queue.appendleft((task, request))
                break
            rid = task.request_id
            self._inflight[rid] = (task, request)
            if self.trace is not None:
                self.trace.emit(EventType.SERVE_ADMIT, request_id=rid,
                                slot=int(task.slot), **self._trace_tags)
            handles = self._req_spans.get(rid)
            if handles is not None:
                sid = handles.pop("queued", None)
                if sid is not None:
                    self.spans.end(sid, slot=int(task.slot))
                handles["prefill"] = self.spans.start(
                    "serve.prefill", kind="serve",
                    parent_id=handles["root"], request_id=rid,
                    slot=int(task.slot),
                )
            if task.emitted:
                self._timing[rid] = [time.perf_counter()]
                self._span_first_token(rid)
                self._stream(request, rid, task.emitted[-1])
                emitted += 1
                if task.done:
                    self._finish(task, request, "completed")
        t_tick = time.perf_counter()
        ticked = self.scheduler.decode_tick()
        self.decode_tick_s += time.perf_counter() - t_tick
        if self.spans is not None and ticked:
            self.spans.add("serve.decode_tick", t_tick,
                           time.perf_counter(), kind="serve",
                           tokens=len(ticked),
                           active=self.scheduler.active_count)
        for task in ticked:
            rid = task.request_id
            if rid not in self._inflight:
                continue
            _, request = self._inflight[rid]
            times = self._timing.setdefault(rid, [])
            if not times:
                self._span_first_token(rid)
            # A speculative tick can emit SEVERAL tokens at once
            # (``tick_tokens``, in emission order); every single-token
            # path leaves it None and streams emitted[-1] exactly as
            # before.  The burst's intra-tick ITLs are honest
            # near-zeros: the tokens really did land together.
            new_tokens = (task.tick_tokens
                          if task.tick_tokens is not None
                          else [task.emitted[-1]])
            for token in new_tokens:
                times.append(time.perf_counter())
                self._stream(request, rid, token)
                emitted += 1
            deadline = request.deadline_s
            expired = (deadline is not None
                       and time.perf_counter() - self._submit_t[rid]
                       > deadline)
            if task.done:
                self._finish(task, request, "completed")
            elif expired:
                self._finish(task, request, "deadline_exceeded")
        # Mid-prefill deadline check (paged chunked prefill): a slot
        # still feeding prompt chunks emits nothing from decode_tick, so
        # the loop above never sees it — without this an already-expired
        # long prompt would keep burning chunk programs (and delaying
        # every other slot's tick) until its first token.
        for rid, (task, request) in list(self._inflight.items()):
            if task.done or task.emitted:
                continue
            deadline = request.deadline_s
            if (deadline is not None
                    and time.perf_counter() - self._submit_t[rid]
                    > deadline):
                self._finish(task, request, "deadline_exceeded")
        self._tokens_emitted += emitted
        if emitted:
            self._tok_counter.inc(emitted, **self._rlabels)

        tif = self.scheduler.tokens_in_flight
        self.peak_tokens_in_flight = max(self.peak_tokens_in_flight, tif)
        self.peak_active = max(self.peak_active,
                               self.scheduler.active_count)
        self._tif_gauge.set(float(tif), **self._rlabels)
        if self.slo is not None:
            self.slo.observe("occupancy", self.scheduler.occupancy)
        if self.paged:
            self._blocks_gauge.set(float(self.scheduler.blocks_in_use),
                                    **self._rlabels)
            hits = self.scheduler.prefix_hits
            if hits > self._prefix_hits_seen:
                self._prefix_counter.inc(hits - self._prefix_hits_seen,
                                         **self._rlabels)
                self._prefix_hits_seen = hits
            if self.spec_k:
                proposed = self.scheduler.spec_proposed
                accepted = self.scheduler.spec_accepted
                seen_p, seen_a = self._spec_seen
                if proposed > seen_p:
                    self._spec_proposed_counter.inc(proposed - seen_p,
                                                    **self._rlabels)
                if accepted > seen_a:
                    self._spec_accepted_counter.inc(accepted - seen_a,
                                                    **self._rlabels)
                self._spec_seen = (proposed, accepted)
        self.metrics.collect_batch_metrics({
            "step": self._iteration,
            "active_slots": self.scheduler.active_count,
            "slot_occupancy": self.scheduler.occupancy,
            "queue_depth": len(self._queue),
            "tokens_emitted": emitted,
            "tokens_in_flight": tif,
            "slots_in_service": self.scheduler.allocator.capacity,
        })
        self.metrics.tick()
        return emitted

    def run_until_idle(self, max_iterations: int = 100_000
                       ) -> Dict[int, ServeResult]:
        """Drive ``step()`` until queue and slots drain (or the iteration
        bound trips — a liveness backstop, not a normal exit)."""
        it = 0
        while self._queue or self._inflight:
            idle_before = not self._inflight
            qlen = len(self._queue)
            self.step()
            it += 1
            # Starvation check: with nothing in flight before the step,
            # a step that admitted nothing and shed nothing proves the
            # queue can never drain — every row quarantined (stripe), or
            # quarantined BLOCKS starving the paged pool even after
            # prefix-cache eviction; no retirement can ever free more
            # capacity.  Shed the queue instead of spinning to the
            # iteration bound.
            if (idle_before and not self._inflight
                    and self._queue and len(self._queue) == qlen):
                while self._queue:
                    task, request = self._queue.popleft()
                    rid = task.request_id
                    self._submit_t.pop(rid, None)
                    self._record_result(ServeResult(
                        request_id=rid, tokens=[],
                        status="no_capacity", ttft_s=None, itl_s=[],
                    ))
                    if self.trace is not None:
                        self.trace.emit(EventType.SERVE_RETIRE,
                                        request_id=rid,
                                        status="no_capacity", tokens=0,
                                        admitted=False, **self._trace_tags)
                    self._close_request_spans(rid, "no_capacity")
                    self._ledger_unadmitted(rid, "no_capacity",
                                            tenant=request.tenant)
                break
            if it >= max_iterations:
                raise RuntimeError(
                    f"serving loop did not drain in {max_iterations} "
                    "iterations"
                )
        return self.results

    # -- internals ---------------------------------------------------------

    def _stream(self, request: ServeRequest, request_id: int,
                token: int) -> None:
        if request.on_token is not None:
            request.on_token(request_id, token)

    def _expire_queued(self, now: float) -> None:
        """Shed queued requests whose deadline passed before admission."""
        keep: Deque[tuple] = deque()
        while self._queue:
            task, request = self._queue.popleft()
            rid = task.request_id
            if (request.deadline_s is not None
                    and now - self._submit_t[rid] > request.deadline_s):
                self._submit_t.pop(rid, None)
                self._record_result(ServeResult(
                    request_id=rid, tokens=[],
                    status="deadline_exceeded", ttft_s=None, itl_s=[],
                ))
                if self.trace is not None:
                    self.trace.emit(EventType.SERVE_RETIRE, request_id=rid,
                                    status="deadline_exceeded", tokens=0,
                                    admitted=False, **self._trace_tags)
                self._close_request_spans(rid, "deadline_exceeded")
                self._ledger_unadmitted(rid, "deadline_exceeded",
                                        tenant=request.tenant)
            else:
                keep.append((task, request))
        self._queue = keep

    def cancel(self, request_id: int, status: str = "cancelled",
               quarantine: bool = False) -> bool:
        """Terminate a queued or in-flight request NOW with ``status``
        (no monitor scoring): the fleet's migrate/hedge hook — a
        draining replica's queue moves elsewhere, a lost hedge's
        duplicate stream stops burning decode slots, a live migration
        releases its source half after the destination commits.
        Resources (slot, blocks) free immediately; partial tokens ride
        the result.  ``quarantine=True`` IMPOUNDS instead of freeing
        (scheduler.retire's quarantine path: row + unshared blocks leave
        the pool) — the source side of a migration OFF a quarantined/
        trust-draining replica must not return suspect blocks to
        service.  Returns False when the id is unknown/already
        terminal."""
        for i in range(len(self._queue)):
            task, _request = self._queue[i]
            if task.request_id != request_id:
                continue
            del self._queue[i]
            self._submit_t.pop(request_id, None)
            self._record_result(ServeResult(
                request_id=request_id, tokens=[], status=status,
                ttft_s=None, itl_s=[],
            ))
            if self.trace is not None:
                self.trace.emit(EventType.SERVE_RETIRE,
                                request_id=request_id, status=status,
                                tokens=0, admitted=False, **self._trace_tags)
            self._close_request_spans(request_id, status)
            self._ledger_unadmitted(request_id, status,
                                    tenant=_request.tenant)
            return True
        pair = self._inflight.get(request_id)
        if pair is None:
            return False
        task, _request = pair
        placement = (self.scheduler.attribution_info(task)
                     if self.ledger is not None
                     or self.retire_hook is not None else None)
        self.scheduler.retire(task, quarantine=quarantine)
        times = self._timing.pop(request_id, [])
        t0 = self._submit_t.pop(request_id, None)
        ttft = (times[0] - t0) if times and t0 is not None else None
        self._record_result(ServeResult(
            request_id=request_id, tokens=list(task.emitted),
            status=status, ttft_s=ttft,
            itl_s=[b - a for a, b in zip(times, times[1:])],
            adapter=task.adapter,
        ), placement=placement)
        if self.trace is not None:
            self.trace.emit(EventType.SERVE_RETIRE, request_id=request_id,
                            status=status, tokens=len(task.emitted), **self._trace_tags)
            if quarantine:
                self.trace.emit(EventType.SERVE_QUARANTINE,
                                request_id=request_id,
                                slot=int(task.slot), **self._trace_tags)
        if self.ledger is not None:
            self.ledger.append({
                "request_id": request_id, "status": status,
                "admitted": True, **placement,
                "kv_dtype": self.kv_dtype,
                "weight_dtype": self.weight_dtype,
                "kv_fallback_reason": self.kv_fallback_reason,
                "flagged": False, "monitor_z": 0.0,
                "tokens": len(task.emitted),
                "token_hash": attribution.token_hash(task.emitted),
                "ttft_s": ttft,
                "tenant": _request.tenant,
            })
        self._close_request_spans(request_id, status,
                                  tokens=len(task.emitted))
        self._inflight.pop(request_id, None)
        return True

    # -- live migration (serve/migrate.py orchestrates) --------------------

    def export_request(self, request_id: int) -> Optional[Dict[str, Any]]:
        """Source half of a live migration: the scheduler's block-table
        snapshot (decode-phase only — mid-prefill and unknown ids
        refuse with None, nothing touched) plus the engine-level timing
        state that must travel for TTFT/ITL and deadline math to stay
        exact across the hand-off.  Read-only: the request keeps
        decoding here until ``cancel(..., status="migrated")`` releases
        it AFTER the destination commits."""
        pair = self._inflight.get(request_id)
        if pair is None:
            return None
        exporter = getattr(self.scheduler, "export_migration", None)
        if exporter is None:          # stripe pool: no block table
            return None
        task, request = pair
        snap = exporter(task)
        if snap is None:
            return None
        snap["request"] = request
        snap["submit_t"] = self._submit_t.get(request_id)
        snap["times"] = list(self._timing.get(request_id, []))
        snap["replica"] = self.replica_id
        return snap

    def adopt_request(self, snapshot: Dict[str, Any],
                      claim: Dict[str, Any], *,
                      on_token: Optional[Callable[[int, int], None]] = None,
                      migrated_from: Optional[Dict[str, Any]] = None
                      ) -> int:
        """Destination COMMIT half of a live migration: register the
        migrated stream under a fresh LOCAL id on the claimed row —
        pure host bookkeeping (the physical block copy already landed),
        so it cannot fail after the claim.  The continuation task
        copies the source's emitted stream, trust signals and the WHOLE
        sampling key stream (the next key index is ``len(emitted)`` —
        rng position travels by construction); ``publish_prefix`` is
        forced off (the destination never prefilled these blocks — the
        prompt was published, if at all, by the source).  Source-side
        ``submit_t``/token times carry over verbatim (same process
        clock), so deadlines, TTFT and ITL read as one request, not
        two."""
        src_task: SlotTask = snapshot["task"]
        src_request: ServeRequest = snapshot["request"]
        rid = self._next_id
        self._next_id += 1
        task = SlotTask(
            request_id=rid,
            prompt=np.asarray(src_task.prompt, np.int32),
            max_new_tokens=int(src_task.max_new_tokens),
            temperature=float(src_task.temperature),
            keys=src_task.keys,
            eos_id=src_task.eos_id,
            publish_prefix=False,
            adapter=src_task.adapter,
        )
        task.emitted = list(src_task.emitted)
        task.next_token = src_task.next_token
        task.entropies = list(src_task.entropies)
        task.margins = list(src_task.margins)
        request = dataclasses.replace(
            src_request,
            on_token=(on_token if on_token is not None
                      else src_request.on_token),
        )
        self.scheduler.commit_migration(task, claim, snapshot["length"],
                                        migrated_from=migrated_from)
        self._inflight[rid] = (task, request)
        t0 = snapshot.get("submit_t")
        self._submit_t[rid] = (t0 if t0 is not None
                               else time.perf_counter())
        self._timing[rid] = list(snapshot.get("times", []))
        if self.trace is not None:
            self.trace.emit(EventType.SERVE_ADMIT, request_id=rid,
                            slot=int(task.slot), migrated=True,
                            **self._trace_tags)
        return rid

    def _finish(self, task: SlotTask, request: ServeRequest,
                status: str) -> None:
        rid = task.request_id
        if self.chaos is not None:
            # Chaos hook point: a SERVE_POISON event for this request id
            # (replica-gated — local ids are ambiguous across a fleet)
            # or an active REPLICA_POISON on this replica rewrites the
            # recorded entropy/margin signals before the monitor scores
            # them (simulating a compromised replica).
            self.chaos.on_serve_retire(task, replica=self.replica_id)
        # Placement snapshot BEFORE retire() clears the slot's table —
        # the attribution record must name the physical blocks the
        # stream actually decoded from.
        placement = (self.scheduler.attribution_info(task)
                     if self.ledger is not None
                     or self.retire_hook is not None else None)
        flagged, z = False, 0.0
        t_mon = time.perf_counter()
        if self.monitor is not None and task.entropies:
            flagged, z = self.monitor.observe(task.entropies, task.margins)
            if self.spans is not None and rid in self._req_spans:
                self.spans.add("serve.monitor", t_mon, time.perf_counter(),
                               kind="serve",
                               parent_id=self._req_spans[rid]["root"],
                               request_id=rid, flagged=flagged,
                               monitor_z=float(z))
        self.scheduler.retire(task, quarantine=flagged)
        times = self._timing.pop(rid, [])
        t0 = self._submit_t.pop(rid, None)
        ttft = (times[0] - t0) if times and t0 is not None else None
        itl = [b - a for a, b in zip(times, times[1:])]
        self._record_result(ServeResult(
            request_id=rid, tokens=list(task.emitted), status=status,
            ttft_s=ttft, itl_s=itl, flagged=flagged, monitor_z=z,
            adapter=task.adapter,
        ), placement=placement)
        if ttft is not None:
            self._ttft_hist.observe(ttft, **self._rlabels)
            if self.slo is not None:
                self.slo.observe("ttft_s", ttft)
            else:
                self._ttft_est.observe(ttft)
        for dt in itl:
            self._itl_hist.observe(dt, **self._rlabels)
            if self.slo is not None:
                self.slo.observe("itl_s", dt)
            else:
                self._itl_est.observe(dt)
            if self.anomaly is not None:
                self.anomaly.observe("itl", dt)
        if self.trace is not None:
            self.trace.emit(EventType.SERVE_RETIRE, request_id=rid,
                            status=status, tokens=len(task.emitted),
                            flagged=flagged, monitor_z=z, **self._trace_tags)
            if flagged:
                self.trace.emit(EventType.SERVE_QUARANTINE, request_id=rid,
                                slot=int(task.slot), **self._trace_tags)
        if self.ledger is not None:
            thash = attribution.token_hash(task.emitted)
            record = {
                "request_id": rid, "status": status, "admitted": True,
                **placement,
                "kv_dtype": self.kv_dtype,
                "weight_dtype": self.weight_dtype,
                "kv_fallback_reason": self.kv_fallback_reason,
                "flagged": bool(flagged), "monitor_z": float(z),
                "tokens": len(task.emitted), "token_hash": thash,
                "ttft_s": ttft,
                "tenant": request.tenant,
            }
            self.ledger.append(record)
            if self.trace is not None:
                self.trace.emit(EventType.ATTRIBUTION, request_id=rid,
                                slot=int(task.slot),
                                n_blocks=len(placement["block_ids"]),
                                token_hash=thash, flagged=bool(flagged),
                                adapter=placement.get("adapter"),
                                adapter_page=placement.get(
                                    "adapter_page", 0), **self._trace_tags)
        self._close_request_spans(rid, status, tokens=len(task.emitted),
                                  flagged=bool(flagged))
        self.metrics.collect_batch_metrics({
            "step": self._iteration,
            "request_id": rid,
            "ttft_s": ttft if ttft is not None else -1.0,
            "tokens": len(task.emitted),
            "flagged": int(flagged),
        })
        self._inflight.pop(rid, None)

    # -- reporting ---------------------------------------------------------

    @property
    def busy(self) -> bool:
        """Work still queued or in flight."""
        return bool(self._queue or self._inflight)

    @property
    def queued_ids(self) -> List[int]:
        """Local request ids awaiting admission (fleet migrate hook)."""
        return [task.request_id for task, _ in self._queue]

    @property
    def inflight_ids(self) -> List[int]:
        """Local request ids holding a slot (fleet fail-over hook)."""
        return list(self._inflight)

    @property
    def decode_ready_ids(self) -> List[int]:
        """In-flight ids past prefill with tokens emitted — the set a
        disaggregated fleet moves off a prefill-specialist replica (a
        migration snapshot exists exactly for these)."""
        prefilling = getattr(self.scheduler, "_prefill", {})
        return [rid for rid, (task, _) in self._inflight.items()
                if task.emitted and not task.done
                and task.slot not in prefilling]

    @property
    def load(self) -> int:
        """Queued + in-flight — the fleet router's least-loaded key."""
        return len(self._queue) + len(self._inflight)

    @property
    def open_requests(self) -> int:
        """Accepted-but-unfinished requests — the closed-loop driver's
        in-flight count (engine spelling of the fleet property)."""
        return self.load

    @property
    def in_service_capacity(self) -> int:
        """Slots currently serviceable (total minus quarantined)."""
        return self.scheduler.allocator.capacity

    def drain_results(self) -> Dict[int, ServeResult]:
        """Return finished results and clear them — the bounded-memory
        retrieval API for long-lived serving loops."""
        out = self.results
        self.results = {}
        return out

    @property
    def attn_kernel_path(self) -> str:
        """The resolved decode-attention path this engine's compiled
        programs bake in: "pallas" | "interpret" | "jnp" (the stripe
        scheduler is always "jnp" — it has no paged kernel).  The
        monitor's entropy/margin come from the kernel's fused trust
        epilogue exactly when this is not "jnp"."""
        return self.scheduler.attn_impl

    @property
    def attn_kernel_paths(self) -> Dict[str, str]:
        """Per-program resolved paths for the whole serving-kernel tier
        (ops.paged_attention.PAGED_PROGRAMS: decode / prefill / verify /
        adapter), each "pallas" | "interpret" | "jnp".  The stripe
        scheduler has no paged programs — every entry is "jnp"."""
        from trustworthy_dl_tpu.ops import paged_attention as pattn

        impls = getattr(self.scheduler, "attn_impls", None)
        if impls is None:
            return {p: "jnp" for p in pattn.PAGED_PROGRAMS}
        return dict(impls)

    @property
    def quarantined_slots(self):
        return self.scheduler.allocator.quarantined

    def release_quarantine(self, slot: int) -> None:
        # Routed through the scheduler: the paged pool returns the
        # blocks impounded with the slot, not just the decode row.
        self.scheduler.release_quarantine(slot)

    def quarantine_adapter(self, name: str) -> None:
        """Apply a fleet-level trust verdict against an ADAPTER to this
        replica's pool: future resolves refuse, the page impounds when
        its last in-flight request drains.  The replica itself stays in
        service — adapter trust and replica trust are separate axes
        (serve/fleet.py owns the verdict and the fleet-wide event)."""
        if self.adapter_pool is not None:
            self.adapter_pool.quarantine(name)

    def unquarantine_adapter(self, name: str) -> None:
        """Operator action: lift an adapter verdict on this replica."""
        if self.adapter_pool is not None:
            self.adapter_pool.unquarantine(name)

    @property
    def quarantined_adapters(self):
        return (self.adapter_pool.quarantined
                if self.adapter_pool is not None else set())

    def metrics_summary(self) -> Dict[str, Any]:
        """Serving-side rollup: throughput, latency percentiles, trust.

        Counters come from the terminal-status rollup and the latency
        percentiles from the streaming P² estimators — both exact/stable
        over EVERY request ever retired, regardless of how many finished
        records the bounded ``results`` ring still retains."""
        elapsed = (
            (time.perf_counter() - self._t_start)
            if self._t_start is not None else 0.0
        )
        out: Dict[str, Any] = {
            "requests_completed": self._status_counts.get("completed", 0),
            "requests_deadline_exceeded":
                self._status_counts.get("deadline_exceeded", 0),
            "requests_rejected": self.rejected,
            "requests_shed_slo": self.shed_slo,
            "requests_flagged": self._flagged_total,
            "quarantined_slots": sorted(self.quarantined_slots),
            "tokens_emitted": self._tokens_emitted,
            "tokens_per_s":
                self._tokens_emitted / elapsed if elapsed > 0 else 0.0,
            "iterations": self._iteration,
            "peak_tokens_in_flight": self.peak_tokens_in_flight,
            "peak_active_requests": self.peak_active,
            # Decode-phase share of the serve wall: the number the perf
            # sentinel bands (a silent attention-path fallback inflates
            # it) and the gauge's companion.
            "decode_tick_fraction":
                (self.decode_tick_s / elapsed) if elapsed > 0 else 0.0,
            "attn_kernel_path": self.attn_kernel_path,
            "attn_kernel_paths": self.attn_kernel_paths,
        }
        if self.paged:
            sched = self.scheduler
            out["blocks_in_use"] = sched.blocks_in_use
            # Phase-share companions to decode_tick_fraction for the
            # two new kernel arms: wall share spent advancing prefill
            # chunks / inside the batched spec verify (both direction
            # LOWER in the sentinel fingerprint — a kernel arm that
            # does not shrink them is a regression signal).
            out["prefill_chunk_fraction"] = (
                sched.prefill_chunk_s / elapsed if elapsed > 0 else 0.0)
            out["spec_verify_fraction"] = (
                sched.spec_verify_s / elapsed if elapsed > 0 else 0.0)
            out["prefix_lookups"] = sched.prefix_lookups
            out["prefix_hits"] = sched.prefix_hits
            out["prefix_tokens_reused"] = sched.prefix_tokens_reused
            out["prefix_hit_rate"] = (
                sched.prefix_hits / sched.prefix_lookups
                if sched.prefix_lookups else 0.0
            )
            if self.spec_k:
                out["spec_k"] = self.spec_k
                out["spec_proposed"] = sched.spec_proposed
                out["spec_accepted"] = sched.spec_accepted
                out["accepted_rate"] = round(sched.accepted_rate, 4)
                out["spec_near_tie_flips"] = sched.spec_near_tie_flips
                out["spec_ticks"] = sched.spec_ticks
                out["spec_fallback_ticks"] = sched.spec_fallback_ticks
        if self.adapter_pool is not None:
            out["adapters"] = {
                "rank": self.adapter_rank,
                "dtype": self.adapter_dtype,
                **self.adapter_pool.metrics(),
            }
        for name, signal, est in (("itl", "itl_s", self._itl_est),
                                  ("ttft", "ttft_s", self._ttft_est)):
            if self.slo is not None:
                p50 = self.slo.quantile(signal, 0.5)
                p99 = self.slo.quantile(signal, 0.99)
            else:
                p50 = est.quantile(0.5) if est.count else None
                p99 = est.quantile(0.99) if est.count else None
            if p50 is not None:
                out[f"{name}_p50_ms"] = float(p50 * 1e3)
                out[f"{name}_p99_ms"] = float(p99 * 1e3)
        return out

    def analyze_programs(self, ledger: Any,
                         memory: Optional[bool] = None) -> Any:
        """Stamp this engine's serve programs (prefill/chunk/decode)
        into an ``obs.hbm.CostLedger`` — analyzed FLOPs and bytes per
        program, temp allocation too when ``memory`` (or
        ``TDDL_OBS_MEMORY_ANALYSIS=1``) is on.  Lowering-only by
        default: no extra backend compile, safe to call after a serve
        run on any engine."""
        self.scheduler.analyze_costs(ledger, memory=memory)
        return ledger

    def verify_attribution(self) -> "tuple[bool, list]":
        """Reconcile the attached ledger's records against the paged
        pool's block-lifecycle journal (obs.attribution) — the audit the
        serve-trust acceptance runs.  Stripe engines verify trivially
        (records carry no block ids)."""
        if self.ledger is None:
            raise ValueError("engine has no attribution ledger attached")
        allocator = getattr(self.scheduler, "blocks", None) \
            if self.paged else self.scheduler.allocator
        return attribution.verify_attribution(self.ledger.records(),
                                              allocator)

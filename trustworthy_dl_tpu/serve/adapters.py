"""Paged multi-tenant adapter pool — the SECOND paged HBM resource.

The serving engine already pages one HBM resource: KV blocks, claimed
per slot through a traced block table so admission churn never
recompiles (serve/kv_slots.py).  This module applies the same
discipline to MODEL WEIGHTS: per-tenant rank-r low-rank deltas
("adapters") on the attention output projection and the MLP, stored in
one pool array per side::

    a        [L, P+1, 2, D, r]   down-projections (sites: 0 = attn out,
    b        [L, P+1, 2, r, D]   1 = MLP), page 0 reserved as the ZERO
                                 page — the adapter-off identity delta
    a_scale  [L, P+1, 2]         int8 tier only: per-(layer, page,
    b_scale  [L, P+1, 2]         site) symmetric dequant scales

and keyed at trace time by a per-slot **adapter-page table** (i32
[max_slots], the ``paged_decode`` block-table pattern): each decode
tick gathers every slot's pages inside the layer scan and adds

    delta_attn = (attn_out @ A[:, 0]) @ B[:, 0]
    delta_mlp  = (ln_2_out @ A[:, 1]) @ B[:, 1]

so a batch can mix N distinct tenants' adapters in ONE compiled
program.  Adapter residency changes are ``.at[:, page].set`` buffer
updates (same shapes, same donation story: none — the pool persists
across ticks), so adapter churn, eviction and tenant-mix changes NEVER
recompile; the CompileWatcher guard on the decode loop enforces it.

Host side, :class:`AdapterPool` composes ``kv_slots.BlockAllocator``
(refcounts + quarantine + attribution journal, reused verbatim) with
the prefix cache's residency discipline: the pool itself holds ONE
reference on every resident page, each in-flight request holds one
more, and LRU eviction only ever considers pages at refcount 1 (cold —
no live request).  A fleet-wide adapter quarantine impounds the page
through the same ``release(quarantine=True)`` trust hook KV blocks
use, deferring until the last in-flight request drains.

Adapter weights are materialised DETERMINISTICALLY from the adapter id
(:func:`materialize_adapter`): every replica of a fleet uploads
bit-identical deltas for the same tenant, so fail-over and verdict
voting stay exact.

**Locality contract (tddl-lint ``adapter-locality``)**: the adapter
page-table row and any adapter PartitionSpec are spelled ONLY here —
:func:`adapter_page_row` / :func:`adapter_partition_specs` — and
imported by the scheduler/engine, never re-derived.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.serve.kv_slots import BlockAllocator

#: Reserved all-zeros pool page: slots without an adapter point here
#: and receive an exactly-zero delta.  Mirrors ``kv_slots.TRASH_BLOCK``.
ZERO_PAGE = 0

#: The two delta injection sites, in pool-axis order.
SITE_ATTN_OUT = 0
SITE_MLP = 1

#: Default init scale for materialised adapter weights — small enough
#: that a benign adapter perturbs rather than destroys the base model's
#: streams, large enough that two tenants' outputs measurably differ.
DEFAULT_INIT_SCALE = 0.02


def adapter_bytes_per_page(cfg: gpt2.GPT2Config, rank: int,
                           adapter_dtype: str = "model") -> int:
    """HBM bytes ONE pool page costs (both sides, both sites, all
    layers) — the unit the engine's headroom-gated sizing works in."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    elems = cfg.n_layer * 2 * cfg.n_embd * rank * 2      # a + b
    if adapter_dtype == "int8":
        return elems + cfg.n_layer * 2 * 2 * 4           # int8 + f32 scales
    import jax.numpy as jnp

    return elems * jnp.dtype(cfg.dtype).itemsize


def adapter_pool_bytes(cfg: gpt2.GPT2Config, pages: int, rank: int,
                       adapter_dtype: str = "model") -> int:
    """Total pool bytes for ``pages`` usable pages (+1 zero page)."""
    return (pages + 1) * adapter_bytes_per_page(cfg, rank, adapter_dtype)


def adapter_page_row(page_by_slot: Dict[int, int],
                     max_slots: int) -> np.ndarray:
    """THE one spelling of the per-slot adapter-page table row: i32
    [max_slots], ``ZERO_PAGE`` everywhere a slot carries no adapter.
    The scheduler feeds this (as a traced array) into every paged
    decode/prefill dispatch — values change per tick, the shape never
    does, so the compile-once pin holds."""
    row = np.full((max_slots,), ZERO_PAGE, np.int32)
    for slot, page in page_by_slot.items():
        row[slot] = page
    return row


def adapter_partition_specs() -> Tuple[Any, Any]:
    """PartitionSpecs for the (a, b) pool arrays: replicated — every
    chip serves every tenant, exactly like the KV pool.  Resolved only
    here (lint: adapter-locality) through the sharding registry; the
    engine applies them when a mesh is active."""
    from trustworthy_dl_tpu.core import sharding as shreg

    return shreg.replicated_spec(), shreg.replicated_spec()


def _adapter_seed(name: str) -> int:
    """Stable 64-bit seed from the adapter id — identical across
    processes, python versions and fleet replicas (``hash()`` is
    salted per process; this must not be)."""
    return int.from_bytes(
        hashlib.sha256(name.encode("utf-8")).digest()[:8], "little")


def materialize_adapter(name: str, cfg: gpt2.GPT2Config, rank: int,
                        init_scale: float = DEFAULT_INIT_SCALE
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic per-tenant weights: (a [L, 2, D, r], b [L, 2, r, D])
    f32, drawn from a generator seeded by the adapter id alone.  In a
    real deployment these load from a registry; here the registry is a
    seeded RNG so drills, benches and every fleet replica agree
    bit-for-bit on what tenant X's model delta IS."""
    rng = np.random.default_rng(_adapter_seed(name))
    d = cfg.n_embd
    a = rng.standard_normal((cfg.n_layer, 2, d, rank),
                            dtype=np.float32) * init_scale
    b = rng.standard_normal((cfg.n_layer, 2, rank, d),
                            dtype=np.float32) * init_scale
    return a, b


def quantize_adapter(a: np.ndarray, b: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray,
                                np.ndarray, np.ndarray]:
    """Symmetric int8 per-(layer, site) quantization of one adapter's
    (a, b): returns (a_q, a_scale [L, 2], b_q, b_scale).  The scales
    multiply back inside the low-rank matmul's f32 accumulator
    (``ops.fused_dequant_matmul.lowrank_delta`` — dequant in register,
    never a materialised f32 pool copy)."""
    out = []
    for w in (a, b):
        amax = np.max(np.abs(w), axis=(2, 3))            # [L, 2]
        scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.rint(w / scale[:, :, None, None]),
                    -127, 127).astype(np.int8)
        out.extend([q, scale])
    return tuple(out)


class AdapterPool:
    """Device pool arrays + the host-side page lifecycle.

    ``pages`` usable pages (ids [1, pages]; page 0 = the zero page).
    Every RESIDENT page carries one reference held by the pool itself
    (the residency ref); each admitted request holds one more.  LRU
    eviction considers only refcount-1 (cold) pages, so an adapter with
    in-flight traffic can never be evicted under it.  ``quarantine``
    impounds a page through ``BlockAllocator.release(quarantine=True)``
    — immediately when cold, else deferred to the last request release.
    """

    def __init__(self, cfg: gpt2.GPT2Config, rank: int, pages: int,
                 adapter_dtype: str = "model",
                 init_scale: float = DEFAULT_INIT_SCALE,
                 pages_gauge: Any = None, evictions_counter: Any = None,
                 trace: Any = None):
        import jax.numpy as jnp

        if pages < 1:
            raise ValueError(f"pages must be >= 1, got {pages}")
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.cfg = cfg
        self.rank = int(rank)
        self.pages = int(pages)
        self.adapter_dtype = adapter_dtype
        self.init_scale = float(init_scale)
        d = cfg.n_embd
        shape_a = (cfg.n_layer, pages + 1, 2, d, rank)
        shape_b = (cfg.n_layer, pages + 1, 2, rank, d)
        if adapter_dtype == "int8":
            self.a = jnp.zeros(shape_a, jnp.int8)
            self.b = jnp.zeros(shape_b, jnp.int8)
            # Scale 1.0 everywhere (incl. the zero page): dequantising
            # an untouched page is exactly 0.0 * 1.0 = 0.0.
            self.a_scale = jnp.ones((cfg.n_layer, pages + 1, 2),
                                    jnp.float32)
            self.b_scale = jnp.ones((cfg.n_layer, pages + 1, 2),
                                    jnp.float32)
        elif adapter_dtype == "model":
            self.a = jnp.zeros(shape_a, cfg.dtype)
            self.b = jnp.zeros(shape_b, cfg.dtype)
            self.a_scale = None
            self.b_scale = None
        else:
            raise ValueError(
                f"adapter_dtype must be 'model' or 'int8', got "
                f"{adapter_dtype!r}")
        # The SAME allocator class KV blocks use — refcounts, LIFO free
        # list over [1, pages], quarantine set, attribution journal.
        self.alloc = BlockAllocator(pages)
        self._page_of: Dict[str, int] = {}
        self._adapter_of: Dict[int, str] = {}
        self._lru: Dict[str, int] = {}
        self._clock = 0
        self._quarantined: Set[str] = set()
        self._impounded: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.uploads = 0
        self._pages_gauge = pages_gauge
        self._evictions_counter = evictions_counter
        self.trace = trace

    # -- device upload -----------------------------------------------------

    def _upload(self, name: str, page: int) -> None:
        """Materialise ``name``'s weights into pool page ``page`` — a
        pure buffer update (``.at[:, page].set``): shapes are static,
        so residency churn can never be a recompile."""
        import jax.numpy as jnp

        a_np, b_np = materialize_adapter(name, self.cfg, self.rank,
                                         self.init_scale)
        if self.adapter_dtype == "int8":
            a_q, a_s, b_q, b_s = quantize_adapter(a_np, b_np)
            self.a = self.a.at[:, page].set(jnp.asarray(a_q))
            self.b = self.b.at[:, page].set(jnp.asarray(b_q))
            self.a_scale = self.a_scale.at[:, page].set(jnp.asarray(a_s))
            self.b_scale = self.b_scale.at[:, page].set(jnp.asarray(b_s))
        else:
            self.a = self.a.at[:, page].set(
                jnp.asarray(a_np, self.a.dtype))
            self.b = self.b.at[:, page].set(
                jnp.asarray(b_np, self.b.dtype))
        self.uploads += 1

    # -- lifecycle ---------------------------------------------------------

    def _touch(self, name: str) -> None:
        self._clock += 1
        self._lru[name] = self._clock

    def _evict_cold(self) -> Optional[str]:
        """Evict the least-recently-used COLD resident (residency ref
        only — no in-flight request) and return its name, or None when
        every resident page is live (backpressure, not an error)."""
        for name in sorted(self._lru, key=self._lru.get):
            page = self._page_of[name]
            if self.alloc.refcount(page) == 1:
                self._page_of.pop(name)
                self._adapter_of.pop(page)
                self._lru.pop(name)
                self.alloc.release(page)           # residency ref -> freed
                self.evictions += 1
                if self._evictions_counter is not None:
                    self._evictions_counter.inc(tenant=name)
                return name
        return None

    def acquire(self, name: str) -> Optional[int]:
        """Claim one request reference on ``name``'s page, resolving
        residency on miss (alloc, else LRU-evict a cold tenant, else
        None = backpressure — the KV-block admission semantics).
        Quarantined adapters never resolve."""
        if name in self._quarantined:
            return None
        page = self._page_of.get(name)
        if page is not None:
            self.hits += 1
            self.alloc.incref(page)
            self._touch(name)
            self._set_gauge()
            return page
        self.misses += 1
        got = self.alloc.alloc(1)
        evicted: Optional[str] = None
        if got is None:
            evicted = self._evict_cold()
            if evicted is None:
                return None
            got = self.alloc.alloc(1)
            assert got is not None, "free page vanished after eviction"
        page = got[0]
        self._upload(name, page)
        self._page_of[name] = page
        self._adapter_of[page] = name
        self._touch(name)
        if self.trace is not None:
            from trustworthy_dl_tpu.obs.events import EventType

            self.trace.emit(EventType.ADAPTER_SWAP, adapter=name,
                            page=page, evicted=evicted)
        self.alloc.incref(page)                    # the request's ref
        self._set_gauge()
        return page

    def release(self, name: str) -> None:
        """Drop one request reference.  A quarantined adapter whose last
        request just drained has its residency ref released too — the
        page leaves the pool impounded (the KV trust hook, deferred)."""
        page = self._page_of.get(name)
        if page is None:
            # Already evicted-on-quarantine; nothing to balance — the
            # impound path released both refs.
            return
        self.alloc.release(page)
        if name in self._quarantined and self.alloc.refcount(page) == 1:
            self._impound(name, page)
        self._set_gauge()

    def _impound(self, name: str, page: int) -> None:
        self._page_of.pop(name)
        self._adapter_of.pop(page)
        self._lru.pop(name, None)
        self.alloc.release(page, quarantine=True)
        self._impounded[name] = page

    def quarantine(self, name: str) -> None:
        """Fleet-wide trust verdict against the ADAPTER: refuse every
        future resolve and impound its page — immediately when no
        request is in flight, else when the last one drains."""
        self._quarantined.add(name)
        page = self._page_of.get(name)
        if page is not None and self.alloc.refcount(page) == 1:
            self._impound(name, page)
        self._set_gauge()

    def unquarantine(self, name: str) -> None:
        """Operator action: lift the verdict.  The page (if impounded)
        returns to the free list; the adapter re-uploads on next use."""
        self._quarantined.discard(name)
        page = self._impounded.pop(name, None)
        if page is not None:
            self.alloc.unquarantine(page)
        self._set_gauge()

    # -- introspection -----------------------------------------------------

    @property
    def resident(self) -> Dict[str, int]:
        return dict(self._page_of)

    @property
    def quarantined(self) -> Set[str]:
        return set(self._quarantined)

    @property
    def pages_in_use(self) -> int:
        """Resident pages (incl. impounded) — what the
        ``tddl_serve_adapter_pages_in_use`` gauge exports."""
        return self.alloc.in_use + len(self._impounded)

    def is_quarantined(self, name: str) -> bool:
        return name in self._quarantined

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def _set_gauge(self) -> None:
        if self._pages_gauge is not None:
            self._pages_gauge.set(float(self.pages_in_use))

    def device_args(self) -> Tuple[Any, Any, Optional[Any], Optional[Any]]:
        """The traced pool-array arguments every paged serve dispatch
        threads: (a, b, a_scale, b_scale) — scales None on the model-
        dtype tier (structural pytree absence, the KVCache pattern)."""
        return self.a, self.b, self.a_scale, self.b_scale

    def metrics(self) -> Dict[str, Any]:
        return {
            "pages": self.pages,
            "pages_in_use": self.pages_in_use,
            "resident": len(self._page_of),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hit_rate(),
            "evictions": self.evictions,
            "uploads": self.uploads,
            "quarantined": sorted(self._quarantined),
        }

"""Continuous (iteration-level) batching over the slotted KV cache.

Orca's insight (Yu et al., OSDI '22): schedule at token granularity, not
request granularity — every iteration admits queued requests into free
slots, runs ONE fused decode step for all live sequences, and retires
finished ones immediately so their slots free up mid-flight.  Here that
schedule drives exactly two kinds of XLA programs:

* **prefill** — per newly admitted slot, over its prompt padded to a
  BUCKET length (``default_buckets``: powers of two), so the number of
  distinct prefill programs is bounded by the bucket count, not by the
  number of distinct prompt lengths ever seen;
* **decode** — one program for the engine's lifetime: [MAX_SLOTS] tokens
  in, [MAX_SLOTS] next tokens out, attending to the slot cache at per-slot
  offsets via the SAME ``models/generate._block_with_cache`` numerics the
  batch sampler uses (vector ``start``).  Admission/retirement never
  change its shapes, so it compiles exactly once.

Inactive slots still compute inside the decode step (static shapes); their
outputs are ignored and their garbage cache writes are masked out by
construction (see kv_slots module docstring).

Sampling is per-slot: greedy is a *traced* bool (mixing greedy and
temperature-sampled requests in one batch cannot recompile), temperature is
traced, and each slot consumes its own key stream — laid out exactly like
``models/generate.generate``'s (first token from the request key, step i
from ``split(fold_in(key, 1), max_new-1)[i-1]``), so a single-slot greedy
or sampled request reproduces the batch sampler token-for-token.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.models import generate as gen
from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.quant import int8 as q8
from trustworthy_dl_tpu.serve.kv_slots import SlotAllocator, SlotKV, init_slots

logger = logging.getLogger(__name__)


def default_buckets(max_seq: int, smallest: int = 16) -> Tuple[int, ...]:
    """Power-of-two prefill buckets up to ``max_seq`` (inclusive) — bounds
    the number of distinct prefill programs at O(log max_seq)."""
    out: List[int] = []
    b = smallest
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


def choose_bucket(buckets: Sequence[int], prompt_len: int) -> int:
    """Smallest bucket holding ``prompt_len`` tokens."""
    for b in sorted(buckets):
        if b >= prompt_len:
            return b
    raise ValueError(
        f"prompt of {prompt_len} tokens exceeds the largest prefill "
        f"bucket {max(buckets)}"
    )


# --------------------------------------------------------------------------
# Device programs.  Jitted lazily (first use) so importing this module never
# initialises a backend; donation of the big cache buffers is enabled only
# where XLA implements it (TPU) to keep CPU test runs warning-free.
# --------------------------------------------------------------------------


def _sample_tokens(logits: jax.Array, keys: jax.Array, temps: jax.Array,
                   greedy: jax.Array) -> jax.Array:
    """[B, V] -> [B] per-slot sampling.  ``greedy`` and ``temps`` are
    traced per-slot values — heterogeneous sampling settings share the one
    compiled program (unlike generate's static flags, which are uniform
    across its batch)."""
    greedy_tok = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    return jnp.where(greedy, greedy_tok, sampled)


def _logit_signals(logits: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-slot trust signals from the step's logits [B, V]: softmax
    entropy (collapse → ~0, garbage → ~log V) and top-1 logit margin.
    Computed in-step — the [B, V] logits never leave the device."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    entropy = -jnp.sum(p * logp, axis=-1)
    top2 = gen._exact_topk(logits, 2)[0]
    return entropy, top2[:, 0] - top2[:, 1]


def _pack_step_outputs(next_tok: jax.Array, ent: jax.Array,
                       margin: jax.Array) -> jax.Array:
    """[3, B] f32 host-facing pack — token ids, entropies, margins in ONE
    array so the scheduler pays a single device→host pull per step
    instead of three (and the copy can start asynchronously while the
    host books the previous tick).  Token ids survive the f32 round-trip
    exactly: vocab sizes (GPT-2: 50257) sit far below 2**24."""
    return jnp.stack([next_tok.astype(jnp.float32), ent, margin])


def _prefill_impl(cfg: gpt2.GPT2Config, slot_k: jax.Array, slot_v: jax.Array,
                  slot_k_scale: Any, slot_v_scale: Any,
                  view: Any, tokens: jax.Array, real_len: jax.Array,
                  slot: jax.Array, key: jax.Array, temp: jax.Array,
                  greedy: jax.Array):
    """Prefill one slot: run the stacked blocks over the bucketed prompt
    [P] (local cache, width P), write the K/V into the slot row, and sample
    the first token from the logits at ``real_len - 1`` (the prompt's last
    REAL position — the bucket padding beyond it is causally invisible to
    it and is overwritten before any decode step can attend to it).
    Host-facing scalars (token, entropy, margin) come back as one packed
    f32[3, 1] — a single sync per admission, not three.

    int8 KV (``slot_*_scale`` not None): the prompt prefills through a
    FULL-PRECISION local cache (prompt self-attention sees exact K/V, so
    the first sampled token is bit-identical to the dense engine's), and
    quantization happens once at the slot write — every scale in
    [0, bucket) is overwritten, so a reused slot cannot leak a stale
    scale (pinned by tests/test_quant.py)."""
    bucket = tokens.shape[0]
    local = gen.init_cache(cfg, 1, bucket)
    logits, local = gen._apply_with_cache(
        view, tokens[None, :], local, cfg, last_pos=real_len - 1
    )
    if slot_k_scale is not None:
        k_q, k_s = q8.quantize_kv(local.k)      # int8, f32 [L,1,H,bucket]
        v_q, v_s = q8.quantize_kv(local.v)
        new_k = jax.lax.dynamic_update_slice(
            slot_k, k_q, (0, slot, 0, 0, 0)
        )
        new_v = jax.lax.dynamic_update_slice(
            slot_v, v_q, (0, slot, 0, 0, 0)
        )
        new_ks = jax.lax.dynamic_update_slice(
            slot_k_scale, k_s, (0, slot, 0, 0)
        )
        new_vs = jax.lax.dynamic_update_slice(
            slot_v_scale, v_s, (0, slot, 0, 0)
        )
    else:
        new_k = jax.lax.dynamic_update_slice(
            slot_k, local.k.astype(slot_k.dtype), (0, slot, 0, 0, 0)
        )
        new_v = jax.lax.dynamic_update_slice(
            slot_v, local.v.astype(slot_v.dtype), (0, slot, 0, 0, 0)
        )
        new_ks, new_vs = slot_k_scale, slot_v_scale
    token = _sample_tokens(logits, key[None], temp[None], greedy[None])
    ent, margin = _logit_signals(logits)
    return new_k, new_v, new_ks, new_vs, _pack_step_outputs(token, ent,
                                                            margin)


def _decode_impl(cfg: gpt2.GPT2Config, slot_k: jax.Array, slot_v: jax.Array,
                 slot_k_scale: Any, slot_v_scale: Any,
                 view: Any, tokens: jax.Array, lengths: jax.Array,
                 keys: jax.Array, temps: jax.Array, greedy: jax.Array):
    """THE fused decode step: one token for every slot, live or not.
    ``lengths`` i32[MAX_SLOTS] are the per-slot write offsets — the vector
    ``start`` path of models/generate._block_with_cache, so serving decode
    and batch generate share one numerics source.  Host-facing outputs
    ride one packed f32[3, MAX_SLOTS] — a single pull per decode tick.
    int8 KV scales (None on the full-precision pool — the pytree branch
    is structural, each engine still compiles this exactly once) thread
    through the same cache."""
    cache = gen.KVCache(k=slot_k, v=slot_v, length=lengths,
                        k_scale=slot_k_scale, v_scale=slot_v_scale)
    logits, cache = gen._apply_with_cache(view, tokens[:, None], cache, cfg)
    next_tok = _sample_tokens(logits, keys, temps, greedy)
    ent, margin = _logit_signals(logits)
    return (_pack_step_outputs(next_tok, ent, margin), cache.k, cache.v,
            cache.k_scale, cache.v_scale)


_PROGRAMS: Dict[str, Any] = {}


def _programs() -> Dict[str, Any]:
    if not _PROGRAMS:
        # Donation covers the KV pool AND its scale planes (args 1-4);
        # donating a None (full-precision pool has no scales) donates
        # zero buffers, so one entry serves both tiers.
        donate = (1, 2, 3, 4) if jax.default_backend() == "tpu" else ()
        _PROGRAMS["prefill"] = jax.jit(
            _prefill_impl, static_argnums=(0,), donate_argnums=donate
        )
        _PROGRAMS["decode"] = jax.jit(
            _decode_impl, static_argnums=(0,), donate_argnums=donate
        )
    return _PROGRAMS


def request_key_stream(rng: jax.Array, max_new_tokens: int) -> np.ndarray:
    """uint32[max_new, 2] per-token sampling keys, laid out exactly like
    generate's stream: token 0 uses the request key itself, token i>0 uses
    ``split(fold_in(key, 1), max_new-1)[i-1]``."""
    keys = [np.asarray(rng, np.uint32)]
    if max_new_tokens > 1:
        rest = jax.random.split(jax.random.fold_in(rng, 1),
                                max_new_tokens - 1)
        keys.extend(np.asarray(rest, np.uint32))
    return np.stack(keys)


@dataclasses.dataclass
class SlotTask:
    """Host-side record of one in-flight sequence (scheduler's view)."""

    request_id: int
    prompt: np.ndarray            # i32[P] token ids
    max_new_tokens: int
    temperature: float
    keys: np.ndarray              # uint32[max_new, 2] sampling key stream
    eos_id: Optional[int] = None
    slot: int = -1
    emitted: List[int] = dataclasses.field(default_factory=list)
    next_token: int = -1          # last emitted token = next decode input
    entropies: List[float] = dataclasses.field(default_factory=list)
    margins: List[float] = dataclasses.field(default_factory=list)
    done: bool = False

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0

    def _record(self, token: int, ent: float, margin: float) -> None:
        self.emitted.append(token)
        self.next_token = token
        self.entropies.append(ent)
        self.margins.append(margin)
        if (len(self.emitted) >= self.max_new_tokens
                or (self.eos_id is not None and token == self.eos_id)):
            self.done = True


class ContinuousBatchingScheduler:
    """Slot admission + fused decode over the slotted KV cache.

    Host state: per-slot lengths (numpy — alloc/free never touch the
    device) and the live ``SlotTask`` table.  Device state: the SlotKV
    arrays, threaded functionally through the prefill/decode programs.
    """

    def __init__(self, params: Any, cfg: gpt2.GPT2Config, max_slots: int,
                 max_seq: int,
                 buckets: Optional[Sequence[int]] = None,
                 kv_dtype: str = "model", weight_dtype: str = "model",
                 view: Any = None):
        q8.validate_dtypes(kv_dtype, weight_dtype)
        self.cfg = cfg
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        if view is not None:
            # Pre-built decode view (the engine builds it once and shares
            # it with the parity probe — don't re-cast/re-quantize here).
            self.view = view
        elif weight_dtype == "int8":
            # Weight-only int8 (quant/int8.py): converted ONCE here; the
            # decode programs stream int8 weight bytes per token.
            self.view = q8.quantize_decode_view(params, cfg)
        else:
            # One numerics source with batch generate: the same pre-cast
            # decode view of the weights (bit-identical by construction
            # — see models/generate._decode_view).
            self.view = gen._decode_view(params, cfg)
        self.kv = init_slots(cfg, max_slots, max_seq,
                             kv_dtype=q8.resolve_kv_dtype(kv_dtype, cfg))
        self.allocator = SlotAllocator(max_slots)
        self.buckets = tuple(sorted(buckets or default_buckets(max_seq)))
        if max(self.buckets) > max_seq:
            raise ValueError("prefill bucket exceeds max_seq")
        self.lengths = np.zeros(max_slots, np.int32)
        self.tasks: Dict[int, SlotTask] = {}   # slot -> task
        self.max_seq = max_seq

    # -- admission ---------------------------------------------------------

    @property
    def has_free_slot(self) -> bool:
        return self.allocator.free_count > 0

    @property
    def active_count(self) -> int:
        return len(self.tasks)

    @property
    def occupancy(self) -> float:
        return len(self.tasks) / max(self.allocator.max_slots, 1)

    def admit(self, task: SlotTask) -> bool:
        """Claim a slot, prefill the prompt, emit the first token.
        Returns False (task untouched) when no slot is free."""
        total = len(task.prompt) + task.max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"request {task.request_id}: prompt+new = {total} exceeds "
                f"max_seq={self.max_seq}"
            )
        p = len(task.prompt)
        # Resolve the bucket BEFORE claiming a slot: with custom (smaller
        # than max_seq) buckets this can raise, and a slot claimed first
        # would leak — the allocator has no owner to free it.
        bucket = choose_bucket(self.buckets, p)
        slot = self.allocator.alloc()
        if slot is None:
            return False
        padded = np.zeros(bucket, np.int32)
        padded[:p] = task.prompt
        new_k, new_v, new_ks, new_vs, packed = _programs()["prefill"](
            self.cfg, self.kv.k, self.kv.v,
            self.kv.k_scale, self.kv.v_scale, self.view,
            jnp.asarray(padded), jnp.asarray(p, jnp.int32),
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(task.keys[0], jnp.uint32),
            jnp.asarray(max(task.temperature, 1e-6), jnp.float32),
            jnp.asarray(task.greedy),
        )
        self.kv = SlotKV(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
        task.slot = slot
        # ONE host sync per admission: token/entropy/margin land together.
        token, ent, margin = np.asarray(packed)[:, 0]
        task._record(int(token), float(ent), float(margin))
        self.lengths[slot] = p
        self.tasks[slot] = task
        return True

    # -- decode ------------------------------------------------------------

    def decode_tick(self) -> List[SlotTask]:
        """One fused decode step for every active slot; returns the tasks
        that received a token this tick (some may now be ``done``)."""
        if not self.tasks:
            return []
        ms = self.allocator.max_slots
        tokens = np.zeros(ms, np.int32)
        keys = np.zeros((ms, 2), np.uint32)
        temps = np.ones(ms, np.float32)
        greedy = np.ones(ms, bool)
        for slot, task in self.tasks.items():
            tokens[slot] = task.next_token
            # Next emission index is len(emitted) (< max_new while live).
            keys[slot] = task.keys[len(task.emitted)]
            temps[slot] = max(task.temperature, 1e-6)
            greedy[slot] = task.greedy
        packed, new_k, new_v, new_ks, new_vs = _programs()["decode"](
            self.cfg, self.kv.k, self.kv.v,
            self.kv.k_scale, self.kv.v_scale, self.view,
            jnp.asarray(tokens), jnp.asarray(self.lengths),
            jnp.asarray(keys), jnp.asarray(temps), jnp.asarray(greedy),
        )
        self.kv = SlotKV(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
        # ONE host pull for the whole tick (the cache stays on device);
        # the per-slot feed below reads the already-landed numpy rows.
        host = np.asarray(packed)
        next_tok, ent, margin = host[0], host[1], host[2]
        live = list(self.tasks.items())
        # The decode step wrote each live slot's token K/V at
        # lengths[slot]; batch the offset bump before the record feed.
        for slot, _ in live:
            self.lengths[slot] += 1
        ticked: List[SlotTask] = []
        for slot, task in live:
            task._record(int(next_tok[slot]), float(ent[slot]),
                         float(margin[slot]))
            ticked.append(task)
        return ticked

    # -- retirement --------------------------------------------------------

    def retire(self, task: SlotTask, quarantine: bool = False) -> None:
        """Release the task's slot (or quarantine it — flagged-anomalous
        output; the slot leaves the pool until an operator releases it)."""
        slot = task.slot
        if slot < 0 or self.tasks.get(slot) is not task:
            return
        del self.tasks[slot]
        if quarantine:
            self.allocator.quarantine(slot)
            logger.warning(
                "slot %d quarantined after request %d was flagged "
                "anomalous (%d slots remain in service)",
                slot, task.request_id, self.allocator.capacity,
            )
        else:
            self.allocator.free(slot)

    def decode_cache_size(self) -> int:
        """Number of compiled decode programs (the static-shape invariant
        says this is 1 for the scheduler's lifetime)."""
        prog = _PROGRAMS.get("decode")
        return prog._cache_size() if prog is not None else 0

"""Continuous (iteration-level) batching over the slotted KV cache.

Orca's insight (Yu et al., OSDI '22): schedule at token granularity, not
request granularity — every iteration admits queued requests into free
slots, runs ONE fused decode step for all live sequences, and retires
finished ones immediately so their slots free up mid-flight.  Here that
schedule drives exactly two kinds of XLA programs:

* **prefill** — per newly admitted slot, over its prompt padded to a
  BUCKET length (``default_buckets``: powers of two), so the number of
  distinct prefill programs is bounded by the bucket count, not by the
  number of distinct prompt lengths ever seen;
* **decode** — one program for the engine's lifetime: [MAX_SLOTS] tokens
  in, [MAX_SLOTS] next tokens out, attending to the slot cache at per-slot
  offsets via the SAME ``models/generate._block_with_cache`` numerics the
  batch sampler uses (vector ``start``).  Admission/retirement never
  change its shapes, so it compiles exactly once.

Inactive slots still compute inside the decode step (static shapes); their
outputs are ignored and their garbage cache writes are masked out by
construction (see kv_slots module docstring).

Sampling is per-slot: greedy is a *traced* bool (mixing greedy and
temperature-sampled requests in one batch cannot recompile), temperature is
traced, and each slot consumes its own key stream — laid out exactly like
``models/generate.generate``'s (first token from the request key, step i
from ``split(fold_in(key, 1), max_new-1)[i-1]``), so a single-slot greedy
or sampled request reproduces the batch sampler token-for-token.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.obs.compilewatch import guarded
from trustworthy_dl_tpu.models import generate as gen
from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.quant import int8 as q8
from trustworthy_dl_tpu.serve.adapters import ZERO_PAGE, adapter_page_row
from trustworthy_dl_tpu.serve.kv_slots import (
    BlockAllocator,
    PagedKV,
    PrefixCache,
    SlotAllocator,
    SlotKV,
    TRASH_BLOCK,
    blocks_for_span,
    init_paged_pool,
    init_slots,
    resolve_prefill_chunk,
    validate_paged_geometry,
)

logger = logging.getLogger(__name__)


def default_buckets(max_seq: int, smallest: int = 16) -> Tuple[int, ...]:
    """Power-of-two prefill buckets up to ``max_seq`` (inclusive) — bounds
    the number of distinct prefill programs at O(log max_seq)."""
    out: List[int] = []
    b = smallest
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


def choose_bucket(buckets: Sequence[int], prompt_len: int) -> int:
    """Smallest bucket holding ``prompt_len`` tokens."""
    for b in sorted(buckets):
        if b >= prompt_len:
            return b
    raise ValueError(
        f"prompt of {prompt_len} tokens exceeds the largest prefill "
        f"bucket {max(buckets)}"
    )


# --------------------------------------------------------------------------
# Device programs.  Jitted lazily (first use) so importing this module never
# initialises a backend; donation of the big cache buffers is enabled only
# where XLA implements it (TPU) to keep CPU test runs warning-free.
# --------------------------------------------------------------------------


def _sample_tokens(logits: jax.Array, keys: jax.Array, temps: jax.Array,
                   greedy: jax.Array) -> jax.Array:
    """[B, V] -> [B] per-slot sampling.  ``greedy`` and ``temps`` are
    traced per-slot values — heterogeneous sampling settings share the one
    compiled program (unlike generate's static flags, which are uniform
    across its batch)."""
    greedy_tok = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    return jnp.where(greedy, greedy_tok, sampled)


def _logit_signals(logits: jax.Array, attn_impl: str = "jnp"
                   ) -> Tuple[jax.Array, jax.Array]:
    """Per-slot trust signals from the step's logits [B, V]: softmax
    entropy (collapse → ~0, garbage → ~log V) and top-1 logit margin.
    Computed in-step — the [B, V] logits never leave the device.

    On the kernel path (``attn_impl`` "pallas"/"interpret" — the same
    static the paged-attention dispatch bakes in) the two reductions run
    as the fused ``ops.paged_attention.logit_trust_stats`` epilogue: one
    streaming pass over the vocab instead of a log_softmax pass, an
    exp/sum pass and a hierarchical top-k — the margin is bit-exact vs
    this jnp spelling, the entropy f32-epsilon-equal (pinned by
    tests/test_paged_attention.py)."""
    if attn_impl != "jnp":
        from trustworthy_dl_tpu.ops import paged_attention as pattn

        return pattn.logit_trust_stats(
            logits, interpret=(attn_impl == "interpret"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    entropy = -jnp.sum(p * logp, axis=-1)
    top2 = gen._exact_topk(logits, 2)[0]
    return entropy, top2[:, 0] - top2[:, 1]


def _pack_step_outputs(next_tok: jax.Array, ent: jax.Array,
                       margin: jax.Array) -> jax.Array:
    """[3, B] f32 host-facing pack — token ids, entropies, margins in ONE
    array so the scheduler pays a single device→host pull per step
    instead of three (and the copy can start asynchronously while the
    host books the previous tick).  Token ids survive the f32 round-trip
    exactly: vocab sizes (GPT-2: 50257) sit far below 2**24."""
    return jnp.stack([next_tok.astype(jnp.float32), ent, margin])


def _local_prefill(cfg: gpt2.GPT2Config, view: Any, tokens: jax.Array,
                   real_len: jax.Array, quantized: bool):
    """The parity-critical prologue BOTH pool layouts' prefill programs
    share (one spelling, so a numerics fix cannot diverge them): run the
    stacked blocks over the padded prompt through a FULL-PRECISION local
    cache — prompt self-attention sees exact K/V, so the first sampled
    token is bit-identical to the dense engine's — and sample logits at
    ``real_len - 1`` (the prompt's last REAL position; padding beyond it
    is causally invisible and overwritten before any decode step can
    attend to it).  ``quantized``: quantize once HERE, at the pool
    write — every scale in the written span is fresh, so a reused
    slot/block cannot leak a stale scale (pinned by tests/test_quant.py).
    Returns (logits, k_rows, v_rows, k_scales, v_scales) with scales None
    on the full-precision path."""
    local = gen.init_cache(cfg, 1, tokens.shape[0])
    logits, local = gen._apply_with_cache(
        view, tokens[None, :], local, cfg, last_pos=real_len - 1
    )
    if quantized:
        k_rows, k_s = q8.quantize_kv(local.k)   # int8, f32 [L,1,H,width]
        v_rows, v_s = q8.quantize_kv(local.v)
        return logits, k_rows, v_rows, k_s, v_s
    return logits, local.k, local.v, None, None


def _sample_pack(logits: jax.Array, key: jax.Array, temp: jax.Array,
                 greedy: jax.Array, attn_impl: str = "jnp") -> jax.Array:
    """Single-slot sampling tail: first token + trust signals as one
    packed f32[3, 1] — a single host sync per prefill, not three."""
    token = _sample_tokens(logits, key[None], temp[None], greedy[None])
    ent, margin = _logit_signals(logits, attn_impl)
    return _pack_step_outputs(token, ent, margin)


def _prefill_impl(cfg: gpt2.GPT2Config, slot_k: jax.Array, slot_v: jax.Array,
                  slot_k_scale: Any, slot_v_scale: Any,
                  view: Any, tokens: jax.Array, real_len: jax.Array,
                  slot: jax.Array, key: jax.Array, temp: jax.Array,
                  greedy: jax.Array):
    """Prefill one STRIPE slot: the shared ``_local_prefill`` prologue
    over the bucketed prompt [P], then write the K/V into the slot row."""
    logits, k_rows, v_rows, k_s, v_s = _local_prefill(
        cfg, view, tokens, real_len, slot_k_scale is not None
    )
    if k_s is not None:
        new_k = jax.lax.dynamic_update_slice(
            slot_k, k_rows, (0, slot, 0, 0, 0)
        )
        new_v = jax.lax.dynamic_update_slice(
            slot_v, v_rows, (0, slot, 0, 0, 0)
        )
        new_ks = jax.lax.dynamic_update_slice(
            slot_k_scale, k_s, (0, slot, 0, 0)
        )
        new_vs = jax.lax.dynamic_update_slice(
            slot_v_scale, v_s, (0, slot, 0, 0)
        )
    else:
        new_k = jax.lax.dynamic_update_slice(
            slot_k, k_rows.astype(slot_k.dtype), (0, slot, 0, 0, 0)
        )
        new_v = jax.lax.dynamic_update_slice(
            slot_v, v_rows.astype(slot_v.dtype), (0, slot, 0, 0, 0)
        )
        new_ks, new_vs = slot_k_scale, slot_v_scale
    return new_k, new_v, new_ks, new_vs, _sample_pack(logits, key, temp,
                                                      greedy)


def _decode_impl(cfg: gpt2.GPT2Config, slot_k: jax.Array, slot_v: jax.Array,
                 slot_k_scale: Any, slot_v_scale: Any,
                 view: Any, tokens: jax.Array, lengths: jax.Array,
                 keys: jax.Array, temps: jax.Array, greedy: jax.Array):
    """THE fused decode step: one token for every slot, live or not.
    ``lengths`` i32[MAX_SLOTS] are the per-slot write offsets — the vector
    ``start`` path of models/generate._block_with_cache, so serving decode
    and batch generate share one numerics source.  Host-facing outputs
    ride one packed f32[3, MAX_SLOTS] — a single pull per decode tick.
    int8 KV scales (None on the full-precision pool — the pytree branch
    is structural, each engine still compiles this exactly once) thread
    through the same cache."""
    cache = gen.KVCache(k=slot_k, v=slot_v, length=lengths,
                        k_scale=slot_k_scale, v_scale=slot_v_scale)
    logits, cache = gen._apply_with_cache(view, tokens[:, None], cache, cfg)
    next_tok = _sample_tokens(logits, keys, temps, greedy)
    ent, margin = _logit_signals(logits)
    return (_pack_step_outputs(next_tok, ent, margin), cache.k, cache.v,
            cache.k_scale, cache.v_scale)


def _paged_prefill_impl(cfg: gpt2.GPT2Config, pool_k: jax.Array,
                        pool_v: jax.Array, pool_ks: Any, pool_vs: Any,
                        view: Any, tokens: jax.Array, real_len: jax.Array,
                        block_ids: jax.Array, key: jax.Array,
                        temp: jax.Array, greedy: jax.Array,
                        attn_impl: str = "jnp"):
    """Fresh whole-prompt prefill into PAGED blocks: the SAME
    ``_local_prefill`` prologue as the stripe path — so prompt
    self-attention and the first sampled token match the stripe engine
    bit-for-bit, int8 tier included (quantization happens once at the
    block write) — then the local cache is re-laid-out block-wise and
    scattered into the pool at ``block_ids`` (i32[C/BLOCK]; entries past
    the slot's allocation point at the trash block).  Dispatched when
    the whole prompt fits one chunk and no prefix blocks were reused;
    longer or prefix-sharing prompts go through ``_paged_chunk_impl``."""
    c = tokens.shape[0]
    bsz = pool_k.shape[3]
    logits, k_rows, v_rows, k_s, v_s = _local_prefill(
        cfg, view, tokens, real_len, pool_ks is not None
    )
    if pool_ks is None:
        k_rows = k_rows.astype(pool_k.dtype)
        v_rows = v_rows.astype(pool_v.dtype)

    def to_blocks(a):                       # [L, 1, H, C, Dh] -> pool rows
        l, _, h, _, dh = a.shape
        a = a[:, 0].transpose(0, 2, 1, 3)                # [L, C, H, Dh]
        a = a.reshape(l, c // bsz, bsz, h, dh)
        return a.transpose(0, 1, 3, 2, 4)                # [L, nCB, H, B, Dh]

    def to_blocks_s(s):                     # [L, 1, H, C] -> scale rows
        l, _, h, _ = s.shape
        s = s[:, 0].transpose(0, 2, 1).reshape(l, c // bsz, bsz, h)
        return s.transpose(0, 1, 3, 2)                   # [L, nCB, H, B]

    new_k = pool_k.at[:, block_ids].set(to_blocks(k_rows))
    new_v = pool_v.at[:, block_ids].set(to_blocks(v_rows))
    if pool_ks is not None:
        new_ks = pool_ks.at[:, block_ids].set(to_blocks_s(k_s))
        new_vs = pool_vs.at[:, block_ids].set(to_blocks_s(v_s))
    else:
        new_ks, new_vs = pool_ks, pool_vs
    return new_k, new_v, new_ks, new_vs, _sample_pack(logits, key, temp,
                                                      greedy, attn_impl)


def _paged_chunk_impl(cfg: gpt2.GPT2Config, pool_k: jax.Array,
                      pool_v: jax.Array, pool_ks: Any, pool_vs: Any,
                      view: Any, tokens: jax.Array, table: jax.Array,
                      start: jax.Array, last_idx: jax.Array,
                      key: jax.Array, temp: jax.Array, greedy: jax.Array,
                      attn_impl: str = "jnp", adapter_impl: str = "jnp",
                      adapter_a: Any = None, adapter_b: Any = None,
                      adapter_as: Any = None, adapter_bs: Any = None,
                      apages: Any = None):
    """One CHUNK of a paged prefill: C prompt positions starting at
    ``start`` (block-aligned — a prefix-cache hit starts the suffix at a
    block boundary), attending to everything already in the slot's
    blocks (shared prefix included) through the gathered view and
    scattering its own K/V into the pool.  ``last_idx`` locates the
    prompt's last real position within this chunk; the sampled token is
    meaningful only on the final chunk (the host ignores it otherwise).
    One compiled program serves every chunk of every prompt.

    The trailing adapter args are the paged adapter pool's device sides
    plus the single-row page table ``apages`` i32[1] (serve/adapters.py)
    — None on adapterless engines, where they contribute zero pytree
    leaves and the trace is the pre-adapter one (bit-identity).
    ``adapter_impl`` (static, like ``attn_impl``) routes the per-layer
    page gather through the in-grid ``ops.adapter_delta`` kernel."""
    adapter = (None if adapter_a is None
               else (adapter_a, adapter_b, adapter_as, adapter_bs, apages))
    logits, new_k, new_v, new_ks, new_vs = gen._apply_with_cache_paged(
        view, tokens[None, :], pool_k, pool_v, pool_ks, pool_vs,
        table, start, cfg, last_pos=last_idx, attn_impl=attn_impl,
        adapter=adapter, adapter_impl=adapter_impl,
    )
    return new_k, new_v, new_ks, new_vs, _sample_pack(logits, key, temp,
                                                      greedy, attn_impl)


def _paged_decode_impl(cfg: gpt2.GPT2Config, pool_k: jax.Array,
                       pool_v: jax.Array, pool_ks: Any, pool_vs: Any,
                       view: Any, tokens: jax.Array, tables: jax.Array,
                       lengths: jax.Array, keys: jax.Array,
                       temps: jax.Array, greedy: jax.Array,
                       attn_impl: str = "jnp", adapter_impl: str = "jnp",
                       adapter_a: Any = None, adapter_b: Any = None,
                       adapter_as: Any = None, adapter_bs: Any = None,
                       apages: Any = None):
    """THE fused paged decode step: one token for every slot, live or
    not.  ``tables`` i32[MAX_SLOTS, NBPS] are the per-slot block maps
    (inactive rows all-trash — their garbage writes land in block 0) and
    ``lengths`` the per-slot write offsets; both are traced VALUES, so
    admission, retirement, block churn and prefix sharing never change
    the program.  The attention core is the same
    ``models/generate._block_with_cache`` the stripe engine and batch
    generate run, over the gathered view — bit-identical streams.

    The trailing adapter args are the paged adapter pool's device sides
    plus the per-slot page table ``apages`` i32[MAX_SLOTS]
    (serve/adapters.py; ZERO_PAGE rows add an exact-zero delta).  All
    traced values: adapter churn, eviction and tenant-mix changes never
    change this program.  None (adapterless engine) contributes zero
    pytree leaves — the compiled program IS the pre-adapter one."""
    adapter = (None if adapter_a is None
               else (adapter_a, adapter_b, adapter_as, adapter_bs, apages))
    logits, new_k, new_v, new_ks, new_vs = gen._apply_with_cache_paged(
        view, tokens[:, None], pool_k, pool_v, pool_ks, pool_vs,
        tables, lengths, cfg, attn_impl=attn_impl, adapter=adapter,
        adapter_impl=adapter_impl,
    )
    next_tok = _sample_tokens(logits, keys, temps, greedy)
    ent, margin = _logit_signals(logits, attn_impl)
    return (_pack_step_outputs(next_tok, ent, margin), new_k, new_v,
            new_ks, new_vs)


def _spec_draft_impl(cfg: gpt2.GPT2Config, pool_k: jax.Array,
                     pool_v: jax.Array, pool_ks: Any, pool_vs: Any,
                     view: Any, tokens: jax.Array, tables: jax.Array,
                     lengths: jax.Array, keys: jax.Array,
                     temps: jax.Array, greedy: jax.Array,
                     attn_impl: str = "jnp"):
    """ONE draft step of the speculative tick: the fused paged decode
    body run with the int8 DRAFT view (quant.draft_decode_view).  Same
    shapes and table/length discipline as ``_paged_decode_impl`` —
    block churn never recompiles it — but it returns the next tokens as
    a separate i32[R] array so the k-step draft chain feeds entirely
    on-device (no host sync until the verify pull), and it skips the
    entropy/margin reductions: draft logits never reach the trust
    monitor, only the verify pass's target logits do."""
    logits, new_k, new_v, new_ks, new_vs = gen._apply_with_cache_paged(
        view, tokens[:, None], pool_k, pool_v, pool_ks, pool_vs,
        tables, lengths, cfg, attn_impl=attn_impl,
    )
    next_tok = _sample_tokens(logits, keys, temps, greedy)
    return next_tok.astype(jnp.int32), new_k, new_v, new_ks, new_vs


def _spec_verify_impl(cfg: gpt2.GPT2Config, pool_k: jax.Array,
                      pool_v: jax.Array, pool_ks: Any, pool_vs: Any,
                      view: Any, tokens: jax.Array, tables: jax.Array,
                      lengths: jax.Array, keys: jax.Array,
                      temps: jax.Array, greedy: jax.Array,
                      attn_impl: str = "jnp", verify_impl: str = "jnp"):
    """THE batched verify: one MODEL-dtype forward over every slot's
    whole draft window ``tokens`` [R, k+1] = [last emitted, d_1 .. d_k],
    attending through the same paged cache at the PRE-draft lengths and
    OVERWRITING the draft positions with target-computed K/V (so every
    accepted position's cache entry is exactly what sequential
    single-token decode would have written — the int8 KV tier included,
    quantization happens at this write).  Per-position sampling uses
    the request's own key stream (``keys`` [R, k+1, 2], position i =
    emission index emitted+i), so the target tokens ARE the spec-off
    stream, greedy and sampled alike; per-position entropy/margin ride
    the packed output for the trust monitor and the near-tie acceptance
    rule.  Returns (packed f32[3, R, k+1], updated pool arrays).

    ``verify_impl`` (static, resolved per-program like ``attn_impl``)
    selects the tail: "jnp" materialises the [R, T, V] logits
    (``all_logits``) and re-reads them for the trust reductions;
    "pallas"/"interpret" runs the fused verify tail — the layer scan
    returns pre-``ln_f`` activations and ``gen.fused_verify_logits``
    streams each vocab tile ONCE for the logits write AND the
    entropy/margin fold (bit-identical logits, pinned epilogue
    algebra), so the all-positions projection never does a second
    HBM round-trip."""
    r, t = tokens.shape
    if verify_impl != "jnp":
        x, new_k, new_v, new_ks, new_vs = gen._apply_with_cache_paged(
            view, tokens, pool_k, pool_v, pool_ks, pool_vs,
            tables, lengths, cfg, hidden=True, attn_impl=attn_impl,
        )
        logits, ent, margin = gen.fused_verify_logits(
            view, x, cfg, interpret=(verify_impl == "interpret"))
        flat = logits.reshape(r * t, -1)
    else:
        logits, new_k, new_v, new_ks, new_vs = gen._apply_with_cache_paged(
            view, tokens, pool_k, pool_v, pool_ks, pool_vs,
            tables, lengths, cfg, all_logits=True, attn_impl=attn_impl,
        )
        flat = logits.reshape(r * t, -1)
        ent, margin = _logit_signals(flat, attn_impl)
    tok = _sample_tokens(flat, keys.reshape(r * t, 2),
                         jnp.repeat(temps, t), jnp.repeat(greedy, t))
    packed = jnp.stack([tok.astype(jnp.float32), ent, margin])
    return packed.reshape(3, r, t), new_k, new_v, new_ks, new_vs


_PROGRAMS: Dict[str, Any] = {}


def _programs() -> Dict[str, Any]:
    if not _PROGRAMS:
        # Donation covers the KV pool AND its scale planes (args 1-4);
        # donating a None (full-precision pool has no scales) donates
        # zero buffers, so one entry serves both tiers.
        donate = (1, 2, 3, 4) if jax.default_backend() == "tpu" else ()
        _PROGRAMS["prefill"] = jax.jit(
            _prefill_impl, static_argnums=(0,), donate_argnums=donate
        )
        _PROGRAMS["decode"] = jax.jit(
            _decode_impl, static_argnums=(0,), donate_argnums=donate
        )
        # The paged programs also take ``attn_impl`` (and, where the
        # program touches adapters or the verify tail, ``adapter_impl``/
        # ``verify_impl``) as STATIC keywords — the scheduler's
        # construction-resolved per-program paths: the jit cache keys on
        # them, so a kernel-on engine and a jnp-fallback engine with
        # identical geometry trace separate programs instead of silently
        # aliasing each other through this process-global table (bench
        # A/B arms and the kernel tests depend on that).
        _PROGRAMS["paged_prefill"] = jax.jit(
            _paged_prefill_impl, static_argnums=(0,),
            static_argnames=("attn_impl",), donate_argnums=donate
        )
        _PROGRAMS["paged_chunk"] = jax.jit(
            _paged_chunk_impl, static_argnums=(0,),
            static_argnames=("attn_impl", "adapter_impl"),
            donate_argnums=donate
        )
        _PROGRAMS["paged_decode"] = jax.jit(
            _paged_decode_impl, static_argnums=(0,),
            static_argnames=("attn_impl", "adapter_impl"),
            donate_argnums=donate
        )
        # Speculative tier: draft + verify get their OWN jit wrappers so
        # the fused-decode compile-once pin (decode_cache_size == 1)
        # stays meaningful — a spec engine runs exactly THREE
        # decode-phase programs: spec_draft (int8 view, dispatched k
        # times per tick), spec_verify (one batched model-dtype pass),
        # and paged_decode as the single-token fallback.
        _PROGRAMS["spec_draft"] = jax.jit(
            _spec_draft_impl, static_argnums=(0,),
            static_argnames=("attn_impl",), donate_argnums=donate
        )
        _PROGRAMS["spec_verify"] = jax.jit(
            _spec_verify_impl, static_argnums=(0,),
            static_argnames=("attn_impl", "verify_impl"),
            donate_argnums=donate
        )
    return _PROGRAMS


def request_key_stream(rng: jax.Array, max_new_tokens: int) -> np.ndarray:
    """uint32[max_new, 2] per-token sampling keys, laid out exactly like
    generate's stream: token 0 uses the request key itself, token i>0 uses
    ``split(fold_in(key, 1), max_new-1)[i-1]``."""
    keys = [np.asarray(rng, np.uint32)]
    if max_new_tokens > 1:
        rest = jax.random.split(jax.random.fold_in(rng, 1),
                                max_new_tokens - 1)
        keys.extend(np.asarray(rest, np.uint32))
    return np.stack(keys)


@dataclasses.dataclass
class SlotTask:
    """Host-side record of one in-flight sequence (scheduler's view)."""

    request_id: int
    prompt: np.ndarray            # i32[P] token ids
    max_new_tokens: int
    temperature: float
    keys: np.ndarray              # uint32[max_new, 2] sampling key stream
    eos_id: Optional[int] = None
    slot: int = -1
    emitted: List[int] = dataclasses.field(default_factory=list)
    next_token: int = -1          # last emitted token = next decode input
    entropies: List[float] = dataclasses.field(default_factory=list)
    margins: List[float] = dataclasses.field(default_factory=list)
    done: bool = False
    # Tokens this task gained in the CURRENT tick, in emission order —
    # set only by the speculative tick (which can emit several per
    # tick); None means "one token, read emitted[-1]" (the single-token
    # paths never pay the list).  The engine streams from it and the
    # normal decode path resets it so a fallback tick after a spec tick
    # can never replay stale tokens.
    tick_tokens: Optional[List[int]] = None
    # False = this task's completed prompt blocks are NEVER published to
    # the shared PrefixCache (the fleet's verdict-vote replays are
    # transient audits: they may READ cached prefixes, but must leave
    # the cache exactly as they found it).
    publish_prefix: bool = True
    # Adapter tier (serve/adapters.py): the tenant's adapter id (None =
    # base model) and the pool page admit() claimed for it — ZERO_PAGE
    # until admission, and again after retirement releases the claim.
    adapter: Optional[str] = None
    adapter_page: int = ZERO_PAGE

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0

    def _record(self, token: int, ent: float, margin: float) -> None:
        self.emitted.append(token)
        self.next_token = token
        self.entropies.append(ent)
        self.margins.append(margin)
        if (len(self.emitted) >= self.max_new_tokens
                or (self.eos_id is not None and token == self.eos_id)):
            self.done = True


class ContinuousBatchingScheduler:
    """Slot admission + fused decode over the slotted KV cache.

    Host state: per-slot lengths (numpy — alloc/free never touch the
    device) and the live ``SlotTask`` table.  Device state: the SlotKV
    arrays, threaded functionally through the prefill/decode programs.
    """

    def __init__(self, params: Any, cfg: gpt2.GPT2Config, max_slots: int,
                 max_seq: int,
                 buckets: Optional[Sequence[int]] = None,
                 kv_dtype: str = "model", weight_dtype: str = "model",
                 view: Any = None):
        q8.validate_dtypes(kv_dtype, weight_dtype)
        self.cfg = cfg
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        if view is not None:
            # Pre-built decode view (the engine builds it once and shares
            # it with the parity probe — don't re-cast/re-quantize here).
            self.view = view
        elif weight_dtype == "int8":
            # Weight-only int8 (quant/int8.py): converted ONCE here; the
            # decode programs stream int8 weight bytes per token.
            self.view = q8.quantize_decode_view(params, cfg)
        else:
            # One numerics source with batch generate: the same pre-cast
            # decode view of the weights (bit-identical by construction
            # — see models/generate._decode_view).
            self.view = gen._decode_view(params, cfg)
        self.kv = init_slots(cfg, max_slots, max_seq,
                             kv_dtype=q8.resolve_kv_dtype(kv_dtype, cfg))
        self.allocator = SlotAllocator(max_slots)
        self.buckets = tuple(sorted(buckets or default_buckets(max_seq)))
        if max(self.buckets) > max_seq:
            raise ValueError("prefill bucket exceeds max_seq")
        self.lengths = np.zeros(max_slots, np.int32)
        self.tasks: Dict[int, SlotTask] = {}   # slot -> task
        self.max_seq = max_seq
        # The stripe pool has no paged-attention kernel: the engine's
        # attention-path surface (gauge, summary) reads this uniformly.
        self.attn_impl = "jnp"
        self.spans: Any = None  # optional obs.spans.SpanTracker (engine)
        # Optional obs.compilewatch.CompileWatcher (engine): the fused
        # decode dispatch runs under its "serve_decode" guard, so a
        # post-warmup recompile storms at runtime, not just in pytest.
        self.compilewatch: Any = None
        # The stripe pool has no adapter tier (validate_adapters pins
        # adapter_rank > 0 to paged=True); the engine reads this
        # uniformly across both scheduler classes.
        self.adapters: Any = None

    def attribution_info(self, task: SlotTask) -> Dict[str, Any]:
        """What the attribution ledger records about THIS task's
        physical placement.  The stripe pool has no block table — the
        slot id is the whole story."""
        return {"layout": "stripe", "slot": int(task.slot),
                "block_ids": [], "prefix_block_ids": [],
                "prefix_publishers": {},
                "adapter": task.adapter,
                "adapter_page": int(task.adapter_page)}

    # -- admission ---------------------------------------------------------

    @property
    def has_free_slot(self) -> bool:
        return self.allocator.free_count > 0

    @property
    def active_count(self) -> int:
        return len(self.tasks)

    @property
    def occupancy(self) -> float:
        return len(self.tasks) / max(self.allocator.max_slots, 1)

    def admit(self, task: SlotTask) -> bool:
        """Claim a slot, prefill the prompt, emit the first token.
        Returns False (task untouched) when no slot is free."""
        total = len(task.prompt) + task.max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"request {task.request_id}: prompt+new = {total} exceeds "
                f"max_seq={self.max_seq}"
            )
        p = len(task.prompt)
        # Resolve the bucket BEFORE claiming a slot: with custom (smaller
        # than max_seq) buckets this can raise, and a slot claimed first
        # would leak — the allocator has no owner to free it.
        bucket = choose_bucket(self.buckets, p)
        slot = self.allocator.alloc()
        if slot is None:
            return False
        padded = np.zeros(bucket, np.int32)
        padded[:p] = task.prompt
        new_k, new_v, new_ks, new_vs, packed = _programs()["prefill"](
            self.cfg, self.kv.k, self.kv.v,
            self.kv.k_scale, self.kv.v_scale, self.view,
            jnp.asarray(padded), jnp.asarray(p, jnp.int32),
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(task.keys[0], jnp.uint32),
            jnp.asarray(max(task.temperature, 1e-6), jnp.float32),
            jnp.asarray(task.greedy),
        )
        self.kv = SlotKV(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
        task.slot = slot
        # ONE host sync per admission: token/entropy/margin land together.
        # tddl-lint: disable=host-sync — the intentional per-prefill pull
        token, ent, margin = np.asarray(packed)[:, 0]
        task._record(int(token), float(ent), float(margin))
        self.lengths[slot] = p
        self.tasks[slot] = task
        return True

    # -- decode ------------------------------------------------------------

    def decode_tick(self) -> List[SlotTask]:
        """One fused decode step for every active slot; returns the tasks
        that received a token this tick (some may now be ``done``)."""
        if not self.tasks:
            return []
        ms = self.allocator.max_slots
        tokens = np.zeros(ms, np.int32)
        keys = np.zeros((ms, 2), np.uint32)
        temps = np.ones(ms, np.float32)
        greedy = np.ones(ms, bool)
        for slot, task in self.tasks.items():
            tokens[slot] = task.next_token
            # Next emission index is len(emitted) (< max_new while live).
            keys[slot] = task.keys[len(task.emitted)]
            temps[slot] = max(task.temperature, 1e-6)
            greedy[slot] = task.greedy
        with guarded(self.compilewatch, "serve_decode"):
            packed, new_k, new_v, new_ks, new_vs = _programs()["decode"](
                self.cfg, self.kv.k, self.kv.v,
                self.kv.k_scale, self.kv.v_scale, self.view,
                jnp.asarray(tokens), jnp.asarray(self.lengths),
                jnp.asarray(keys), jnp.asarray(temps), jnp.asarray(greedy),
            )
        self.kv = SlotKV(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
        # ONE host pull for the whole tick (the cache stays on device);
        # the per-slot feed below reads the already-landed numpy rows.
        # tddl-lint: disable=host-sync — the tick's single intentional pull
        host = np.asarray(packed)
        next_tok, ent, margin = host[0], host[1], host[2]
        live = list(self.tasks.items())
        # The decode step wrote each live slot's token K/V at
        # lengths[slot]; batch the offset bump before the record feed.
        for slot, _ in live:
            self.lengths[slot] += 1
        ticked: List[SlotTask] = []
        for slot, task in live:
            task._record(int(next_tok[slot]), float(ent[slot]),
                         float(margin[slot]))
            ticked.append(task)
        return ticked

    # -- retirement --------------------------------------------------------

    def retire(self, task: SlotTask, quarantine: bool = False) -> None:
        """Release the task's slot (or quarantine it — flagged-anomalous
        output; the slot leaves the pool until an operator releases it)."""
        slot = task.slot
        if slot < 0 or self.tasks.get(slot) is not task:
            return
        del self.tasks[slot]
        if quarantine:
            self.allocator.quarantine(slot)
            logger.warning(
                "slot %d quarantined after request %d was flagged "
                "anomalous (%d slots remain in service)",
                slot, task.request_id, self.allocator.capacity,
            )
        else:
            self.allocator.free(slot)

    def release_quarantine(self, slot: int) -> None:
        """Operator action: return a quarantined slot to service."""
        self.allocator.release(slot)

    @property
    def tokens_in_flight(self) -> int:
        """Cached tokens currently backing live sequences."""
        return int(sum(int(self.lengths[s]) for s in self.tasks))

    def decode_cache_size(self) -> int:
        """Number of compiled decode programs (the static-shape invariant
        says this is 1 for the scheduler's lifetime)."""
        prog = _PROGRAMS.get("decode")
        return prog._cache_size() if prog is not None else 0

    def analyze_costs(self, ledger: Any,
                      memory: Optional[bool] = None) -> None:
        """Stamp this engine's serve programs into an obs.hbm.CostLedger
        (lowering-only by default — no extra backend compile)."""
        kv = self.kv
        ms = self.allocator.max_slots
        bucket = max(self.buckets)
        prog = _programs()
        pool = (kv.k, kv.v, kv.k_scale, kv.v_scale)
        ledger.analyze(
            "serve.prefill", prog["prefill"], self.cfg, *pool, self.view,
            jnp.zeros(bucket, jnp.int32), jnp.asarray(1, jnp.int32),
            jnp.asarray(0, jnp.int32), jnp.zeros(2, jnp.uint32),
            jnp.asarray(1.0, jnp.float32), jnp.asarray(True),
            memory=memory,
        )
        ledger.analyze(
            "serve.decode", prog["decode"], self.cfg, *pool, self.view,
            jnp.zeros(ms, jnp.int32), jnp.asarray(self.lengths),
            jnp.zeros((ms, 2), jnp.uint32), jnp.ones(ms, jnp.float32),
            jnp.ones(ms, bool), memory=memory,
        )


# ---------------------------------------------------------------------------
# Paged scheduler (the default data path since the paged-KV PR)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _PrefillProgress:
    """Host record of a slot mid-prefill (chunked): ``pos`` is the next
    prompt position to feed (block-aligned; starts past the shared
    prefix), advanced one chunk per engine tick so long prompts never
    head-of-line-block the fused decode step."""

    task: SlotTask
    pos: int
    plen: int
    shared_len: int


class PagedBatchingScheduler:
    """Continuous batching over the paged block pool (kv_slots.PagedKV).

    Same engine-facing surface as ``ContinuousBatchingScheduler`` (admit
    / decode_tick / retire / allocator / lengths / kv), different memory
    discipline: a request claims ``ceil((prompt + max_new) / BLOCK)``
    blocks at admission — occupancy is bounded by tokens in flight, not
    by request count — reusing cached prefix blocks where its prompt
    matches the radix cache (refcounted; prefill then covers only the
    unshared suffix, fed in bounded chunks interleaved with decode
    ticks).  Decode stays ONE compiled program for the scheduler's
    lifetime: block tables are traced gather indices.
    """

    def __init__(self, params: Any, cfg: gpt2.GPT2Config, max_slots: int,
                 max_seq: int,
                 buckets: Optional[Sequence[int]] = None,
                 kv_dtype: str = "model", weight_dtype: str = "model",
                 view: Any = None,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefill_chunk: Optional[int] = None,
                 spec_k: int = 0, draft_view: Any = None,
                 attn_impl: str = "auto",
                 adapters: Any = None):
        q8.validate_dtypes(kv_dtype, weight_dtype)
        validate_paged_geometry(max_seq, block_size, num_blocks,
                                prefill_chunk)
        if max_seq > cfg.n_positions:
            # The stripe pool gets this from init_slots; the paged pool
            # allocates per-block, so check the LOGICAL depth here — a
            # sequence past the position table would silently gather
            # clamped position embeddings, not raise.
            raise ValueError(
                f"max_seq={max_seq} exceeds the model's position table "
                f"(n_positions={cfg.n_positions})"
            )
        self.cfg = cfg
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        if view is not None:
            self.view = view
        elif weight_dtype == "int8":
            self.view = q8.quantize_decode_view(params, cfg)
        else:
            self.view = gen._decode_view(params, cfg)
        self.block_size = block_size
        self.nbps = max_seq // block_size          # blocks per slot table
        self.num_blocks = (num_blocks if num_blocks is not None
                           else max_slots * self.nbps)
        if prefill_chunk is None and kv_dtype == "int8":
            # Full-prompt prefill by default under int8 KV: a chunked
            # continuation attends to the previous chunk's
            # already-QUANTIZED blocks, while the stripe int8 engine
            # runs the whole prompt through a full-precision local
            # cache — bit-parity with it holds only on the one-chunk
            # path.  An explicit prefill_chunk opts back into chunking
            # (near-tie caveat in README §Serving; prefix-cache hits
            # read quantized prefix blocks the same way).
            self.chunk = max_seq
        else:
            self.chunk = resolve_prefill_chunk(max_seq, block_size,
                                               prefill_chunk)
        self.kv = init_paged_pool(cfg, self.num_blocks, block_size,
                                  kv_dtype=q8.resolve_kv_dtype(kv_dtype,
                                                               cfg))
        # Serving-kernel paths, resolved ONCE here (never inside a
        # traced program) and baked into the paged programs as statics:
        # "pallas" (compiled Mosaic kernels, TPU), "interpret" (same
        # kernels through the Pallas interpreter — tests), or "jnp"
        # (the gather/materialise fallbacks, the default wherever the
        # gate is off or the geometry cannot tile).  One dict covers the
        # whole tier — decode attention, chunked-prefill attention, the
        # fused verify tail, the in-grid adapter gather — each program
        # downgrading independently (ops/paged_attention.py documents
        # the gate TDDL_PAGED_ATTN and the per-program tiling rules);
        # ``self.attn_impl`` stays the decode path, the tier's anchor.
        from trustworthy_dl_tpu.ops import paged_attention as pattn

        self.attn_impls = pattn.resolve_attn_impls(
            attn_impl, head_dim=cfg.n_embd // cfg.n_head,
            block_size=block_size,
            kv_dtype=q8.resolve_kv_dtype(kv_dtype, cfg),
            n_embd=cfg.n_embd,
            adapter_rank=getattr(adapters, "rank", None),
        )
        self.attn_impl = self.attn_impls["decode"]
        self.allocator = SlotAllocator(max_slots)  # decode rows
        self.blocks = BlockAllocator(self.num_blocks)
        self.prefix = (PrefixCache(block_size, self.blocks)
                       if prefix_cache else None)
        # ``buckets`` is the stripe engine's prefill-program bound; the
        # paged engine has ONE chunk program, but the engine's submit
        # contract (reject unprefillable prompts up front) reads
        # max(buckets) — honour a caller-provided cap, default max_seq.
        self.buckets = tuple(sorted(buckets or (max_seq,)))
        if max(self.buckets) > max_seq:
            raise ValueError("prefill bucket exceeds max_seq")
        self.lengths = np.zeros(max_slots, np.int32)
        self.tables: List[List[int]] = [[] for _ in range(max_slots)]
        self.tasks: Dict[int, SlotTask] = {}       # slot -> task
        self._prefill: Dict[int, _PrefillProgress] = {}
        self._q_blocks_by_slot: Dict[int, List[int]] = {}
        # slot -> attribution snapshot taken at admission (block table,
        # prefix reuse, publishers) — the ledger reads it at retirement,
        # AFTER retire() has already cleared the live table.
        self._attrib: Dict[int, Dict[str, Any]] = {}
        self.spans: Any = None  # optional obs.spans.SpanTracker (engine)
        # Optional obs.compilewatch.CompileWatcher (engine) — the fused
        # paged decode dispatch runs under its "serve_decode" guard.
        self.compilewatch: Any = None
        # Optional serve.adapters.AdapterPool (engine-built, HBM-gated):
        # the second paged resource.  admit() claims a page per
        # adapter-carrying request with the SAME backpressure-and-unwind
        # semantics as KV blocks; every decode tick threads the pool
        # sides plus the per-slot page row into the fused programs as
        # traced values.  None = adapterless engine: the device programs
        # are called without adapter args and trace bit-identically to
        # the pre-adapter ones.
        self.adapters: Any = adapters
        # slot -> block ids the slot's request PUBLISHED to the prefix
        # cache (newly cached at its prefill completion) — what a
        # quarantine-retire must purge from the cache.
        self._published: Dict[int, List[int]] = {}
        self.max_seq = max_seq
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        # -- speculative decoding (spec_k > 0; README §Serving) --------
        # Per tick: draft spec_k tokens per active slot with the int8
        # ``draft_view`` (k dispatches of ONE compiled draft program,
        # fed on-device), verify the whole window in ONE batched
        # model-dtype forward, accept the longest draft/target-matching
        # prefix, and roll back rejected draft KV by releasing the
        # speculative COW block claims (host refcount decrement).
        self.spec_k = int(spec_k)
        self.draft_view = draft_view
        if self.spec_k > 0 and draft_view is None:
            raise ValueError(
                "spec_k > 0 needs a draft_view (the int8 weight tier; "
                "quant.draft_decode_view — the engine builds it)"
            )
        self._spec_claims: Dict[int, List[int]] = {}
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_near_tie_flips = 0
        self.spec_ticks = 0
        self.spec_fallback_ticks = 0
        # Host-observed wall time inside the two spec phases (the draft
        # chain syncs at the token pull, the verify at the packed pull)
        # — the bench A/B's draft/verify tick fractions.
        self.spec_draft_s = 0.0
        self.spec_verify_s = 0.0
        # Host-observed wall time advancing prefills (chunk dispatches
        # plus the final chunk's packed pull) — the bench prefill-arm
        # A/B's ``prefill_chunk_fraction`` numerator.
        self.prefill_chunk_s = 0.0

    # -- admission ---------------------------------------------------------

    @property
    def has_free_slot(self) -> bool:
        return self.allocator.free_count > 0

    @property
    def active_count(self) -> int:
        return len(self.tasks)

    @property
    def occupancy(self) -> float:
        return len(self.tasks) / max(self.allocator.max_slots, 1)

    @property
    def tokens_in_flight(self) -> int:
        """Cached tokens currently backing live sequences (decode-phase
        lengths plus prefill progress, shared prefix included)."""
        total = sum(int(self.lengths[s]) for s in self.tasks
                    if s not in self._prefill)
        total += sum(min(st.pos, st.plen) for st in self._prefill.values())
        return int(total)

    @property
    def blocks_in_use(self) -> int:
        return self.blocks.in_use

    def attribution_info(self, task: SlotTask) -> Dict[str, Any]:
        """The admission-time placement snapshot for the attribution
        ledger: physical block table, which blocks came from the prefix
        cache, and their publisher request ids.  Read it BEFORE
        ``retire`` (which drops the snapshot with the row)."""
        info = self._attrib.get(task.slot)
        if info is None or self.tasks.get(task.slot) is not task:
            return {"layout": "paged", "slot": int(task.slot),
                    "block_ids": [], "prefix_block_ids": [],
                    "prefix_publishers": {},
                    "adapter": task.adapter,
                    "adapter_page": int(task.adapter_page)}
        return {**info, "prefix_publishers": dict(info["prefix_publishers"]),
                "block_ids": list(info["block_ids"]),
                "prefix_block_ids": list(info["prefix_block_ids"])}

    def admit(self, task: SlotTask) -> bool:
        """Claim a decode row and the request's blocks (reusing cached
        prefix blocks), enqueue its chunked prefill.  Pure host work — no
        device program runs until the next ``decode_tick``.  Returns
        False (task untouched) when no row is free or the block pool
        cannot cover the request even after prefix-cache eviction
        (out-of-blocks backpressure)."""
        p = len(task.prompt)
        total = p + task.max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"request {task.request_id}: prompt+new = {total} exceeds "
                f"max_seq={self.max_seq}"
            )
        slot = self.allocator.alloc()
        if slot is None:
            return False
        shared: List[int] = []
        if self.prefix is not None:
            self.prefix_lookups += 1
            import time as _time

            t0 = _time.perf_counter()
            # Cap at (p-1)//block: at least one prompt token always
            # prefills, so the first sampled token has fresh logits.
            shared = self.prefix.lookup(task.prompt.tolist(),
                                        (p - 1) // self.block_size)
            if self.spans is not None:
                self.spans.add("serve.prefix_lookup", t0,
                               _time.perf_counter(), kind="serve",
                               request_id=task.request_id,
                               hit=bool(shared), shared_blocks=len(shared))
        n_total = -(-total // self.block_size)             # ceil
        n_new = n_total - len(shared)
        fresh = self.blocks.alloc(n_new)
        if fresh is None and self.prefix is not None:
            self.prefix.evict(n_new - self.blocks.free_count)
            fresh = self.blocks.alloc(n_new)
        if fresh is None:
            for b in shared:
                self.blocks.release(b)
            self.allocator.free(slot)
            return False
        if task.adapter is not None and self.adapters is not None:
            # Second paged resource: claim the tenant's adapter page with
            # the SAME backpressure-and-unwind semantics as the KV blocks
            # above — a full pool (every resident page live) or a
            # quarantined adapter refuses admission and the task stays
            # queued, untouched.
            page = self.adapters.acquire(task.adapter)
            if page is None:
                for b in shared + fresh:
                    self.blocks.release(b)
                self.allocator.free(slot)
                return False
            task.adapter_page = page
        if shared:
            self.prefix_hits += 1
            self.prefix_tokens_reused += len(shared) * self.block_size
        self.tables[slot] = shared + fresh
        self.lengths[slot] = 0
        task.slot = slot
        self.tasks[slot] = task
        self._attrib[slot] = {
            "layout": "paged", "slot": slot,
            "block_ids": list(shared + fresh),
            "prefix_block_ids": list(shared),
            "prefix_publishers": (self.prefix.publishers(shared)
                                  if self.prefix is not None else {}),
            "adapter": task.adapter,
            "adapter_page": int(task.adapter_page),
        }
        self._prefill[slot] = _PrefillProgress(
            task=task, pos=len(shared) * self.block_size, plen=p,
            shared_len=len(shared) * self.block_size,
        )
        return True

    # -- decode ------------------------------------------------------------

    def _table_row(self, slot: int) -> np.ndarray:
        row = np.full(self.nbps, TRASH_BLOCK, np.int32)
        t = self.tables[slot]
        row[:len(t)] = t
        return row

    def _advance_prefill(self, slot: int) -> Optional[SlotTask]:
        """Run ONE chunk for a prefilling slot; returns the task when the
        chunk completed its prompt (first token recorded)."""
        st = self._prefill[slot]
        task = st.task
        c = self.chunk
        import time as _time

        t_chunk = _time.perf_counter()
        n_real = min(st.plen - st.pos, c)
        chunk = np.zeros(c, np.int32)
        chunk[:n_real] = task.prompt[st.pos:st.pos + n_real]
        final = st.pos + n_real >= st.plen
        kv = self.kv
        if st.pos == 0 and st.plen <= c and task.adapter_page == ZERO_PAGE:
            # Whole prompt in one chunk, nothing shared: full-precision
            # local prefill (stripe-engine numerics, bit-for-bit — the
            # int8 tier quantizes once at the block write).  An
            # adapter-carrying request takes the chunk path below
            # instead: its prompt must run through the adapter-delta'd
            # layers, and there is no stripe twin to hold parity with.
            ids = np.full(c // self.block_size, TRASH_BLOCK, np.int32)
            n_ids = min(len(self.tables[slot]), len(ids))
            ids[:n_ids] = self.tables[slot][:n_ids]
            new_k, new_v, new_ks, new_vs, packed = _programs()[
                "paged_prefill"](
                self.cfg, kv.k, kv.v, kv.k_scale, kv.v_scale, self.view,
                jnp.asarray(chunk), jnp.asarray(st.plen, jnp.int32),
                jnp.asarray(ids),
                jnp.asarray(task.keys[0], jnp.uint32),
                jnp.asarray(max(task.temperature, 1e-6), jnp.float32),
                jnp.asarray(task.greedy),
                attn_impl=self.attn_impl,
            )
        else:
            last_idx = int(np.clip(st.plen - 1 - st.pos, 0, c - 1))
            extra: Dict[str, Any] = {}
            if self.adapters is not None:
                a, b, a_s, b_s = self.adapters.device_args()
                extra = dict(
                    adapter_a=a, adapter_b=b, adapter_as=a_s,
                    adapter_bs=b_s,
                    apages=jnp.asarray([task.adapter_page], jnp.int32),
                )
            new_k, new_v, new_ks, new_vs, packed = _programs()[
                "paged_chunk"](
                self.cfg, kv.k, kv.v, kv.k_scale, kv.v_scale, self.view,
                jnp.asarray(chunk), jnp.asarray(self._table_row(slot)[None]),
                jnp.asarray(st.pos, jnp.int32),
                jnp.asarray(last_idx, jnp.int32),
                jnp.asarray(task.keys[0], jnp.uint32),
                jnp.asarray(max(task.temperature, 1e-6), jnp.float32),
                jnp.asarray(task.greedy),
                attn_impl=self.attn_impls["prefill"],
                adapter_impl=self.attn_impls["adapter"],
                **extra,
            )
        self.kv = PagedKV(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
        self.prefill_chunk_s += _time.perf_counter() - t_chunk
        if self.spans is not None:
            self.spans.add("serve.prefill_chunk", t_chunk,
                           _time.perf_counter(), kind="serve",
                           request_id=task.request_id, pos=int(st.pos),
                           tokens=int(n_real), final=bool(final))
        if not final:
            st.pos += c
            return None
        # tddl-lint: disable=host-sync — the intentional per-prefill pull
        token, ent, margin = np.asarray(packed)[:, 0]
        task._record(int(token), float(ent), float(margin))
        self.lengths[slot] = st.plen
        del self._prefill[slot]
        if self.prefix is not None and task.publish_prefix:
            # The prompt's FULL blocks are now authoritative in the pool
            # — publish them so later same-prefix requests skip their
            # prefill.  (Generated tokens are never cached; a
            # publish_prefix=False audit replay caches nothing at all.)
            # The newly cached ids are remembered: if THIS request is
            # later flagged, its publications must leave the cache with
            # it.
            self._published[slot] = self.prefix.insert(
                task.prompt.tolist(),
                self.tables[slot][:st.plen // self.block_size],
                publisher=task.request_id,
            )
        return task

    def decode_tick(self) -> List[SlotTask]:
        """One engine tick: advance every mid-prefill slot by ONE chunk
        (prompts finishing their last chunk emit their first token), then
        run the fused decode step for every decode-phase slot.  Returns
        the tasks that received a token this tick."""
        ticked: List[SlotTask] = []
        finished_prefill = set()
        for slot in sorted(self._prefill):
            done = self._advance_prefill(slot)
            if done is not None:
                finished_prefill.add(slot)
                ticked.append(done)
        active = {s: t for s, t in self.tasks.items()
                  if s not in self._prefill and not t.done
                  and s not in finished_prefill}
        if not active:
            return ticked
        if self.spec_k > 0 and any(
                t.max_new_tokens - len(t.emitted) > 1
                for t in active.values()):
            ticked.extend(self._spec_tick(active))
            return ticked
        if self.spec_k > 0:
            # Every live slot has exactly one token left: drafting would
            # be pure waste — dispatch the single-token FALLBACK program
            # (today's fused decode, the third compiled decode-phase
            # program of a spec engine).
            self.spec_fallback_ticks += 1
        ms = self.allocator.max_slots
        tokens = np.zeros(ms, np.int32)
        keys = np.zeros((ms, 2), np.uint32)
        temps = np.ones(ms, np.float32)
        greedy = np.ones(ms, bool)
        tables = np.full((ms, self.nbps), TRASH_BLOCK, np.int32)
        for slot, task in active.items():
            tokens[slot] = task.next_token
            keys[slot] = task.keys[len(task.emitted)]
            temps[slot] = max(task.temperature, 1e-6)
            greedy[slot] = task.greedy
            tables[slot] = self._table_row(slot)
        kv = self.kv
        extra: Dict[str, Any] = {}
        if self.adapters is not None:
            # The adapter pool rides every tick: pool sides as traced
            # arrays, per-slot pages as ONE traced i32[MAX_SLOTS] row
            # (inactive and adapterless slots at ZERO_PAGE — an exact
            # zero delta).  Residency churn changes buffer VALUES only;
            # the program under the compile-once guard never changes.
            a, b, a_s, b_s = self.adapters.device_args()
            row = adapter_page_row(
                {s: t.adapter_page for s, t in active.items()}, ms)
            extra = dict(adapter_a=a, adapter_b=b, adapter_as=a_s,
                         adapter_bs=b_s, apages=jnp.asarray(row))
        with guarded(self.compilewatch, "serve_decode"):
            packed, new_k, new_v, new_ks, new_vs = \
                _programs()["paged_decode"](
                    self.cfg, kv.k, kv.v, kv.k_scale, kv.v_scale,
                    self.view,
                    jnp.asarray(tokens), jnp.asarray(tables),
                    jnp.asarray(self.lengths),
                    jnp.asarray(keys), jnp.asarray(temps),
                    jnp.asarray(greedy),
                    attn_impl=self.attn_impl,
                    adapter_impl=self.attn_impls["adapter"],
                    **extra,
                )
        self.kv = PagedKV(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
        # tddl-lint: disable=host-sync — the tick's single intentional pull
        host = np.asarray(packed)
        next_tok, ent, margin = host[0], host[1], host[2]
        for slot in active:
            self.lengths[slot] += 1
        for slot, task in active.items():
            task.tick_tokens = None   # single-token tick: emitted[-1]
            task._record(int(next_tok[slot]), float(ent[slot]),
                         float(margin[slot]))
            ticked.append(task)
        return ticked

    def _spec_tick(self, active: Dict[int, SlotTask]) -> List[SlotTask]:
        """One speculative tick for every decode-phase slot: claim the
        draft window's blocks, draft ``spec_k`` tokens with the int8
        view (k dispatches of the compiled draft program, chained
        on-device), verify the whole window in ONE batched model-dtype
        forward (which also overwrites the draft KV with target-exact
        values), accept per slot the longest prefix where the draft
        matched the target (greedy near-ties under the parity-probe
        margin tolerated as draft-token flips), then release the claims
        — rejection is a refcount decrement plus NOT advancing the
        host-side length past the accepted prefix."""
        import time as _time

        k = self.spec_k
        ms = self.allocator.max_slots
        tokens0 = np.zeros(ms, np.int32)
        temps = np.ones(ms, np.float32)
        greedy = np.ones(ms, bool)
        tables = np.full((ms, self.nbps), TRASH_BLOCK, np.int32)
        keys = np.zeros((ms, k + 1, 2), np.uint32)
        # Per-slot PROPOSABLE draft count: a slot with r tokens of
        # budget left can emit at most r this tick, of which at most
        # r-1 can come from drafts (the verify bonus is always one of
        # the emissions) — counting the full k for it would make
        # accepted_rate conflate budget truncation with real draft/
        # target disagreement, and the sentinel would page a workload
        # shift toward short requests as a draft-quality regression.
        proposable: Dict[int, int] = {}
        for slot, task in active.items():
            tokens0[slot] = task.next_token
            temps[slot] = max(task.temperature, 1e-6)
            greedy[slot] = task.greedy
            tables[slot] = self._table_row(slot)
            base = len(task.emitted)
            proposable[slot] = min(k, task.max_new_tokens - base - 1)
            for i in range(k + 1):
                # Emission index base+i — the SAME key spec-off decode
                # would consume there (over-draft past the request's
                # budget clamps; those emissions are discarded anyway).
                keys[slot, i] = task.keys[
                    min(base + i, task.max_new_tokens - 1)]
            claimed = blocks_for_span(
                self.tables[slot], self.block_size,
                int(self.lengths[slot]), int(self.lengths[slot]) + k + 1,
            )
            self.blocks.claim_speculative(claimed)
            self._spec_claims[slot] = claimed
        lengths0 = self.lengths.copy()
        prog = _programs()
        kv = self.kv
        pool = (kv.k, kv.v, kv.k_scale, kv.v_scale)
        tables_dev = jnp.asarray(tables)
        temps_dev = jnp.asarray(temps)
        greedy_dev = jnp.asarray(greedy)
        t0 = _time.perf_counter()
        cur = jnp.asarray(tokens0)
        draft_dev = []
        for j in range(k):
            with guarded(self.compilewatch, "serve_spec_draft"):
                cur, pk, pv, pks, pvs = prog["spec_draft"](
                    self.cfg, *pool, self.draft_view, cur, tables_dev,
                    jnp.asarray(lengths0 + j), jnp.asarray(keys[:, j]),
                    temps_dev, greedy_dev, attn_impl=self.attn_impl,
                )
            pool = (pk, pv, pks, pvs)
            draft_dev.append(cur)
        # ONE host sync point for the whole draft chain: the k draft
        # token rows land together and become the verify inputs.
        # tddl-lint: disable=host-sync — the draft chain's one deliberate sync
        drafts = np.stack([np.asarray(d) for d in draft_dev], axis=1)
        t1 = _time.perf_counter()
        self.spec_draft_s += t1 - t0
        tokens_v = np.concatenate([tokens0[:, None], drafts], axis=1)
        with guarded(self.compilewatch, "serve_spec_verify"):
            packed, pk, pv, pks, pvs = prog["spec_verify"](
                self.cfg, *pool, self.view, jnp.asarray(tokens_v),
                tables_dev, jnp.asarray(lengths0), jnp.asarray(keys),
                temps_dev, greedy_dev, attn_impl=self.attn_impl,
                verify_impl=self.attn_impls["verify"],
            )
        self.kv = PagedKV(k=pk, v=pv, k_scale=pks, v_scale=pvs)
        # tddl-lint: disable=host-sync — verify lands all windows in one pull
        host = np.asarray(packed)                     # [3, ms, k+1]
        t2 = _time.perf_counter()
        self.spec_verify_s += t2 - t1
        self.spec_ticks += 1
        ticked: List[SlotTask] = []
        tick_proposed = tick_accepted = 0
        for slot, task in active.items():
            tgt = host[0, slot]
            ent = host[1, slot]
            margin = host[2, slot]
            d = drafts[slot]
            # Acceptance walk: position i emits the TARGET token v_{i+1}
            # (bit-identical to spec-off by construction — same logits,
            # same key); the walk continues past i only when the draft
            # guessed the emitted token, so every later target token was
            # conditioned on the true stream.  A greedy mismatch under a
            # near-tie top-1 margin (< the int8 parity probe's
            # tolerance) emits the DRAFT token instead and continues —
            # the same numerics-equivalence class the kv parity probe
            # accepts, counted in ``spec_near_tie_flips``.
            window: List[Tuple[int, float, float]] = []
            for i in range(k + 1):
                tok = int(tgt[i])
                cont = False
                if i < k:
                    if int(d[i]) == tok:
                        cont = True
                    elif task.greedy and \
                            float(margin[i]) < q8.PARITY_MARGIN_TOL:
                        tok = int(d[i])
                        self.spec_near_tie_flips += 1
                        cont = True
                window.append((tok, float(ent[i]), float(margin[i])))
                if not cont:
                    break
            task.tick_tokens = []
            n_fed = 0
            for tok, e_sig, m_sig in window:
                task._record(tok, e_sig, m_sig)
                task.tick_tokens.append(tok)
                n_fed += 1
                if task.done:
                    break          # eos / budget: later wins discarded
            # Commit exactly the accepted inputs' KV: positions
            # [len, len + n_fed) hold target-exact K/V for the emitted
            # stream; everything beyond is rejected-draft garbage,
            # causally invisible and rewritten before it could be seen.
            self.lengths[slot] += n_fed
            tick_proposed += proposable[slot]
            tick_accepted += max(n_fed - 1, 0)
            self.blocks.release_speculative(
                self._spec_claims.pop(slot, []))
            ticked.append(task)
        self.spec_proposed += tick_proposed
        self.spec_accepted += tick_accepted
        if self.spans is not None:
            self.spans.add("serve.spec_verify", t1, _time.perf_counter(),
                           kind="serve", slots=len(active),
                           proposed=tick_proposed,
                           accepted=tick_accepted)
        return ticked

    # -- retirement --------------------------------------------------------

    def retire(self, task: SlotTask, quarantine: bool = False) -> None:
        """Release the task's decode row and drop its block references.
        Blocks still shared (prefix cache, other requests) stay resident;
        under ``quarantine`` the task's UNSHARED blocks leave the pool
        with the row, and any blocks the task itself PUBLISHED to the
        prefix cache are purged from it first (the trust mirror: a
        flagged request's private KV — generated tail AND the prompt
        blocks it prefilled — is suspect; a prefix a different clean
        request published and others share is not)."""
        slot = task.slot
        if slot < 0 or self.tasks.get(slot) is not task:
            return
        del self.tasks[slot]
        self._prefill.pop(slot, None)
        self._attrib.pop(slot, None)
        if task.adapter is not None and self.adapters is not None:
            # Drop the request's residency claim on its adapter page.
            # The pool's OWN ref keeps the page resident (warm for the
            # tenant's next request) unless the adapter was quarantined
            # mid-flight — then this last release impounds it.  Replica
            # ``quarantine`` does NOT quarantine the adapter: adapter
            # trust is a fleet-level verdict (serve/fleet.py), scoped to
            # the adapter across replicas, not to this replica's pool.
            self.adapters.release(task.adapter)
            task.adapter_page = ZERO_PAGE
        # Outstanding speculative claims MUST unwind before the table
        # release: a leftover claim would make the quarantine release
        # below see the block as "shared" and FREE it on the claim's
        # decrement instead of impounding it — un-verified draft KV from
        # a flagged request would re-enter the pool.  (A normal tick
        # releases its claims inline; this is the abort path — e.g.
        # quarantine-at-retire racing a failed tick.)
        self.blocks.release_speculative(self._spec_claims.pop(slot, []))
        published = self._published.pop(slot, [])
        if quarantine and self.prefix is not None and published:
            # The flagged request's own PUBLISHED prompt blocks leave
            # the cache FIRST — otherwise the cache's reference keeps
            # them "shared" in the release loop below and a later
            # same-prefix request would decode straight off suspect KV
            # without any prefill.  (A prefix published by a DIFFERENT,
            # clean request stays cached: this request merely read it.)
            self.prefix.purge(set(published))
        q_blocks: List[int] = []
        for b in self.tables[slot]:
            if self.blocks.release(b, quarantine=quarantine) \
                    == "quarantined":
                q_blocks.append(b)
        self.tables[slot] = []
        if quarantine:
            self._q_blocks_by_slot[slot] = q_blocks
            self.allocator.quarantine(slot)
            logger.warning(
                "slot %d quarantined after request %d was flagged "
                "anomalous (%d private block(s) impounded, %d slots "
                "remain in service)",
                slot, task.request_id, len(q_blocks),
                self.allocator.capacity,
            )
        else:
            self.allocator.free(slot)

    def release_quarantine(self, slot: int) -> None:
        """Operator action: return a quarantined slot AND the blocks
        impounded with it to service."""
        self.allocator.release(slot)
        for b in self._q_blocks_by_slot.pop(slot, []):
            self.blocks.unquarantine(b)

    # -- live migration (serve/migrate.py) ---------------------------------

    def export_migration(self, task: SlotTask) -> Optional[Dict[str, Any]]:
        """Source-side snapshot of a DECODE-PHASE task for a live
        hand-off: the physical block table, the committed length and the
        admission-time placement (the destination's provenance record).
        Refuses (None, nothing touched) mid-prefill — chunk progress is
        not block state, the destination would have to re-prefill anyway
        — and unknown/stale tasks.  Outstanding speculative claims
        unwind FIRST (abort semantics, same ordering rule as retire):
        a migration never travels with un-verified draft claims, and
        the accepted ``lengths`` already exclude rejected draft KV."""
        slot = task.slot
        if slot < 0 or self.tasks.get(slot) is not task:
            return None
        if slot in self._prefill or not task.emitted:
            return None
        self.blocks.release_speculative(self._spec_claims.pop(slot, []))
        return {
            "task": task,
            "length": int(self.lengths[slot]),
            "block_ids": list(self.tables[slot]),
            "placement": self.attribution_info(task),
        }

    def claim_migration(self, n_blocks: int, adapter: Optional[str]
                        ) -> Optional[Dict[str, Any]]:
        """Destination-side CLAIM phase: reserve a decode row,
        ``n_blocks`` fresh physical blocks (prefix-evict retry — the
        same out-of-blocks backpressure as ``admit``) and, for an
        adapter-carrying request, the tenant's adapter page.  Returns
        None with NOTHING claimed on any shortage — a refusal here must
        leave both replicas exactly as they were.  No prefill and no
        prefix sharing: the blocks' contents arrive by device copy."""
        slot = self.allocator.alloc()
        if slot is None:
            return None
        fresh = self.blocks.alloc(n_blocks)
        if fresh is None and self.prefix is not None:
            self.prefix.evict(n_blocks - self.blocks.free_count)
            fresh = self.blocks.alloc(n_blocks)
        if fresh is None:
            self.allocator.free(slot)
            return None
        page = ZERO_PAGE
        if adapter is not None:
            page = (self.adapters.acquire(adapter)
                    if self.adapters is not None else None)
            if page is None:
                # Adapterless destination, full pool, or quarantined
                # adapter: full unwind, refusal leaves the source alone.
                for b in fresh:
                    self.blocks.release(b)
                self.allocator.free(slot)
                return None
        return {"slot": slot, "block_ids": list(fresh),
                "adapter": adapter, "adapter_page": int(page)}

    def abort_migration(self, claim: Dict[str, Any]) -> None:
        """Unwind a CLAIM that never committed (copy failed upstream or
        the orchestrator gave up): releases the blocks, the row and the
        adapter page — the exact inverse of ``claim_migration``."""
        if claim.get("adapter") is not None and self.adapters is not None:
            self.adapters.release(claim["adapter"])
        for b in claim["block_ids"]:
            self.blocks.release(b)
        self.allocator.free(claim["slot"])

    def commit_migration(self, task: SlotTask, claim: Dict[str, Any],
                         length: int,
                         migrated_from: Optional[Dict[str, Any]] = None
                         ) -> None:
        """COMMIT phase: register the migrated task on the claimed row.
        Pure host bookkeeping — the physical block copy already happened
        (serve/migrate.py) — so commit cannot fail.  The attribution
        snapshot names only the DESTINATION's fresh blocks as owned;
        ``migrated_from`` carries the source journal key + source block
        ids so ``verify_attribution`` reconciles the hand-off across
        both allocators' journals."""
        slot = claim["slot"]
        task.slot = slot
        task.adapter_page = int(claim["adapter_page"])
        task.tick_tokens = None
        self.tables[slot] = list(claim["block_ids"])
        self.lengths[slot] = int(length)
        self.tasks[slot] = task
        info: Dict[str, Any] = {
            "layout": "paged", "slot": slot,
            "block_ids": list(claim["block_ids"]),
            "prefix_block_ids": [], "prefix_publishers": {},
            "adapter": task.adapter,
            "adapter_page": int(claim["adapter_page"]),
        }
        if migrated_from is not None:
            info["migrated_from"] = dict(migrated_from)
        self._attrib[slot] = info

    def decode_cache_size(self) -> int:
        """Number of compiled paged-decode programs (the compile-once
        pin: block-table churn must keep this at 1)."""
        prog = _PROGRAMS.get("paged_decode")
        return prog._cache_size() if prog is not None else 0

    def spec_cache_sizes(self) -> Dict[str, int]:
        """Compiled-program counts for the three decode-phase programs
        of a speculative engine (the extended compile-once pin: draft,
        verify and the single-token fallback each compile exactly once
        for the engine's lifetime — accept/reject churn, block churn
        and draft-window block crossings never recompile)."""
        out: Dict[str, int] = {}
        for name in ("spec_draft", "spec_verify", "paged_decode"):
            prog = _PROGRAMS.get(name)
            out[name] = prog._cache_size() if prog is not None else 0
        return out

    @property
    def accepted_rate(self) -> float:
        """Fraction of PROPOSABLE drafted tokens that became emitted
        stream tokens — the draft-quality headline the bench A/B and
        the perf sentinel fingerprint track.  The denominator is
        budget-clamped per slot (min(k, remaining-1)), so the rate
        measures int8-draft-vs-target agreement, not how short the
        workload's requests were; eos truncation still counts against
        it (an eos is a property of the stream both arms share)."""
        return (self.spec_accepted / self.spec_proposed
                if self.spec_proposed else 0.0)

    def analyze_costs(self, ledger: Any,
                      memory: Optional[bool] = None) -> None:
        """Stamp the paged serve programs into an obs.hbm.CostLedger
        (lowering-only by default — no extra backend compile)."""
        kv = self.kv
        ms = self.allocator.max_slots
        c = self.chunk
        bsz = self.block_size
        prog = _programs()
        pool = (kv.k, kv.v, kv.k_scale, kv.v_scale)
        ledger.analyze(
            "serve.paged_prefill", prog["paged_prefill"], self.cfg,
            *pool, self.view, jnp.zeros(c, jnp.int32),
            jnp.asarray(1, jnp.int32),
            jnp.zeros(c // bsz, jnp.int32), jnp.zeros(2, jnp.uint32),
            jnp.asarray(1.0, jnp.float32), jnp.asarray(True),
            memory=memory, attn_impl=self.attn_impl,
        )
        ledger.analyze(
            "serve.paged_chunk", prog["paged_chunk"], self.cfg,
            *pool, self.view, jnp.zeros(c, jnp.int32),
            jnp.zeros((1, self.nbps), jnp.int32),
            jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
            jnp.zeros(2, jnp.uint32), jnp.asarray(1.0, jnp.float32),
            jnp.asarray(True), memory=memory,
            attn_impl=self.attn_impls["prefill"],
        )
        ledger.analyze(
            "serve.paged_decode", prog["paged_decode"], self.cfg,
            *pool, self.view, jnp.zeros(ms, jnp.int32),
            jnp.zeros((ms, self.nbps), jnp.int32),
            jnp.asarray(self.lengths), jnp.zeros((ms, 2), jnp.uint32),
            jnp.ones(ms, jnp.float32), jnp.ones(ms, bool),
            memory=memory, attn_impl=self.attn_impl,
        )

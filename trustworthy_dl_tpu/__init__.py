"""trustworthy_dl_tpu — TPU-native trustworthy distributed deep learning.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
Tanmoy058/Trustworthy-Distributed-Deep-Learning (reference mounted read-only
at /root/reference): trust-scored nodes, in-step statistical attack detection,
gradient verification, elastic task reassignment — all executed as SPMD
programs over a `jax.sharding.Mesh` instead of the reference's NCCL/torch
process groups (reference: distributed_trainer.py:99-114).

The reference's "node" is re-interpreted as a mesh coordinate (a device or a
device group along a mesh axis).  Detection and trust updates run *inside* the
compiled train step as XLA reductions; gradient aggregation is a trust-gated
weighted psum, so Byzantine mitigation costs no host round-trips.
"""

__version__ = "0.1.0"

# Public API is re-exported lazily so importing the package stays cheap (no
# jax tracing at import) and subpackages have no import-order constraints.
_EXPORTS = {
    "AttackConfig": "trustworthy_dl_tpu.core.config",
    "ExperimentConfig": "trustworthy_dl_tpu.core.config",
    "NodeConfig": "trustworthy_dl_tpu.core.config",
    "ServeConfig": "trustworthy_dl_tpu.core.config",
    "TrainingConfig": "trustworthy_dl_tpu.core.config",
    "load_config": "trustworthy_dl_tpu.core.config",
    "TrustManager": "trustworthy_dl_tpu.trust.manager",
    "NodeStatus": "trustworthy_dl_tpu.trust.state",
    "TrustState": "trustworthy_dl_tpu.trust.state",
    "AttackDetector": "trustworthy_dl_tpu.detect.detector",
    "AttackType": "trustworthy_dl_tpu.detect.detector",
    "AttackDetectionResult": "trustworthy_dl_tpu.detect.detector",
    "GradientVerifier": "trustworthy_dl_tpu.detect.verifier",
    "DistributedTrainer": "trustworthy_dl_tpu.engine.trainer",
    "TrainingState": "trustworthy_dl_tpu.engine.trainer",
    "ModelFactory": "trustworthy_dl_tpu.models.factory",
    "get_dataloader": "trustworthy_dl_tpu.data.loader",
    "MetricsCollector": "trustworthy_dl_tpu.utils.metrics",
    "NodeMonitor": "trustworthy_dl_tpu.utils.monitor",
    "AdversarialAttacker": "trustworthy_dl_tpu.attacks.adversarial",
    "FaultInjector": "trustworthy_dl_tpu.chaos.injector",
    "FaultKind": "trustworthy_dl_tpu.chaos.plan",
    "FaultPlan": "trustworthy_dl_tpu.chaos.plan",
    "SimulatedPreemption": "trustworthy_dl_tpu.chaos.injector",
    "TrainingSupervisor": "trustworthy_dl_tpu.engine.supervisor",
    "ExperimentRunner": "trustworthy_dl_tpu.experiments.runner",
    "ObsSession": "trustworthy_dl_tpu.obs.session",
    "MetricsRegistry": "trustworthy_dl_tpu.obs.registry",
    "TraceBus": "trustworthy_dl_tpu.obs.events",
    "EventType": "trustworthy_dl_tpu.obs.events",
    "FlightRecorder": "trustworthy_dl_tpu.obs.recorder",
    "StepTimeReporter": "trustworthy_dl_tpu.obs.report",
    "run_metadata": "trustworthy_dl_tpu.obs.meta",
    "generate": "trustworthy_dl_tpu.models.generate",
    "ServingEngine": "trustworthy_dl_tpu.serve.engine",
    "ServeRequest": "trustworthy_dl_tpu.serve.engine",
    "ServeResult": "trustworthy_dl_tpu.serve.engine",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module_path = _EXPORTS.get(name)
    if module_path is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module_path), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))

"""Tensor parallelism — GSPMD intra-layer sharding over the 'model' axis.

Absent from the reference (SURVEY §2.4: no intra-layer sharding anywhere).
TPU-native TP is declarative: annotate the Megatron-style layout on the
parameter tree and let XLA partition the matmuls and insert the collectives —
no hand-written all-reduces.

Layout (per GPT-2 block):
  qkv / mlp-fc weights  [D, k·D]   → shard output dim  (column parallel)
  attn-proj / mlp-proj  [k·D, D]   → shard input dim   (row parallel)
  biases of column-parallel layers → sharded; row-parallel biases replicated
  embeddings / layernorms          → replicated

In the trust architecture TP lives *inside* a node: the trust/detection unit
stays the data-parallel shard (a node = a TP group), so "tensor" mode builds
a ('data', 'model') mesh with num_nodes data shards and the remaining
devices as each node's TP group.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from trustworthy_dl_tpu.core import sharding as shreg
from trustworthy_dl_tpu.core.mesh import MODEL_AXIS

Params = Dict[str, Any]

#: One rule table for the whole module: the TP layout is the model's
#: logical declaration (models/gpt2.py:logical_axes) resolved under the
#: "tensor" rules — no PartitionSpec is spelled here.
_TP_RULES = shreg.rules_for("tensor")


def gpt2_tp_specs(params: Params) -> Params:
    """PartitionSpec tree for GPT-2 params: the model's logical-axis
    declaration resolved through the registry (blocks have a leading
    stacked layer axis)."""
    from trustworthy_dl_tpu.models.gpt2 import logical_axes

    return shreg.resolve_tree(logical_axes(), _TP_RULES)


def _spec_tree_for(params: Params) -> Params:
    """Match a spec tree to the params structure; anything unspecified is
    replicated.  A layout/params structure mismatch raises immediately with
    the offending paths — a silent mismatch would otherwise surface later as
    an opaque tree_map error inside apply_tp_sharding."""
    if not ("blocks" in params and "wte" in params):
        # Vision models: no TP layout defined — replicate everything (TP is
        # a transformer play; convs scale via data/spatial sharding).
        return jax.tree_util.tree_map(
            lambda _: shreg.replicated_spec(), params)
    specs = gpt2_tp_specs(params)
    is_spec = lambda x: isinstance(x, PartitionSpec)
    p_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    s_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=is_spec
        )[0]
    }
    if p_paths != s_paths:
        missing = sorted(p_paths - s_paths)
        extra = sorted(s_paths - p_paths)
        raise ValueError(
            "TP layout does not match the parameter tree; "
            f"params-only paths: {missing[:8]}, layout-only paths: {extra[:8]}"
        )
    return specs


def apply_tp_sharding(params: Params, mesh: Mesh) -> Params:
    """device_put the params with the TP layout (no-op shardings if the
    mesh has no 'model' axis)."""
    if MODEL_AXIS not in mesh.axis_names:
        return params
    specs = _spec_tree_for(params)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs,
    )


def apply_tp_sharding_to_opt(opt_state: Any, params: Params,
                             mesh: Mesh) -> Any:
    """Re-place optimizer-moment mirrors with the params' TP layout.

    Adam's mu/nu are params-structured subtrees inside the optax state;
    after an elastic mesh rebuild (eviction/readmission in tensor mode)
    they must follow their weights back onto the TP shardings — structure
    matching (treedef equality with ``params``) finds them exactly, and
    every other leaf (step counts, schedule state) is left as placed.

    Leaves that share the params STRUCTURE but not the params SHAPES
    (adafactor's factored v_row/v_col statistics, its (1,)-placeholder
    slots) replicate instead — a full-rank TP spec cannot apply to a
    reduced-rank statistic."""
    if MODEL_AXIS not in mesh.axis_names:
        return opt_state
    specs = _spec_tree_for(params)
    pdef = jax.tree_util.tree_structure(params)
    repl = shreg.replicated_sharding(mesh)

    def params_like(node):
        try:
            return jax.tree_util.tree_structure(node) == pdef
        except Exception:
            return False

    def place(leaf, param, spec):
        if getattr(leaf, "shape", None) == param.shape:
            return jax.device_put(leaf, NamedSharding(mesh, spec))
        return jax.device_put(leaf, repl)

    leaves, treedef = jax.tree_util.tree_flatten(
        opt_state, is_leaf=params_like
    )
    placed = [
        jax.tree_util.tree_map(place, node, params, specs)
        if params_like(node) else node
        for node in leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, placed)


def tp_group_size(mesh: Mesh) -> int:
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return sizes.get(MODEL_AXIS, 1)

"""Pipeline (stage) parallelism — the reference's one real strategy,
TPU-native.

The reference splits ``transformer.h`` into contiguous per-node chunks and
runs them in a *sequential Python loop in one process*
(distributed_trainer.py:124-135, 148-175).  Here the same partitioning is an
SPMD program: stacked block params [L, ...] reshape to [S, L/S, ...] and
shard over the mesh's 'stage' axis; a GPipe microbatch schedule runs inside
``shard_map``, rotating activations to the next stage with ``lax.ppermute``
each tick.  The backward schedule is not hand-written — JAX transposes the
``ppermute`` under ``jax.grad``, so reverse-mode AD *is* the backward
pipeline.

Per-stage trust integration:
  * each stage computes the detector battery over its boundary activations
    (masked mean over its active ticks) — the pipeline analogue of the
    reference's per-node ``detect_output_anomaly`` hook (:168-170);
  * per-stage gradient batteries come from the [S, ...] leading axis of the
    block gradients;
  * the trust gate zeroes a compromised stage's *parameter updates* (its
    layers freeze until reassignment) — unlike the reference, which silently
    drops compromised layers from the forward pass and corrupts the model
    (:154-157, flagged in SURVEY §7.5).
  * the cross-sectional outlier filter used in data-parallel mode is OFF
    here: different stages legitimately have different activation
    distributions, so only temporal z-scores apply (SURVEY §7.4(4)).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from trustworthy_dl_tpu.attacks.adversarial import AttackPlan, poison_gradients
from trustworthy_dl_tpu.core.config import TrainingConfig
from trustworthy_dl_tpu.core.mesh import STAGE_AXIS
from trustworthy_dl_tpu.detect import baseline as bl
from trustworthy_dl_tpu.detect import stats as st
from trustworthy_dl_tpu.detect.detector import anomaly_verdicts
from trustworthy_dl_tpu.detect.verifier import verify_gradients_array
from trustworthy_dl_tpu.engine.state import TrainState, update_monitor
from trustworthy_dl_tpu.engine.step import StepMetrics, _gradient_stat_vector
from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.models import layers as L
from trustworthy_dl_tpu.trust import state as ts

Array = jax.Array


def stack_stages(blocks: Any, num_stages: int) -> Any:
    """[L, ...] stacked blocks -> [S, L/S, ...] stage-major stacking — the
    TPU analogue of the reference's contiguous layer chunks
    (distributed_trainer.py:126-134)."""
    def reshape(leaf):
        l = leaf.shape[0]
        if l % num_stages:
            raise ValueError(
                f"{l} layers not divisible by {num_stages} stages"
            )
        return leaf.reshape((num_stages, l // num_stages) + leaf.shape[1:])
    return jax.tree_util.tree_map(reshape, blocks)


def unstack_stages(blocks: Any) -> Any:
    """Inverse of stack_stages."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((leaf.shape[0] * leaf.shape[1],) + leaf.shape[2:]),
        blocks,
    )


def _right_rotation(axis: str, size: int):
    return [(i, (i + 1) % size) for i in range(size)]


def build_pipeline_apply(
    cfg: gpt2.GPT2Config,
    mesh: Mesh,
    num_stages: int,
    num_microbatches: int,
    max_sort: int = 16384,
) -> Callable[[Any, Array], Tuple[Array, Array, Array, Array]]:
    """Returns pipe_apply(stage_blocks, x_microbatches) ->
    (y_microbatches, stage_stats[S,17], act_mean[S], act_std[S]).

    ``stage_blocks`` leaves are [S, L/S, ...] (sharded P('stage')),
    ``x_microbatches`` is [M, mb, T, D] (replicated).  The schedule runs
    M + S - 1 ticks; each tick every stage applies its layer slice to its
    current activation and passes it right around the ring.
    """
    S, M = num_stages, num_microbatches
    total_ticks = M + S - 1

    def apply_local(local_blocks, x):
        def body(h, block):
            return gpt2.block_forward(block, h, cfg), None
        y, _ = jax.lax.scan(body, x, local_blocks)
        return y

    def pipe_local(local_blocks, x_mb):
        # Inside shard_map: local_blocks [1, L/S, ...] (this stage's slice),
        # x_mb [M, mb, T, D] (full, replicated).
        local_blocks = jax.tree_util.tree_map(lambda a: a[0], local_blocks)
        stage = jax.lax.axis_index(STAGE_AXIS)
        mb_shape = x_mb.shape[1:]
        state0 = jnp.zeros(mb_shape, x_mb.dtype)
        outputs0 = jnp.zeros((M,) + mb_shape, x_mb.dtype)
        # Sufficient statistics of boundary activations over active ticks.
        stats0 = jnp.zeros((st.NUM_GRADIENT_STATS,), jnp.float32)
        acc0 = (state0, outputs0, stats0, jnp.zeros((), jnp.float32),
                jnp.asarray(0.0), jnp.asarray(0.0))

        def tick(carry, t):
            state, outputs, stats_sum, n_active, mean_sum, std_sum = carry
            mb_idx = t - stage
            active = (mb_idx >= 0) & (mb_idx < M)
            safe_idx = jnp.clip(mb_idx, 0, M - 1)
            # Stage 0 ingests a fresh microbatch; others use the ring input.
            fresh = x_mb[jnp.clip(t, 0, M - 1)]
            current = jnp.where(stage == 0, fresh, state)
            out = apply_local(local_blocks, current)
            # Boundary battery for this tick (zeros batched out when idle).
            tick_stats = st.tensor_statistics_sampled(
                out.reshape(-1).astype(jnp.float32), max_sort
            )
            tick_stats = jnp.concatenate(
                [tick_stats,
                 jnp.zeros((st.NUM_GRADIENT_STATS - st.NUM_TENSOR_STATS,),
                           jnp.float32)]
            )
            stats_sum = stats_sum + jnp.where(active, tick_stats, 0.0)
            mean_sum = mean_sum + jnp.where(active, jnp.mean(out), 0.0)
            std_sum = std_sum + jnp.where(active, jnp.std(out), 0.0)
            n_active = n_active + active.astype(jnp.float32)
            # Final stage records completed microbatches.
            write = active & (stage == S - 1)
            outputs = jnp.where(
                write,
                outputs.at[safe_idx].set(out),
                outputs,
            )
            # Rotate activations one stage rightward over ICI.
            nxt = jax.lax.ppermute(
                out, STAGE_AXIS, _right_rotation(STAGE_AXIS, S)
            )
            return (nxt, outputs, stats_sum, n_active, mean_sum, std_sum), None

        (_, outputs, stats_sum, n_active, mean_sum, std_sum), _ = jax.lax.scan(
            tick, acc0, jnp.arange(total_ticks)
        )
        denom = jnp.maximum(n_active, 1.0)
        stage_stats = (stats_sum / denom)[None, :]           # [1, 17] local
        act_mean = (mean_sum / denom)[None]
        act_std = (std_sum / denom)[None]
        # Completed outputs live only on the last stage; psum replicates
        # them (other stages contribute zeros) so unembed/loss is SPMD.
        outputs = jax.lax.psum(outputs, STAGE_AXIS)
        return outputs, stage_stats, act_mean, act_std

    pipe = shard_map(
        pipe_local,
        mesh=mesh,
        in_specs=(P(STAGE_AXIS), P()),
        out_specs=(P(), P(STAGE_AXIS), P(STAGE_AXIS), P(STAGE_AXIS)),
        check_vma=False,
    )
    return pipe


def build_pipeline_train_step(
    bundle,
    config: TrainingConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    max_sort: int = 16384,
) -> Callable[[TrainState, Dict[str, Array], AttackPlan],
              Tuple[TrainState, StepMetrics]]:
    """Jitted pipeline train step.  TrainState.params must hold 'blocks'
    stacked as [S, L/S, ...] (see stack_stages); the trainer handles that.

    Batches are global {'input': [B, T], 'target': [B, T]} with
    B % num_microbatches == 0.
    """
    if bundle.kind != "lm":
        raise ValueError(
            "pipeline parallelism currently supports the GPT family only "
            "(the reference's partitioner also only implemented GPT, "
            "distributed_trainer.py:124-144)"
        )
    cfg = bundle.config
    S = config.num_nodes
    M = config.num_microbatches
    detection = config.attack_detection_enabled
    verification = config.gradient_verification_enabled
    pipe_apply = build_pipeline_apply(cfg, mesh, S, M, max_sort)

    def forward(params, tokens):
        x = gpt2.embed(params, tokens, cfg)
        b, t, d = x.shape
        mb = b // M
        x_mb = x.reshape(M, mb, t, d)
        y_mb, stage_stats, act_mean, act_std = pipe_apply(params["blocks"], x_mb)
        y = y_mb.reshape(b, t, d)
        logits = gpt2.unembed(params, y, cfg)
        return logits, (stage_stats, act_mean, act_std)

    def loss_fn(params, batch):
        logits, aux = forward(params, batch["input"])
        return L.cross_entropy_loss(logits, batch["target"]), aux

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def train_step(state: TrainState, batch: Dict[str, Array],
                   plan: AttackPlan) -> Tuple[TrainState, StepMetrics]:
        rng, k_grad = jax.random.split(state.rng)
        now = state.step.astype(jnp.float32) * config.time_per_step

        (loss, aux), grads = grad_fn(state.params, batch)
        stage_stats_out, act_mean, act_std = aux

        # Attack injection: a compromised stage emits poisoned block
        # gradients (the [S, ...] leading axis maps nodes → stages).
        grads = dict(grads)
        grads["blocks"] = jax.lax.cond(
            plan.is_live(state.step),
            lambda g: poison_gradients(plan, g, state.step, k_grad),
            lambda g: g,
            grads["blocks"],
        )

        # Per-stage gradient batteries over each stage's block slice.
        grad_stats, leaf_norms, finite = jax.vmap(
            lambda g: _gradient_stat_vector(g, max_sort)
        )(grads["blocks"])
        global_norms = jnp.sqrt(jnp.sum(leaf_norms**2, axis=1))

        if detection:
            out_v = anomaly_verdicts(stage_stats_out, state.out_baseline,
                                     warmup=config.detector_warmup)
            grad_v = anomaly_verdicts(grad_stats, state.grad_baseline,
                                      warmup=config.detector_warmup)
            # Compromise verdicts come from the gradient battery (and the
            # verifier below): stage activation distributions drift
            # legitimately as the model trains and, unlike DP, there is no
            # cross-node population to separate drift from attack — so the
            # output battery feeds the output_deviation *trust signal* and
            # the reported score, not the hard verdict.
            candidates = grad_v.is_attack
            out_bl = bl.push_stats(state.out_baseline, stage_stats_out)
            grad_bl = bl.push_stats(state.grad_baseline, grad_stats,
                                    mask=~candidates)
            attacked = candidates & state.prev_suspects
            out_score, grad_score = out_v.score, grad_v.score
            attack_type = jnp.where(grad_v.is_attack, grad_v.attack_type,
                                    out_v.attack_type)
        else:
            out_bl, grad_bl = state.out_baseline, state.grad_baseline
            candidates = attacked = jnp.zeros((S,), bool)
            out_score = grad_score = jnp.zeros((S,), jnp.float32)
            attack_type = jnp.zeros((S,), jnp.int32)

        if verification:
            verifier, verified = verify_gradients_array(
                state.verifier, global_norms, finite
            )
        else:
            verifier = state.verifier
            verified = finite.astype(bool)

        trust = ts.mark_compromised(state.trust, attacked | ~verified)

        # Trust signals per stage (distributed_trainer.py:228-271 analogue).
        warm = state.monitor.warm
        exp_mean = state.monitor.out_mean_avg
        exp_std = jnp.maximum(state.monitor.out_std_avg, 1e-6)
        deviation = jnp.where(
            warm,
            jnp.minimum(
                1.0,
                (jnp.abs(act_mean - exp_mean) / exp_std
                 + jnp.abs(act_std - state.monitor.out_std_avg) / exp_std) / 2.0,
            ),
            0.0,
        )
        per_leaf = jnp.minimum(
            1.0, leaf_norms / jnp.maximum(state.monitor.grad_norm_avg, 1e-12)
        )
        usable = state.monitor.grad_norm_avg > 0
        consistency = jnp.where(
            warm,
            jnp.sum(jnp.where(usable, per_leaf, 0.0), axis=1)
            / jnp.maximum(jnp.sum(usable, axis=1), 1),
            1.0,
        )
        trust = ts.update_trust(trust, deviation, consistency, now,
                                alpha=config.trust_alpha)

        # Gate: a flagged stage's parameters freeze (update zeroed) — the
        # model topology is preserved, unlike the reference's layer-drop.
        # Hard-mask with jnp.where, not scale: 0 * NaN = NaN, so a frozen
        # stage emitting non-finite gradients would otherwise still poison
        # its own (and via the optimizer, the shared) parameter updates.
        weights = ts.contribution_weights(trust, verified & ~candidates)

        def _gate_stage(g):
            shape = (S,) + (1,) * (g.ndim - 1)
            mask = (weights > 0).reshape(shape)
            return jnp.where(mask, g * weights.reshape(shape).astype(g.dtype), 0)

        blocks = jax.tree_util.tree_map(_gate_stage, grads["blocks"])
        # Shared leaves (embed/unembed) are not per-stage gated; zero any
        # non-finite leaf so a NaN forward cannot corrupt shared params.
        # (Block grads are already handled by _gate_stage — a non-finite
        # stage always fails the finite check and carries weight 0.)
        grads = {
            k: (blocks if k == "blocks" else jax.tree_util.tree_map(
                lambda g: jnp.where(jnp.all(jnp.isfinite(g)), g, 0), v))
            for k, v in grads.items()
        }
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)

        absorb = verified & ~candidates
        monitor = update_monitor(state.monitor, act_mean, act_std, leaf_norms,
                                 absorb)
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            trust=trust,
            out_baseline=out_bl,
            grad_baseline=grad_bl,
            verifier=verifier,
            monitor=monitor,
            prev_suspects=candidates,
            step=state.step + 1,
            epoch=state.epoch,
            rng=rng,
        )
        metrics = StepMetrics(
            loss=loss,
            per_node_loss=jnp.broadcast_to(loss, (S,)),
            trust_scores=trust.scores,
            status=trust.status,
            attacked=attacked,
            verified=verified,
            weights=weights,
            system_trust=ts.system_trust(trust),
            grad_norm=optax.global_norm(grads),
            out_score=out_score,
            grad_score=grad_score,
            attack_type=attack_type,
            byzantine=jnp.zeros((S,), bool),
            backdoor=jnp.zeros((S,), bool),
        )
        return new_state, metrics

    return train_step


def build_pipeline_eval_step(bundle, config: TrainingConfig, mesh: Mesh
                             ) -> Callable[[Any, Dict[str, Array]],
                                           Dict[str, Array]]:
    """Validation through the pipeline (params hold stacked [S, L/S, ...]
    blocks, so the DP eval path cannot be reused)."""
    cfg = bundle.config
    pipe_apply = build_pipeline_apply(cfg, mesh, config.num_nodes,
                                      config.num_microbatches)

    def eval_step(params, batch):
        tokens = batch["input"]
        x = gpt2.embed(params, tokens, cfg)
        b, t, d = x.shape
        mb = b // config.num_microbatches
        x_mb = x.reshape(config.num_microbatches, mb, t, d)
        y_mb, _, _, _ = pipe_apply(params["blocks"], x_mb)
        logits = gpt2.unembed(params, y_mb.reshape(b, t, d), cfg)
        return {
            "loss": L.cross_entropy_loss(logits, batch["target"]),
            "accuracy": L.accuracy(logits, batch["target"]),
        }

    return eval_step

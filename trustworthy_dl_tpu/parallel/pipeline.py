"""Pipeline (stage) parallelism — the reference's one real strategy,
TPU-native.

The reference splits ``transformer.h`` into contiguous per-node chunks and
runs them in a *sequential Python loop in one process*
(distributed_trainer.py:124-135, 148-175).  Here the same partitioning is an
SPMD program: stacked block params [L, ...] reshape to [S, L/S, ...] and
shard over the mesh's 'stage' axis; a GPipe microbatch schedule runs inside
``shard_map``, rotating activations to the next stage with ``lax.ppermute``
each tick.  The backward schedule is not hand-written — JAX transposes the
``ppermute`` under ``jax.grad``, so reverse-mode AD *is* the backward
pipeline.

Per-stage trust integration:
  * each stage computes the detector battery over its boundary activations
    (masked mean over its active ticks) — the pipeline analogue of the
    reference's per-node ``detect_output_anomaly`` hook (:168-170);
  * per-stage gradient batteries come from the [S, ...] leading axis of the
    block gradients;
  * the trust gate zeroes a compromised stage's *parameter updates* (its
    layers freeze until reassignment) — unlike the reference, which silently
    drops compromised layers from the forward pass and corrupts the model
    (:154-157, flagged in SURVEY §7.5).
  * the cross-sectional outlier filter used in data-parallel mode is OFF
    here: different stages legitimately have different activation
    distributions, so only temporal z-scores apply (SURVEY §7.4(4)).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from trustworthy_dl_tpu.core import sharding as shreg

from trustworthy_dl_tpu.attacks.adversarial import AttackPlan, \
    corrupt_stage_compute, poison_gradients
from trustworthy_dl_tpu.core.config import TrainingConfig
from trustworthy_dl_tpu.core.mesh import DATA_AXIS, STAGE_AXIS, \
    shard_map_compat as shard_map
from trustworthy_dl_tpu.detect import baseline as bl
from trustworthy_dl_tpu.detect import stats as st
from trustworthy_dl_tpu.detect.detector import AttackType, anomaly_verdicts
from trustworthy_dl_tpu.detect.verifier import absorb_norms, norm_suspicions
from trustworthy_dl_tpu.engine.state import TrainState, update_monitor
from trustworthy_dl_tpu.engine.step import (
    StepMetrics,
    _gradient_stat_vector,
    guarded_update,
)
from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.models import layers as L
from trustworthy_dl_tpu.trust import state as ts

Array = jax.Array

#: Registry rules for pipeline mode ("model"): the stage axis carries
#: the trust nodes, microbatch rows shard over the DP replica rows.
_PP_RULES = shreg.rules_for("model")


def stack_stages(blocks: Any, num_stages: int) -> Any:
    """[L, ...] stacked blocks -> [S, L/S, ...] stage-major stacking — the
    TPU analogue of the reference's contiguous layer chunks
    (distributed_trainer.py:126-134)."""
    def reshape(leaf):
        l = leaf.shape[0]
        if l % num_stages:
            raise ValueError(
                f"{l} layers not divisible by {num_stages} stages"
            )
        return leaf.reshape((num_stages, l // num_stages) + leaf.shape[1:])
    return jax.tree_util.tree_map(reshape, blocks)


def unstack_stages(blocks: Any) -> Any:
    """Inverse of stack_stages."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((leaf.shape[0] * leaf.shape[1],) + leaf.shape[2:]),
        blocks,
    )


def _right_rotation(axis: str, size: int):
    return [(i, (i + 1) % size) for i in range(size)]


def choose_num_microbatches(batch_size: int, num_stages: int,
                            dp: int = 1) -> int:
    """Auto schedule depth (``TrainingConfig.num_microbatches = 0``).

    The bubble fraction (S-1)/(M+S-1) falls with M, so fixed global batch
    wants M as large as the batch allows — measured on the 8-stage mesh
    (experiments/pipeline_schedule_study): B=64 step time drops 3.0x
    from M=2 to M=16.  Past M ≈ 4·S the marginal bubble gain is < ~6 %
    while per-tick battery/bookkeeping overhead keeps growing linearly
    and per-microbatch arithmetic intensity falls (mb shrinks toward 1),
    so the cap keeps the MXU fed.  An exact divisor of the per-replica-row
    batch B/dp is preferred (every microbatch full, no samples trimmed);
    when none <= cap exists (prime-ish batches) the fallback picks the
    trim-tolerant M that maximises the utilised batch (M * (per_row // M),
    ties resolved toward the larger M for the smaller bubble) instead of
    silently degrading to M=1 — at S=8 that old fallback ran an ~88 %
    bubble, far worse than trimming a couple of samples per row (the
    trainer's _node_batch already trims every batch to the M*dp quantum).
    Degraded auto-selection is logged with the utilisation it settles for.
    """
    import logging as _logging

    per_row = max(batch_size // max(dp, 1), 1)
    cap = min(per_row, 4 * num_stages)
    for m in range(cap, 1, -1):
        if per_row % m == 0:
            return m
    best_m, best_used = 1, 0
    for m in range(2, cap + 1):
        used = (per_row // m) * m
        if used >= best_used:  # >= : ties prefer the deeper schedule
            best_m, best_used = m, used
    if best_m > 1:
        _logging.getLogger(__name__).warning(
            "no exact microbatch divisor of per-row batch %d <= cap %d; "
            "auto-selected trim-tolerant M=%d (utilises %d/%d samples "
            "per row, bubble %.0f%% vs %.0f%% at M=1)",
            per_row, cap, best_m, best_used, per_row,
            100.0 * bubble_fraction(num_stages, best_m),
            100.0 * bubble_fraction(num_stages, 1),
        )
    return best_m


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """GPipe pipeline bubble: the idle fraction of the M + S - 1 tick
    schedule, (S-1)/(M+S-1).  The backward schedule is the AD transpose of
    the same ``ppermute`` ring, so it mirrors the forward bubble — raising
    ``num_microbatches`` is the schedule-level lever (M=4,S=4 → 43 %;
    M=32,S=4 → 8.6 %), and DP pipeline replica rows (the TPU (group, S)
    mesh) scale batch throughput without touching it."""
    return (num_stages - 1) / (num_microbatches + num_stages - 1)


def build_pipeline_apply(
    cfg: gpt2.GPT2Config,
    mesh: Mesh,
    num_stages: int,
    num_microbatches: int,
    max_sort: int = 16384,
) -> Callable[[Any, Array], Tuple[Array, Array, Array, Array]]:
    """Returns pipe_apply(stage_blocks, x_microbatches) ->
    (y_microbatches, stage_stats[S,17], act_mean[S], act_std[S]).

    ``stage_blocks`` leaves are [S, L/S, ...] (sharded P('stage')),
    ``x_microbatches`` is [M, mb, T, D] — its mb dim shards over the
    mesh's data axis when the mesh carries DP pipeline replica rows (the
    TPU (group, S) layout, core/mesh.py), so surplus chips beyond S scale
    batch throughput.  The schedule runs M + S - 1 ticks; each tick every
    stage applies its layer slice to its current activation and passes it
    right around the ring (per data row — shard_map scopes the ppermute
    to each row's stage subgroup).
    """
    S, M = num_stages, num_microbatches
    total_ticks = M + S - 1
    dp = mesh.shape.get(DATA_AXIS, 1)

    def apply_local(local_blocks, x):
        def body(h, block):
            return gpt2.block_forward(block, h, cfg), None
        y, _ = jax.lax.scan(body, x, local_blocks)
        return y

    def pipe_local(local_blocks, x_mb):
        # Inside shard_map: local_blocks [1, L/S, ...] (this stage's slice),
        # x_mb [M, mb, T, D] (full, replicated).
        local_blocks = jax.tree_util.tree_map(lambda a: a[0], local_blocks)
        stage = jax.lax.axis_index(STAGE_AXIS)
        mb_shape = x_mb.shape[1:]
        state0 = jnp.zeros(mb_shape, x_mb.dtype)
        outputs0 = jnp.zeros((M,) + mb_shape, x_mb.dtype)
        # Sufficient statistics of boundary activations over active ticks.
        stats0 = jnp.zeros((st.NUM_GRADIENT_STATS,), jnp.float32)
        acc0 = (state0, outputs0, stats0, jnp.zeros((), jnp.float32),
                jnp.asarray(0.0), jnp.asarray(0.0))

        def tick(carry, t):
            state, outputs, stats_sum, n_active, mean_sum, std_sum = carry
            mb_idx = t - stage
            active = (mb_idx >= 0) & (mb_idx < M)
            safe_idx = jnp.clip(mb_idx, 0, M - 1)
            # Stage 0 ingests a fresh microbatch; others use the ring input.
            fresh = x_mb[jnp.clip(t, 0, M - 1)]
            current = jnp.where(stage == 0, fresh, state)
            out = apply_local(local_blocks, current)
            # Boundary battery for this tick (zeros batched out when idle).
            # stop_gradient: the battery is diagnostics, constant under
            # differentiation by contract (same as ops/fused_moments) —
            # and keeping it out of the VJP keeps its per-stage scalar
            # accumulators out of the shard_map residual set, whose spec
            # check this container's jax (0.4.37) enforces even under
            # check_rep=False (unreplicated scalar residuals -> a
            # _SpecError at trace time on dp>1 meshes).
            out_sg = jax.lax.stop_gradient(out)
            tick_stats = st.tensor_statistics_sampled(
                out_sg.reshape(-1).astype(jnp.float32), max_sort
            )
            tick_stats = jnp.concatenate(
                [tick_stats,
                 jnp.zeros((st.NUM_GRADIENT_STATS - st.NUM_TENSOR_STATS,),
                           jnp.float32)]
            )
            stats_sum = stats_sum + jnp.where(active, tick_stats, 0.0)
            mean_sum = mean_sum + jnp.where(active, jnp.mean(out_sg), 0.0)
            std_sum = std_sum + jnp.where(active, jnp.std(out_sg), 0.0)
            n_active = n_active + active.astype(jnp.float32)
            # Final stage records completed microbatches.
            write = active & (stage == S - 1)
            outputs = jnp.where(
                write,
                outputs.at[safe_idx].set(out),
                outputs,
            )
            # Rotate activations one stage rightward over ICI.
            nxt = jax.lax.ppermute(
                out, STAGE_AXIS, _right_rotation(STAGE_AXIS, S)
            )
            return (nxt, outputs, stats_sum, n_active, mean_sum, std_sum), None

        (_, outputs, stats_sum, n_active, mean_sum, std_sum), _ = jax.lax.scan(
            tick, acc0, jnp.arange(total_ticks)
        )
        denom = jnp.maximum(n_active, 1.0)
        stage_stats = (stats_sum / denom)[None, :]           # [1, 17] local
        act_mean = (mean_sum / denom)[None]
        act_std = (std_sum / denom)[None]
        if dp > 1:
            # DP replica rows each saw a different microbatch shard:
            # average the boundary batteries across rows so the per-stage
            # baseline describes the whole batch (consistent with the
            # tick-average above).
            stage_stats = jax.lax.psum(stage_stats, DATA_AXIS) / dp
            act_mean = jax.lax.psum(act_mean, DATA_AXIS) / dp
            act_std = jax.lax.psum(act_std, DATA_AXIS) / dp
        # Completed outputs live only on the last stage; psum replicates
        # them (other stages contribute zeros) so unembed/loss is SPMD.
        outputs = jax.lax.psum(outputs, STAGE_AXIS)
        return outputs, stage_stats, act_mean, act_std

    pipe = shard_map(
        pipe_local,
        mesh=mesh,
        # mb (dim 1 of x_mb / outputs) shards over the DP replica rows; on
        # the (1, S) mesh the spec degenerates to full replication.
        in_specs=(_PP_RULES.partition_spec(shreg.STAGE),
                  _PP_RULES.partition_spec(None, shreg.BATCH)),
        out_specs=(_PP_RULES.partition_spec(None, shreg.BATCH),
                   _PP_RULES.partition_spec(shreg.STAGE),
                   _PP_RULES.partition_spec(shreg.STAGE),
                   _PP_RULES.partition_spec(shreg.STAGE)),
        check_vma=False,
    )
    return pipe


class CanaryState(NamedTuple):
    """Per-stage reference signal for Byzantine/backdoor detection under
    pipeline parallelism (SURVEY §7.4(4)).

    Cross-stage comparison is meaningless (stages compute different layers)
    and a poisoned stage corrupts all downstream activations, so each stage
    is probed *in isolation*: every step it applies its layer slice to the
    same fixed replicated canary activations.  Honest stages change their
    transform only by one optimizer step (tiny relative delta); a Byzantine
    stage that corrupts its compute moves abruptly (``prev`` check), and a
    slow persistent repurposing of the transform drifts away from the
    long-horizon EMA signature (``sig_ema`` KL check)."""

    prev: Array     # f32[S, cb, tc, d] last step's canary outputs
    sig_ema: Array  # f32[S, d] EMA softmax signature of canary outputs
    count: Array    # i32[] probes absorbed


def init_canary_state(num_stages: int, canary: Array) -> CanaryState:
    cb, tc, d = canary.shape
    return CanaryState(
        prev=jnp.zeros((num_stages, cb, tc, d), jnp.float32),
        sig_ema=jnp.full((num_stages, d), 1.0 / d, jnp.float32),
        count=jnp.zeros((), jnp.int32),
    )


def make_canary(cfg: gpt2.GPT2Config, canary_tokens: int = 8,
                canary_batch: int = 1) -> Array:
    """The fixed probe input: deterministic unit-Gaussian activations at the
    block interface (constant across the run — the whole point)."""
    return jax.random.normal(
        jax.random.PRNGKey(0xCA9A12),
        (canary_batch, canary_tokens, cfg.n_embd),
        jnp.float32,
    )


CANARY_BYZ_REL_CHANGE = 0.25   # honest per-step transform drift is ~lr-sized
CANARY_BACKDOOR_KL = 2.0       # same bar as the reference's backdoor check
                               # (attack_detector.py:164-183)


def canary_probe(
    canary_state: CanaryState,
    blocks: Any,
    canary: Array,
    cfg: gpt2.GPT2Config,
    warmup: int,
) -> Tuple[CanaryState, Array, Array]:
    """Probe every stage's transform; returns (new_state, byz[S], backdoor[S]).

    ``blocks`` leaves are [S, L/S, ...]; the vmap over the stage axis rides
    the 'stage' sharding, so each stage probes on its own device with the
    replicated canary — one tiny forward per stage, no extra collectives."""

    def one_stage(stage_blocks):
        def body(h, block):
            return gpt2.block_forward(block, h, cfg), None
        y, _ = jax.lax.scan(body, canary, stage_blocks)
        return y.astype(jnp.float32)

    y = jax.vmap(one_stage)(blocks)                      # [S, cb, tc, d]
    s_axes = tuple(range(1, y.ndim))

    # Abrupt-change (Byzantine) check vs the previous step's probe.
    delta = jnp.sqrt(jnp.sum((y - canary_state.prev) ** 2, axis=s_axes))
    ref = jnp.sqrt(jnp.sum(canary_state.prev ** 2, axis=s_axes)) + 1e-8
    byz = (delta / ref > CANARY_BYZ_REL_CHANGE) & (canary_state.count >= 1)

    # Slow-drift (backdoor) check: softmax signature vs long-horizon EMA.
    sig = jax.nn.softmax(jnp.mean(y, axis=(1, 2)), axis=-1)      # [S, d]
    ema = canary_state.sig_ema
    kl = jnp.sum(sig * (jnp.log(sig + 1e-12) - jnp.log(ema + 1e-12)), axis=-1)
    backdoor = (kl > CANARY_BACKDOOR_KL) & (canary_state.count >= warmup)

    flagged = byz | backdoor
    new_ema = jnp.where(flagged[:, None], ema, 0.9 * ema + 0.1 * sig)
    # Freeze BOTH references on flagged stages: absorbing a corrupted probe
    # into prev would make the first *clean* step after the attack ends read
    # as another abrupt change and re-flag an honest stage.
    new_prev = jnp.where(
        flagged.reshape((-1,) + (1,) * (y.ndim - 1)), canary_state.prev, y
    )
    new_state = CanaryState(
        prev=new_prev, sig_ema=new_ema, count=canary_state.count + 1
    )
    return new_state, byz, backdoor


def build_pipeline_train_step(
    bundle,
    config: TrainingConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    max_sort: int = 16384,
) -> Callable[[TrainState, Dict[str, Array], AttackPlan],
              Tuple[TrainState, StepMetrics]]:
    """Jitted pipeline train step.  TrainState.params must hold 'blocks'
    stacked as [S, L/S, ...] (see stack_stages); the trainer handles that.

    Batches are global {'input': [B, T], 'target': [B, T]} with
    B % num_microbatches == 0.
    """
    if bundle.kind != "lm":
        raise ValueError(
            "pipeline parallelism currently supports the GPT family only "
            "(the reference's partitioner also only implemented GPT, "
            "distributed_trainer.py:124-144)"
        )
    cfg = bundle.config
    S = config.num_nodes
    M = config.num_microbatches
    detection = config.attack_detection_enabled
    verification = config.gradient_verification_enabled
    pipe_apply = build_pipeline_apply(cfg, mesh, S, M, max_sort)
    canary_const = make_canary(cfg, config.canary_tokens)

    dp = mesh.shape.get(DATA_AXIS, 1)
    logger_msg = (
        "pipeline schedule: S=%d stages, M=%d microbatches, %d DP replica "
        "row(s); GPipe bubble fraction %.1f%%" % (
            S, M, dp, 100.0 * bubble_fraction(S, M))
    )
    import logging as _logging

    _logging.getLogger(__name__).info(logger_msg)

    def loss_fn(params, batch):
        x = gpt2.embed(params, batch["input"], cfg)
        b, t, d = x.shape
        mb = b // M
        x_mb = x.reshape(M, mb, t, d)
        y_mb, stage_stats, act_mean, act_std = pipe_apply(params["blocks"], x_mb)
        if dp > 1:
            # Merge with mb leading so the data-sharded dim stays the
            # (contiguous) row dim of the merged batch — a plain
            # [M, mb] → [b] merge would need a strided sharding and
            # GSPMD would all-gather the activations instead.  Targets
            # take the identical permutation; the loss is a mean over
            # all positions, so the reorder changes nothing but
            # summation order.
            y = y_mb.transpose(1, 0, 2, 3).reshape(b, t, d)
            targets = batch["target"].reshape(M, mb, t).transpose(
                1, 0, 2
            ).reshape(b, t)
        else:
            y = y_mb.reshape(b, t, d)
            targets = batch["target"]
        # Head via the shared helper: honours cfg.lm_head_chunk (fused
        # vocab-chunked CE — the logits never materialise), identical to
        # the data-parallel loss path so the modes cannot drift.
        loss, _ = gpt2.head_loss_and_signature(params, y, targets, cfg)
        return loss, (stage_stats, act_mean, act_std)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def train_step(state: TrainState, batch: Dict[str, Array],
                   plan: AttackPlan) -> Tuple[TrainState, StepMetrics]:
        rng, k_grad, k_byz = jax.random.split(state.rng, 3)
        now = state.step.astype(jnp.float32) * config.time_per_step

        # Byzantine *compute* corruption: the attacked stage's transform is
        # garbage for this step (forward AND the canary probe below ride the
        # same corrupted blocks), while stored params stay clean.
        fwd_params = dict(state.params)
        fwd_params["blocks"] = jax.lax.cond(
            plan.is_live(state.step) & plan.byzantine,
            lambda b: corrupt_stage_compute(plan, b, state.step, k_byz),
            lambda b: b,
            state.params["blocks"],
        )

        (loss, aux), grads = grad_fn(fwd_params, batch)
        stage_stats_out, act_mean, act_std = aux

        # Attack injection: a compromised stage emits poisoned block
        # gradients (the [S, ...] leading axis maps nodes → stages).
        grads = dict(grads)
        grads["blocks"] = jax.lax.cond(
            plan.is_live(state.step),
            lambda g: poison_gradients(plan, g, state.step, k_grad),
            lambda g: g,
            grads["blocks"],
        )

        # Per-stage gradient batteries over each stage's block slice.
        grad_stats, leaf_norms, finite = jax.vmap(
            lambda g: _gradient_stat_vector(g, max_sort)
        )(grads["blocks"])
        global_norms = jnp.sqrt(jnp.sum(leaf_norms**2, axis=1))

        # Gradient verification verdict (pure read) BEFORE the detector so
        # the raw norm suspicion can mask this step's baseline absorption
        # (a stage excluded for a suspect norm must not push that step's
        # stats into the rolling windows).  The Welford baseline absorbs
        # after the probe below, under the same clean-this-step rule as
        # every other baseline — in particular NOT during a live
        # canary-Byzantine verdict, when every stage's gradients flow
        # through a corrupted pipeline.
        finite_b = finite.astype(bool)
        if verification:
            norm_suspect = norm_suspicions(state.verifier, global_norms)
        else:
            norm_suspect = jnp.zeros_like(finite_b)

        if detection:
            out_v = anomaly_verdicts(stage_stats_out, state.out_baseline,
                                     warmup=config.detector_warmup)
            grad_v = anomaly_verdicts(grad_stats, state.grad_baseline,
                                      warmup=config.detector_warmup)
            # Per-stage canary probe (SURVEY §7.4(4)): the Byzantine/backdoor
            # checks cross-node comparison can't provide under pipelining.
            canary_state, byz, backdoor = canary_probe(
                state.canary, fwd_params["blocks"], canary_const, cfg,
                config.detector_warmup,
            )
            # Stages are serially dependent: a Byzantine stage corrupts every
            # downstream activation AND the whole backward pass, so while a
            # canary-Byzantine verdict is live (byz_any) only the canary can
            # localise the culprit — the statistical batteries would
            # false-flag honest stages on the contaminated gradients.  They
            # are suppressed, the rolling baselines freeze (no contaminated
            # absorption), and the optimizer update is skipped entirely
            # below.  Otherwise, compromise verdicts come from the gradient
            # battery, the canary, and the verifier: stage activation
            # distributions drift legitimately as the model trains and,
            # unlike DP, there is no cross-node population to separate drift
            # from attack — so the output battery feeds the output_deviation
            # *trust signal* and the reported score, not the hard verdict.
            byz_any = jnp.any(byz)
            stat_cand = grad_v.is_attack & ~byz_any
            candidates = stat_cand | byz | backdoor
            # Absorb only stages with NO suspicion of any kind this step —
            # battery/canary verdicts, verifier norm-suspect, or non-finite
            # gradients — and never while a Byzantine verdict is live (the
            # whole pipeline's stats are contaminated then).
            clean_now = ~(candidates | norm_suspect | ~finite_b) & ~byz_any
            out_bl = bl.push_stats(state.out_baseline, stage_stats_out,
                                   mask=clean_now)
            grad_bl = bl.push_stats(state.grad_baseline, grad_stats,
                                    mask=clean_now)
            # Canary verdicts are unambiguous (fixed probe, no statistical
            # drift), so they confirm immediately — only the statistical
            # battery needs the two-consecutive-steps debounce.
            attacked = (stat_cand & state.prev_suspects) | byz | backdoor
            out_score, grad_score = out_v.score, grad_v.score
            attack_type = jnp.select(
                [byz, backdoor, stat_cand],
                [jnp.full((S,), int(AttackType.BYZANTINE), jnp.int32),
                 jnp.full((S,), int(AttackType.BACKDOOR), jnp.int32),
                 grad_v.attack_type],
                default=out_v.attack_type,
            )
        else:
            out_bl, grad_bl = state.out_baseline, state.grad_baseline
            canary_state = state.canary
            candidates = attacked = byz = backdoor = jnp.zeros((S,), bool)
            byz_any = jnp.zeros((), bool)
            out_score = grad_score = jnp.zeros((S,), jnp.float32)
            attack_type = jnp.zeros((S,), jnp.int32)
            clean_now = finite_b & ~norm_suspect

        # No cross-stage gate on norm suspicion (stages differ
        # legitimately), but a live canary verdict contaminates every
        # stage's gradients, so it is suppressed like the statistical
        # battery.
        norm_suspect = norm_suspect & ~byz_any
        verified = finite_b & ~norm_suspect

        # Verifier baseline absorption under the same clean-this-step rule
        # as the stat baselines (incl. the ~byz_any freeze carried by
        # clean_now): corrupted-pipeline norms must never form the Welford
        # baseline honest stages are later z-scored against.
        if verification:
            verifier = absorb_norms(state.verifier, global_norms, clean_now)
        else:
            verifier = state.verifier

        # Statistical norm suspicion debounces like the battery verdicts:
        # excluded from this step's update immediately (weights gate), but
        # confirmed-compromised only on the second consecutive hit.
        candidates = candidates | norm_suspect
        attacked = attacked | (norm_suspect & state.prev_suspects)

        trust = ts.mark_compromised(state.trust, attacked | ~finite_b)

        # Trust signals per stage (distributed_trainer.py:228-271 analogue).
        warm = state.monitor.warm
        exp_mean = state.monitor.out_mean_avg
        exp_std = jnp.maximum(state.monitor.out_std_avg, 1e-6)
        deviation = jnp.where(
            warm,
            jnp.minimum(
                1.0,
                (jnp.abs(act_mean - exp_mean) / exp_std
                 + jnp.abs(act_std - state.monitor.out_std_avg) / exp_std) / 2.0,
            ),
            0.0,
        )
        per_leaf = jnp.minimum(
            1.0, leaf_norms / jnp.maximum(state.monitor.grad_norm_avg, 1e-12)
        )
        usable = state.monitor.grad_norm_avg > 0
        consistency = jnp.where(
            warm,
            jnp.sum(jnp.where(usable, per_leaf, 0.0), axis=1)
            / jnp.maximum(jnp.sum(usable, axis=1), 1),
            1.0,
        )
        # While a Byzantine stage is live the deviation/consistency signals
        # of every stage are computed through corrupted activations —
        # freeze the trust EMA rather than punish honest stages with
        # garbage metrics.
        trust = ts.update_trust(trust, deviation, consistency, now,
                                alpha=config.trust_alpha,
                                update_mask=jnp.broadcast_to(~byz_any, (S,)))

        # Probation recovery (trust_manager.py:198-206 wired in): a frozen
        # stage with enough consecutive clean steps re-enters as RECOVERING
        # and its updates resume.  ~byz_any: a live canary verdict means the
        # whole pipeline's evidence is contaminated — no streak credit.
        trust, clean_streak = ts.probation_recovery(
            trust, state.clean_streak,
            verified & ~candidates & ~byz_any,
            config.recovery_probation_steps,
        )

        # Gate: a flagged stage's parameters freeze (update zeroed) — the
        # model topology is preserved, unlike the reference's layer-drop.
        # Hard-mask with jnp.where, not scale: 0 * NaN = NaN, so a frozen
        # stage emitting non-finite gradients would otherwise still poison
        # its own (and via the optimizer, the shared) parameter updates.
        weights = ts.contribution_weights(trust, verified & ~candidates)
        # Global skip under a live canary-Byzantine verdict: the step's loss
        # was computed through a corrupted pipeline, so NO stage's gradient
        # is trustworthy (serial dependence) — zero the whole update.
        step_scale = jnp.where(byz_any, 0.0, 1.0)

        def _gate_stage(g):
            shape = (S,) + (1,) * (g.ndim - 1)
            mask = (weights > 0).reshape(shape)
            gated = jnp.where(mask, g * weights.reshape(shape).astype(g.dtype), 0)
            return gated * step_scale.astype(g.dtype)

        blocks = jax.tree_util.tree_map(_gate_stage, grads["blocks"])
        # Shared leaves (embed/unembed) are not per-stage gated; zero any
        # non-finite leaf so a NaN forward cannot corrupt shared params.
        # (Block grads are already handled by _gate_stage — a non-finite
        # stage always fails the finite check and carries weight 0.)
        grads = {
            k: (blocks if k == "blocks" else jax.tree_util.tree_map(
                lambda g: jnp.where(jnp.all(jnp.isfinite(g)), g, 0)
                * step_scale.astype(g.dtype), v))
            for k, v in grads.items()
        }
        # True skip on the "zero the whole update" paths: a live canary-
        # Byzantine verdict, or every stage gated out — params and optimizer
        # state freeze together (zeroed grads alone would still let AdamW's
        # momentum/weight-decay move every parameter).
        params, opt_state = guarded_update(
            ~byz_any & (jnp.sum(weights) > 0), optimizer, grads,
            state.opt_state, state.params,
        )

        absorb = verified & ~candidates & ~byz_any
        monitor = update_monitor(state.monitor, act_mean, act_std, leaf_norms,
                                 absorb)
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            trust=trust,
            out_baseline=out_bl,
            grad_baseline=grad_bl,
            verifier=verifier,
            monitor=monitor,
            prev_suspects=candidates,
            step=state.step + 1,
            epoch=state.epoch,
            rng=rng,
            canary=canary_state,
            clean_streak=clean_streak,
            # Fleet norm-surge state passes through untouched: the alarm
            # is a data-mode construct (pipeline stages compute different
            # layers, so a cross-stage norm median is meaningless; the
            # canary probe is this mode's fleet-level check).
            fleet_norm=state.fleet_norm,
            fleet_raw_streak=state.fleet_raw_streak,
        )
        metrics = StepMetrics(
            loss=loss,
            per_node_loss=jnp.broadcast_to(loss, (S,)),
            trust_scores=trust.scores,
            status=trust.status,
            attacked=attacked,
            verified=verified,
            finite=finite_b,
            weights=weights,
            system_trust=ts.system_trust(trust),
            grad_norm=optax.global_norm(grads),
            out_score=out_score,
            grad_score=grad_score,
            attack_type=attack_type,
            byzantine=byz,
            backdoor=backdoor,
            out_stats=stage_stats_out,
            grad_stats=grad_stats,
        )
        return new_state, metrics

    return train_step


def build_pipeline_eval_step(bundle, config: TrainingConfig, mesh: Mesh
                             ) -> Callable[[Any, Dict[str, Array]],
                                           Dict[str, Array]]:
    """Validation through the pipeline (params hold stacked [S, L/S, ...]
    blocks, so the DP eval path cannot be reused)."""
    cfg = bundle.config
    pipe_apply = build_pipeline_apply(cfg, mesh, config.num_nodes,
                                      config.num_microbatches)

    dp = mesh.shape.get(DATA_AXIS, 1)

    def eval_step(params, batch):
        tokens = batch["input"]
        x = gpt2.embed(params, tokens, cfg)
        b, t, d = x.shape
        M = config.num_microbatches
        mb = b // M
        x_mb = x.reshape(M, mb, t, d)
        y_mb, _, _, _ = pipe_apply(params["blocks"], x_mb)
        if dp > 1:
            # Same sharding-preserving merge + target permutation as the
            # train loss (see build_pipeline_train_step.loss_fn).
            y = y_mb.transpose(1, 0, 2, 3).reshape(b, t, d)
            batch = dict(
                batch,
                target=batch["target"].reshape(M, mb, t).transpose(
                    1, 0, 2
                ).reshape(b, t),
            )
        else:
            y = y_mb.reshape(b, t, d)
        chunk = gpt2.resolve_lm_head_chunk(cfg, int(batch["target"].size))
        if chunk:
            # Same memory contract as training: the fused eval never
            # materialises the [B, T, V] logits (ops/fused_ce.py).
            from trustworthy_dl_tpu.ops.fused_ce import fused_lm_eval

            normed = L.layernorm(params["ln_f"], y)
            loss, acc = fused_lm_eval(normed, params["wte"],
                                      batch["target"], chunk,
                                      cfg.dtype)
            return {"loss": loss, "accuracy": acc}
        logits = gpt2.unembed(params, y, cfg)
        return {
            "loss": L.cross_entropy_loss(logits, batch["target"]),
            "accuracy": L.accuracy(logits, batch["target"]),
        }

    return eval_step

"""Sequence / context parallelism — first-class long-context support.

Entirely absent from the reference (SURVEY §5.7: no sequence-dimension
handling, no attention code at all); required by the build charter.  Two
strategies over the 'seq' mesh axis:

* **Ulysses** (`ulysses_attention`): activations outside attention are
  sharded on the sequence dim; around the attention core they reshard to
  head-sharding via GSPMD constraints, so XLA inserts the all_to_all pair.
  Simple, exact, bandwidth-heavy — the easier first implementation.

* **Ring attention** (`ring_attention`): each device keeps its Q chunk and
  rotates K/V chunks around the ICI ring with ``ppermute``, accumulating
  flash-style online softmax (running max + normaliser), so attention over
  the full sequence costs O(T/s) memory per device and overlaps compute
  with neighbour transfers.  Exact (not approximate) — verified against
  full attention in tests.

Both register with the GPT-2 attention registry (models/gpt2.py) under
"ulysses" / "ring"; a mesh context (``use_sequence_mesh``) supplies the mesh
since model forwards run under plain ``jit``.  With no context set they fall
back to full attention so models stay runnable anywhere.
"""

from __future__ import annotations

import contextlib
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from trustworthy_dl_tpu.core import sharding as shreg
from trustworthy_dl_tpu.core.mesh import SEQ_AXIS, \
    shard_map_compat as shard_map

#: Registry rules for this mode: the Ulysses exchange is exactly the
#: head<->seqlen logical rename the table encodes (both map onto the
#: 'seq' mesh axis).
_SP_RULES = shreg.rules_for("sequence")
from trustworthy_dl_tpu.models.gpt2 import full_attention, register_attention

_SEQ_MESH: Optional[Mesh] = None

NEG_INF = -1e30


def set_sequence_mesh(mesh: Optional[Mesh]) -> None:
    global _SEQ_MESH
    _SEQ_MESH = mesh


def get_sequence_mesh() -> Optional[Mesh]:
    if _SEQ_MESH is not None and SEQ_AXIS in _SEQ_MESH.axis_names:
        return _SEQ_MESH
    return None


@contextlib.contextmanager
def use_sequence_mesh(mesh: Mesh):
    prev = _SEQ_MESH
    set_sequence_mesh(mesh)
    try:
        yield
    finally:
        set_sequence_mesh(prev)


# ---------------------------------------------------------------------------
# Ulysses: all_to_all head<->sequence reshard around full attention
# ---------------------------------------------------------------------------


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      causal: bool = True) -> jax.Array:
    """[B, H, T, D] attention with Ulysses-style resharding.

    Inputs arrive sequence-sharded (P(None, None, 'seq', None) — the natural
    layout of seq-sharded activations after the QKV projection); constraints
    flip them to head-sharding for the exact attention core and back, which
    GSPMD lowers to the canonical all_to_all pair over ICI.
    """
    mesh = get_sequence_mesh()
    if mesh is None:
        return full_attention(q, k, v, causal)
    heads_sharded = _SP_RULES.named_sharding(
        mesh, None, shreg.HEAD, None, None)
    seq_sharded = _SP_RULES.named_sharding(
        mesh, None, None, shreg.SEQLEN, None)
    q, k, v = (jax.lax.with_sharding_constraint(a, heads_sharded)
               for a in (q, k, v))
    out = full_attention(q, k, v, causal)
    out = jax.lax.with_sharding_constraint(out, heads_sharded)
    return jax.lax.with_sharding_constraint(out, seq_sharded)


# ---------------------------------------------------------------------------
# Ring attention: ppermute K/V rotation + online softmax
# ---------------------------------------------------------------------------


def _use_flash_chunks(tl: int, d: int) -> bool:
    """The Pallas flash kernel handles the per-rotation chunk attention
    when the chunk shape is kernel-eligible (ops/flash_attention.
    supports_flash — the single predicate shared with the public wrapper);
    otherwise the einsum body below runs.  For long-context runs (the
    reason ring attention exists) the kernel path is what makes the memory
    story real: the einsum body materialises [B, H, Tl, Tl] scores per
    rotation — at Tl = 8k that is gigabytes — while the kernel streams
    K/V blocks through VMEM at O(Tl·D)."""
    from trustworthy_dl_tpu.ops.flash_attention import supports_flash

    return supports_flash(tl, d)


def _merge_chunk(lse_run, out_run, lse_i, o_i):
    """Combine a normalized chunk result (o_i, lse_i) into the running
    (lse, out) accumulator — the cross-chunk half of online softmax.

    The "no contribution" sentinel is the finite NEG_INF (-1e30), not
    -inf (which would NaN the logaddexp/exp gradients), so the guards
    test against the sentinel explicitly rather than isfinite."""
    new_lse = jnp.logaddexp(lse_run, lse_i)
    w_run = jnp.where(lse_run > NEG_INF / 2, jnp.exp(lse_run - new_lse), 0.0)
    w_i = jnp.where(lse_i > NEG_INF / 2, jnp.exp(lse_i - new_lse), 0.0)
    out = out_run * w_run[..., None] + o_i.astype(jnp.float32) * w_i[..., None]
    return new_lse, out


def _ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                          causal: bool, ring_size: int) -> jax.Array:
    """Per-device body under shard_map: q/k/v are this device's sequence
    chunk [B, H, Tl, D].  K/V rotate ``ring_size`` times; online-softmax
    accumulation keeps the result exact across chunks.  Per-rotation chunk
    attention runs through the Pallas flash kernel when the chunk tiles
    (see _use_flash_chunks), else through a fused einsum."""
    stage = jax.lax.axis_index(SEQ_AXIS)
    b, h, tl, d = q.shape
    scale = 1.0 / math.sqrt(d)
    q_pos = stage * tl + jnp.arange(tl)

    if _use_flash_chunks(tl, d):
        from trustworthy_dl_tpu.ops.flash_attention import (
            _blocks_for,
            flash_chunk,
        )

        bq, bk = _blocks_for(tl)
        merge = lambda a: a.reshape(b * h, tl, d)

        def chunk(k_cur, v_cur, chunk_causal: bool):
            o, lse = flash_chunk(merge(q), merge(k_cur), merge(v_cur),
                                 chunk_causal, bq, bk)
            return (o.reshape(b, h, tl, d),
                    lse.reshape(b, h, tl))

        def attend(k_cur, v_cur, i):
            src = (stage - i) % ring_size
            if not causal:
                return chunk(k_cur, v_cur, False)
            # src > stage: chunk entirely in the future — skip.
            # src == stage: the diagonal chunk — causal kernel.
            # src < stage: entirely visible — non-causal kernel.
            return jax.lax.switch(
                jnp.clip(jnp.sign(src - stage) + 1, 0, 2).astype(jnp.int32),
                [
                    lambda: chunk(k_cur, v_cur, False),
                    lambda: chunk(k_cur, v_cur, True),
                    lambda: (jnp.zeros((b, h, tl, d), q.dtype),
                             jnp.full((b, h, tl), NEG_INF, jnp.float32)),
                ],
            )

        def body(carry, i):
            # Rotate FIRST, then attend: the i=0 chunk is consumed outside
            # the scan, so only ring_size-1 rotations happen and no K/V
            # ppermute pair is ever computed just to be discarded.
            k_cur, v_cur, lse, out = carry
            perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]
            k_cur = jax.lax.ppermute(k_cur, SEQ_AXIS, perm)
            v_cur = jax.lax.ppermute(v_cur, SEQ_AXIS, perm)
            o_i, lse_i = attend(k_cur, v_cur, i)
            lse, out = _merge_chunk(lse, out, lse_i, o_i)
            return (k_cur, v_cur, lse, out), None

        out0 = jnp.zeros((b, h, tl, d), jnp.float32)
        lse0 = jnp.full((b, h, tl), NEG_INF, jnp.float32)
        o_0, lse_0 = attend(k, v, jnp.zeros((), jnp.int32))
        lse0, out0 = _merge_chunk(lse0, out0, lse_0, o_0)
        (_, _, _, out), _ = jax.lax.scan(
            body, (k, v, lse0, out0), jnp.arange(1, ring_size)
        )
        return out.astype(q.dtype)

    def accumulate(m, l, acc, k_cur, v_cur, i):
        # After i rotations this device holds the chunk originating at
        # stage - i (mod ring).
        src = (stage - i) % ring_size
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur).astype(
            jnp.float32
        ) * scale
        if causal:
            k_pos = src * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        else:
            mask = jnp.ones((tl, tl), bool)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # Masked entries contribute exactly zero probability mass.
        p = jnp.where(mask[None, None],
                      jnp.exp(scores - m_new[..., None]), 0.0)
        correction = jnp.exp(m - m_new)
        l = l * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32)
        )
        return m_new, l, acc

    def body(carry, i):
        # Rotate first (see the flash body): ring_size-1 rotations total.
        k_cur, v_cur, m, l, acc = carry
        perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]
        k_cur = jax.lax.ppermute(k_cur, SEQ_AXIS, perm)
        v_cur = jax.lax.ppermute(v_cur, SEQ_AXIS, perm)
        m, l, acc = accumulate(m, l, acc, k_cur, v_cur, i)
        return (k_cur, v_cur, m, l, acc), None

    m0 = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    acc0 = jnp.zeros((b, h, tl, d), jnp.float32)
    m0, l0, acc0 = accumulate(m0, l0, acc0, k, v, jnp.zeros((), jnp.int32))
    (_, _, m, l, acc), _ = jax.lax.scan(
        body, (k, v, m0, l0, acc0), jnp.arange(1, ring_size)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True) -> jax.Array:
    """[B, H, T, D] exact blockwise ring attention over the 'seq' axis
    (SURVEY §5.7; ring schedule over ICI)."""
    mesh = get_sequence_mesh()
    if mesh is None:
        return full_attention(q, k, v, causal)
    ring_size = dict(zip(mesh.axis_names, mesh.devices.shape))[SEQ_AXIS]
    if q.shape[2] % ring_size:
        return full_attention(q, k, v, causal)
    spec = _SP_RULES.partition_spec(None, None, shreg.SEQLEN, None)
    fn = shard_map(
        lambda q_, k_, v_: _ring_attention_local(q_, k_, v_, causal, ring_size),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


register_attention("ulysses", ulysses_attention)
register_attention("ring", ring_attention)

"""``python -m trustworthy_dl_tpu.analysis`` == trustworthy-dl-lint."""

import sys

from trustworthy_dl_tpu.analysis.cli import main

sys.exit(main())

"""Recompile and host-sync hazard rules for the jitted hot paths.

Two real shipped bugs sit behind these:

* PR 10's compile watcher caught the trainer silently recompiling the
  ENTIRE train step on the first step after every threshold-adjustment
  epoch (an input's sharding drifted, changing the jit cache key).  The
  static cousins of that failure — re-``jit`` inside a loop,
  ``jax.jit(lambda ...)`` (a fresh cache key per evaluation), and
  device-constant literals built per hot-loop iteration — are all
  visible in the AST.
* PR 4 coalesced the serve hot path to ONE host pull per decode tick
  and per prefill; an accidental ``np.asarray``/``float()``/``.item()``
  on a traced value in those functions silently re-serialises the
  pipeline.  The rule taints locals assigned from device-producing
  calls (jnp.*, ``*_impl``, ``_programs()[...]``-style dispatches) and
  flags sync spellings applied to tainted values; the intentional
  single pulls are inline-suppressed at the site with their
  justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from trustworthy_dl_tpu.analysis import astutil
from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule,
                                                match_any)

_JIT_CALLS = frozenset({"jax.jit", "jax.pmap"})

#: jnp constructors whose all-literal call builds a device constant.
_DEVICE_LITERAL_CTORS = frozenset({
    "jnp.array", "jnp.asarray", "jnp.zeros", "jnp.ones", "jnp.full",
    "jnp.arange",
})

#: Function-name shapes that mark a serving/training hot loop body.
_HOT_FUNCTION_PATTERNS = ("*tick*", "*decode*", "*prefill*", "*step*",
                          "train_epoch", "run_until_idle")

_SYNC_FUNCS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                         "numpy.array", "jax.device_get"})
_SYNC_BUILTINS = frozenset({"float", "int", "bool"})
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    return False


class RecompileHazardRule(Rule):
    """jit cache-key churn visible statically: re-jit inside loops,
    jit-of-lambda, and per-iteration device-constant literals in hot
    loops."""

    name = "recompile-hazard"
    description = ("no jax.jit in loops, no jax.jit(lambda), no "
                   "jnp.array literals inside hot loops")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return rel.startswith(config.package_name + "/") \
            or rel == "bench.py"

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        hot_module = match_any(module.rel, config.hot_loop_modules)
        for node, parents in astutil.walk_with_parents(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.dotted(node.func)
            if name in _JIT_CALLS:
                if astutil.inside_loop(parents):
                    yield self.finding(
                        module, node,
                        f"{name}() inside a loop re-traces every "
                        f"iteration — build the jitted callable once "
                        f"outside")
                if node.args and isinstance(node.args[0], ast.Lambda):
                    yield self.finding(
                        module, node,
                        f"{name}(lambda ...) creates a fresh cache "
                        f"entry per evaluation — jit a named function")
            elif hot_module and name in _DEVICE_LITERAL_CTORS \
                    and node.args and _is_literal(node.args[0]):
                func = astutil.enclosing_function(parents)
                if func is None or not any(
                        astutil.match_name(func.name, p)
                        for p in _HOT_FUNCTION_PATTERNS):
                    continue
                if func.name.endswith("_impl"):
                    continue  # traced program body: constants fold
                if astutil.inside_loop(parents, within=func):
                    yield self.finding(
                        module, node,
                        f"{name}({ast.unparse(node.args[0])}) builds a "
                        f"device constant every {func.name}() loop "
                        f"iteration — hoist it (PR 10 storm pattern)")


def _device_producing(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Subscript):
        # _programs()["decode"](...) / prog["spec_draft"](...)
        return True
    name = astutil.dotted(func)
    if name is None:
        return False
    if name.startswith("jnp.") or name.startswith("jax."):
        return name not in _SYNC_FUNCS
    tail = name.rsplit(".", 1)[-1]
    return tail.endswith("_impl") or tail in ("_train_step",
                                              "_eval_step", "_jit_pack")


def _sync_kind(node: ast.Call) -> str:
    """'' when not a sync spelling, else a short description."""
    name = astutil.dotted(node.func)
    if name in _SYNC_FUNCS:
        return name
    if name in _SYNC_BUILTINS:
        return name
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _SYNC_METHODS:
        return f".{node.func.attr}()"
    return ""


_COMPS = (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)


class _TaintScan:
    """Per-function device-taint propagation (flow-insensitive to a
    fixpoint, which is conservative and cheap).  Comprehension targets
    are scoped, exactly as in Python 3: ``[np.asarray(d) for d in
    device_list]`` must not leak a tainted ``d`` over an unrelated
    host-side ``d`` later in the function."""

    def __init__(self, func: ast.AST):
        self.func = func
        self.tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in ast.walk(func):
                names: Set[str] = set()
                if isinstance(node, ast.Assign) \
                        and self._expr_tainted(node.value):
                    for t in node.targets:
                        names.update(astutil.assigned_names(t))
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                        and node.value is not None \
                        and self._expr_tainted(node.value):
                    names.update(astutil.assigned_names(node.target))
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "append" \
                        and isinstance(node.func.value, ast.Name) \
                        and any(self._expr_tainted(a) for a in node.args):
                    # xs.append(device_value): the container now yields
                    # device values when iterated.
                    names.add(node.func.value.id)
                elif isinstance(node, (ast.For, ast.AsyncFor)) \
                        and self._expr_tainted(node.iter):
                    names.update(astutil.assigned_names(node.target))
                if not names <= self.tainted:
                    self.tainted |= names
                    changed = True

    def comp_scope(self, node: ast.AST,
                   extra: frozenset = frozenset()) -> frozenset:
        """Comprehension-local tainted targets (targets bound from a
        tainted iterable), given already-accumulated ``extra``."""
        out = set(extra)
        for gen in getattr(node, "generators", ()):
            if self._expr_tainted(gen.iter, frozenset(out)):
                out.update(astutil.assigned_names(gen.target))
        return frozenset(out)

    def _expr_tainted(self, expr: ast.AST,
                      extra: frozenset = frozenset()) -> bool:
        """Does the expression's VALUE carry a device buffer?  Sync
        calls are boundaries (their result is host memory); a
        comprehension's value is its element expression, evaluated with
        the comprehension targets scoped in."""
        stack = [(expr, extra)]
        while stack:
            node, ctx = stack.pop()
            if isinstance(node, ast.Call):
                if _sync_kind(node):
                    continue  # result is host-side
                if _device_producing(node):
                    return True
            if isinstance(node, _COMPS):
                scope = self.comp_scope(node, ctx)
                if isinstance(node, ast.DictComp):
                    stack.append((node.key, scope))
                    stack.append((node.value, scope))
                else:
                    stack.append((node.elt, scope))
                continue
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and (node.id in self.tainted or node.id in ctx):
                return True
            stack.extend((child, ctx)
                         for child in ast.iter_child_nodes(node))
        return False


class HostSyncRule(Rule):
    """No device→host pulls on traced values inside the decode tick /
    ``_train_step`` dispatch paths beyond the inline-suppressed
    intentional ones."""

    name = "host-sync"
    description = ("float()/int()/.item()/np.asarray on device values "
                   "is banned in the decode tick and train dispatch")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return rel in config.host_sync_scopes

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        scoped = set(config.host_sync_scopes.get(module.rel, ()))
        for func in module.functions():
            if func.name not in scoped:
                continue
            scan = _TaintScan(func)
            for node, parents in astutil.walk_with_parents(func):
                if not isinstance(node, ast.Call):
                    continue
                kind = _sync_kind(node)
                if not kind:
                    continue
                arg: ast.AST
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_METHODS:
                    arg = node.func.value
                elif node.args:
                    arg = node.args[0]
                else:
                    continue
                # A sync call INSIDE a comprehension sees that
                # comprehension's scoped targets (``np.asarray(d) for d
                # in device_list`` is a real sync on d).
                extra: frozenset = frozenset()
                for ancestor in parents:
                    if isinstance(ancestor, _COMPS):
                        extra = scan.comp_scope(ancestor, extra)
                if scan._expr_tainted(arg, extra):
                    yield self.finding(
                        module, node,
                        f"{kind} forces a device->host sync on a "
                        f"traced value inside {func.name}() — batch it "
                        f"into the tick's single pull or suppress with "
                        f"justification")

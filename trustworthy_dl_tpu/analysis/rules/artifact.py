"""Artifact-contract rules: the reason-string vocabulary.

Flight dumps and their paired incident reports correlate BY reason
string — ``flight_007_slo_breach.json`` ↔ ``incident_007_slo_breach
.json`` ↔ the trigger event the assembler searches the trace for.  A
typo'd reason ("slo_breech") still writes an artifact, still passes
every runtime check, and silently orphans the incident from its
trigger: the timeline renders empty and nobody notices until the
post-mortem that needed it.  So the vocabulary is registered once in
``analysis/contracts.py`` (``ARTIFACT_REASONS``) and every LITERAL
reason at a dump/assemble call site must come from it — exactly the
stance ``metric-label-vocab`` takes for label names.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule)

#: Callables whose FIRST argument is the reason: ``ObsSession.
#: dump_flight`` (and the bound ``dump=`` handle the watchers hold),
#: ``IncidentAssembler.assemble``, and the fleet's ``_forensic_incident``
#: wrapper that forwards to both.
_REASON_FIRST = frozenset({"dump_flight", "assemble",
                           "_forensic_incident"})


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class ArtifactReasonRule(Rule):
    """Literal reason strings at flight-dump / incident-assembly call
    sites must come from ``contracts.ARTIFACT_REASONS``.  Dynamic
    reasons (a forwarded ``reason`` variable) are the producer's
    responsibility and pass through unchecked."""

    name = "artifact-reason-vocab"
    description = ("flight-dump/incident reason literals must come "
                   "from the registered vocabulary")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return (rel.startswith(config.package_name + "/")
                or rel == "bench.py" or rel.startswith("tests/"))

    def _reason_args(self, node: ast.Call):
        """Candidate literal reasons this call carries, with the node
        to anchor the finding on."""
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        out = []
        if name in _REASON_FIRST or name == "dump":
            # Only the dump/assemble surfaces own the vocabulary; a
            # ``reason=`` kwarg on anything else (pytest marks, trace
            # emits, failover scheduling) is a different namespace.
            for kw in node.keywords:
                if kw.arg == "reason" \
                        and _const_str(kw.value) is not None:
                    out.append(kw.value)
        if name in _REASON_FIRST:
            if node.args and _const_str(node.args[0]) is not None:
                out.append(node.args[0])
        elif name == "dump":
            # ``FlightRecorder.dump(directory, reason)`` carries the
            # reason SECOND; the bound ``dump=`` handles the watchers
            # call carry it FIRST.  Either position being a string
            # literal marks it as a reason (json.dump/pickle.dump pass
            # objects and file handles there, never string literals).
            for arg in node.args[:2]:
                if _const_str(arg) is not None:
                    out.append(arg)
        return name, out

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name, args = self._reason_args(node)
            for arg in args:
                reason = _const_str(arg)
                if reason not in config.artifact_reasons:
                    yield self.finding(
                        module, arg,
                        f"{name}() reason {reason!r} is outside the "
                        f"registered vocabulary (add it to contracts."
                        f"ARTIFACT_REASONS deliberately)")

"""Resource-locality rules: one-spelling contracts for shared device
resources.

The paged serving tier keeps its compile-once pin by funnelling every
shape- or sharding-relevant decision through a single home module; a
helpful second spelling elsewhere (a local ``adapter_page_row`` clone,
an ad-hoc adapter ``PartitionSpec``) compiles — and silently forks the
pin, so churn that must never recompile starts recompiling on the
replica that took the fork.  These rules make the locality contract a
lint invariant instead of a code-review hope.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule,
                                                match_any)


def _partition_spec_aliases(module: ModuleInfo) -> Set[str]:
    """Local names ``jax.sharding.PartitionSpec`` is bound to in this
    module (``import ... as P`` included) — construction sites resolve
    through these the way the interpreter would."""
    names: Set[str] = set()
    for node in module.walk():
        if isinstance(node, ast.ImportFrom) and node.module \
                and "sharding" in node.module:
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return names


class AdapterLocalityRule(Rule):
    """The adapter page-table row and the adapter-pool PartitionSpecs
    are spelled ONLY in serve/adapters.py (contracts.
    ADAPTER_HOME_MODULE): a definition of ``adapter_page_row``/
    ``adapter_partition_specs`` elsewhere, or a ``PartitionSpec(...)``
    built inside an adapter-handling function elsewhere, forks the
    compile-once pin the paged programs key on.  Importing and CALLING
    the home spellings is the sanctioned path and is not flagged."""

    name = "adapter-locality"
    description = ("adapter page-table/PartitionSpec spellings live "
                   "only in serve/adapters.py")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return rel != config.adapter_home_module and (
            rel.startswith(config.package_name + "/") or rel == "bench.py")

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        reserved = set(config.adapter_locality_names)
        spec_names = _partition_spec_aliases(module)
        for node in module.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in reserved:
                    yield self.finding(
                        module, node,
                        f"{node.name}() redefined outside "
                        f"{config.adapter_home_module} — the adapter "
                        f"page table/PartitionSpecs have one spelling")
                    continue
                if "adapter" not in node.name.lower() or not spec_names:
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Name) \
                            and sub.func.id in spec_names:
                        yield self.finding(
                            module, sub,
                            f"adapter-targeted PartitionSpec built in "
                            f"{node.name}() — adapter sharding is "
                            f"spelled only in "
                            f"{config.adapter_home_module}")


class ShardingRegistryRule(Rule):
    """Every ``PartitionSpec(...)`` resolves through the logical-axis
    rule table in core/sharding.py (contracts.SHARDING_HOME_MODULE) —
    a spec constructed anywhere else hard-codes a mesh-axis name the
    registry can no longer retarget, and forks the layout the
    compile-once pins and elastic migrations key on.  Catches direct
    calls, ``... as P`` aliases, and attribute spellings
    (``jax.sharding.PartitionSpec(...)``); importing the registry's
    helpers is the sanctioned path and is not flagged."""

    name = "sharding-registry-only"
    description = ("PartitionSpec construction lives only in "
                   "core/sharding.py (the logical-axis registry)")

    def applies(self, rel: str, config: LintConfig) -> bool:
        if rel == config.sharding_home_module:
            return False
        if match_any(rel, config.sharding_spec_whitelist):
            return False
        return rel.startswith(config.package_name + "/") \
            or rel == "bench.py"

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        spec_names = _partition_spec_aliases(module)
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            direct = isinstance(node.func, ast.Name) \
                and node.func.id in spec_names
            attr = isinstance(node.func, ast.Attribute) \
                and node.func.attr == "PartitionSpec"
            if direct or attr:
                yield self.finding(
                    module, node,
                    f"PartitionSpec constructed outside "
                    f"{config.sharding_home_module} — shardings "
                    f"resolve through the logical-axis registry "
                    f"(core.sharding helpers), not ad-hoc specs")

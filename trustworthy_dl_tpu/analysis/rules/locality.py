"""Resource-locality rules: one-spelling contracts for shared device
resources.

The paged serving tier keeps its compile-once pin by funnelling every
shape- or sharding-relevant decision through a single home module; a
helpful second spelling elsewhere (a local ``adapter_page_row`` clone,
an ad-hoc adapter ``PartitionSpec``) compiles — and silently forks the
pin, so churn that must never recompile starts recompiling on the
replica that took the fork.  These rules make the locality contract a
lint invariant instead of a code-review hope.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule)


def _partition_spec_aliases(module: ModuleInfo) -> Set[str]:
    """Local names ``jax.sharding.PartitionSpec`` is bound to in this
    module (``import ... as P`` included) — construction sites resolve
    through these the way the interpreter would."""
    names: Set[str] = set()
    for node in module.walk():
        if isinstance(node, ast.ImportFrom) and node.module \
                and "sharding" in node.module:
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return names


class AdapterLocalityRule(Rule):
    """The adapter page-table row and the adapter-pool PartitionSpecs
    are spelled ONLY in serve/adapters.py (contracts.
    ADAPTER_HOME_MODULE): a definition of ``adapter_page_row``/
    ``adapter_partition_specs`` elsewhere, or a ``PartitionSpec(...)``
    built inside an adapter-handling function elsewhere, forks the
    compile-once pin the paged programs key on.  Importing and CALLING
    the home spellings is the sanctioned path and is not flagged."""

    name = "adapter-locality"
    description = ("adapter page-table/PartitionSpec spellings live "
                   "only in serve/adapters.py")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return rel != config.adapter_home_module and (
            rel.startswith(config.package_name + "/") or rel == "bench.py")

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        reserved = set(config.adapter_locality_names)
        spec_names = _partition_spec_aliases(module)
        for node in module.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in reserved:
                    yield self.finding(
                        module, node,
                        f"{node.name}() redefined outside "
                        f"{config.adapter_home_module} — the adapter "
                        f"page table/PartitionSpecs have one spelling")
                    continue
                if "adapter" not in node.name.lower() or not spec_names:
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Name) \
                            and sub.func.id in spec_names:
                        yield self.finding(
                            module, sub,
                            f"adapter-targeted PartitionSpec built in "
                            f"{node.name}() — adapter sharding is "
                            f"spelled only in "
                            f"{config.adapter_home_module}")

"""Determinism rules for the tick-deterministic / prediction modules.

The drill architecture (``FaultPlan.predict*``, ``predict_attacker_
trajectory``, ``autoscale_pressure``) pins EXACT counts against seeded
runs — a wall clock, an unseeded RNG, or a hash-order-dependent set
iteration in those modules turns a pinned drill into a flake that only
fires in CI at 3am.
"""

from __future__ import annotations

import ast
from typing import Iterable

from trustworthy_dl_tpu.analysis import astutil
from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule,
                                                match_any)

#: Wall-clock / ambient-state calls that leak real time into decisions.
_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "date.today", "datetime.date.today",
})

#: np.random attrs that are NOT the seeded-generator constructors.
_SEEDED_FACTORIES = frozenset({"default_rng", "Generator", "PCG64",
                               "SeedSequence"})


class TickDeterminismRule(Rule):
    """No wall clocks, unseeded RNGs, or set iteration in the modules
    whose decisions drills replay from (seed, tick) alone."""

    name = "tick-determinism"
    description = ("deterministic modules must not read clocks, "
                   "unseeded RNGs, or iterate sets")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return match_any(rel, config.deterministic_modules)

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        for node in module.walk():
            if isinstance(node, ast.Call):
                yield from self._check_call(module, node)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                yield from self._check_iter(module, node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    yield from self._check_iter(module, gen.iter)

    def _check_call(self, module: ModuleInfo, node: ast.Call
                    ) -> Iterable[Finding]:
        name = astutil.dotted(node.func)
        if name is None:
            return
        if name in _CLOCK_CALLS:
            yield self.finding(
                module, node,
                f"{name}() reads the wall clock in a tick-deterministic "
                f"module — decisions must be functions of (seed, tick)")
        elif name == "random" or name.startswith("random."):
            yield self.finding(
                module, node,
                f"{name}() uses the process-global RNG — use a seeded "
                f"np.random.default_rng(seed)")
        elif name.startswith("np.random.") or \
                name.startswith("numpy.random."):
            tail = name.rsplit(".", 1)[-1]
            if tail not in _SEEDED_FACTORIES:
                yield self.finding(
                    module, node,
                    f"{name}() draws from the global numpy RNG — use a "
                    f"seeded default_rng(seed)")
            elif tail == "default_rng" and not node.args:
                yield self.finding(
                    module, node,
                    "default_rng() without a seed is entropy-seeded — "
                    "pass the plan/config seed")

    def _check_iter(self, module: ModuleInfo, it: ast.AST
                    ) -> Iterable[Finding]:
        target = it
        if isinstance(target, ast.Call) \
                and astutil.dotted(target.func) in ("set", "frozenset"):
            pass
        elif isinstance(target, (ast.Set, ast.SetComp)):
            pass
        else:
            return
        yield self.finding(
            module, it,
            "iterating a set is hash-order dependent (string hashing is "
            "per-process randomised) — sort it first")


class PredictPurityRule(Rule):
    """The pure prediction functions drills pin against
    (``predict_*``, ``autoscale_pressure``, ``diurnal_rate``,
    ``predicted_replicas``) must compute from their arguments alone: no
    ``global``/``nonlocal`` declarations and no reads of module-level
    MUTABLE bindings (lists/dicts/sets/caches), which would make the
    pinned counts silently dependent on call history."""

    name = "predict-purity"
    description = ("predict_*/autoscale_pressure-style pure functions "
                   "must not touch module-global mutable state")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return rel.startswith(config.package_name + "/")

    def _mutable_globals(self, module: ModuleInfo) -> set:
        out: set = set()
        for stmt in module.tree.body:
            targets = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is not None and astutil.is_mutable_default(value):
                for t in targets:
                    out.update(astutil.assigned_names(t))
        return out

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        mutable = self._mutable_globals(module)
        for func in module.functions():
            if not any(astutil.match_name(func.name, p)
                       for p in config.predict_function_patterns):
                continue
            local = {a.arg for a in (
                func.args.posonlyargs + func.args.args
                + func.args.kwonlyargs)}
            if func.args.vararg:
                local.add(func.args.vararg.arg)
            if func.args.kwarg:
                local.add(func.args.kwarg.arg)
            for node in ast.walk(func):
                for name in getattr(node, "targets", []):
                    local.update(astutil.assigned_names(name))
                if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    local.update(astutil.assigned_names(node.target))
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    local.update(astutil.assigned_names(node.target))
                elif isinstance(node, ast.comprehension):
                    local.update(astutil.assigned_names(node.target))
                elif isinstance(node, (ast.Global, ast.Nonlocal)):
                    yield self.finding(
                        module, node,
                        f"{func.name}() declares "
                        f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                        f"{', '.join(node.names)} — prediction functions "
                        f"must be pure")
            for node in ast.walk(func):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in mutable and node.id not in local:
                    yield self.finding(
                        module, node,
                        f"{func.name}() reads module-global mutable "
                        f"{node.id!r} — pass it as an argument so the "
                        f"pinned prediction stays a pure function")

"""Rule registry: one instance of every shipped rule, stable order.

Adding a rule: subclass :class:`~..engine.Rule` in the family module it
belongs to (or a new one), give it a unique kebab-case ``name`` and a
one-line ``description``, scope it via ``applies`` against the tables
in :mod:`~..contracts`, and list it here.  Ship it with fixture tests
in ``tests/test_lint.py`` (positive, negative, suppression) and fix —
or baseline, with a justification — whatever it finds in the package.
"""

from __future__ import annotations

from typing import List

from trustworthy_dl_tpu.analysis.engine import Rule
from trustworthy_dl_tpu.analysis.rules.artifact import ArtifactReasonRule
from trustworthy_dl_tpu.analysis.rules.determinism import (
    PredictPurityRule, TickDeterminismRule)
from trustworthy_dl_tpu.analysis.rules.hygiene import (
    ArtifactMetadataRule, AtomicWriteRule, BareExceptRule,
    MutableDefaultRule)
from trustworthy_dl_tpu.analysis.rules.jit import (HostSyncRule,
                                                   RecompileHazardRule)
from trustworthy_dl_tpu.analysis.rules.locality import (
    AdapterLocalityRule, ShardingRegistryRule)
from trustworthy_dl_tpu.analysis.rules.obs import (MetricLabelRule,
                                                   MetricPrefixRule,
                                                   ObsEmitRule)
from trustworthy_dl_tpu.analysis.rules.purity import ImportPurityRule


def all_rules() -> List[Rule]:
    """Fresh instances (rules are stateless, but cheap anyway)."""
    return [
        # obs contracts
        ObsEmitRule(),
        MetricPrefixRule(),
        MetricLabelRule(),
        # artifact contracts
        ArtifactReasonRule(),
        # determinism
        TickDeterminismRule(),
        PredictPurityRule(),
        # import purity
        ImportPurityRule(),
        # jit hazards
        RecompileHazardRule(),
        HostSyncRule(),
        # resource locality
        AdapterLocalityRule(),
        ShardingRegistryRule(),
        # hygiene
        MutableDefaultRule(),
        BareExceptRule(),
        ArtifactMetadataRule(),
        AtomicWriteRule(),
    ]

"""Obs-contract rules: typed emissions, metric naming, label vocabulary.

These replace the regex perimeter that lived in ``tests/test_obs.py``
(PR 3/7) with AST-accurate checks: a multi-line ``.emit(`` call, an
aliased registry handle, or an ``f"tddl_..."`` name all resolve the
same way the interpreter would, not the way a regex hopes they do.
"""

from __future__ import annotations

import ast
from typing import Iterable

from trustworthy_dl_tpu.analysis import astutil
from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule)

_REGISTER_METHODS = frozenset({"counter", "gauge", "histogram"})


def _package_scope(rel: str, config: LintConfig) -> bool:
    """Package sources + bench.py; the test tree deliberately registers
    invalid names/labels to exercise the registry's own validation."""
    return (rel.startswith(config.package_name + "/")
            or rel == "bench.py")


class ObsEmitRule(Rule):
    """Every ``*.emit(...)`` call site passes an ``EventType.<NAME>``
    member — new instrumentation cannot bypass schema validation with a
    raw string or a typo'd member (PR 7 caught two real raw-string
    sites in checkpoint.py/injector.py with the regex ancestor)."""

    name = "obs-emit-type"
    description = ("emit() must pass an EventType member whose schema "
                   "exists in EVENT_SCHEMAS")

    def applies(self, rel: str, config: LintConfig) -> bool:
        # events.py is the bus itself (validates at runtime); the test
        # tree drives emit through EventType already and negative cases
        # go through validate_event, not emit.
        return rel != f"{config.package_name}/obs/events.py" and (
            _package_scope(rel, config) or rel.startswith("tests/"))

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        members = config.resolved_event_members()
        for node in module.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"):
                continue
            if not node.args:
                yield self.finding(
                    module, node, "emit() without a positional "
                    "EventType argument")
                continue
            arg = node.args[0]
            name = astutil.dotted(arg)
            if name is None or not name.startswith("EventType."):
                got = name or ast.unparse(arg)
                yield self.finding(
                    module, arg,
                    f"emit() argument is not an EventType member: "
                    f"{got!r}")
            elif name.split(".", 1)[1] not in members:
                yield self.finding(
                    module, arg, f"emit() passes unknown member {name}")


class MetricPrefixRule(Rule):
    """Every literal metric name registered on a registry — directly
    via ``counter``/``gauge``/``histogram`` or through serve/engine.py's
    ``_metric`` degrade-on-conflict wrapper — carries the ``tddl_``
    prefix the Prometheus surface promises."""

    name = "metric-prefix"
    description = "registered metric literals must start with tddl_"

    def applies(self, rel: str, config: LintConfig) -> bool:
        return _package_scope(rel, config)

    def _name_arg(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) \
                and func.attr in _REGISTER_METHODS:
            return node.args[0] if node.args else None
        if astutil.dotted(func) == "_metric":
            # _metric(register, name, help, ...): name is the SECOND
            # positional.
            return node.args[1] if len(node.args) > 1 else None
        return None

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            arg = self._name_arg(node)
            if arg is None:
                continue
            head = astutil.literal_head(arg)
            if head is None:
                continue  # fully dynamic name: runtime validation owns it
            if not head.startswith(config.metric_prefix):
                yield self.finding(
                    module, arg,
                    f"metric name {head!r} lacks the "
                    f"{config.metric_prefix!r} prefix")


class MetricLabelRule(Rule):
    """Label names on registered metrics come from the known dashboard
    vocabulary (contracts.KNOWN_METRIC_LABELS) — a label outside it is
    a typo or an undeclared new dimension.  Dynamic label expressions
    (e.g. ``self._rlabel_names``) contribute their literal parts."""

    name = "metric-label-vocab"
    description = ("metric label names must come from the known "
                   "vocabulary")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return _package_scope(rel, config)

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_register = (isinstance(func, ast.Attribute)
                           and func.attr in _REGISTER_METHODS) \
                or astutil.dotted(func) == "_metric"
            if not is_register:
                continue
            for kw in node.keywords:
                if kw.arg != "labels":
                    continue
                for sub in ast.walk(kw.value):
                    label = astutil.const_str(sub)
                    if label is not None and \
                            label not in config.known_metric_labels:
                        yield self.finding(
                            module, sub,
                            f"label {label!r} is outside the known "
                            f"vocabulary (add it to contracts."
                            f"KNOWN_METRIC_LABELS deliberately)")

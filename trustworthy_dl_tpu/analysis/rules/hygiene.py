"""Hygiene rules: mutable defaults, bare excepts in recovery paths,
unstamped artifacts, non-atomic artifact writes.

Each is a shipped-bug class: PR 1 fixed ``StepMetrics.model_aux``'s
shared ``{}`` default; PR 2's topology sidecar was truncation-prone
until it went tmp+``os.replace``; VERDICT weak #5 flagged experiment
numbers published without the platform that produced them.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from trustworthy_dl_tpu.analysis import astutil
from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule,
                                                match_any)


def _is_dataclass_decorated(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = astutil.dotted(target) or ""
        if name.rsplit(".", 1)[-1] == "dataclass":
            return True
    return False


class MutableDefaultRule(Rule):
    """No mutable default arguments and no mutable dataclass-field
    defaults: the default is created ONCE and shared by every call /
    instance (the PR 1 ``model_aux={}`` bug)."""

    name = "mutable-default"
    description = ("function and dataclass defaults must not be "
                   "mutable containers")

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        for func in module.functions():
            args = func.args
            for default in list(args.defaults) + \
                    [d for d in args.kw_defaults if d is not None]:
                if astutil.is_mutable_default(default):
                    yield self.finding(
                        module, default,
                        f"{func.name}() has a mutable default "
                        f"({ast.unparse(default)}) shared across calls "
                        f"— use None and normalise inside")
        for node in module.walk():
            if not (isinstance(node, ast.ClassDef)
                    and _is_dataclass_decorated(node)):
                continue
            for stmt in node.body:
                value: Optional[ast.AST] = None
                if isinstance(stmt, ast.AnnAssign):
                    value = stmt.value
                elif isinstance(stmt, ast.Assign):
                    value = stmt.value
                if value is None:
                    continue
                if isinstance(value, ast.Call) and (
                        astutil.dotted(value.func) or ""
                ).rsplit(".", 1)[-1] == "field":
                    for kw in value.keywords:
                        if kw.arg == "default" \
                                and astutil.is_mutable_default(kw.value):
                            yield self.finding(
                                module, kw.value,
                                f"dataclass {node.name} field default "
                                f"is mutable — use default_factory")
                elif astutil.is_mutable_default(value):
                    yield self.finding(
                        module, value,
                        f"dataclass {node.name} has a mutable class "
                        f"default ({ast.unparse(value)}) — use "
                        f"field(default_factory=...)")


class BareExceptRule(Rule):
    """No bare ``except:`` in supervisor/fleet/chaos/checkpoint
    recovery paths — it swallows KeyboardInterrupt/SystemExit and can
    wedge the very ladder that exists to recover."""

    name = "bare-except"
    description = "recovery paths must not use bare except:"

    def applies(self, rel: str, config: LintConfig) -> bool:
        return match_any(rel, config.recovery_modules)

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        for node in module.walk():
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(
                    module, node,
                    "bare except: catches KeyboardInterrupt/SystemExit "
                    "— name the exception class (Exception at the "
                    "broadest)")


class ArtifactMetadataRule(Rule):
    """Every experiments//bench module that ``json.dump``s an artifact
    must reference the shared ``run_metadata`` helper (VERDICT weak #5:
    numbers without the platform that produced them)."""

    name = "artifact-metadata"
    description = ("json.dump artifact writers must stamp run_metadata")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return match_any(rel, config.stamped_artifact_modules)

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        stamped = any(
            (isinstance(n, ast.Name) and n.id == "run_metadata")
            or (isinstance(n, ast.Attribute) and n.attr == "run_metadata")
            for n in module.walk())
        if stamped:
            return
        for node in module.walk():
            if isinstance(node, ast.Call) and astutil.dotted(node.func) \
                    in ("json.dump", "atomic_write_json"):
                yield self.finding(
                    module, node,
                    "JSON artifact without a run_metadata stamp "
                    "anywhere in the module (use trustworthy_dl_tpu."
                    "obs.run_metadata)")
                return


class AtomicWriteRule(Rule):
    """Persistent artifacts must be written tmp-then-``os.replace`` (or
    via ``utils.io.atomic_write_*``): a direct ``open(path, "w")``
    truncates the old artifact before the new one is durable, so a
    crash mid-write destroys BOTH (the PR 2 topology-sidecar class)."""

    name = "atomic-write"
    description = ("artifact writes need tmp + os.replace (or the "
                   "atomic_write_* helpers)")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return match_any(rel, config.artifact_modules)

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        for node, parents in astutil.walk_with_parents(module.tree):
            if not isinstance(node, ast.Call):
                continue
            mode: Optional[str] = None
            target_desc = ""
            name = astutil.dotted(node.func)
            if name == "open" and len(node.args) >= 2:
                mode = astutil.const_str(node.args[1])
                target_desc = ast.unparse(node.args[0])
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("write_text", "write_bytes"):
                mode = "w"
                target_desc = ast.unparse(node.func.value)
            if mode is None or "w" not in mode:
                continue
            scope = astutil.enclosing_function(parents) or module.tree
            replaces = any(
                isinstance(n, ast.Call)
                and astutil.dotted(n.func) in ("os.replace", "os.rename")
                for n in ast.walk(scope))
            if not replaces:
                yield self.finding(
                    module, node,
                    f"write to {target_desc} truncates in place — "
                    f"write a tmp file and os.replace it (see "
                    f"utils.io.atomic_write_json)")

"""Import purity: modules documented host-only must not reach jax.

The obs CLI diagnoses runs on machines whose accelerator backend is the
broken thing; the sentinel diffs artifacts offline; the control plane
runs inside the fleet tick; the linter lints itself.  Importing jax —
even transitively, even without using it — initialises the backend and
breaks all of that.  The rule builds the package's MODULE-LEVEL import
graph (function-local lazy imports are the sanctioned escape hatch and
are ignored) and walks it from each host-only module; any path that
reaches ``jax``/``jaxlib`` is reported with the full chain.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from trustworthy_dl_tpu.analysis.engine import (Finding, LintConfig,
                                                ModuleInfo, Project, Rule,
                                                match_any)

# (imported module name, lineno) edges, cached per Project.
_GRAPH_ATTR = "_tddl_import_graph"


def _module_name(rel: str, package_name: str) -> Optional[str]:
    """Repo-relative path -> dotted module name (package files only)."""
    if not rel.endswith(".py"):
        return None
    if rel == "bench.py":
        return "bench"
    parts = rel[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if parts and (parts[0] == package_name or rel == "bench.py"):
        return ".".join(parts)
    return None


def _resolve(name: str, project: Project, package_name: str
             ) -> Optional[str]:
    """Dotted module name -> repo-relative file, if it's ours."""
    if not (name == package_name or name.startswith(package_name + ".")):
        return None
    base = name.replace(".", "/")
    for candidate in (f"{base}.py", f"{base}/__init__.py"):
        if project.get(candidate) is not None:
            return candidate
        if os.path.exists(os.path.join(project.root, candidate)):
            return candidate
    return None


def _skip_if(test: ast.AST) -> bool:
    """Imports guarded by ``if TYPE_CHECKING:`` never execute."""
    names = {n.id for n in ast.walk(test) if isinstance(n, ast.Name)}
    attrs = {n.attr for n in ast.walk(test)
             if isinstance(n, ast.Attribute)}
    return "TYPE_CHECKING" in names | attrs


def _module_level_imports(module: ModuleInfo, package_name: str
                          ) -> List[Tuple[str, int]]:
    """(imported dotted name, lineno) for every import that executes at
    module import time — including inside top-level if/try blocks."""
    out: List[Tuple[str, int]] = []

    def visit(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Import):
                out.extend((alias.name, stmt.lineno)
                           for alias in stmt.names)
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level:
                    pkg_parts = module.rel[:-3].split("/")
                    if pkg_parts[-1] == "__init__":
                        pkg_parts = pkg_parts[:-1]
                    else:
                        pkg_parts = pkg_parts[:-1]
                    anchor = pkg_parts[:len(pkg_parts) - (stmt.level - 1)]
                    base = ".".join(anchor + ([stmt.module]
                                              if stmt.module else []))
                else:
                    base = stmt.module or ""
                if base:
                    out.append((base, stmt.lineno))
                    # ``from pkg import name`` may bind a SUBMODULE —
                    # resolving decides; a plain attribute resolves to
                    # nothing and is dropped.
                    for alias in stmt.names:
                        if alias.name != "*":
                            out.append((f"{base}.{alias.name}",
                                        stmt.lineno))
            elif isinstance(stmt, ast.If):
                if not _skip_if(stmt.test):
                    visit(stmt.body)
                visit(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                visit(stmt.body)
                for handler in stmt.handlers:
                    visit(handler.body)
                visit(stmt.orelse)
                visit(stmt.finalbody)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                visit(stmt.body)

    if module.tree is not None:
        visit(module.tree.body)
    return out


def _import_graph(project: Project, package_name: str
                  ) -> Dict[str, List[Tuple[str, int]]]:
    graph = getattr(project, _GRAPH_ATTR, None)
    if graph is None:
        graph = {rel: _module_level_imports(m, package_name)
                 for rel, m in project.modules.items()}
        setattr(project, _GRAPH_ATTR, graph)
    return graph


class ImportPurityRule(Rule):
    """Host-only modules must not import jax/jaxlib transitively at
    module level; findings carry the offending chain."""

    name = "import-purity"
    description = ("host-only modules must not reach jax through "
                   "module-level imports")

    def applies(self, rel: str, config: LintConfig) -> bool:
        return match_any(rel, config.host_only_modules)

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        graph = _import_graph(project, config.package_name)
        # BFS over package-internal edges from this module; the FIRST
        # hop's lineno anchors the finding (that import is the one the
        # author of this module can actually fix or defer).
        seen = {module.rel}
        queue: List[Tuple[str, Tuple[str, ...], int]] = []
        for name, lineno in graph.get(module.rel, ()):
            queue.append((name, (module.rel,), lineno))
        reported = set()
        while queue:
            name, chain, first_lineno = queue.pop(0)
            top = name.split(".", 1)[0]
            if top in config.device_runtime_modules:
                key = (chain[0], chain[1] if len(chain) > 1 else name)
                if key not in reported:
                    reported.add(key)
                    pretty = " -> ".join(chain[1:] + (top,)) or top
                    yield self.finding(
                        module, first_lineno,
                        f"host-only module reaches {top!r} at module "
                        f"level via {pretty} — defer the import into "
                        f"the function that needs it")
                continue
            target = _resolve(name, project, config.package_name)
            if target is None or target in seen:
                continue
            seen.add(target)
            edges = graph.get(target)
            if edges is None:
                # Reachable module outside the scanned path set (e.g. a
                # single-file lint run): parse it on demand and cache.
                info = project.get(target)
                if info is None:
                    try:
                        info = ModuleInfo(
                            project.root,
                            os.path.join(project.root, target))
                    except OSError:
                        continue
                edges = _module_level_imports(info, config.package_name)
                graph[target] = edges
            for nxt, _ in edges:
                queue.append((nxt, chain + (target,), first_lineno))
        return

"""Small shared AST helpers for the rule modules (stdlib-only)."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

MUTABLE_DISPLAYS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                    ast.DictComp, ast.SetComp)

#: Constructor names whose call produces a fresh mutable container.
MUTABLE_FACTORIES = frozenset({
    "list", "dict", "set", "bytearray", "deque", "defaultdict",
    "OrderedDict", "Counter",
})


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None (calls/subscripts
    in the chain break it)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_with_parents(tree: ast.AST) -> Iterator[Tuple[ast.AST,
                                                       Tuple[ast.AST, ...]]]:
    """Yield (node, ancestors) pairs, ancestors outermost-first."""
    stack: List[Tuple[ast.AST, Tuple[ast.AST, ...]]] = [(tree, ())]
    while stack:
        node, parents = stack.pop()
        yield node, parents
        child_parents = parents + (node,)
        for child in ast.iter_child_nodes(node):
            stack.append((child, child_parents))


def enclosing_function(parents: Tuple[ast.AST, ...]
                       ) -> Optional[ast.AST]:
    """Innermost FunctionDef/AsyncFunctionDef ancestor, if any."""
    for node in reversed(parents):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
    return None


def inside_loop(node_parents: Tuple[ast.AST, ...],
                within: Optional[ast.AST] = None) -> bool:
    """True when any ancestor (optionally only those inside ``within``)
    is a for/while loop."""
    seen_within = within is None
    for parent in node_parents:
        if parent is within:
            seen_within = True
            continue
        if seen_within and isinstance(parent, (ast.For, ast.AsyncFor,
                                               ast.While)):
            return True
    return False


def is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, MUTABLE_DISPLAYS):
        return True
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name is not None and \
                name.rsplit(".", 1)[-1] in MUTABLE_FACTORIES:
            return True
    return False


def match_name(name: str, pattern: str) -> bool:
    """fnmatch on a bare identifier (function-name patterns)."""
    import fnmatch

    return fnmatch.fnmatch(name, pattern)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def literal_head(node: ast.AST) -> Optional[str]:
    """The statically-known string (or string PREFIX for f-strings) a
    name expression starts with; None when fully dynamic."""
    s = const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.JoinedStr) and node.values:
        return const_str(node.values[0])
    return None


def assigned_names(target: ast.AST) -> Iterator[str]:
    """Every plain Name bound by an assignment target (tuples/lists/
    starred unpacked recursively; attribute/subscript targets skipped)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, ast.Starred):
        yield from assigned_names(target.value)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from assigned_names(elt)

"""``trustworthy-dl-lint`` — run the invariant linter from the shell.

Host-only by contract: this process never imports jax (the
``import-purity`` rule lints this module's own import chain), so it
runs on CI boxes and broken-backend machines alike.

Exit codes: 0 clean (baselined findings and stale-baseline warnings do
not fail), 1 findings, 2 usage errors.

Usage::

    trustworthy-dl-lint                         # full perimeter
    trustworthy-dl-lint trustworthy_dl_tpu/serve
    trustworthy-dl-lint --rules obs-emit-type,metric-prefix
    trustworthy-dl-lint --format json           # machine-readable
    trustworthy-dl-lint --write-baseline        # grandfather current
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from trustworthy_dl_tpu.analysis import contracts
from trustworthy_dl_tpu.analysis.baseline import (load_baseline,
                                                  write_baseline)
from trustworthy_dl_tpu.analysis.engine import (LintEngine, repo_root,
                                                run_lint)
from trustworthy_dl_tpu.analysis.rules import all_rules


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trustworthy-dl-lint",
        description="AST-based invariant linter for the tddl codebase "
                    "(rule catalog: README.md §Static analysis)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the package, "
             "bench.py, and tests/)")
    parser.add_argument(
        "--root", default=None,
        help="repo root paths are reported relative to (default: "
             "autodetected from the installed package)")
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule names to run (default: all)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <root>/{contracts.DEFAULT_BASELINE})")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report grandfathered findings too")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings (pre-baseline) to the baseline "
             "file and exit 0; edit in the per-entry justifications")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="findings only, no summary line")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name:20s} {rule.description}")
        return 0

    root = os.path.abspath(args.root or repo_root())
    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",")
                      if r.strip()]
    paths = [os.path.abspath(p) for p in args.paths] or None

    baseline_path = args.baseline or os.path.join(
        root, contracts.DEFAULT_BASELINE)

    if args.write_baseline:
        if rule_names or paths:
            # A filtered run sees only a SUBSET of findings; writing it
            # wholesale would silently delete every other grandfathered
            # entry (and its hand-written justification).
            print("trustworthy-dl-lint: error: --write-baseline "
                  "replaces the whole baseline and cannot be combined "
                  "with --rules or path arguments", file=sys.stderr)
            return 2
        result = run_lint(root=root, paths=paths, rule_names=rule_names,
                          use_baseline=False)
        write_baseline(result.findings, baseline_path)
        print(f"baseline: {len(result.findings)} finding(s) written to "
              f"{baseline_path} — add a real justification per entry")
        return 0

    try:
        result = run_lint(root=root, paths=paths, rule_names=rule_names,
                          baseline_path=baseline_path,
                          use_baseline=not args.no_baseline)
    except ValueError as exc:           # unknown rule, bad baseline
        print(f"trustworthy-dl-lint: error: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        payload = {
            "files_scanned": result.files_scanned,
            "findings": [f.as_dict() for f in result.findings],
            "baselined": result.baselined,
            "stale_baseline": result.stale_baseline,
            "by_rule": result.by_rule(),
            "clean": result.clean,
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f"{f.location}: [{f.rule}] {f.message}")
        for entry in result.stale_baseline:
            print(f"stale baseline entry (matched nothing — delete "
                  f"it): [{entry['rule']}] {entry['path']}: "
                  f"{entry['message']}", file=sys.stderr)
        if not args.quiet:
            counts = ", ".join(f"{k}={v}"
                               for k, v in result.by_rule().items())
            print(f"{len(result.findings)} finding(s) in "
                  f"{result.files_scanned} file(s)"
                  + (f" [{counts}]" if counts else "")
                  + (f"; {result.baselined} baselined"
                     if result.baselined else ""))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())

"""Committed baseline of grandfathered findings.

Format (``tddl_lint_baseline.json`` at the repo root)::

    {"version": 1,
     "findings": [
       {"rule": "host-sync", "path": "trustworthy_dl_tpu/...",
        "message": "...", "justification": "one line of WHY"}]}

Every entry MUST carry a non-empty ``justification`` — a baseline entry
without a reason is just a hidden violation, and the loader refuses it.
Entries match on (rule, path, message); stale entries (matching no
current finding) are reported by the engine so the file only shrinks.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List

BASELINE_VERSION = 1


def load_baseline(path: str) -> List[Dict[str, str]]:
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version "
            f"{payload.get('version')!r} (expected {BASELINE_VERSION})")
    entries = payload.get("findings", [])
    for entry in entries:
        missing = [k for k in ("rule", "path", "message") if not
                   entry.get(k)]
        if missing:
            raise ValueError(
                f"baseline {path}: entry {entry!r} missing {missing}")
        if not str(entry.get("justification", "")).strip():
            raise ValueError(
                f"baseline {path}: entry for {entry['rule']} at "
                f"{entry['path']} has no justification — grandfathering "
                "requires a reason")
    return entries


def write_baseline(findings: Iterable, path: str,
                   justification: str = "grandfathered at baseline "
                   "creation — burn down before extending") -> Dict:
    """Serialise current findings as a fresh baseline (atomic write)."""
    payload = {
        "version": BASELINE_VERSION,
        "findings": [
            dict(f.fingerprint(), justification=justification)
            for f in findings
        ],
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return payload

"""The repo-specific contract tables the rules consult.

Each table is distilled from a shipped bug or an explicitly documented
module contract — when a module gains or sheds a contract (e.g. a new
host-only CLI, a new tick-deterministic controller), THIS file is the
one place to update; the rules read it through :class:`~.engine.
LintConfig`, so tests can substitute synthetic tables for fixtures.

Paths are repo-relative posix strings matched with :func:`fnmatch.
fnmatch` (``*`` crosses ``/`` — ``trustworthy_dl_tpu/obs/*.py`` covers
the whole subtree).
"""

from __future__ import annotations

#: Modules whose decisions must be reproducible from (seed, tick) alone
#: so chaos/fleet drills can pin exact counts (``FaultPlan.predict*``,
#: ``autoscale_pressure``): no wall clocks, no unseeded RNGs, no
#: cross-process-nondeterministic set iteration.  serve/control.py and
#: chaos/plan.py document this contract in their module docstrings;
#: chaos/adversary.py's controller is ONE pure function shared with
#: ``predict_attacker_trajectory``; obs/sentinel.py verdicts must not
#: depend on when the comparison runs.
DETERMINISTIC_MODULES = (
    "trustworthy_dl_tpu/serve/control.py",
    "trustworthy_dl_tpu/chaos/plan.py",
    "trustworthy_dl_tpu/chaos/adversary.py",
    "trustworthy_dl_tpu/obs/sentinel.py",
)

#: Modules documented host-only / jax-free: the obs CLI path must work
#: on a machine with a broken accelerator backend, the sentinel diffs
#: artifacts offline, the control plane runs inside the fleet tick, and
#: the linter lints itself.  A module-level import chain from any of
#: these that reaches ``jax``/``jaxlib`` is a contract break even when
#: the jax name is never used (importing it initialises the backend).
HOST_ONLY_MODULES = (
    "trustworthy_dl_tpu/obs/sentinel.py",
    "trustworthy_dl_tpu/obs/events.py",
    "trustworthy_dl_tpu/obs/meta.py",
    "trustworthy_dl_tpu/obs/recorder.py",
    "trustworthy_dl_tpu/obs/registry.py",
    "trustworthy_dl_tpu/obs/forensics.py",
    "trustworthy_dl_tpu/obs/verdicts.py",
    "trustworthy_dl_tpu/serve/control.py",
    "trustworthy_dl_tpu/cli.py",
    "trustworthy_dl_tpu/utils/io.py",
    "trustworthy_dl_tpu/analysis/*.py",
)

#: External top-level module names whose import breaks host-only purity.
DEVICE_RUNTIME_MODULES = frozenset({"jax", "jaxlib"})

#: Modules whose loops are serving/training hot paths: a ``jnp.array``
#: LITERAL built per iteration is a fresh device constant (and, closed
#: over a varying Python scalar, a fresh jit cache key — the PR 10
#: threshold-pushback storm pattern).
HOT_LOOP_MODULES = (
    "trustworthy_dl_tpu/serve/scheduler.py",
    "trustworthy_dl_tpu/serve/engine.py",
    "trustworthy_dl_tpu/engine/step.py",
    "trustworthy_dl_tpu/engine/trainer.py",
    "trustworthy_dl_tpu/models/generate.py",
    # The paged-attention kernel module runs INSIDE every paged decode
    # program (its wrapper traces per layer per tick) — a per-call
    # device constant here is a per-tick constant upload.
    "trustworthy_dl_tpu/ops/paged_attention.py",
)

#: module -> function names forming the latency-critical dispatch paths
#: where an accidental device->host pull (``np.asarray``/``float``/
#: ``.item()`` on a traced value) serialises the pipeline.  The ONE
#: intentional pull per tick is inline-suppressed at the site.
HOST_SYNC_SCOPES = {
    "trustworthy_dl_tpu/serve/scheduler.py": (
        "decode_tick", "_spec_tick", "_advance_prefill", "admit",
    ),
    "trustworthy_dl_tpu/engine/trainer.py": ("train_epoch",),
    # The kernel dispatch wrappers trace inside jitted serve programs:
    # any host pull of a traced value here would serialise every decode
    # tick (there is no intentional pull — these scopes allow zero).
    "trustworthy_dl_tpu/ops/paged_attention.py": (
        "paged_attention", "paged_prefill_attention", "fused_verify_tail",
        "adapter_delta", "logit_trust_stats",
    ),
}

#: Modules that write persistent artifacts (checkpoints, ledgers,
#: reports, experiment results): ``open(path, "w")`` without a
#: tmp-then-``os.replace`` swap in the same function truncates the old
#: artifact before the new one is durable (the PR 2 topology-sidecar
#: bug class).
ARTIFACT_MODULES = (
    "trustworthy_dl_tpu/obs/*.py",
    "trustworthy_dl_tpu/experiments/*.py",
    "trustworthy_dl_tpu/engine/checkpoint.py",
    "trustworthy_dl_tpu/trust/manager.py",
    "trustworthy_dl_tpu/detect/detector.py",
    "trustworthy_dl_tpu/serve/*.py",
    "trustworthy_dl_tpu/utils/*.py",
    "bench.py",
)

#: Modules whose JSON artifacts must carry the run_metadata stamp
#: (VERDICT weak #5: numbers published without the platform that
#: produced them).  Mirrors tests/test_obs.py's standing contract test.
STAMPED_ARTIFACT_MODULES = (
    "trustworthy_dl_tpu/experiments/*.py",
    "bench.py",
)

#: Recovery/supervision paths where a bare ``except:`` can swallow
#: KeyboardInterrupt/SystemExit and wedge the very ladder that exists
#: to recover (supervisor retries, fleet drains, chaos unwinds,
#: checkpoint commit).
RECOVERY_MODULES = (
    "trustworthy_dl_tpu/engine/supervisor.py",
    "trustworthy_dl_tpu/engine/checkpoint.py",
    "trustworthy_dl_tpu/serve/fleet.py",
    "trustworthy_dl_tpu/serve/engine.py",
    "trustworthy_dl_tpu/chaos/*.py",
)

#: Function-name patterns (fnmatch) of the pure prediction functions
#: drills pin against: ``FaultPlan.predict*``,
#: ``predict_attacker_trajectory``, ``autoscale_pressure``,
#: ``diurnal_rate``/``predicted_replicas``.  Pure means: output from
#: arguments only — reading module-global MUTABLE state (or declaring
#: ``global``) makes the pin silently dependent on call history.
PREDICT_FUNCTION_PATTERNS = (
    "predict_*",
    "autoscale_pressure",
    "diurnal_rate",
    "predicted_replicas",
)

#: The label-name vocabulary dashboards key on.  A label outside this
#: set is either a typo (``tenent``) or a new dimension that must be
#: added HERE (and to the dashboards) deliberately, not slipped in.
KNOWN_METRIC_LABELS = frozenset({
    "action", "adapter", "device", "direction", "dtype", "kind", "metric",
    "node", "outcome", "path", "phase", "program", "reason", "replica",
    "role", "scope", "signal", "slo", "slo_class", "stage", "state",
    "status", "tenant", "to_state", "type",
})

#: Metric-name prefix every registered literal must carry (the
#: Prometheus surface's naming promise).
METRIC_PREFIX = "tddl_"

#: The flight-dump / incident reason vocabulary.  Incident artifacts
#: pair with their flight dump and their trigger events BY reason
#: string — a typo'd reason silently orphans the incident from its
#: trigger (the timeline renders empty) — so every literal ``reason``
#: passed to ``dump_flight``/``recorder.dump``/``assemble`` must come
#: from this registered set.  New episode classes add their reason HERE
#: first (and to the README catalog), not inline.
ARTIFACT_REASONS = frozenset({
    # training supervisor ladder (engine/supervisor.py)
    "guard_trip", "rollback", "preemption",
    # watcher-driven dumps (obs/slo.py, anomaly.py, compilewatch.py)
    "slo_breach", "anomaly", "compile_storm",
    # fleet forensic episodes (serve/fleet.py)
    "replica_quarantine", "replica_preempt", "adapter_quarantine",
    "migration_refused",
    # operator-initiated artifacts (examples, tests, CLI)
    "drill", "manual",
})

#: The adapter-resource locality contract (PR 16): the per-slot adapter
#: page-table row and the pool's PartitionSpecs each have exactly ONE
#: spelling, in serve/adapters.py — the compile-once pin of the paged
#: decode/prefill programs keys on that table's shape and the pool's
#: sharding, so a second spelling elsewhere is a fork of the pin, not a
#: convenience.  A definition of either name, or an adapter-targeted
#: ``PartitionSpec(...)`` construction, outside the home module is a
#: finding.
ADAPTER_HOME_MODULE = "trustworthy_dl_tpu/serve/adapters.py"
ADAPTER_LOCALITY_NAMES = ("adapter_page_row", "adapter_partition_specs")

#: The sharding-registry locality contract (PR 19): EVERY
#: ``PartitionSpec(...)`` in the package resolves through the
#: logical-axis rule table in core/sharding.py — the one place the
#: logical->mesh axis mapping is spelled.  A PartitionSpec constructed
#: anywhere else (including under a ``... as P`` alias) bypasses the
#: registry: it hard-codes a mesh-axis name that the rule table can no
#: longer retarget, and it forks the layout the compile-once pins and
#: the elastic migrations key on.  Modules with a sanctioned reason to
#: spell specs directly are whitelisted HERE, deliberately.
SHARDING_HOME_MODULE = "trustworthy_dl_tpu/core/sharding.py"
SHARDING_SPEC_WHITELIST = (
    # The adapter pool's home module: its spec spellings are already
    # governed (and scoped) by the adapter-locality rule above.
    ADAPTER_HOME_MODULE,
)

#: Default committed baseline of grandfathered findings (repo-relative).
DEFAULT_BASELINE = "tddl_lint_baseline.json"


def event_type_members():
    """Names of the ``EventType`` enum — imported lazily from the
    (host-only) events module so contract tables stay import-light."""
    from trustworthy_dl_tpu.obs.events import EventType

    return frozenset(EventType.__members__)

"""tddl-lint — AST-based invariant linter for the tddl codebase.

Thirteen PRs of trustworthy-serving work accumulated contracts that were
enforced only by convention, by regex scans in ``tests/test_obs.py``, or
by runtime watchers that fire after the damage (PR 10's CompileWatcher
caught a real silent full-step recompile; PR 2 shipped four latent
donation/aliasing heap corruptions).  This package turns those
hard-won contracts into *static* rules that fail at review time:

* **obs contracts** — every ``.emit(`` passes a real ``EventType``
  member; every registered metric literal is ``tddl_``-prefixed and its
  label names come from the known dashboard vocabulary.
* **determinism** — no wall clocks / unseeded RNGs / set-iteration in
  the tick-deterministic modules drills pin exact counts against.
* **import purity** — modules documented host-only must not reach
  ``jax``/``jaxlib`` through any module-level import chain.
* **recompile hazards** — no re-``jit`` inside loops, no
  ``jax.jit(lambda ...)`` cache-key churn, no ``jnp.array`` literals
  built inside hot loops (the PR 10 storm pattern).
* **host-sync hazards** — no ``np.asarray``/``float()``/``.item()`` on
  device values inside the decode tick / ``_train_step`` dispatch.
* **hygiene** — mutable defaults, bare ``except:`` in recovery paths,
  unstamped or non-atomic artifact writes.

Host-only by contract: nothing in this package (or anything it imports
at module level) may import jax — the ``import-purity`` rule lints the
linter itself.

Entry points: the ``trustworthy-dl-lint`` console script
(:mod:`trustworthy_dl_tpu.analysis.cli`), the tier-1 test perimeter
(``tests/test_lint.py``), and the ``TDDL_BENCH_LINT=1`` bench hook.
"""

from trustworthy_dl_tpu.analysis.engine import (  # noqa: F401
    Finding,
    LintConfig,
    LintEngine,
    LintResult,
    ModuleInfo,
    Project,
    Rule,
    run_lint,
)
from trustworthy_dl_tpu.analysis.baseline import (  # noqa: F401
    load_baseline,
    write_baseline,
)
from trustworthy_dl_tpu.analysis.rules import all_rules  # noqa: F401

__all__ = [
    "Finding", "LintConfig", "LintEngine", "LintResult", "ModuleInfo",
    "Project", "Rule", "all_rules", "load_baseline", "run_lint",
    "write_baseline",
]

"""Rule engine: parse once, visit per rule, report ``file:line``
findings with inline suppressions and a committed baseline.

The engine is deliberately boring infrastructure — the interesting
content lives in the rule modules and :mod:`~.contracts`.  Contracts:

* **Host-only.**  Parsing is :mod:`ast`; nothing here imports jax.
* **One parse per file.**  Every rule sees the same
  :class:`ModuleInfo`; a file that fails to parse yields a single
  ``parse-error`` finding instead of crashing the run.
* **Suppressions are line-anchored.**  ``# tddl-lint: disable=RULE``
  on the finding's line (or the pure-comment line directly above it)
  silences that rule there; ``# tddl-lint: disable-file=RULE`` anywhere
  silences the rule for the whole file.  Suppressing a rule that did
  not fire is harmless (the comment documents intent).
* **Baseline is for grandfathering.**  Findings matching a committed
  baseline entry (rule + path + message) are filtered out and counted
  separately; stale entries (matched nothing) are surfaced so the
  baseline shrinks monotonically.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from trustworthy_dl_tpu.analysis import contracts

_SUPPRESS_RE = re.compile(
    r"#\s*tddl-lint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[\w*-]+(?:\s*,\s*[\w*-]+)*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source location."""

    rule: str
    path: str          # repo-relative posix path
    line: int
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def fingerprint(self) -> Dict[str, str]:
        """The baseline identity: line numbers drift under unrelated
        edits, so grandfathering matches on rule + path + message."""
        return {"rule": self.rule, "path": self.path,
                "message": self.message}

    def as_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclasses.dataclass
class LintConfig:
    """Contract tables the rules consult — defaults from
    :mod:`~.contracts`, overridable so fixture trees can exercise
    module-scoped rules without mimicking the real layout."""

    deterministic_modules: Sequence[str] = contracts.DETERMINISTIC_MODULES
    host_only_modules: Sequence[str] = contracts.HOST_ONLY_MODULES
    device_runtime_modules: frozenset = contracts.DEVICE_RUNTIME_MODULES
    hot_loop_modules: Sequence[str] = contracts.HOT_LOOP_MODULES
    host_sync_scopes: Dict[str, Sequence[str]] = dataclasses.field(
        default_factory=lambda: dict(contracts.HOST_SYNC_SCOPES))
    artifact_modules: Sequence[str] = contracts.ARTIFACT_MODULES
    stamped_artifact_modules: Sequence[str] = \
        contracts.STAMPED_ARTIFACT_MODULES
    recovery_modules: Sequence[str] = contracts.RECOVERY_MODULES
    predict_function_patterns: Sequence[str] = \
        contracts.PREDICT_FUNCTION_PATTERNS
    known_metric_labels: frozenset = contracts.KNOWN_METRIC_LABELS
    metric_prefix: str = contracts.METRIC_PREFIX
    artifact_reasons: frozenset = contracts.ARTIFACT_REASONS
    adapter_home_module: str = contracts.ADAPTER_HOME_MODULE
    adapter_locality_names: Sequence[str] = contracts.ADAPTER_LOCALITY_NAMES
    sharding_home_module: str = contracts.SHARDING_HOME_MODULE
    sharding_spec_whitelist: Sequence[str] = contracts.SHARDING_SPEC_WHITELIST
    package_name: str = "trustworthy_dl_tpu"
    #: EventType member names; ``None`` = resolve from the real enum.
    event_members: Optional[frozenset] = None

    def resolved_event_members(self) -> frozenset:
        if self.event_members is None:
            return contracts.event_type_members()
        return self.event_members


def match_any(rel: str, patterns: Iterable[str]) -> bool:
    return any(fnmatch.fnmatch(rel, p) for p in patterns)


class ModuleInfo:
    """One parsed source file: AST (or parse error) + suppressions."""

    def __init__(self, root: str, path: str):
        self.path = path
        self.rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=self.rel)
        except SyntaxError as exc:
            self.parse_error = f"line {exc.lineno}: {exc.msg}"
        self._file_disables: set = set()
        self._line_disables: Dict[int, set] = {}
        for lineno, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            names = {r.strip() for r in m.group("rules").split(",")}
            if m.group("scope"):
                self._file_disables |= names
            else:
                self._line_disables.setdefault(lineno, set()).update(names)

    def suppressed(self, rule: str, line: int) -> bool:
        def hit(names: set) -> bool:
            return rule in names or "*" in names \
                or any(fnmatch.fnmatch(rule, n) for n in names)

        if hit(self._file_disables):
            return True
        if hit(self._line_disables.get(line, set())):
            return True
        # The contiguous pure-comment block directly above the finding
        # counts too: long call expressions anchor on their first line,
        # and a reviewer writes the justification (possibly spanning
        # several comment lines) immediately above the statement.
        prev = line - 1
        while prev >= 1 and self.lines[prev - 1].lstrip().startswith("#"):
            if hit(self._line_disables.get(prev, set())):
                return True
            prev -= 1
        return False

    # -- AST conveniences ---------------------------------------------------

    def walk(self):
        return ast.walk(self.tree) if self.tree is not None else ()

    def functions(self):
        """Every (possibly nested) function/method definition."""
        for node in self.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


class Project:
    """All modules of one lint run, keyed by repo-relative path — rules
    needing whole-program context (the import-purity BFS) read this."""

    def __init__(self, root: str, modules: Sequence[ModuleInfo]):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {m.rel: m for m in modules}

    def get(self, rel: str) -> Optional[ModuleInfo]:
        return self.modules.get(rel)


class Rule:
    """Base class: subclass, set ``name``/``description``, implement
    ``check``.  ``applies`` gates which files the rule sees; the engine
    handles suppressions and the baseline."""

    name: str = ""
    description: str = ""

    def applies(self, rel: str, config: LintConfig) -> bool:
        return True

    def check(self, module: ModuleInfo, project: Project,
              config: LintConfig) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleInfo, node: Any, message: str
                ) -> Finding:
        line = getattr(node, "lineno", 0) if not isinstance(node, int) \
            else node
        return Finding(rule=self.name, path=module.rel, line=line,
                       message=message)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files_scanned: int
    baselined: int = 0
    stale_baseline: List[Dict[str, str]] = dataclasses.field(
        default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))


_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "checkpoints",
              "build", "dist"}


def default_scan_paths(root: str, package_name: str) -> List[str]:
    """The standing perimeter: the package tree, ``bench.py``, and the
    test suite (rules scope themselves tighter via ``applies``)."""
    paths = [os.path.join(root, package_name)]
    for extra in ("bench.py", "tests"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            paths.append(p)
    return paths


def collect_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(os.path.abspath(path))
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.abspath(
                        os.path.join(dirpath, name)))
    return out


class LintEngine:
    def __init__(self, rules: Sequence[Rule],
                 config: Optional[LintConfig] = None):
        self.rules = list(rules)
        self.config = config or LintConfig()
        names = [r.name for r in self.rules]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes or "" in names:
            raise ValueError(f"rules need unique non-empty names: {names}")

    def run(self, root: str, paths: Optional[Sequence[str]] = None,
            baseline: Optional[Sequence[Dict[str, str]]] = None,
            rule_names: Optional[Sequence[str]] = None) -> LintResult:
        root = os.path.abspath(root)
        if paths is None:
            paths = default_scan_paths(root, self.config.package_name)
        files = collect_files(paths)
        modules = [ModuleInfo(root, f) for f in files]
        project = Project(root, modules)

        active = self.rules
        if rule_names is not None:
            known = {r.name for r in self.rules}
            unknown = sorted(set(rule_names) - known)
            if unknown:
                raise ValueError(f"unknown rule(s): {unknown}; "
                                 f"known: {sorted(known)}")
            active = [r for r in self.rules if r.name in rule_names]

        findings: List[Finding] = []
        for module in modules:
            if module.parse_error is not None:
                findings.append(Finding(
                    rule="parse-error", path=module.rel, line=0,
                    message=f"file does not parse: {module.parse_error}"))
                continue
            for rule in active:
                if not rule.applies(module.rel, self.config):
                    continue
                for f in rule.check(module, project, self.config):
                    if not module.suppressed(f.rule, f.line):
                        findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))

        baselined = 0
        stale: List[Dict[str, str]] = []
        if baseline:
            used = [False] * len(baseline)
            keyed = {}
            for i, entry in enumerate(baseline):
                key = (entry.get("rule"), entry.get("path"),
                       entry.get("message"))
                keyed.setdefault(key, []).append(i)
            kept: List[Finding] = []
            for f in findings:
                idxs = keyed.get(
                    (f.rule, f.path, f.message))
                if idxs:
                    for i in idxs:
                        used[i] = True
                    baselined += 1
                else:
                    kept.append(f)
            findings = kept
            stale = [dict(entry) for entry, u in zip(baseline, used)
                     if not u]
        return LintResult(findings=findings, files_scanned=len(files),
                          baselined=baselined, stale_baseline=stale)


def repo_root() -> str:
    """The repo checkout this installed package lives in (parent of the
    package directory)."""
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))
    return os.path.dirname(package_dir)


def run_lint(root: Optional[str] = None,
             paths: Optional[Sequence[str]] = None,
             rule_names: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             use_baseline: bool = True,
             config: Optional[LintConfig] = None) -> LintResult:
    """One-call API: default rules over the default perimeter with the
    committed baseline — what the CLI, the tier-1 test, and the bench
    hook all share."""
    from trustworthy_dl_tpu.analysis.baseline import load_baseline
    from trustworthy_dl_tpu.analysis.rules import all_rules

    root = os.path.abspath(root or repo_root())
    entries: Optional[List[Dict[str, str]]] = None
    if use_baseline:
        if baseline_path is None:
            baseline_path = os.path.join(root, contracts.DEFAULT_BASELINE)
        if os.path.exists(baseline_path):
            entries = load_baseline(baseline_path)
    engine = LintEngine(all_rules(), config=config)
    return engine.run(root, paths=paths, baseline=entries,
                      rule_names=rule_names)

"""Native host-runtime tier: C++ data-loader core with ctypes bindings.

The reference framework is pure Python (SURVEY §0: no native code anywhere);
its data layer is an implied module that doesn't even exist in the snapshot
(§2.3).  This package gives the TPU build a real native input pipeline:
``dataloader.cpp`` implements the batch-assembly hot path (synthetic token
chains, epoch permutations, multi-threaded row gathers), compiled lazily
with g++ into ``libtddl_native.so`` and loaded via ctypes — no pybind11
dependency, per the environment contract.

Every entry point has a bit-exact numpy fallback in this module, selected
automatically when no compiler/library is available (or when
``TDDL_NATIVE=0``).  tests/test_native.py pins C++ == Python on every
routine, so the two tiers can never drift.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


# ---------------------------------------------------------------------------
# Build / load
# ---------------------------------------------------------------------------


def _source_path() -> str:
    return os.path.join(os.path.dirname(__file__), "dataloader.cpp")


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "libtddl_native.so")


def build_library(force: bool = False) -> Optional[str]:
    """Compile dataloader.cpp with g++ (cached next to the source)."""
    out = _lib_path()
    src = _source_path()
    if not force and os.path.exists(out) and (
        os.path.getmtime(out) >= os.path.getmtime(src)
    ):
        return out
    # Build into a temp file then rename, so a concurrent test runner never
    # dlopens a half-written library.
    tmp_path = None
    try:
        with tempfile.NamedTemporaryFile(
            dir=os.path.dirname(out), suffix=".so", delete=False
        ) as tmp:
            tmp_path = tmp.name
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp_path,
             src, "-lpthread"],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp_path, out)
        logger.info("native: built %s", out)
        return out
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError, OSError) as exc:
        if tmp_path is not None:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
        logger.warning("native: build failed (%s); using Python fallback", exc)
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    if os.environ.get("TDDL_NATIVE") == "0":
        return None
    path = build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as exc:
        logger.warning("native: dlopen failed (%s); using Python fallback", exc)
        return None
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.tddl_splitmix_fill.argtypes = [ctypes.c_uint64, ctypes.c_int64, u64p]
    lib.tddl_synthetic_tokens.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, i32p
    ]
    lib.tddl_permutation.argtypes = [ctypes.c_uint64, ctypes.c_int64, i64p]
    lib.tddl_gather_rows.argtypes = [
        u8p, i64p, ctypes.c_int64, ctypes.c_int64, u8p, ctypes.c_int32
    ]
    lib.tddl_window_gather.argtypes = [
        i32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_uint64, i32p, i32p, ctypes.c_int32
    ]
    lib.tddl_bpe_load.argtypes = [i32p, i32p, i32p, ctypes.c_int64]
    lib.tddl_bpe_encode.argtypes = [
        i32p, i64p, ctypes.c_int64, i32p, i64p
    ]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# splitmix64 — shared deterministic generator (numpy fallback)
# ---------------------------------------------------------------------------


def _splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorised splitmix64 finaliser on uint64 states (wrapping)."""
    with np.errstate(over="ignore"):
        z = x + _GOLDEN
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def splitmix_fill(seed: int, n: int) -> np.ndarray:
    """u64[n] raw stream: splitmix64(seed + i*GOLDEN)."""
    lib = _load()
    out = np.empty(n, np.uint64)
    if lib is not None and n:
        lib.tddl_splitmix_fill(
            ctypes.c_uint64(seed), n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
        return out
    with np.errstate(over="ignore"):
        states = np.uint64(seed) + np.arange(n, dtype=np.uint64) * _GOLDEN
    return _splitmix64_np(states)


def _splitmix_scalar(x: int) -> int:
    return int(_splitmix64_np(np.asarray([x], np.uint64))[0])


def synthetic_tokens(n: int, vocab: int, seed: int) -> np.ndarray:
    """i32[n] learnable affine next-token chain with 10% uniform resets —
    the LM synthetic source of data/loader.py, native-accelerated."""
    lib = _load()
    if lib is not None and n:
        out = np.empty(n, np.int32)
        lib.tddl_synthetic_tokens(
            n, vocab, ctypes.c_uint64(seed),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out
    a, b = 31, 7
    noise_seed = _splitmix_scalar(seed ^ 0xA5A5A5A5A5A5A5A5)
    tok_seed = _splitmix_scalar(seed ^ 0x5A5A5A5A5A5A5A5A)
    noise_u = splitmix_fill(noise_seed, n) if n else np.empty(0, np.uint64)
    reset = (noise_u >> np.uint64(48)) < np.uint64(6554)
    resets_tok = (splitmix_fill(tok_seed, n) % np.uint64(vocab)).astype(np.int32)
    out = np.empty(n, np.int32)
    t = _splitmix_scalar(seed) % vocab
    out[0] = t
    for i in range(1, n):
        t = int(resets_tok[i]) if reset[i] else (a * t + b) % vocab
        out[i] = t
    return out


def permutation(seed: int, n: int) -> np.ndarray:
    """i64[n] Fisher-Yates permutation from the splitmix stream."""
    lib = _load()
    if lib is not None and n:
        out = np.empty(n, np.int64)
        lib.tddl_permutation(
            ctypes.c_uint64(seed), n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out
    out = np.arange(n, dtype=np.int64)
    if n:
        with np.errstate(over="ignore"):
            us = _splitmix64_np(
                np.uint64(seed) + np.arange(n, dtype=np.uint64) * _GOLDEN
            )
        for i in range(n - 1, 0, -1):
            j = int(us[i] % np.uint64(i + 1))
            out[i], out[j] = out[j], out[i]
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray,
                n_threads: int = 4) -> np.ndarray:
    """out[k] = src[idx[k]] for a C-contiguous array — the per-batch row
    gather, multi-threaded memcpy on the native path.

    Internal API: indices must lie in [0, len(src)) — the native path does
    no bounds checking (it is fed only by ``permutation`` over the same
    array in ArrayDataLoader)."""
    lib = _load()
    idx = np.ascontiguousarray(idx, np.int64)
    if lib is None or not src.flags.c_contiguous or src.ndim < 1:
        return src[idx]
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    lib.tddl_gather_rows(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx), row_bytes,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_threads,
    )
    return out


def window_gather(stream: np.ndarray, seq_len: int, batch: int, seed: int,
                  n_threads: int = 4) -> "tuple[np.ndarray, np.ndarray]":
    """(inputs i32[batch, seq_len], targets i32[batch, seq_len]): random
    seq_len+1 windows of a contiguous token stream at splitmix-derived
    offsets — the nanoGPT-style sampler, multi-threaded memcpy on the
    native path.  Offsets are O(1) addressable (pure function of
    (seed, row)), so batches are reproducible and the Python fallback is
    bit-exact."""
    stream = np.ascontiguousarray(stream, np.int32)
    # A window consumes seq_len+1 tokens, so valid offsets are
    # [0, len - seq_len - 1] — span = len - seq_len of them.
    span = len(stream) - seq_len
    if span <= 0:
        raise ValueError(
            f"stream of {len(stream)} tokens too short for seq_len={seq_len}"
        )
    lib = _load()
    if lib is not None and batch:
        inputs = np.empty((batch, seq_len), np.int32)
        targets = np.empty((batch, seq_len), np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.tddl_window_gather(
            stream.ctypes.data_as(i32p), len(stream), seq_len, batch,
            ctypes.c_uint64(seed),
            inputs.ctypes.data_as(i32p), targets.ctypes.data_as(i32p),
            n_threads,
        )
        return inputs, targets
    offs = (splitmix_fill(seed, batch) % np.uint64(span)).astype(np.int64)
    gather = offs[:, None] + np.arange(seq_len + 1, dtype=np.int64)[None, :]
    windows = stream[gather]
    return windows[:, :-1].copy(), windows[:, 1:].copy()


__all__ = [
    "build_library",
    "gather_rows",
    "native_available",
    "permutation",
    "splitmix_fill",
    "synthetic_tokens",
    "window_gather",
]


# ---------------------------------------------------------------------------
# Byte-level BPE encoder (hot path of data/tokenizer.py)
# ---------------------------------------------------------------------------


def bpe_load(lefts: np.ndarray, rights: np.ndarray, prods: np.ndarray
             ) -> bool:
    """Install the merge table (id pairs -> product id, rank = position)
    into the native encoder.  Returns False when the native tier is
    unavailable — the tokenizer then runs its bit-exact Python merge
    loop."""
    lib = _load()
    if lib is None:
        return False
    i32p = ctypes.POINTER(ctypes.c_int32)
    lefts = np.ascontiguousarray(lefts, np.int32)
    rights = np.ascontiguousarray(rights, np.int32)
    prods = np.ascontiguousarray(prods, np.int32)
    lib.tddl_bpe_load(
        lefts.ctypes.data_as(i32p), rights.ctypes.data_as(i32p),
        prods.ctypes.data_as(i32p), len(lefts),
    )
    return True


def bpe_encode(flat: np.ndarray, offsets: np.ndarray
               ) -> "tuple[np.ndarray, np.ndarray]":
    """Encode a flat batch of unit-id words (``offsets`` delimits each
    word) with the table installed by ``bpe_load``.  Returns
    (flat_out, out_offsets)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native tier unavailable; call bpe_load first")
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    flat = np.ascontiguousarray(flat, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    out = np.empty(max(len(flat), 1), np.int32)
    out_offsets = np.empty(len(offsets), np.int64)
    lib.tddl_bpe_encode(
        flat.ctypes.data_as(i32p), offsets.ctypes.data_as(i64p),
        len(offsets) - 1, out.ctypes.data_as(i32p),
        out_offsets.ctypes.data_as(i64p),
    )
    return out[: out_offsets[-1]], out_offsets

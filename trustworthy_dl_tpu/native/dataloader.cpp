// Native data-loader core for the TPU framework's host-side input pipeline.
//
// The reference's data layer is an *implied* module (imported but missing
// from the snapshot — SURVEY §2.3 utils/data_loader.py); its runtime is pure
// Python end to end.  Here the batch-assembly hot path — synthetic token
// synthesis, epoch permutations, and row gathers — is C++ behind ctypes,
// with bit-exact Python fallbacks (trustworthy_dl_tpu/native/__init__.py) so
// the framework runs identically where no compiler exists.
//
// Determinism contract: every routine is a pure function of (seed, n) using
// splitmix64; the Python fallbacks implement the same arithmetic, and
// tests/test_native.py pins C++ == Python bit-for-bit.
//
// Build: g++ -O3 -shared -fPIC -o libtddl_native.so dataloader.cpp -lpthread

#include <climits>
#include <cstdint>
#include <cstring>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {

// splitmix64 (public-domain algorithm, Steele et al.): the shared
// deterministic generator.  state walks seed + i*GOLDEN.
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Raw stream: out[i] = splitmix64(seed + i*GOLDEN) — stateless, so any
// subrange can be regenerated independently (the Python fallback vectorises
// exactly this).
void tddl_splitmix_fill(uint64_t seed, int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = splitmix64(seed + (uint64_t)i * 0x9E3779B97F4A7C15ULL);
  }
}

// Learnable synthetic LM stream (data/loader.py contract): affine
// next-token chain t_{i+1} = (a*t_i + b) mod V with 10% uniform resets.
// Noise decisions and reset tokens come from two independent splitmix
// streams so the chain stays sequential but the randomness is O(1)
// addressable.
void tddl_synthetic_tokens(int64_t n, int32_t vocab, uint64_t seed,
                           int32_t* out) {
  const int32_t a = 31, b = 7;
  const uint64_t noise_seed = splitmix64(seed ^ 0xA5A5A5A5A5A5A5A5ULL);
  const uint64_t tok_seed = splitmix64(seed ^ 0x5A5A5A5A5A5A5A5AULL);
  int32_t t = (int32_t)(splitmix64(seed) % (uint64_t)vocab);
  out[0] = t;
  for (int64_t i = 1; i < n; ++i) {
    uint64_t u = splitmix64(noise_seed + (uint64_t)i * 0x9E3779B97F4A7C15ULL);
    if ((u >> 48) < 6554) {  // top 16 bits < 0.1 * 65536
      uint64_t r = splitmix64(tok_seed + (uint64_t)i * 0x9E3779B97F4A7C15ULL);
      t = (int32_t)(r % (uint64_t)vocab);
    } else {
      t = (int32_t)(((int64_t)a * t + b) % vocab);
    }
    out[i] = t;
  }
}

// Fisher-Yates permutation of [0, n) driven by the splitmix stream.
// Rejection-free modulo bias is acceptable here (shuffling quality, not
// cryptography), but the arithmetic must match the Python fallback exactly.
void tddl_permutation(uint64_t seed, int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t u = splitmix64(seed + (uint64_t)i * 0x9E3779B97F4A7C15ULL);
    int64_t j = (int64_t)(u % (uint64_t)(i + 1));
    int64_t tmp = out[i];
    out[i] = out[j];
    out[j] = tmp;
  }
}

// Batch assembly: gather rows of a contiguous [num_rows, row_bytes] buffer
// into out following idx.  Multi-threaded memcpy — this is the per-batch
// hot path the Python loader paid numpy fancy-indexing overhead for.
void tddl_gather_rows(const uint8_t* src, const int64_t* idx, int64_t n_idx,
                      int64_t row_bytes, uint8_t* out, int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  if (n_threads == 1 || n_idx < 64) {
    for (int64_t i = 0; i < n_idx; ++i) {
      std::memcpy(out + i * row_bytes, src + idx[i] * row_bytes,
                  (size_t)row_bytes);
    }
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t w = 0; w < n_threads; ++w) {
    int64_t lo = (int64_t)w * chunk;
    int64_t hi = lo + chunk < n_idx ? lo + chunk : n_idx;
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(out + i * row_bytes, src + idx[i] * row_bytes,
                    (size_t)row_bytes);
      }
    });
  }
  for (auto& t : workers) t.join();
}

// Random-window sampling over a contiguous token stream (the nanoGPT-style
// loader): row r of the batch reads seq_len+1 consecutive int32 tokens at
// offset splitmix64(seed + r*GOLDEN) % (stream_len - seq_len - 1), split
// into input (first seq_len) and next-token target (last seq_len).
// Multi-threaded over rows; offsets are O(1) addressable so the Python
// fallback reproduces them bit-for-bit.
void tddl_window_gather(const int32_t* stream, int64_t stream_len,
                        int64_t seq_len, int64_t batch, uint64_t seed,
                        int32_t* out_inputs, int32_t* out_targets,
                        int32_t n_threads) {
  // A window consumes seq_len+1 tokens: valid offsets are
  // [0, stream_len - seq_len - 1], span = stream_len - seq_len of them.
  const int64_t span = stream_len - seq_len;
  if (span <= 0) return;
  auto work = [=](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      uint64_t u = splitmix64(seed + (uint64_t)r * 0x9E3779B97F4A7C15ULL);
      int64_t off = (int64_t)(u % (uint64_t)span);
      std::memcpy(out_inputs + r * seq_len, stream + off,
                  (size_t)seq_len * sizeof(int32_t));
      std::memcpy(out_targets + r * seq_len, stream + off + 1,
                  (size_t)seq_len * sizeof(int32_t));
    }
  };
  if (n_threads < 1) n_threads = 1;
  if (n_threads == 1 || batch < 64) {
    work(0, batch);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (batch + n_threads - 1) / n_threads;
  for (int32_t w = 0; w < n_threads; ++w) {
    int64_t lo = (int64_t)w * chunk;
    int64_t hi = lo + chunk < batch ? lo + chunk : batch;
    if (lo >= hi) break;
    workers.emplace_back([=]() { work(lo, hi); });
  }
  for (auto& t : workers) t.join();
}


// ---------------------------------------------------------------------------
// Byte-level BPE encoder (data/tokenizer.py hot path).
//
// Works entirely in token-id space: the Python layer maps byte units to
// their vocabulary ids and hands over (a) the merge table as id pairs with
// each product's id, (b) a flat batch of pre-tokenized words.  The merge
// loop (find the lowest-rank adjacent pair, fuse, repeat) is the
// per-character-quadratic inner loop that dominates corpus tokenization in
// Python.  Merges whose product is absent from the vocabulary are excluded
// by the caller — both tiers share that rule, so outputs are bit-exact.
// ---------------------------------------------------------------------------

static std::unordered_map<uint64_t, int32_t> g_bpe_ranks;
static std::vector<int32_t> g_bpe_prod;  // rank -> product token id

void tddl_bpe_load(const int32_t* lefts, const int32_t* rights,
                   const int32_t* prods, int64_t n_merges) {
  g_bpe_ranks.clear();
  g_bpe_ranks.reserve((size_t)n_merges * 2);
  g_bpe_prod.assign((size_t)n_merges, 0);
  for (int64_t i = 0; i < n_merges; ++i) {
    uint64_t key =
        ((uint64_t)(uint32_t)lefts[i] << 32) | (uint32_t)rights[i];
    // First occurrence wins (lowest rank), matching dict-of-ranks
    // semantics on duplicate pairs in a merges file.
    g_bpe_ranks.emplace(key, (int32_t)i);
    g_bpe_prod[(size_t)i] = prods[i];
  }
}

// words: flat unit-id stream; offsets[n_words+1] delimit each word.
// out must hold offsets[n_words] ids (output never exceeds input);
// out_offsets[n_words+1] receives the encoded extents.
void tddl_bpe_encode(const int32_t* flat, const int64_t* offsets,
                     int64_t n_words, int32_t* out, int64_t* out_offsets) {
  std::vector<int32_t> buf;
  int64_t w = 0;
  out_offsets[0] = 0;
  for (int64_t i = 0; i < n_words; ++i) {
    const int32_t* word = flat + offsets[i];
    const int64_t n = offsets[i + 1] - offsets[i];
    buf.assign(word, word + n);
    while (buf.size() > 1) {
      int32_t best_rank = INT_MAX;
      int64_t best = -1;
      for (int64_t j = 0; j + 1 < (int64_t)buf.size(); ++j) {
        uint64_t key =
            ((uint64_t)(uint32_t)buf[j] << 32) | (uint32_t)buf[j + 1];
        auto it = g_bpe_ranks.find(key);
        if (it != g_bpe_ranks.end() && it->second < best_rank) {
          best_rank = it->second;
          best = j;
        }
      }
      if (best < 0) break;
      buf[(size_t)best] = g_bpe_prod[(size_t)best_rank];
      buf.erase(buf.begin() + best + 1);
    }
    for (int32_t t : buf) out[w++] = t;
    out_offsets[i + 1] = w;
  }
}

}  // extern "C"

"""Persistent XLA compilation cache wiring.

Repeat runs of this framework compile the SAME SPMD programs (the fused
trusted step, eval step, serve prefill/decode) from scratch every
process start — minutes of wall time on big models, pure waste for
sweeps, bench A/Bs and CI.  JAX ships a persistent on-disk cache
(``jax_compilation_cache_dir``); this module is the one switch the
config/CLI/bench layers flip, so the thresholds stay consistent
everywhere (the test suite's conftest has used the same settings since
round 5 — this generalises it to runs).

Off by default: ``TrainingConfig.compilation_cache_dir=None``.  Enable
with a path under the run directory (``cli.py --compile-cache``,
``bench.py`` ``TDDL_BENCH_COMPILE_CACHE=1``) — cache entries are keyed
by program + compiler fingerprint, so a shared directory is safe but a
run-local one keeps artifacts self-contained.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_ENABLED_DIR: Optional[str] = None


def enable_persistent_cache(cache_dir: str) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (created if missing).  Idempotent; re-pointing at a different
    directory logs the switch.  Returns the active cache dir."""
    global _ENABLED_DIR
    import jax

    cache_dir = os.path.abspath(str(cache_dir))
    if _ENABLED_DIR == cache_dir:
        return cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache everything that takes >= 1 s to compile, however small the
    # serialized entry — the fused step dominates, but serve's bucketed
    # prefill programs are many and individually cheap-ish.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if _ENABLED_DIR is not None:
        logger.info("compilation cache re-pointed: %s -> %s",
                    _ENABLED_DIR, cache_dir)
    else:
        logger.info("persistent compilation cache enabled at %s", cache_dir)
    _ENABLED_DIR = cache_dir
    return cache_dir


def active_cache_dir() -> Optional[str]:
    """The directory enabled via :func:`enable_persistent_cache`, or
    None when the cache was never switched on by this module."""
    return _ENABLED_DIR

from trustworthy_dl_tpu.utils.metrics import MetricsCollector
from trustworthy_dl_tpu.utils.monitor import NodeMonitor

__all__ = ["MetricsCollector", "NodeMonitor"]

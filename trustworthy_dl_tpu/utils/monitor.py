"""NodeMonitor — the implied ``core.node_monitor`` module (imported at
distributed_trainer.py:20; call sites get_expected_mean/std at :234-235 and
get_expected_gradient_norms at :259).

The live expected-behaviour statistics are computed inside the train step as
``MonitorState`` (engine/state.py); this host class mirrors that state for
the reference API and for host-driven loops.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class NodeMonitor:
    """Per-node expected output/gradient behaviour (running averages)."""

    def __init__(self, warmup: int = 5):
        self.warmup = warmup
        self._count: Dict[int, int] = {}
        self._mean_avg: Dict[int, float] = {}
        self._std_avg: Dict[int, float] = {}
        self._grad_norms_avg: Dict[int, np.ndarray] = {}

    # -- absorption --------------------------------------------------------

    def observe_output(self, node_id: int, mean: float, std: float) -> None:
        c = self._count.get(node_id, 0) + 1
        w = 1.0 / c
        self._mean_avg[node_id] = self._mean_avg.get(node_id, 0.0) * (1 - w) + mean * w
        self._std_avg[node_id] = self._std_avg.get(node_id, 0.0) * (1 - w) + std * w
        self._count[node_id] = c

    def observe_gradient_norms(self, node_id: int, norms: List[float]) -> None:
        arr = np.asarray(norms, np.float64)
        prev = self._grad_norms_avg.get(node_id)
        c = self._count.get(node_id, 1)
        if prev is None or prev.shape != arr.shape:
            self._grad_norms_avg[node_id] = arr
        else:
            w = 1.0 / max(c, 1)
            self._grad_norms_avg[node_id] = prev * (1 - w) + arr * w

    def sync_from_device(self, monitor_state, node_ids=None) -> None:
        """Absorb an engine MonitorState pytree.  ``node_ids`` maps device
        coordinates to original node ids (post-eviction meshes cover only
        the survivors)."""
        counts = np.asarray(monitor_state.count)
        means = np.asarray(monitor_state.out_mean_avg)
        stds = np.asarray(monitor_state.out_std_avg)
        norms = np.asarray(monitor_state.grad_norm_avg)
        if node_ids is None:
            node_ids = list(range(counts.shape[0]))
        for coord, i in enumerate(node_ids):
            self._count[i] = int(counts[coord])
            self._mean_avg[i] = float(means[coord])
            self._std_avg[i] = float(stds[coord])
            self._grad_norms_avg[i] = norms[coord].astype(np.float64)

    # -- reference API -----------------------------------------------------

    def get_expected_mean(self, node_id: int) -> Optional[float]:
        if self._count.get(node_id, 0) < self.warmup:
            return None
        return self._mean_avg.get(node_id)

    def get_expected_std(self, node_id: int) -> Optional[float]:
        if self._count.get(node_id, 0) < self.warmup:
            return None
        return self._std_avg.get(node_id)

    def get_expected_gradient_norms(self, node_id: int) -> List[float]:
        if self._count.get(node_id, 0) < self.warmup:
            return []
        arr = self._grad_norms_avg.get(node_id)
        return [] if arr is None else [float(v) for v in arr]

"""Atomic artifact writes: tmp file + ``os.replace`` in one helper.

The repo's durability rule (enforced statically by tddl-lint's
``atomic-write``): a persistent artifact is never truncated in place —
a crash mid-write must leave either the OLD complete artifact or the
NEW complete artifact, never a torn one.  ``os.replace`` is atomic on
POSIX when source and destination share a filesystem, which the
sibling ``.tmp`` path guarantees.

Host-only, stdlib-only (obs/ and the experiments writers import it).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional


def atomic_write_text(path: Any, text: str, encoding: str = "utf-8"
                      ) -> str:
    """Write ``text`` to ``path`` atomically; returns the path."""
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding=encoding) as f:
        f.write(text)
    os.replace(tmp, path)
    return path


def atomic_write_json(path: Any, payload: Any, *,
                      indent: Optional[int] = 2,
                      sort_keys: bool = False,
                      default: Any = None) -> str:
    """``json.dump`` with the same atomicity guarantee."""
    return atomic_write_text(
        os.fspath(path),
        json.dumps(payload, indent=indent, sort_keys=sort_keys,
                   default=default) + "\n",
    )

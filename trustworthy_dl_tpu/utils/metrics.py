"""MetricsCollector — the implied ``utils.metrics`` module (imported at
distributed_trainer.py:23, experiment_runner.py:25; call sites
collect_batch_metrics at distributed_trainer.py:417 and get_summary at
:520).

Optional TensorBoard export: the reference pinned ``tensorboard``/``wandb``
in requirements.txt:44-45 but never imported either; here a
``tensorboard_dir`` writes real event files (scalars per batch/epoch) via
torch's SummaryWriter when available, and degrades to a no-op otherwise.

Since the obs PR the collector also feeds the process-wide metrics
registry (obs/registry.py): numeric batch metrics become
``tddl_<namespace>_<key>`` gauges (per-node dicts gain a ``node``
label), ``tick()`` observes ``tddl_<namespace>_step_time_seconds`` —
so one snapshot/Prometheus surface covers training and serving without
changing any collector call site.
"""

from __future__ import annotations

import logging
import re
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

# Correlation ids / bookkeeping keys that would be nonsense as gauges
# (and ``request_id`` would otherwise look like a metric).
_NON_METRIC_KEYS = frozenset({"timestamp", "step", "epoch", "request_id"})
_KEY_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


class _NullHist:
    """No-op histogram stand-in after a registration conflict."""

    def observe(self, *a: Any, **kw: Any) -> None:
        pass


def _make_tb_writer(logdir: str):
    try:
        from torch.utils.tensorboard import SummaryWriter

        return SummaryWriter(logdir)
    except Exception as exc:  # tensorboard optional — degrade, don't fail
        logger.warning("TensorBoard writer unavailable (%s); metrics stay "
                       "in-memory only", exc)
        return None


class MetricsCollector:
    """Accumulates per-batch metric dicts and summarises them."""

    def __init__(self, max_records: int = 100_000,
                 tensorboard_dir: Optional[str] = None,
                 registry: Any = None, namespace: str = "train",
                 labels: Optional[Dict[str, str]] = None):
        # ``labels``: constant label set stamped on every registry
        # series this collector produces (the serving fleet passes
        # ``{"replica": i}`` so N replicas' occupancy/queue/token gauges
        # are individually readable instead of last-writer-winning one
        # unlabelled singleton).
        self._const_labels = {k: str(v) for k, v in (labels or {}).items()}
        self.max_records = max_records
        self.batch_metrics: List[Dict[str, Any]] = []
        self.epoch_metrics: List[Dict[str, Any]] = []
        self._step_times: List[float] = []
        self._last_tick: Optional[float] = None
        self._tb = _make_tb_writer(tensorboard_dir) if tensorboard_dir \
            else None
        # Registry absorption: default to the process-wide registry so
        # every collector (trainer, serving engine) lands on one export
        # surface; pass an explicit registry for isolation in tests.
        if registry is None:
            from trustworthy_dl_tpu.obs.registry import get_registry

            registry = get_registry()
        self._ns = _KEY_SANITIZE.sub("_", namespace)
        self.bind_registry(registry)

    def bind_registry(self, registry: Any) -> None:
        """Re-point the export surface at ``registry`` (the trainer's
        ``attach_obs`` calls this so an ObsSession's per-run snapshots
        are not contaminated by the process-wide default registry)."""
        self._registry = registry
        self._gauges: Dict[str, Any] = {}
        const = tuple(self._const_labels)
        try:
            self._tick_hist = registry.histogram(
                f"tddl_{self._ns}_step_time_seconds",
                "step/iteration wall time", labels=const,
            )
        except ValueError:
            # Label-shape clash (an unlabelled collector registered the
            # series before a replica-labelled one, or vice versa):
            # degrade this collector's export, keep the record lists.
            logger.debug("metrics: registry rejected "
                         "tddl_%s_step_time_seconds%s", self._ns, const,
                         exc_info=True)
            self._tick_hist = _NullHist()

    def _registry_gauge(self, key: str, value: Any,
                        node: Optional[Any] = None) -> None:
        name = f"tddl_{self._ns}_{_KEY_SANITIZE.sub('_', key)}"
        cache_key = (name, node is not None)
        gauge = self._gauges.get(cache_key)
        try:
            if gauge is None:
                labels = tuple(self._const_labels)
                if node is not None:
                    labels = ("node",) + labels
                gauge = self._registry.gauge(name, labels=labels)
                self._gauges[cache_key] = gauge
            if node is not None:
                gauge.set(float(value), node=node, **self._const_labels)
            else:
                gauge.set(float(value), **self._const_labels)
        except ValueError:
            # Name/kind collision or cardinality bound: the record list
            # is the source of truth — never let export kill training.
            logger.debug("metrics: registry rejected %s", name,
                         exc_info=True)

    def _tb_scalars(self, prefix: str, record: Dict[str, Any],
                    step: int) -> None:
        if self._tb is None:
            return
        for key, value in record.items():
            if isinstance(value, (int, float)) and key != "timestamp":
                self._tb.add_scalar(f"{prefix}/{key}", value, step)
            elif isinstance(value, dict):  # e.g. per-node trust scores
                for sub, v in value.items():
                    if isinstance(v, (int, float)):
                        self._tb.add_scalar(f"{prefix}/{key}/{sub}", v,
                                            step)

    def collect_batch_metrics(self, metrics: Dict[str, Any]) -> None:
        if len(self.batch_metrics) >= self.max_records:
            self.batch_metrics.pop(0)
        record = dict(metrics)
        record.setdefault("timestamp", time.time())
        self.batch_metrics.append(record)
        self._tb_scalars("batch", record,
                         int(record.get("step", len(self.batch_metrics))))
        for key, value in record.items():
            if key in _NON_METRIC_KEYS:
                continue
            if isinstance(value, (int, float)):
                self._registry_gauge(key, value)
            elif isinstance(value, dict):  # per-node maps -> node label
                for sub, v in value.items():
                    if isinstance(v, (int, float)):
                        self._registry_gauge(key, v, node=sub)

    def collect_epoch_metrics(self, metrics: Dict[str, Any]) -> None:
        record = dict(metrics)
        record.setdefault("timestamp", time.time())
        self.epoch_metrics.append(record)
        self._tb_scalars("epoch", record,
                         int(record.get("epoch", len(self.epoch_metrics))))

    def flush(self) -> None:
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        """Flush and release the event-file writer (thread + fd)."""
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def tick(self) -> None:
        """Step-time histogram support (SURVEY §5.1)."""
        now = time.perf_counter()
        if self._last_tick is not None:
            dt = now - self._last_tick
            self._step_times.append(dt)
            self._tick_hist.observe(dt, **self._const_labels)
        self._last_tick = now

    def step_time_stats(self) -> Dict[str, float]:
        if not self._step_times:
            return {}
        arr = np.array(self._step_times)
        return {
            "mean_s": float(arr.mean()),
            "p50_s": float(np.percentile(arr, 50)),
            "p95_s": float(np.percentile(arr, 95)),
            "max_s": float(arr.max()),
            "count": int(arr.size),
        }

    def get_summary(self) -> Dict[str, Any]:
        summary: Dict[str, Any] = {
            "num_batches": len(self.batch_metrics),
            "num_epochs": len(self.epoch_metrics),
        }
        losses = [m["loss"] for m in self.batch_metrics if "loss" in m]
        if losses:
            summary["mean_loss"] = float(np.mean(losses))
            summary["final_loss"] = float(losses[-1])
            summary["min_loss"] = float(np.min(losses))
        st = self.step_time_stats()
        if st:
            summary["step_time"] = st
        return summary

    def reset(self) -> None:
        self.batch_metrics.clear()
        self.epoch_metrics.clear()
        self._step_times.clear()
        self._last_tick = None

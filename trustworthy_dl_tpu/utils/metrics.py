"""MetricsCollector — the implied ``utils.metrics`` module (imported at
distributed_trainer.py:23, experiment_runner.py:25; call sites
collect_batch_metrics at distributed_trainer.py:417 and get_summary at
:520)."""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np


class MetricsCollector:
    """Accumulates per-batch metric dicts and summarises them."""

    def __init__(self, max_records: int = 100_000):
        self.max_records = max_records
        self.batch_metrics: List[Dict[str, Any]] = []
        self.epoch_metrics: List[Dict[str, Any]] = []
        self._step_times: List[float] = []
        self._last_tick: Optional[float] = None

    def collect_batch_metrics(self, metrics: Dict[str, Any]) -> None:
        if len(self.batch_metrics) >= self.max_records:
            self.batch_metrics.pop(0)
        record = dict(metrics)
        record.setdefault("timestamp", time.time())
        self.batch_metrics.append(record)

    def collect_epoch_metrics(self, metrics: Dict[str, Any]) -> None:
        record = dict(metrics)
        record.setdefault("timestamp", time.time())
        self.epoch_metrics.append(record)

    def tick(self) -> None:
        """Step-time histogram support (SURVEY §5.1)."""
        now = time.perf_counter()
        if self._last_tick is not None:
            self._step_times.append(now - self._last_tick)
        self._last_tick = now

    def step_time_stats(self) -> Dict[str, float]:
        if not self._step_times:
            return {}
        arr = np.array(self._step_times)
        return {
            "mean_s": float(arr.mean()),
            "p50_s": float(np.percentile(arr, 50)),
            "p95_s": float(np.percentile(arr, 95)),
            "max_s": float(arr.max()),
            "count": int(arr.size),
        }

    def get_summary(self) -> Dict[str, Any]:
        summary: Dict[str, Any] = {
            "num_batches": len(self.batch_metrics),
            "num_epochs": len(self.epoch_metrics),
        }
        losses = [m["loss"] for m in self.batch_metrics if "loss" in m]
        if losses:
            summary["mean_loss"] = float(np.mean(losses))
            summary["final_loss"] = float(losses[-1])
            summary["min_loss"] = float(np.min(losses))
        st = self.step_time_stats()
        if st:
            summary["step_time"] = st
        return summary

    def reset(self) -> None:
        self.batch_metrics.clear()
        self.epoch_metrics.clear()
        self._step_times.clear()
        self._last_tick = None

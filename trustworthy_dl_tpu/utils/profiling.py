"""Tracing / profiling + debug subsystem (SURVEY §5.1, §5.2).

The reference has neither: only wall-clock epoch timers
(experiment_runner.py:154,170-172) and tensorboard/wandb pinned in
requirements but never imported (requirements.txt:44-45).  Race detection
(§5.2) does not apply to the SPMD design — there is no shared mutable state
inside the compiled step — so the debug story here is numerical: XLA-level
NaN trapping plus the step-time histogram in utils/metrics.py.

* ``trace(log_dir)`` — context manager around ``jax.profiler.trace``;
  produces TensorBoard/Perfetto-loadable device+host traces of everything
  dispatched inside.
* ``step_annotation(step)`` — ``StepTraceAnnotation`` so per-step slices are
  attributed in the trace timeline.
* ``enable_nan_debugging()`` — flips ``jax_debug_nans``: any NaN produced by
  a jitted computation re-runs un-jitted and raises FloatingPointError at
  the exact primitive.  Training-time detection of *adversarial* non-finite
  gradients does NOT rely on this (the verifier's finite flag handles that
  in-step); this is a developer mode for debugging the framework itself.

Wired into DistributedTrainer via TrainingConfig.profile_dir /
TrainingConfig.debug_nans.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

import jax

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Profile everything dispatched inside the context into ``log_dir``
    (no-op when log_dir is falsy, so call sites need no branching)."""
    if not log_dir:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    logger.info("profiler: tracing to %s", log_dir)
    with jax.profiler.trace(log_dir):
        yield
    logger.info("profiler: trace written to %s", log_dir)


def step_annotation(step: int):
    """Label one train step in the trace timeline."""
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)


def enable_nan_debugging(enabled: bool = True) -> None:
    """jax_debug_nans: jitted NaN producers re-run op-by-op and raise at the
    exact primitive (SURVEY §5.2 plan)."""
    jax.config.update("jax_debug_nans", enabled)
    if enabled:
        logger.warning(
            "NaN debugging enabled: NaN-producing steps re-execute un-jitted "
            "and raise FloatingPointError (debug builds only — this also "
            "fires on adversarial NaN injections the engine would otherwise "
            "gate out in-step)"
        )

"""Tracing / profiling + debug subsystem (SURVEY §5.1, §5.2).

The reference has neither: only wall-clock epoch timers
(experiment_runner.py:154,170-172) and tensorboard/wandb pinned in
requirements but never imported (requirements.txt:44-45).  Race detection
(§5.2) does not apply to the SPMD design — there is no shared mutable state
inside the compiled step — so the debug story here is numerical: XLA-level
NaN trapping plus the step-time histogram in obs/report.py.

* ``trace(log_dir)`` — context manager around ``jax.profiler.trace``;
  produces TensorBoard/Perfetto-loadable device+host traces of everything
  dispatched inside.
* ``step_annotation(step)`` — ``StepTraceAnnotation`` so per-step slices are
  attributed in the trace timeline.
* ``phase_annotation(name)`` — ``TraceAnnotation`` carrying one of the
  canonical ``obs.report.PHASES`` names, so the XLA timeline and the
  host-side ``obs_report.json`` breakdown use the same vocabulary.
* ``enable_nan_debugging()`` — flips ``jax_debug_nans``: any NaN produced by
  a jitted computation re-runs un-jitted and raises FloatingPointError at
  the exact primitive.  Training-time detection of *adversarial* non-finite
  gradients does NOT rely on this (the verifier's finite flag handles that
  in-step); this is a developer mode for debugging the framework itself.

All annotations are **no-op-safe**: constructing or entering one outside
an active profiler session (or on a backend whose profiler plugin is
broken) degrades to a null context instead of raising — the trainer's
hot loop annotates every step, and an instrumentation shim must never be
the thing that kills a run.

Wired into DistributedTrainer via TrainingConfig.profile_dir /
TrainingConfig.debug_nans.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

import jax

from trustworthy_dl_tpu.obs.report import PHASES  # canonical phase names

__all__ = ["PHASES", "enable_nan_debugging", "phase_annotation",
           "step_annotation", "trace"]

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Profile everything dispatched inside the context into ``log_dir``
    (no-op when log_dir is falsy, so call sites need no branching)."""
    if not log_dir:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    logger.info("profiler: tracing to %s", log_dir)
    with jax.profiler.trace(log_dir):
        yield
    logger.info("profiler: trace written to %s", log_dir)


class _SafeAnnotation:
    """Wraps a jax.profiler annotation so that construction, entry and
    exit failures (no active profiler session, missing plugin) all
    degrade to a no-op.  Re-entrant per instance is not supported —
    build one per ``with`` block, as the factories below do."""

    __slots__ = ("_ctx",)

    def __init__(self, factory, *args, **kwargs):
        try:
            self._ctx = factory(*args, **kwargs)
        except Exception:
            self._ctx = None

    def __enter__(self):
        if self._ctx is not None:
            try:
                self._ctx.__enter__()
            except Exception:
                self._ctx = None
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            try:
                return bool(self._ctx.__exit__(*exc))
            except Exception:
                pass
        return False


def step_annotation(step: int) -> _SafeAnnotation:
    """Label one train step in the trace timeline (no-op-safe)."""
    return _SafeAnnotation(jax.profiler.StepTraceAnnotation, "train_step",
                           step_num=step)


def phase_annotation(name: str) -> _SafeAnnotation:
    """Label a host-side phase in the trace timeline with one of the
    canonical ``obs.report.PHASES`` names (no-op-safe)."""
    if name not in PHASES:
        raise ValueError(f"unknown phase {name!r}; one of {PHASES}")
    return _SafeAnnotation(jax.profiler.TraceAnnotation, name)


def enable_nan_debugging(enabled: bool = True) -> None:
    """jax_debug_nans: jitted NaN producers re-run op-by-op and raise at the
    exact primitive (SURVEY §5.2 plan)."""
    jax.config.update("jax_debug_nans", enabled)
    if enabled:
        logger.warning(
            "NaN debugging enabled: NaN-producing steps re-execute un-jitted "
            "and raise FloatingPointError (debug builds only — this also "
            "fires on adversarial NaN injections the engine would otherwise "
            "gate out in-step)"
        )

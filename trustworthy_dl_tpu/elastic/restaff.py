"""Pipeline-stage restaffing: REAL layer-shard migration in model-parallel
mode.

This is the reference's headline capability on its own parallelism
strategy — ``reassign_node_tasks`` / ``perform_task_reassignment``
(distributed_trainer.py:324-380) promise to hand a compromised node's layer
partition to the max-trust node, but actually only alias a Python object and
relabel a string; the compromised layers either keep running or are silently
dropped from the forward pass (:154-157).

TPU-native restaffing is a *repartition*: block params are stage-stacked
[S, L/S, ...] over the 'stage' mesh axis (parallel/pipeline.py), so moving
layer shards is a reshape + device_put —

1. the compromised stage's device column leaves the mesh;
2. blocks (and their optimizer moments) unstack to [L, ...] and restack to
   [S', L/S'] where S' is the largest stage count ≤ S-1 dividing L — every
   layer, including the compromised stage's, keeps training on trusted
   hardware;
3. the S' highest-trust candidates staff the new stages (the reference's
   max-trust selection, :337-344) — candidates are the surviving on-mesh
   stages plus the trainer's idle pool (healthy nodes a previous restaff
   could not seat); unseated survivors park in the pool with their
   devices and re-enter at the next restaff;
4. per-stage detector/canary state re-initialises (stage k now computes a
   different layer slice — its old baselines describe the wrong
   distribution), trust rows carry over with their owners;
5. the pipeline step re-jits for S' stages (rare path, recompilation
   accepted per SURVEY §7.4(1)).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.core.mesh import STAGE_AXIS, build_mesh
from trustworthy_dl_tpu.engine.state import fleet_scalar_fields, \
    init_monitor_state

logger = logging.getLogger(__name__)


def choose_stage_count(num_layers: int, max_stages: int) -> int:
    """Largest S' ≤ max_stages with num_layers % S' == 0 (S'=1 always
    works: the degenerate single-stage pipeline is still a valid, complete
    model)."""
    for s in range(max_stages, 0, -1):
        if num_layers % s == 0:
            return s
    return 1


def _restack_leaf(leaf: Any, new_stages: int) -> Any:
    """[S, L/S, ...] -> [S', L/S', ...] preserving layer order."""
    total = leaf.shape[0] * leaf.shape[1]
    return leaf.reshape((new_stages, total // new_stages) + leaf.shape[2:])


def _under_blocks(path) -> bool:
    """THE 'this optimizer/param leaf belongs to the stage-stacked blocks
    subtree' predicate — shared by the moment restack and the placement
    pass so the two can never drift."""
    return any(
        getattr(k, "key", getattr(k, "name", None)) == "blocks"
        for k in path
    )


def restack_blocks(blocks: Any, new_stages: int) -> Any:
    """[S, L/S, ...] -> [S', L/S', ...] preserving layer order — the layer
    migration itself.  Works on any params-shaped pytree (block params and
    their optimizer moment mirrors alike)."""
    return jax.tree_util.tree_map(
        lambda leaf: _restack_leaf(leaf, new_stages), blocks
    )


def _restack_in_opt_state(opt_state: Any, new_stages: int,
                          old_shape_prefix) -> Any:
    """Restack every optimizer leaf that mirrors a stage-stacked block
    leaf.  Moments are per-parameter, so reshaping them alongside their
    layers is exact — Adam's mu/nu follow their weights to the new stage."""
    def maybe(path, leaf):
        if _under_blocks(path) and getattr(leaf, "ndim", 0) >= 2 and \
                tuple(leaf.shape[:2]) == old_shape_prefix:
            return _restack_leaf(leaf, new_stages)
        return leaf
    return jax.tree_util.tree_map_with_path(maybe, opt_state)


def restaff_pipeline(trainer, drop: Sequence[int]) -> Dict[str, Any]:
    """Evict compromised stage coordinates and repartition the model over
    the survivors.  ``drop`` holds CURRENT stage coordinates.  Returns the
    migration record (same contract as evict_and_reshard)."""
    from trustworthy_dl_tpu.parallel.pipeline import (
        build_pipeline_eval_step,
        build_pipeline_train_step,
        init_canary_state,
        make_canary,
    )

    config = trainer.config
    if config.parallelism != "model":
        raise ValueError("restaff_pipeline requires parallelism='model'")
    S = config.num_nodes
    drop = sorted(set(int(d) for d in drop))
    survivors = [i for i in range(S) if i not in drop]
    if not survivors:
        raise ValueError("cannot evict every stage")

    # Quiesce the in-flight step before repartitioning and dropping the
    # old state (see evict_and_reshard — freeing still-being-written
    # output buffers races the async runtime).
    jax.block_until_ready(trainer.state)
    state = trainer.state
    blocks = state.params["blocks"]
    lead = jax.tree_util.tree_leaves(blocks)[0]
    num_layers = lead.shape[0] * lead.shape[1]

    # Staffing candidates: surviving on-mesh stages PLUS the idle pool —
    # healthy nodes parked by an earlier restaff (when S' < survivor
    # count, the leftovers wait here instead of being discarded; their
    # devices return to the mesh the next time the stage count allows).
    pool: Dict[int, list] = getattr(trainer, "_idle_pool", {})
    trust_scores = np.asarray(state.trust.scores)
    candidates = [
        (float(trust_scores[c]), trainer.node_map[c], c) for c in survivors
    ] + [
        (trainer.trust_manager.get_trust_score(nid), nid, None)
        for nid in sorted(pool)
    ]
    new_S = choose_stage_count(num_layers, len(candidates))

    t0 = time.perf_counter()

    # --- staffing: highest-trust candidates take the new stages ----------
    ranked = sorted(candidates, key=lambda x: -x[0])
    chosen = sorted(ranked[:new_S], key=lambda x: x[1])  # stable id order
    chosen_keys = {(nid, coord) for _, nid, coord in chosen}
    idle_entries = [e for e in candidates
                    if (e[1], e[2]) not in chosen_keys]

    # --- devices: evicted columns leave; chosen pool nodes bring theirs
    # back; idle columns park in the pool for the next restaff ----------
    mesh = trainer.mesh
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    old_devices = list(mesh.devices.flat)
    multi_device = sizes.get(STAGE_AXIS, 1) == S
    new_pool: Dict[int, list] = {}
    if multi_device:
        grid = mesh.devices.reshape(-1, S)
        new_devices = []
        for _, nid, coord in chosen:
            if coord is not None:
                new_devices.extend(list(grid[:, coord]))
            else:
                new_devices.extend(pool.get(nid, []))
        for _, nid, coord in idle_entries:
            new_pool[nid] = list(grid[:, coord]) if coord is not None \
                else list(pool.get(nid, []))
    else:
        # Dev mode (stages vmapped within fewer devices): no device moves.
        new_devices = old_devices
        for _, nid, coord in idle_entries:
            new_pool[nid] = []
    # Park the evicted stages' device columns so a cooled-off identity can
    # bring them back through the idle pool (_readmit_stages) — the
    # model-mode return path; without this an evicted column's hardware
    # would be lost to the run forever.
    for i in drop:
        trainer._evicted_devices[trainer.node_map[i]] = (
            list(mesh.devices.reshape(-1, S)[:, i]) if multi_device else []
        )
    new_mesh = build_mesh(new_S, "model", devices=new_devices)
    new_config = dataclasses.replace(config, num_nodes=new_S)

    # --- trust rows: on-mesh rows carry over; pool rows synthesise from
    # the host TrustManager's standing — TRUSTED for a healthy survivor a
    # previous restaff could not seat, RECOVERING with the boosted rate
    # for a cooled-off evicted identity re-entering on probation
    # (begin_probation; the reference's mode-blind recovery ladder,
    # trust_manager.py:198-206) ------------------------------------------
    from trustworthy_dl_tpu.trust.state import METRIC_DEFAULTS

    now = float(state.step) * config.time_per_step
    host = trainer.trust_manager.state

    def host_row(attr, nid, default):
        arr = np.asarray(getattr(host, attr))
        return arr[nid] if nid < arr.shape[0] else default

    def gather_rows(field, synth):
        rows = []
        arr = np.asarray(field)
        for score, nid, coord in chosen:
            rows.append(arr[coord] if coord is not None
                        else synth(score, nid))
        return jnp.asarray(np.stack(rows))

    trust = state.trust._replace(
        scores=gather_rows(state.trust.scores,
                           lambda s, nid: np.float32(s)),
        status=gather_rows(
            state.trust.status,
            lambda s, nid: np.int32(
                int(trainer.trust_manager.get_node_status(nid))
            ),
        ),
        update_count=gather_rows(state.trust.update_count,
                                 lambda s, nid: np.int32(0)),
        last_updated=gather_rows(state.trust.last_updated,
                                 lambda s, nid: np.float32(now)),
        decay_rate=gather_rows(state.trust.decay_rate,
                               lambda s, nid: np.float32(
                                   config.trust_decay_rate)),
        recovery_rate=gather_rows(
            state.trust.recovery_rate,
            lambda s, nid: np.float32(host_row(
                "recovery_rate", nid, config.trust_recovery_rate
            )),
        ),
        metrics=gather_rows(state.trust.metrics,
                            lambda s, nid: np.asarray(METRIC_DEFAULTS)),
        attack_count=gather_rows(
            state.trust.attack_count,
            lambda s, nid: np.int32(host_row("attack_count", nid, 0)),
        ),
    )

    # --- the layer migration: restack blocks + their moments ------------
    old_prefix = tuple(lead.shape[:2])
    new_blocks = restack_blocks(blocks, new_S)
    params = dict(state.params)
    params["blocks"] = new_blocks
    opt_state = _restack_in_opt_state(state.opt_state, new_S, old_prefix)

    # --- fresh per-stage intelligence (stage k = new layer slice) --------
    from trustworthy_dl_tpu.detect.baseline import init_baseline_state
    from trustworthy_dl_tpu.detect.stats import NUM_GRADIENT_STATS
    from trustworthy_dl_tpu.detect.verifier import init_verifier_state

    window = state.out_baseline.ring.shape[1]
    num_leaves = state.monitor.grad_norm_avg.shape[1]
    out_bl = init_baseline_state(new_S, window, NUM_GRADIENT_STATS)
    grad_bl = init_baseline_state(new_S, window, NUM_GRADIENT_STATS)
    verifier = init_verifier_state(new_S)
    monitor = init_monitor_state(new_S, num_leaves)
    canary = init_canary_state(
        new_S, make_canary(trainer.model.config, config.canary_tokens)
    )

    # --- placement on the new mesh (declared logical-axis layout) --------
    # Stage-stacked leaves are DECLARED [STAGE, ...] in the sharding
    # registry's model-parallel rule table; resolving the repartition
    # through rules_for("model") + named_sharding keeps restaff on the
    # same declaration every other placement site reads, instead of
    # re-deriving the row split through the reassignment helpers (which
    # encode the per-NODE rule, coincidentally identical today).
    from trustworthy_dl_tpu.core import sharding as shreg

    rules = shreg.rules_for("model")
    repl = shreg.replicated_sharding(new_mesh)
    stage_size = dict(new_mesh.shape).get(STAGE_AXIS, 1)

    def place_stage(leaf):
        nd = getattr(leaf, "ndim", 0)
        if nd >= 1 and leaf.shape[0] == new_S and stage_size > 1 \
                and new_S % stage_size == 0:
            sharding = rules.named_sharding(
                new_mesh, shreg.STAGE, *([None] * (nd - 1)))
            return jax.device_put(leaf, sharding)
        return jax.device_put(leaf, repl)

    params["blocks"] = jax.tree_util.tree_map(place_stage, params["blocks"])
    params = {
        k: (v if k == "blocks"
            else jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), v))
        for k, v in params.items()
    }

    def place_opt(path, leaf):
        if _under_blocks(path) and getattr(leaf, "ndim", 0) >= 2 and \
                leaf.shape[0] == new_S:
            return place_stage(leaf)
        return jax.device_put(leaf, repl)

    opt_state = jax.tree_util.tree_map_with_path(place_opt, opt_state)

    per_stage = dict(
        trust=trust, out_baseline=out_bl, grad_baseline=grad_bl,
        verifier=verifier, monitor=monitor, canary=canary,
        prev_suspects=jnp.zeros((new_S,), bool),
        clean_streak=jnp.zeros((new_S,), jnp.int32),
    )
    per_stage = {k: jax.tree_util.tree_map(place_stage, v)
                 for k, v in per_stage.items()}
    scalars = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, repl),
        {"step": state.step, "epoch": state.epoch, "rng": state.rng,
         **fleet_scalar_fields(state)},
    )
    new_state = state._replace(params=params, opt_state=opt_state,
                               **per_stage, **scalars)
    # NOTE: no jnp.copy re-owning here (unlike evict/readmit_and_reshard):
    # the restaff path has not exhibited the donated-alias crash the
    # data-parallel migrations did, and the pipeline step's shard_map
    # spec checks are strict about the exact placements this function
    # constructs — re-add the copy only with pipeline coverage green on
    # the target container.
    jax.block_until_ready(new_state)
    migration_time = time.perf_counter() - t0
    bytes_moved = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(
            (params["blocks"],)
        )
    )
    measured_gbps = bytes_moved / max(migration_time, 1e-9) / 1024**3

    # --- re-jit + host bookkeeping ---------------------------------------
    trainer.mesh = new_mesh
    trainer.config = new_config
    trainer._train_step = jax.jit(
        build_pipeline_train_step(trainer.model, new_config,
                                  trainer.optimizer, new_mesh),
        donate_argnums=(0,),
    )
    trainer._eval_step = jax.jit(
        build_pipeline_eval_step(trainer.model, new_config, new_mesh)
    )
    trainer.state = new_state
    evicted_ids = [trainer.node_map[i] for i in drop]
    idle_ids = sorted(new_pool)
    new_map = [nid for _, nid, _ in chosen]
    trainer.node_map = new_map
    trainer._idle_pool = new_pool
    bits = np.array([bool(trainer._plan_bits.get(nid, False))
                     for nid in new_map], bool)
    trainer.attack_plan = trainer._place_plan(
        trainer.attack_plan._replace(target_mask=jnp.asarray(bits))
    )

    record = {
        "evicted_nodes": evicted_ids,
        "surviving_nodes": list(new_map),
        "idle_nodes": idle_ids,
        "old_num_stages": S,
        "new_num_stages": new_S,
        "layers_per_stage": num_layers // new_S,
        "migration_time_s": migration_time,
        "bytes_moved": bytes_moved,
        "measured_gbps": measured_gbps,
        "new_device_count": len(new_devices),
        "timestamp": time.time(),
    }
    logger.warning(
        "Pipeline restaff: stage(s) %s evicted; %d layers repartitioned "
        "%d -> %d stages on %d device(s) (%.1f MB in %.3fs); idle "
        "survivors %s", evicted_ids, num_layers, S, new_S,
        len(new_devices), bytes_moved / 2**20, migration_time, idle_ids,
    )
    return record

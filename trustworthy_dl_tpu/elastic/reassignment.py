"""Elastic reassignment: REAL mesh rebuild + state migration.

The reference's novelty path ends in a no-op: ``perform_task_reassignment``
aliases the partition object and relabels a string
(distributed_trainer.py:367-380), and its migration-time "estimate" is a
hardcoded 1 GB/s guess (:354-365).  Here eviction is real:

1. confirmed-compromised mesh coordinates are *removed from the device set*;
2. a fresh ``Mesh`` is built over the survivors;
3. every per-node row of the training world-view (trust, detector
   baselines, verifier, monitor, suspect flags) is compacted to the
   surviving coordinates and every array is migrated onto the new mesh with
   ``jax.device_put``;
4. the train step is re-jitted for the reduced node count (the slow path —
   reassignment is rare; see SURVEY §7.4(1));
5. the migration is *timed*, and the measured GB/s replaces the config's
   ``migration_gbps`` estimate for future planning.

Trust bookkeeping keeps ORIGINAL node ids throughout: the trainer's
``node_map[k] -> original id`` translates device coordinates, so reports
and the host TrustManager stay stable across evictions.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from trustworthy_dl_tpu.core.mesh import DATA_AXIS, build_mesh
from trustworthy_dl_tpu.engine.state import MonitorState, TrainState

logger = logging.getLogger(__name__)


def _tree_bytes(tree: Any) -> int:
    return sum(leaf.size * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(tree))


def compact_train_state(state: TrainState, keep: Sequence[int]) -> TrainState:
    """Slice every per-node leading-axis array down to the surviving
    coordinates.  Params/opt_state are node-replicated in data-parallel
    mode and pass through untouched; scalars (threshold, step, epoch, rng)
    likewise."""
    idx = np.asarray(list(keep), np.int32)

    def take(leaf):
        return leaf[idx]

    trust = state.trust._replace(
        scores=take(state.trust.scores),
        status=take(state.trust.status),
        update_count=take(state.trust.update_count),
        last_updated=take(state.trust.last_updated),
        decay_rate=take(state.trust.decay_rate),
        recovery_rate=take(state.trust.recovery_rate),
        metrics=take(state.trust.metrics),
        attack_count=take(state.trust.attack_count),
    )
    out_bl = state.out_baseline._replace(
        ring=take(state.out_baseline.ring),
        count=take(state.out_baseline.count),
    )
    grad_bl = state.grad_baseline._replace(
        ring=take(state.grad_baseline.ring),
        count=take(state.grad_baseline.count),
    )
    verifier = state.verifier._replace(
        count=take(state.verifier.count),
        mean=take(state.verifier.mean),
        m2=take(state.verifier.m2),
    )
    monitor = MonitorState(
        count=take(state.monitor.count),
        out_mean_avg=take(state.monitor.out_mean_avg),
        out_std_avg=take(state.monitor.out_std_avg),
        grad_norm_avg=take(state.monitor.grad_norm_avg),
    )
    return state._replace(
        trust=trust,
        out_baseline=out_bl,
        grad_baseline=grad_bl,
        verifier=verifier,
        monitor=monitor,
        prev_suspects=take(state.prev_suspects),
    )


def surviving_devices(mesh: jax.sharding.Mesh, num_nodes: int,
                      drop: Sequence[int]) -> List[jax.Device]:
    """Device list after evicting node coordinates.

    When the data axis maps one device per node, the evicted node's chip
    leaves the mesh (true elasticity).  When logical nodes are vmapped
    within fewer devices (dev mode / small hosts), the device set is
    unchanged — eviction then only narrows the logical node axis."""
    devices = list(mesh.devices.flat)
    if len(devices) == num_nodes:
        return [d for i, d in enumerate(devices) if i not in set(drop)]
    return devices


def evict_and_reshard(trainer, drop: Sequence[int]) -> Dict[str, Any]:
    """Evict mesh coordinates, migrate state, re-jit; returns the measured
    migration record.  ``drop`` holds CURRENT coordinates (the trainer
    translates original ids before calling)."""
    from trustworthy_dl_tpu.engine.step import build_eval_step, \
        build_train_step

    config = trainer.config
    if config.parallelism != "data":
        raise NotImplementedError(
            "elastic resharding currently supports data parallelism; a "
            "compromised pipeline stage is frozen in-step instead "
            "(parallel/pipeline.py trust gate)"
        )
    n = config.num_nodes
    drop = sorted(set(int(d) for d in drop))
    keep = [i for i in range(n) if i not in drop]
    if not keep:
        raise ValueError("cannot evict every node")

    t0 = time.perf_counter()
    new_devices = surviving_devices(trainer.mesh, n, drop)
    new_mesh = build_mesh(len(keep), "data", devices=new_devices)
    new_config = dataclasses.replace(config, num_nodes=len(keep))

    compact = compact_train_state(trainer.state, keep)

    # Migrate onto the new mesh: per-node arrays shard over the surviving
    # data axis; everything else replicates.  This is the device_put
    # migration the reference's no-op claimed to do.
    mesh_axis = dict(zip(new_mesh.axis_names, new_mesh.devices.shape))
    data_size = mesh_axis.get(DATA_AXIS, 1)
    replicated = NamedSharding(new_mesh, P())

    def shard_per_node(leaf):
        if leaf.ndim >= 1 and leaf.shape[0] == len(keep) and \
                data_size > 1 and len(keep) % data_size == 0:
            spec = P(DATA_AXIS, *([None] * (leaf.ndim - 1)))
            return jax.device_put(leaf, NamedSharding(new_mesh, spec))
        return jax.device_put(leaf, replicated)

    per_node_fields = dict(
        trust=compact.trust, out_baseline=compact.out_baseline,
        grad_baseline=compact.grad_baseline, verifier=compact.verifier,
        monitor=compact.monitor, prev_suspects=compact.prev_suspects,
    )
    migrated_nodes = {
        k: jax.tree_util.tree_map(shard_per_node, v)
        for k, v in per_node_fields.items()
    }
    migrated_shared = jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, replicated),
        {"params": compact.params,
         "step": compact.step, "epoch": compact.epoch, "rng": compact.rng},
    )
    if config.shard_opt_state and data_size > 1:
        from trustworthy_dl_tpu.engine.state import zero1_place_opt_state

        migrated_shared["opt_state"] = zero1_place_opt_state(
            compact.opt_state, new_mesh
        )
    else:
        migrated_shared["opt_state"] = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, replicated), compact.opt_state
        )
    new_state = compact._replace(**migrated_nodes, **migrated_shared)
    jax.block_until_ready(new_state)
    migration_time = time.perf_counter() - t0

    bytes_moved = _tree_bytes(new_state)
    measured_gbps = bytes_moved / max(migration_time, 1e-9) / 1024**3

    # Re-jit for the reduced node count (rare path; recompilation accepted
    # per SURVEY §7.4(1)).
    trainer.mesh = new_mesh
    trainer.config = new_config
    trainer._train_step = jax.jit(
        build_train_step(trainer.model, new_config, trainer.optimizer),
        donate_argnums=(0,),
    )
    trainer._eval_step = jax.jit(build_eval_step(trainer.model))
    trainer.state = new_state
    trainer.attack_plan = trainer.attack_plan._replace(
        target_mask=trainer.attack_plan.target_mask[np.asarray(keep)]
    )
    evicted_ids = [trainer.node_map[i] for i in drop]
    trainer.node_map = [trainer.node_map[i] for i in keep]
    # The measured rate replaces the 1 GB/s guess for future estimates
    # (distributed_trainer.py:360).
    trainer.config = dataclasses.replace(
        new_config, migration_gbps=max(measured_gbps, 1e-3)
    )

    record = {
        "evicted_nodes": evicted_ids,
        "surviving_nodes": list(trainer.node_map),
        "migration_time_s": migration_time,
        "bytes_moved": bytes_moved,
        "measured_gbps": measured_gbps,
        "new_device_count": len(new_devices),
        "timestamp": time.time(),
    }
    logger.warning(
        "Elastic eviction: nodes %s removed; %d coordinates remain on %d "
        "device(s); migrated %.1f MB in %.3fs (%.2f GB/s)",
        evicted_ids, len(keep), len(new_devices), bytes_moved / 2**20,
        migration_time, measured_gbps,
    )
    return record

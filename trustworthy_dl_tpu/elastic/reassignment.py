"""Elastic reassignment: REAL mesh rebuild + state migration.

The reference's novelty path ends in a no-op: ``perform_task_reassignment``
aliases the partition object and relabels a string
(distributed_trainer.py:367-380), and its migration-time "estimate" is a
hardcoded 1 GB/s guess (:354-365).  Here eviction is real:

1. confirmed-compromised mesh coordinates are *removed from the device set*;
2. a fresh ``Mesh`` is built over the survivors;
3. every per-node row of the training world-view (trust, detector
   baselines, verifier, monitor, suspect flags) is compacted to the
   surviving coordinates and every array is migrated onto the new mesh with
   ``jax.device_put``;
4. the train step is re-jitted for the reduced node count (the slow path —
   reassignment is rare; see SURVEY §7.4(1));
5. the migration is *timed*, and the measured GB/s replaces the config's
   ``migration_gbps`` estimate for future planning.

Trust bookkeeping keeps ORIGINAL node ids throughout: the trainer's
``node_map[k] -> original id`` translates device coordinates, so reports
and the host TrustManager stay stable across evictions.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from trustworthy_dl_tpu.core import sharding as shreg
from trustworthy_dl_tpu.core.mesh import DATA_AXIS, build_mesh
from trustworthy_dl_tpu.engine.state import MonitorState, TrainState, \
    fleet_scalar_fields

logger = logging.getLogger(__name__)


def _tree_bytes(tree: Any) -> int:
    return sum(leaf.size * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(tree))


# TrainState fields whose arrays carry a per-node leading axis.  ONE list —
# eviction compaction, readmission expansion, and every migration below
# iterate it, so a new per-node field is added here (and in the compaction
# /expansion surgeries) exactly once.
PER_NODE_FIELDS = ("trust", "out_baseline", "grad_baseline", "verifier",
                   "monitor", "prev_suspects", "clean_streak")


def row_placer(mesh: jax.sharding.Mesh, axis: str, n: int):
    """The ONE per-node placement rule shared by eviction, readmission and
    stage restaff — a thin wrapper over the registry's
    :func:`core.sharding.row_placer` (the trainer's ``_place_on_mesh``
    calls the same helper, so evict/readmit reproduces exactly the
    shardings a fresh trainer would choose).  Returns
    (place_row, replicated_sharding)."""
    return shreg.row_placer(mesh, axis, n), shreg.replicated_sharding(mesh)


def migrate_state(state: TrainState, mesh: jax.sharding.Mesh, axis: str,
                  n: int, shard_opt: bool,
                  place_params: bool = True,
                  shard_params: bool = False) -> TrainState:
    """Place a (compacted or expanded) TrainState onto ``mesh``: per-node
    rows shard over ``axis``, params/opt/scalars replicate (opt optionally
    ZeRO-1-sharded, params optionally FSDP-sharded, both over the data
    axis via the registry's shared ``place_zero_sharded`` rule).

    ``place_params=False`` skips the params/opt placement entirely —
    tensor mode passes it because _reapply_mode_shardings immediately
    re-lays those subtrees with the TP shardings; replicating a large
    model's full parameter+moment set onto every chip first would be a
    wasted whole-model transfer AND a transient unsharded-peak-memory
    spike."""
    place_row, repl = row_placer(mesh, axis, n)
    per_node = {
        k: jax.tree_util.tree_map(place_row, getattr(state, k))
        for k in PER_NODE_FIELDS
    }
    shared = jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, repl),
        {"step": state.step, "epoch": state.epoch, "rng": state.rng,
         **fleet_scalar_fields(state)},
    )
    if not place_params:
        return state._replace(**per_node, **shared)
    if shard_params:
        shared["params"] = shreg.place_zero_sharded(
            state.params, mesh, DATA_AXIS
        )
    else:
        shared["params"] = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, repl), state.params
        )
    if shard_opt or shard_params:
        # Same registry helper the trainer's _place_on_mesh uses — the
        # dedupe that guarantees identical shardings after evict/readmit.
        shared["opt_state"] = shreg.place_zero_sharded(
            state.opt_state, mesh, DATA_AXIS
        )
    else:
        shared["opt_state"] = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, repl), state.opt_state
        )
    return state._replace(**per_node, **shared)


def compact_train_state(state: TrainState, keep: Sequence[int]) -> TrainState:
    """Slice every per-node leading-axis array down to the surviving
    coordinates.  Params/opt_state are node-replicated in data-parallel
    mode and pass through untouched; scalars (threshold, step, epoch, rng)
    likewise."""
    idx = np.asarray(list(keep), np.int32)

    def take(leaf):
        return leaf[idx]

    trust = state.trust._replace(
        scores=take(state.trust.scores),
        status=take(state.trust.status),
        update_count=take(state.trust.update_count),
        last_updated=take(state.trust.last_updated),
        decay_rate=take(state.trust.decay_rate),
        recovery_rate=take(state.trust.recovery_rate),
        metrics=take(state.trust.metrics),
        attack_count=take(state.trust.attack_count),
    )
    out_bl = state.out_baseline._replace(
        ring=take(state.out_baseline.ring),
        count=take(state.out_baseline.count),
    )
    grad_bl = state.grad_baseline._replace(
        ring=take(state.grad_baseline.ring),
        count=take(state.grad_baseline.count),
    )
    verifier = state.verifier._replace(
        count=take(state.verifier.count),
        mean=take(state.verifier.mean),
        m2=take(state.verifier.m2),
    )
    monitor = MonitorState(
        count=take(state.monitor.count),
        out_mean_avg=take(state.monitor.out_mean_avg),
        out_std_avg=take(state.monitor.out_std_avg),
        grad_norm_avg=take(state.monitor.grad_norm_avg),
    )
    return state._replace(
        trust=trust,
        out_baseline=out_bl,
        grad_baseline=grad_bl,
        verifier=verifier,
        monitor=monitor,
        prev_suspects=take(state.prev_suspects),
        clean_streak=take(state.clean_streak),
    )


# Parallelism modes with mode-agnostic elastic eviction/readmission: the
# node axis is the data axis (one device — or one device GROUP for
# tensor/sequence/expert/hybrid — per node; core/mesh.py build_mesh), so
# removing a node coordinate removes its whole group.  Pipeline ("model")
# reshapes instead (elastic/restaff.py); the reference's contract is
# mode-blind (trust_manager.py:198-206, distributed_trainer.py:324-352).
# Hybrid qualifies when its data axis carries the trust nodes within one
# slice (see _check_hybrid_elastic).
ELASTIC_MODES = ("data", "tensor", "sequence", "expert", "hybrid")


def _check_hybrid_elastic(config) -> None:
    """Hybrid elasticity preconditions: the mesh_shape's data extent IS
    the node count (group modes' invariant), within a single slice, and
    no stage axis (stage repartition is restaff's job)."""
    ms = config.mesh_shape or {}
    if (config.dcn_mesh_shape or ms.get("stage", 1) > 1
            or ms.get("data", 1) != config.num_nodes):
        raise NotImplementedError(
            "hybrid elasticity requires mesh_shape['data'] == num_nodes "
            "within one slice (no dcn_mesh_shape, no stage axis); got "
            f"mesh_shape={ms}, dcn={config.dcn_mesh_shape}"
        )


def elastic_supported(config) -> bool:
    """Can evict_and_reshard handle this config?  The trainer's gates use
    THIS (not bare ELASTIC_MODES membership) so an ineligible hybrid
    layout (multi-slice, stage axis, data extent != node count) falls
    back to the in-step gating + legacy reassignment mitigation instead
    of crashing the training loop on its first confirmed incident."""
    if config.parallelism not in ELASTIC_MODES:
        return False
    if config.parallelism == "hybrid":
        try:
            _check_hybrid_elastic(config)
        except NotImplementedError:
            return False
    return True


def elastic_mesh_shape(config, n: int):
    """mesh_shape for a rebuilt mesh whose data axis now carries ``n``
    nodes (hybrid keeps its other extents; single-axis modes pass their
    shape through untouched — build_mesh derives groups itself)."""
    if config.parallelism != "hybrid":
        return config.mesh_shape
    return {**(config.mesh_shape or {}), "data": n}


def node_device_group(mesh: jax.sharding.Mesh, num_nodes: int,
                      coord: int) -> List[jax.Device]:
    """Devices owned by node ``coord``: its single chip in 1-per-node data
    mode, its whole TP/sequence group row in group modes, nothing in dev
    mode (logical nodes vmapped within fewer devices — no device leaves)."""
    devices = np.asarray(mesh.devices)
    if devices.size == num_nodes:
        return [devices.flat[coord]]
    if devices.ndim >= 1 and devices.shape[0] == num_nodes:
        return list(devices[coord].flat)
    return []


def surviving_devices(mesh: jax.sharding.Mesh, num_nodes: int,
                      drop: Sequence[int]) -> List[jax.Device]:
    """Device list after evicting node coordinates.

    When the node axis maps one device (or one device group) per node, the
    evicted node's chips leave the mesh (true elasticity).  When logical
    nodes are vmapped within fewer devices (dev mode / small hosts), the
    device set is unchanged — eviction then only narrows the logical node
    axis."""
    devices = np.asarray(mesh.devices)
    dropped = set(drop)
    if devices.size == num_nodes:
        return [d for i, d in enumerate(devices.flat) if i not in dropped]
    if devices.ndim >= 1 and devices.shape[0] == num_nodes:
        return [d for i in range(num_nodes) if i not in dropped
                for d in devices[i].flat]
    return list(devices.flat)


def _tp_placement_owns_params(parallelism: str,
                              mesh: jax.sharding.Mesh) -> bool:
    """True when _reapply_mode_shardings will place the params/opt
    subtrees itself (TP layout covers EVERY param leaf — unspecified
    leaves get P() replication), so migrate_state can skip its redundant
    replicate-first pass."""
    from trustworthy_dl_tpu.core.mesh import MODEL_AXIS

    return parallelism == "tensor" or (
        parallelism == "hybrid" and MODEL_AXIS in mesh.axis_names
    )


def _reapply_mode_shardings(state: TrainState, mesh: jax.sharding.Mesh,
                            parallelism: str) -> TrainState:
    """Mode-specific placement after a mesh rebuild: tensor (and hybrid
    with a 'model' axis) re-lays the TP parameter/optimizer shardings on
    the new mesh; sequence/expert re-bind their global collectives mesh.
    Data mode needs nothing — migrate_state already placed everything."""
    if _tp_placement_owns_params(parallelism, mesh):
        from trustworthy_dl_tpu.parallel.tensor_parallel import (
            apply_tp_sharding,
            apply_tp_sharding_to_opt,
        )

        params = apply_tp_sharding(state.params, mesh)
        opt = apply_tp_sharding_to_opt(state.opt_state, params, mesh)
        # migrate_state skipped params/opt (place_params=False), so any
        # opt leaf apply_tp_sharding_to_opt did not cover (step counts,
        # schedule state — not params-shaped) still sits on the OLD mesh;
        # replicate it onto the new one.
        repl = shreg.replicated_sharding(mesh)
        opt = jax.tree_util.tree_map(
            lambda leaf: leaf
            if isinstance(getattr(leaf, "sharding", None), NamedSharding)
            and leaf.sharding.mesh == mesh
            else jax.device_put(leaf, repl),
            opt,
        )
        return state._replace(params=params, opt_state=opt)
    from trustworthy_dl_tpu.core.mesh import bind_mode_mesh

    bind_mode_mesh(mesh, parallelism)
    return state


def evict_and_reshard(trainer, drop: Sequence[int]) -> Dict[str, Any]:
    """Evict mesh coordinates, migrate state, re-jit; returns the measured
    migration record.  ``drop`` holds CURRENT coordinates (the trainer
    translates original ids before calling)."""
    from trustworthy_dl_tpu.engine.step import build_node_eval_step, \
        build_train_step

    config = trainer.config
    if config.parallelism not in ELASTIC_MODES:
        raise NotImplementedError(
            f"elastic resharding supports {ELASTIC_MODES}; a compromised "
            "pipeline stage restaffs instead (elastic/restaff.py)"
        )
    if config.parallelism == "hybrid":
        _check_hybrid_elastic(config)
    n = config.num_nodes
    drop = sorted(set(int(d) for d in drop))
    keep = [i for i in range(n) if i not in drop]
    if not keep:
        raise ValueError("cannot evict every node")

    # Quiesce the in-flight step before compacting/migrating and then
    # DROPPING the old state: the caller (mid-_record_batch) has only
    # materialised a few metric outputs, and freeing still-being-written
    # output buffers races the async runtime (intermittent heap
    # corruption on the CPU client — same hazard the supervisor's
    # rollback quiesces).
    jax.block_until_ready(trainer.state)
    t0 = time.perf_counter()
    # Remember each evicted coordinate's device group so a later
    # readmission (readmit_and_reshard) can restore it to the mesh.  In
    # dev mode (logical nodes vmapped within fewer devices) no device
    # leaves and the group is empty.
    for i in drop:
        trainer._evicted_devices[trainer.node_map[i]] = node_device_group(
            trainer.mesh, n, i
        )
    new_devices = surviving_devices(trainer.mesh, n, drop)
    new_shape = elastic_mesh_shape(config, len(keep))
    new_mesh = build_mesh(len(keep), config.parallelism, new_shape,
                          devices=new_devices)
    new_config = dataclasses.replace(config, num_nodes=len(keep),
                                     mesh_shape=new_shape)

    compact = compact_train_state(trainer.state, keep)

    # Migrate onto the new mesh: per-node arrays shard over the surviving
    # data axis; everything else replicates (then the TP modes re-lay
    # their param/opt shardings).  This is the device_put migration the
    # reference's no-op claimed to do.
    data_size = new_mesh.shape.get(DATA_AXIS, 1)
    new_state = migrate_state(
        compact, new_mesh, DATA_AXIS, len(keep),
        shard_opt=config.shard_opt_state and data_size > 1
        and config.parallelism == "data",
        place_params=not _tp_placement_owns_params(config.parallelism,
                                                   new_mesh),
        shard_params=config.shard_params and data_size > 1
        and config.parallelism == "data",
    )
    new_state = _reapply_mode_shardings(new_state, new_mesh,
                                        config.parallelism)
    # Re-own the migrated leaves before they enter the donated step: a
    # cross-mesh device_put on the virtual-device CPU backend can alias
    # host buffers across shards, and donating aliased buffers corrupts
    # the heap (same family as the checkpoint-restore ownership fix).
    new_state = jax.tree_util.tree_map(jnp.copy, new_state)
    jax.block_until_ready(new_state)
    migration_time = time.perf_counter() - t0

    bytes_moved = _tree_bytes(new_state)
    measured_gbps = bytes_moved / max(migration_time, 1e-9) / 1024**3

    # Re-jit for the reduced node count (rare path; recompilation accepted
    # per SURVEY §7.4(1)).
    trainer.mesh = new_mesh
    trainer.config = new_config
    trainer._train_step = jax.jit(
        build_train_step(trainer.model, new_config, trainer.optimizer),
        donate_argnums=(0,),
    )
    trainer._eval_step = jax.jit(build_node_eval_step(trainer.model))
    trainer.state = new_state
    trainer.attack_plan = trainer._place_plan(
        trainer.attack_plan._replace(
            target_mask=trainer.attack_plan.target_mask[np.asarray(keep)]
        )
    )
    evicted_ids = [trainer.node_map[i] for i in drop]
    trainer.node_map = [trainer.node_map[i] for i in keep]
    # The measured rate replaces the 1 GB/s guess for future estimates
    # (distributed_trainer.py:360).
    trainer.config = dataclasses.replace(
        new_config, migration_gbps=max(measured_gbps, 1e-3)
    )

    record = {
        "evicted_nodes": evicted_ids,
        "surviving_nodes": list(trainer.node_map),
        "migration_time_s": migration_time,
        "bytes_moved": bytes_moved,
        "measured_gbps": measured_gbps,
        "new_device_count": len(new_devices),
        "timestamp": time.time(),
    }
    logger.warning(
        "Elastic eviction: nodes %s removed; %d coordinates remain on %d "
        "device(s); migrated %.1f MB in %.3fs (%.2f GB/s)",
        evicted_ids, len(keep), len(new_devices), bytes_moved / 2**20,
        migration_time, measured_gbps,
    )
    return record


def expand_train_state(state: TrainState, num_new: int,
                       now: float,
                       decay_rate: float,
                       readmit_trust: float = 0.5) -> TrainState:
    """Append ``num_new`` fresh per-node rows to every per-node array of the
    training world-view — the state surgery behind readmission.

    Readmitted rows start in probation: trust at ``readmit_trust`` with
    RECOVERING status and the boosted 0.02 recovery rate
    (``initiate_recovery`` semantics, trust_manager.py:198-206), empty
    detector baselines/verifier/monitor (fresh warmup — their old history
    described a poisoned node), no suspicion carry-over."""
    from trustworthy_dl_tpu.trust.state import METRIC_DEFAULTS, NodeStatus

    r = num_new

    def app(leaf, fill=0):
        fresh = jnp.full((r,) + leaf.shape[1:], fill, leaf.dtype)
        return jnp.concatenate([jnp.asarray(leaf), fresh], axis=0)

    trust = state.trust._replace(
        scores=app(state.trust.scores, readmit_trust),
        status=app(state.trust.status, int(NodeStatus.RECOVERING)),
        update_count=app(state.trust.update_count),
        last_updated=app(state.trust.last_updated, now),
        decay_rate=app(state.trust.decay_rate, decay_rate),
        recovery_rate=app(state.trust.recovery_rate, 0.02),
        metrics=jnp.concatenate(
            [jnp.asarray(state.trust.metrics),
             jnp.tile(METRIC_DEFAULTS[None, :], (r, 1))], axis=0
        ),
        attack_count=app(state.trust.attack_count),
    )
    out_bl = state.out_baseline._replace(
        ring=app(state.out_baseline.ring),
        count=app(state.out_baseline.count),
    )
    grad_bl = state.grad_baseline._replace(
        ring=app(state.grad_baseline.ring),
        count=app(state.grad_baseline.count),
    )
    verifier = state.verifier._replace(
        count=app(state.verifier.count),
        mean=app(state.verifier.mean),
        m2=app(state.verifier.m2),
    )
    monitor = MonitorState(
        count=app(state.monitor.count),
        out_mean_avg=app(state.monitor.out_mean_avg),
        out_std_avg=app(state.monitor.out_std_avg),
        grad_norm_avg=app(state.monitor.grad_norm_avg),
    )
    return state._replace(
        trust=trust,
        out_baseline=out_bl,
        grad_baseline=grad_bl,
        verifier=verifier,
        monitor=monitor,
        prev_suspects=app(state.prev_suspects),
        clean_streak=app(state.clean_streak),
    )


def readmit_and_reshard(trainer, node_ids: Sequence[int]) -> Dict[str, Any]:
    """Re-admit evicted ORIGINAL node ids: restore their devices to the
    mesh, append probation state rows (see expand_train_state), re-jit.

    This is the missing half of elasticity: without it an eviction — even a
    false positive — permanently costs 1/n of the fleet.  The readmitted
    coordinate re-enters RECOVERING with fresh detector baselines; if it is
    still hostile, the cross-sectional checks (which need no history) and
    the post-warmup batteries evict it again."""
    from trustworthy_dl_tpu.engine.step import build_node_eval_step, \
        build_train_step

    config = trainer.config
    if config.parallelism not in ELASTIC_MODES:
        raise NotImplementedError(
            f"elastic readmission follows eviction: {ELASTIC_MODES} only "
            "(model-parallel stages re-enter via the restaff idle pool)"
        )
    if config.parallelism == "hybrid":
        _check_hybrid_elastic(config)
    node_ids = [int(i) for i in node_ids]
    unknown = [i for i in node_ids if i not in trainer._evicted_devices]
    if unknown:
        raise ValueError(f"nodes {unknown} were never evicted")
    n_old = config.num_nodes
    n_new = n_old + len(node_ids)

    # Same quiesce as evict_and_reshard: the old state is dropped below
    # while the caller's step may still be writing its unread outputs.
    jax.block_until_ready(trainer.state)
    t0 = time.perf_counter()
    devices = list(trainer.mesh.devices.flat)
    for nid in node_ids:
        # The node's whole device group returns (its single chip in
        # 1-per-node data mode; empty in dev mode — no device ever left).
        devices.extend(trainer._evicted_devices.get(nid) or [])
    new_shape = elastic_mesh_shape(config, n_new)
    new_mesh = build_mesh(n_new, config.parallelism, new_shape,
                          devices=devices)
    new_config = dataclasses.replace(config, num_nodes=n_new,
                                     mesh_shape=new_shape)

    now = float(trainer.state.step) * config.time_per_step
    expanded = expand_train_state(
        trainer.state, len(node_ids), now=now,
        decay_rate=config.trust_decay_rate,
    )

    data_size = new_mesh.shape.get(DATA_AXIS, 1)
    new_state = migrate_state(
        expanded, new_mesh, DATA_AXIS, n_new,
        shard_opt=config.shard_opt_state and data_size > 1
        and config.parallelism == "data",
        place_params=not _tp_placement_owns_params(config.parallelism,
                                                   new_mesh),
        shard_params=config.shard_params and data_size > 1
        and config.parallelism == "data",
    )
    new_state = _reapply_mode_shardings(new_state, new_mesh,
                                        config.parallelism)
    # Re-own before donation — see evict_and_reshard.
    new_state = jax.tree_util.tree_map(jnp.copy, new_state)
    jax.block_until_ready(new_state)
    migration_time = time.perf_counter() - t0

    trainer.mesh = new_mesh
    trainer.config = new_config
    trainer._train_step = jax.jit(
        build_train_step(trainer.model, new_config, trainer.optimizer),
        donate_argnums=(0,),
    )
    trainer._eval_step = jax.jit(build_node_eval_step(trainer.model))
    trainer.state = new_state
    trainer.node_map = list(trainer.node_map) + node_ids
    # Rebuild the injection mask from original identities: a readmitted
    # node that is still in the experiment's target set will attack again
    # and be re-evicted — the probation does not whitewash it.
    bits = np.array(
        [bool(trainer._plan_bits.get(nid, False))
         for nid in trainer.node_map], bool,
    )
    trainer.attack_plan = trainer._place_plan(
        trainer.attack_plan._replace(target_mask=jnp.asarray(bits))
    )

    for nid in node_ids:
        trainer._evicted_devices.pop(nid, None)
        trainer._evicted_at.pop(nid, None)
        trainer._open_incidents.discard(nid)
        trainer.trust_manager.initiate_recovery(nid)

    record = {
        "readmitted_nodes": node_ids,
        "all_nodes": list(trainer.node_map),
        "migration_time_s": migration_time,
        "new_device_count": len(devices),
        "timestamp": time.time(),
    }
    logger.warning(
        "Elastic readmission: nodes %s restored on probation; %d "
        "coordinates on %d device(s)", node_ids, n_new, len(devices),
    )
    return record

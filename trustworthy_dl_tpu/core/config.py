"""Configuration tree for the TPU-native framework.

Mirrors the reference's three config surfaces and unifies them (the reference
never unified its own: dataclasses at distributed_trainer.py:48-61 and
experiment_runner.py:31-46, a YAML schema documented only in README.md:111-132,
and an argparse CLI whose --config flag was parsed but ignored,
experiment_runner.py:605,613-623).  Here one dataclass tree backs all three,
and the YAML loader honours the README schema for real.
"""

from __future__ import annotations

import dataclasses
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


# Parallelism strategy names accepted by ``TrainingConfig.parallelism``.
#  - "data":     node axis = data shards, trust-gated gradient psum
#  - "model":    node axis = pipeline stages (the reference's only real
#                strategy, distributed_trainer.py:124-135)
#  - "tensor":   intra-layer sharding over a 'model' mesh axis (GSPMD)
#  - "sequence": sequence-dim sharding (Ulysses all_to_all / ring attention)
#  - "expert":   MoE expert-dim sharding over an 'expert' mesh axis
#  - "hybrid":   explicit mesh_shape dict combining several axes
PARALLELISM_MODES = ("data", "model", "tensor", "sequence", "expert", "hybrid")

#: Largest accepted ``ServeConfig.spec_k`` — the speculative draft depth
#: is a compile-time shape (one fixed-[R, k+1] verify program per
#: engine lifetime); acceptance rates past a handful of tokens decay
#: geometrically, so a deeper draft only burns verify FLOPs.
SPEC_K_MAX = 8


def validate_spec(spec_k: int, paged: bool, weight_dtype: str) -> None:
    """Loud construction-time validation of the speculative-decoding
    knob — shared by ``ServeConfig`` and the serving engine so a bad
    combination fails where the operator typed it.

    * ``spec_k`` must sit in [0, SPEC_K_MAX] (0 = disabled — the
      serve path is bit-for-bit today's).
    * spec decoding runs over the PAGED pool only: rejected draft KV
      rolls back by COW refcount decrement, which the legacy stripe
      pool has no machinery for.
    * the draft model IS the weight-only int8 tier, built automatically
      at engine construction; the verify pass is the MODEL-dtype tier —
      ``weight_dtype="int8"`` would collapse draft and verify onto the
      same weights (no cheap draft left, and fallback ticks would emit
      int8-decoded tokens inside a model-dtype-verified stream), so it
      is rejected loudly.
    """
    if not 0 <= int(spec_k) <= SPEC_K_MAX:
        raise ValueError(
            f"spec_k must be in [0, {SPEC_K_MAX}], got {spec_k}"
        )
    if spec_k > 0 and not paged:
        raise ValueError(
            "spec_k > 0 requires the paged KV pool (paged=True): "
            "rejected draft tokens roll back by releasing COW block "
            "claims, which the legacy stripe pool cannot express"
        )
    if spec_k > 0 and weight_dtype != "model":
        raise ValueError(
            f"spec_k > 0 requires weight_dtype='model' (got "
            f"{weight_dtype!r}): the int8 weight tier is the DRAFT — "
            "it is built automatically — and the verify pass must be "
            "the model-dtype tier, or draft and verify would share one "
            "set of weights"
        )


@dataclass
class NodeConfig:
    """Per-node configuration (reference: distributed_trainer.py:37-46).

    On TPU a "node" is a mesh coordinate; ``device_id`` generalises the
    reference's ``gpu_id``.
    """

    node_id: int
    rank: int
    world_size: int
    device_id: int = 0
    model_partition: str = ""
    trust_score: float = 1.0
    status: str = "trusted"

    # Back-compat alias for the reference's field name.
    @property
    def gpu_id(self) -> int:
        return self.device_id


@dataclass
class TrainingConfig:
    """Training configuration (reference: distributed_trainer.py:48-61,
    extended with the TPU execution knobs the reference never had)."""

    model_name: str = "gpt2"
    dataset_name: str = "openwebtext"
    batch_size: int = 32
    learning_rate: float = 5e-5
    num_epochs: int = 10
    num_nodes: int = 4
    trust_threshold: float = 0.7
    attack_detection_enabled: bool = True
    gradient_verification_enabled: bool = True
    checkpoint_interval: int = 100
    max_reassignment_attempts: int = 3

    # ---- TPU-native execution knobs (no reference equivalent) ----
    parallelism: str = "data"          # one of PARALLELISM_MODES
    mesh_shape: Optional[Dict[str, int]] = None  # for "hybrid" (within-slice)
    # Across-slice (DCN) extents for multi-slice pods: {axis: n_slices}.
    # Axes listed here parallelise over DCN; all others stay on ICI.
    dcn_mesh_shape: Optional[Dict[str, int]] = None
    # Pipeline schedule depth; 0 = auto (largest M dividing the
    # per-replica-row batch, capped at 4*S — the measured sweet spot of
    # experiments/pipeline_schedule_study: bubble (S-1)/(M+S-1) falls
    # with M, marginal gain < ~6 % past 4*S).
    num_microbatches: int = 0
    # Gradient accumulation (data-parallel modes): each node's batch is
    # processed in this many sequential microbatches inside the step
    # (lax.scan), averaging the gradients — activation memory shrinks by
    # the same factor, so effective batches grow without remat/chunking.
    # Detector semantics: batteries run on the ACCUMULATED gradient (what
    # is aggregated); output stats ride the last microbatch's features.
    grad_accum_steps: int = 1
    dtype: str = "bfloat16"            # compute dtype (params stay f32)
    seed: int = 0
    remat: bool = False                # jax.checkpoint the blocks
    # Trust/detector timing: the reference decays trust by wall-clock seconds
    # (trust_manager.py:113-114); inside a compiled step we use
    # step_count * time_per_step as the clock so the math stays pure.
    time_per_step: float = 1.0
    # Remat granularity when ``remat`` is set: "block" (whole transformer
    # block) or "attention" (only the O(T²) attention core recomputes;
    # falls back to block for non-"full" attention impls).
    remat_policy: str = "block"
    # Exact order statistics (median/percentiles) cost a sort on TPU
    # (attack_detector.py:190-196 computes them on host numpy); disable to
    # trade fidelity for speed — see SURVEY §7.4(2).
    exact_order_stats: bool = True
    detector_history: int = 1000       # rolling window (attack_detector.py:44)
    # Input-pipeline double buffering: batch k+1 assembles on the host
    # (native gathers) while batch k trains on device.  0 disables.
    prefetch_depth: int = 2
    detector_warmup: int = 10          # min history before verdicts (:91,:126)
    # Async host pipeline (engine/async_host.py): keep up to this many
    # steps in flight — each step's host-facing metrics are packed into ONE
    # flat device array whose device→host copy starts asynchronously, and
    # the host bookkeeping (detector history feed, trust mirror, incident
    # records, step guard) drains up to this many steps behind the
    # dispatch frontier, so the accelerator never idles waiting for Python.
    # 0 = fully synchronous (the pre-pipeline behavior: every step blocks
    # on ~10 separate device→host pulls before the next dispatch).
    # Semantics at depth K>0 are identical on the healthy path (same
    # losses/trust/incidents, just observed up to K steps late); supervisor
    # guard trips within the in-flight window roll back to the newest
    # verified checkpoint, which by construction predates the window
    # (checkpoint saves force a full drain first) — see README
    # §Performance.  Deterministic chaos drills that assert exact retry
    # counts (FaultPlan.predict) must run at depth 0: the lagged guard
    # skips in-place retries.
    async_host_depth: int = 2
    # Persistent XLA compilation cache (jax_compilation_cache_dir): repeat
    # runs of identical SPMD programs skip recompiles.  None = off (the
    # default); set a path (conventionally under the run dir) to enable —
    # cli.py --compile-cache and bench.py TDDL_BENCH_COMPILE_CACHE=1 wire
    # it for their run dirs.
    compilation_cache_dir: Optional[str] = None
    # Epoch-cadence host intelligence — the reference defined these but never
    # called them (SURVEY §7.5: trust_manager.py:333; attack_detector.py:381).
    adaptive_thresholds: bool = True   # trust_manager.adaptive_threshold_adjustment
    ml_detectors: bool = True          # attack_detector.update_detection_models
    # Pipeline-mode canary probe length (per-stage Byzantine/backdoor
    # reference signal, SURVEY §7.4(4)).
    canary_tokens: int = 8
    # Profiling/debug subsystems (SURVEY §5.1, §5.2 — absent in the
    # reference).  profile_dir: jax.profiler traces of training (viewable in
    # TensorBoard/Perfetto) with per-step annotations.  debug_nans: trap the
    # first NaN-producing primitive (developer mode; adversarial NaNs are
    # normally gated in-step by the verifier instead).
    profile_dir: Optional[str] = None
    debug_nans: bool = False
    # TensorBoard event-file export of batch/epoch metrics (the reference
    # pinned tensorboard in requirements but never wrote an event).
    tensorboard_dir: Optional[str] = None
    # Vocab-chunked fused lm-head+cross-entropy (ops/fused_ce.py): the LM
    # loss never materialises the [B, T, V] logits — removes the dominant
    # HBM tensor of the loss step and unlocks larger per-chip batches.
    # -1 (default) leaves the model's "auto" per-shape dispatch in charge
    # (gpt2.resolve_lm_head_chunk); 0 forces the materialised-logits CE;
    # >0 forces chunking at that width (multiple of 128 for MXU tiling,
    # typical 8192).
    lm_head_chunk: int = -1
    # ZeRO-1-style optimizer-state sharding over the data axis (data
    # parallelism only).  Pure GSPMD annotation: the Adam moments shard
    # across the data devices, XLA partitions the update computation and
    # gathers the params — identical numerics, ~(1 - 1/n_data) of the
    # moment memory reclaimed per chip.
    shard_opt_state: bool = False
    # FSDP/ZeRO-3-style PARAMETER sharding over the data axis (data
    # parallelism only), via the same registry rule as shard_opt_state
    # (core/sharding.py:place_zero_sharded): each weight's first evenly-
    # divisible dim shards across the data devices and GSPMD gathers it
    # where the forward needs it — ~1/n_data of the param bytes resident
    # per chip.  Composes with shard_opt_state; identical numerics.
    shard_params: bool = False
    # Storage dtype for the optimizer's FIRST moment (optax mu_dtype;
    # SGD's momentum accumulator).  None keeps the parameter dtype (f32);
    # "bfloat16" frees 2 bytes/param.  The second moment stays f32; for
    # the big second-moment saving use optimizer="adafactor".
    moment_dtype: Optional[str] = None
    checkpoint_dir: str = "checkpoints"
    # Async checkpointing: save() returns after the device→host snapshot;
    # disk serialisation overlaps the next training steps (Orbax async
    # path).  cleanup()/restore join any in-flight write.
    async_checkpoint: bool = False
    # Migration-time model rate for reassignment estimates.  The reference
    # hardcodes 1 GB/s (distributed_trainer.py:360); on TPU the transfer
    # rides ICI, so measure and override (elastic/reassignment.py).
    migration_gbps: float = 1.0
    # Real elastic eviction (elastic/reassignment.py): on a confirmed
    # compromise, remove the node's mesh coordinate, migrate state to the
    # surviving devices and re-jit.  Off by default: the in-step trust gate
    # already neutralises the node immediately; eviction additionally
    # reclaims its device at the cost of a recompile.
    elastic_resharding: bool = False
    # Recovery / readmission (trust_manager.py:198-206 semantics, wired
    # into the engine — the reference exposed initiate_recovery but no
    # path ever called it).  A confirmed-compromised (hard-gated, NOT
    # evicted) node that produces this many consecutive clean steps
    # transitions COMPROMISED -> RECOVERING in-step (boosted recovery
    # rate, weight restored); 0 disables the probation path.
    recovery_probation_steps: int = 25
    # Elastic-readmission: an evicted mesh coordinate is re-admitted
    # (device restored to the mesh, fresh detector rows, RECOVERING
    # status) this many steps after its eviction.  0 disables — an
    # eviction is then permanent, and a false positive costs 1/n of the
    # fleet for the rest of the run.
    readmit_after_steps: int = 0
    # Optimizer
    optimizer: str = "adamw"
    weight_decay: float = 0.0
    grad_clip_norm: float = 0.0        # 0 disables
    # LR schedule — the reference steps a torch scheduler once per epoch
    # (distributed_trainer.py:478-489) but never constructs one; here the
    # schedule is a real optax schedule evaluated per step inside the
    # compiled update.  "constant" | "cosine" | "linear"; warmup_steps
    # prepends a linear ramp from 0.  lr_decay_steps sets the decay
    # horizon (0 → num_epochs is unknown at build time, stay constant
    # after warmup).
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    lr_decay_steps: int = 0
    min_lr_ratio: float = 0.0          # floor as a fraction of peak LR
    # Trust dynamics (trust_manager.py:31-32,49-54; README.md:72-74 uses
    # 0.1/0.05 — we expose both, defaulting to the code's values per SURVEY
    # §7.5).
    initial_trust: float = 1.0
    trust_decay_rate: float = 0.01
    trust_recovery_rate: float = 0.005
    trust_alpha: float = 0.1           # EMA learning rate (trust_manager.py:117)

    def __post_init__(self) -> None:
        if self.parallelism not in PARALLELISM_MODES:
            raise ValueError(
                f"parallelism must be one of {PARALLELISM_MODES}, "
                f"got {self.parallelism!r}"
            )
        if self.remat_policy not in ("block", "attention"):
            raise ValueError(
                "remat_policy must be 'block' or 'attention', "
                f"got {self.remat_policy!r}"
            )
        if self.async_host_depth < 0:
            raise ValueError(
                "async_host_depth must be >= 0 (0 = synchronous), "
                f"got {self.async_host_depth}"
            )


@dataclass
class ExperimentConfig:
    """Experiment configuration (reference: experiment_runner.py:31-46)."""

    experiment_name: str
    model_name: str = "gpt2"
    dataset_name: str = "openwebtext"
    num_nodes: int = 4
    num_epochs: int = 10
    batch_size: int = 32
    learning_rate: float = 5e-5
    attack_enabled: bool = True
    attack_start_epoch: int = 2
    # Transient attacks: deactivate injection from this epoch on (None =
    # sustained for the rest of the run) — the vehicle for recovery /
    # readmission experiments.
    attack_end_epoch: Optional[int] = None
    attack_intensity: float = 0.5
    trust_threshold: float = 0.7
    save_interval: int = 100
    output_dir: str = "results"
    # TPU extensions
    parallelism: str = "data"
    steps_per_epoch: int = 50
    seed: int = 0
    attack_types: List[str] = field(
        default_factory=lambda: ["gradient_poisoning", "data_poisoning"]
    )
    # The reference hardcodes nodes [1, 3] (experiment_runner.py:93).
    target_nodes: List[int] = field(default_factory=lambda: [1, 3])
    num_microbatches: int = 0  # 0 = auto (see TrainingConfig)
    # Elastic / recovery knobs forwarded to the trainer (recovery
    # experiments: transient attack -> eviction -> readmission).
    elastic_resharding: bool = False
    readmit_after_steps: int = 0
    recovery_probation_steps: int = 25

    def to_training_config(self) -> TrainingConfig:
        """Build the trainer config the way the reference runner does
        (experiment_runner.py:66-75)."""
        return TrainingConfig(
            model_name=self.model_name,
            dataset_name=self.dataset_name,
            batch_size=self.batch_size,
            learning_rate=self.learning_rate,
            num_epochs=self.num_epochs,
            num_nodes=self.num_nodes,
            trust_threshold=self.trust_threshold,
            parallelism=self.parallelism,
            num_microbatches=self.num_microbatches,
            seed=self.seed,
            elastic_resharding=self.elastic_resharding,
            readmit_after_steps=self.readmit_after_steps,
            recovery_probation_steps=self.recovery_probation_steps,
        )


def validate_adapters(adapter_rank: int,
                      adapter_pool_pages: Optional[int],
                      adapter_dtype: str, paged: bool,
                      spec_k: int) -> None:
    """Loud construction-time validation of the adapter-tier knobs —
    shared by ``ServeConfig`` and ``serve.adapters`` so a bad
    combination fails where the operator typed it.

    * ``adapter_rank`` must be >= 0 (0 = disabled: the serve programs
      keep their adapter-free signatures, bit-for-bit today's output).
    * adapters ride the PAGED pool only: the per-slot adapter-page
      table is the same traced-table discipline as the KV block table,
      which the legacy stripe pool has no machinery for.
    * ``spec_k`` > 0 is rejected: the int8 draft model carries no
      adapter deltas, so draft and verify would diverge on every
      adapter-carrying request and speculation would never accept.
    * ``adapter_dtype`` must be "model" or "int8".
    * ``adapter_pool_pages`` (when given) must be >= 1 usable page.
    """
    if adapter_rank < 0:
        raise ValueError(
            f"adapter_rank must be >= 0 (0 disables), got {adapter_rank}"
        )
    if adapter_rank == 0:
        return
    if not paged:
        raise ValueError(
            "adapter_rank > 0 requires the paged KV pool (paged=True): "
            "adapter pages are claimed per slot through the same traced "
            "page-table discipline as KV blocks, which the legacy "
            "stripe pool cannot express"
        )
    if spec_k > 0:
        raise ValueError(
            "adapter_rank > 0 is incompatible with spec_k > 0: the int8 "
            "draft model carries no adapter deltas, so draft and verify "
            "would diverge on every adapter-carrying request"
        )
    if adapter_dtype not in ("model", "int8"):
        raise ValueError(
            f"adapter_dtype must be 'model' or 'int8', got "
            f"{adapter_dtype!r}"
        )
    if adapter_pool_pages is not None and adapter_pool_pages < 1:
        raise ValueError(
            f"adapter_pool_pages must be >= 1 (or None = max_slots), "
            f"got {adapter_pool_pages}"
        )


@dataclass
class ServeConfig:
    """Serving-engine configuration (serve/engine.py).

    The quantization knobs select the KV-pool storage dtype and the
    decode weight tier (quant/int8.py):

    * ``kv_dtype``: "model" (follow the model compute dtype — the
      pre-quantization behaviour), "bfloat16", "float32", or "int8"
      (per-(head, position) scaled int8 — roughly half the KV bytes per
      slot, so ~2x the slot pool at fixed HBM; parity-gated at engine
      construction with automatic fallback to "model").
    * ``weight_dtype``: "model" or "int8" (weight-only int8 for the
      decode matmuls; embedding/lm-head stay high precision).

    The paged-pool knobs select the KV memory discipline (the default
    since the paged-KV PR; README §Serving):

    * ``paged``: block-pooled KV with per-slot block tables — occupancy
      bounded by tokens in flight, not request count.  ``False`` is the
      legacy per-request stripe pool escape hatch.
    * ``block_size``: token positions per block (``max_seq`` must be a
      multiple).
    * ``num_blocks``: usable pool blocks; ``None`` sizes the pool to
      ``max_slots`` full stripes (a strict superset of the stripe pool).
    * ``prefix_cache``: radix prefix cache — requests sharing a prompt
      prefix reuse already-filled blocks copy-on-write.
    * ``prefill_chunk``: positions fed per chunked-prefill tick (a
      multiple of ``block_size``); ``None`` auto-sizes.

    ``spec_k`` enables self-speculative decoding (README §Serving
    /"Speculative decoding"): per decode tick the engine drafts
    ``spec_k`` tokens per active slot with the int8 weight tier (built
    automatically as the draft model), verifies them all in ONE batched
    model-dtype forward over the same paged cache, accepts the longest
    draft/target-matching prefix (a greedy near-tie flip under the
    parity-probe margin is tolerated and emits the DRAFT token — the
    one counted departure from spec-off bit-parity), and rolls back
    rejected draft KV by COW refcount decrement.  0 (default) disables
    — the serve path is bit-for-bit today's; ``spec_k`` > 0 requires
    ``paged=True`` and ``weight_dtype="model"``
    (:func:`validate_spec`).

    Unknown dtype strings and bad paged geometry fail HERE, at
    construction — never at trace time inside a jitted serving program.
    Paged knobs set on a ``paged=False`` config WARN loudly (the legacy
    path has no block pool — silent dropping would mask an operator
    error), but construction proceeds.
    """

    max_slots: int = 8
    max_seq: int = 256
    queue_limit: int = 64
    kv_dtype: str = "model"
    weight_dtype: str = "model"
    paged: bool = True
    block_size: int = 16
    num_blocks: Optional[int] = None
    prefix_cache: bool = True
    prefill_chunk: Optional[int] = None
    spec_k: int = 0
    # Decode-attention path (paged pool only): "auto" resolves through
    # the shared Pallas gate (TDDL_PAGED_ATTN; kernel on TPU, jnp gather
    # fallback elsewhere), "pallas"/"interpret"/"jnp" force a path —
    # README §Serving/"Decode attention kernel".
    attn_impl: str = "auto"
    # Multi-tenant adapter tier (serve/adapters.py; README §Adapters):
    # per-tenant rank-r low-rank A/B deltas on the attention out
    # projection + the MLP, stored in a SECOND paged HBM pool keyed by
    # a traced per-slot adapter-page table, so tenant mix / adapter
    # churn never recompiles the decode/prefill programs.
    #
    # * ``adapter_rank``: the low-rank width r; 0 (default) disables —
    #   the serve path is bit-for-bit today's (the adapter arguments
    #   stay structurally absent from every program signature).
    # * ``adapter_pool_pages``: usable adapter pages (resident tenants);
    #   None sizes the pool to ``max_slots`` (every slot could carry a
    #   distinct adapter).  One extra reserved zero page (page 0) always
    #   exists — the adapter-off slot's identity delta.
    # * ``adapter_dtype``: "model" stores deltas in the model compute
    #   dtype; "int8" stores symmetric-quantized int8 A/B with per-
    #   (layer, page, site) scales, dequantized in-register inside the
    #   low-rank matmul (ops/fused_dequant_matmul.py's template).
    adapter_rank: int = 0
    adapter_pool_pages: Optional[int] = None
    adapter_dtype: str = "model"
    # Tensor-parallel replica width: the engine owns a tp_size-device
    # submesh over the 'model' axis and the weights carry the model's
    # registry-declared TP layout (core/sharding.py) — the KV pool's
    # heads shard with them, so the HBM headroom gate sizes the pool
    # per SHARD.  1 (default) is byte-for-byte the single-chip engine.
    tp_size: int = 1

    def __post_init__(self) -> None:
        from trustworthy_dl_tpu.quant import validate_dtypes
        from trustworthy_dl_tpu.serve.kv_slots import validate_paged_geometry

        validate_dtypes(self.kv_dtype, self.weight_dtype)
        if self.tp_size < 1:
            raise ValueError(f"tp_size must be >= 1, got {self.tp_size}")
        if self.attn_impl not in ("auto", "pallas", "interpret", "jnp"):
            # Mirrors ops.paged_attention.ATTN_IMPLS — checked here with
            # a literal so a bad knob fails without touching jax.
            raise ValueError(
                f"attn_impl must be one of ('auto', 'pallas', "
                f"'interpret', 'jnp'), got {self.attn_impl!r}"
            )
        validate_spec(self.spec_k, self.paged, self.weight_dtype)
        validate_adapters(self.adapter_rank, self.adapter_pool_pages,
                          self.adapter_dtype, self.paged, self.spec_k)
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {self.max_slots}")
        if self.max_seq < 1:
            raise ValueError(f"max_seq must be >= 1, got {self.max_seq}")
        if self.paged:
            validate_paged_geometry(self.max_seq, self.block_size,
                                    self.num_blocks, self.prefill_chunk)
        else:
            paged_knobs = ("block_size", "num_blocks", "prefix_cache",
                           "prefill_chunk", "attn_impl")
            # Compare against the dataclass field defaults themselves —
            # a hand-written (name, default) table here would be a third
            # copy of the defaults that could silently drift.
            ignored = [
                f.name for f in dataclasses.fields(self)
                if f.name in paged_knobs
                and getattr(self, f.name) != f.default
            ]
            if ignored:
                warnings.warn(
                    f"ServeConfig(paged=False) ignores paged-pool knob(s) "
                    f"{', '.join(ignored)}: the legacy stripe pool has no "
                    f"block pool, no prefix cache and no chunked prefill. "
                    f"Drop paged=False or drop the knob(s).",
                    UserWarning, stacklevel=2,
                )


@dataclass
class AttackConfig:
    """Adversarial attack configuration (implied module; call sites at
    experiment_runner.py:90-97)."""

    attack_types: List[str] = field(
        default_factory=lambda: ["gradient_poisoning", "data_poisoning"]
    )
    target_nodes: List[int] = field(default_factory=lambda: [1, 3])
    intensity: float = 0.5
    start_step: int = 200
    seed: int = 0
    # Adaptive-adversary knobs: slow-boil intensity ramp (added per
    # attacked step on top of `intensity`) and colluding coordination
    # (all attackers submit the same perturbation direction).
    intensity_ramp: float = 0.0
    collude: bool = False


# ---------------------------------------------------------------------------
# YAML loading — honours the README schema (README.md:111-132):
#   model: {name, size}
#   training: {batch_size, learning_rate, num_epochs}
#   distributed: {num_nodes, parallelism}
#   security: {trust_threshold, attack_detection, gradient_verification}
# Flat keys matching TrainingConfig fields are also accepted, and flag-style
# overrides win over file values (fixing the reference's ignored --config).
# ---------------------------------------------------------------------------

_MODEL_SIZE_SUFFIX = {"small": "", "medium": "-medium", "large": "-large", "xl": "-xl"}


def _config_from_mapping(raw: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten the README-schema nested mapping into TrainingConfig kwargs."""
    out: Dict[str, Any] = {}
    model = raw.get("model", {})
    if isinstance(model, dict):
        name = model.get("name")
        if name:
            size = str(model.get("size", "")).lower()
            suffix = _MODEL_SIZE_SUFFIX.get(size, "")
            out["model_name"] = f"{name}{suffix}" if name.startswith("gpt") else name
    training = raw.get("training", {})
    if isinstance(training, dict):
        for key in ("batch_size", "learning_rate", "num_epochs",
                    "lr_schedule", "warmup_steps", "lr_decay_steps",
                    "min_lr_ratio", "optimizer", "weight_decay",
                    "grad_clip_norm"):
            if key in training:
                out[key] = training[key]
    distributed = raw.get("distributed", {})
    if isinstance(distributed, dict):
        if "num_nodes" in distributed:
            out["num_nodes"] = distributed["num_nodes"]
        if "parallelism" in distributed:
            out["parallelism"] = distributed["parallelism"]
        if "mesh_shape" in distributed:
            out["mesh_shape"] = dict(distributed["mesh_shape"])
        if "dcn_mesh_shape" in distributed:
            out["dcn_mesh_shape"] = dict(distributed["dcn_mesh_shape"])
        if "num_microbatches" in distributed:
            out["num_microbatches"] = distributed["num_microbatches"]
    security = raw.get("security", {})
    if isinstance(security, dict):
        if "trust_threshold" in security:
            out["trust_threshold"] = security["trust_threshold"]
        if "attack_detection" in security:
            out["attack_detection_enabled"] = bool(security["attack_detection"])
        if "gradient_verification" in security:
            out["gradient_verification_enabled"] = bool(
                security["gradient_verification"]
            )
    if "dataset" in raw:
        out["dataset_name"] = raw["dataset"]
    # Flat TrainingConfig field names pass straight through.
    valid = {f.name for f in dataclasses.fields(TrainingConfig)}
    for key, value in raw.items():
        if key in valid:
            out[key] = value
    return out


def _load_mapping(path: str) -> Dict[str, Any]:
    """Parse a YAML (or JSON) config file to a mapping."""
    import json

    with open(path) as f:
        text = f.read()
    raw: Optional[Dict[str, Any]] = None
    try:
        import yaml  # type: ignore

        raw = yaml.safe_load(text)
    except ImportError:
        try:
            raw = json.loads(text)
        except json.JSONDecodeError as e:
            raise RuntimeError(
                f"pyyaml unavailable and {path} is not JSON: {e}"
            ) from e
    if not isinstance(raw, dict):
        raise ValueError(f"config file {path} did not parse to a mapping")
    return raw


def load_config(path: str, **overrides: Any) -> TrainingConfig:
    """Load a TrainingConfig from a YAML (or JSON) file.

    ``overrides`` (e.g. CLI flags) take precedence over file values — the
    behaviour the reference documented but never implemented
    (experiment_runner.py:605,613-623).
    """
    kwargs = _config_from_mapping(_load_mapping(path))
    kwargs.update({k: v for k, v in overrides.items() if v is not None})
    return TrainingConfig(**kwargs)


def load_experiment_config(path: str, **overrides: Any) -> ExperimentConfig:
    """Load an ExperimentConfig from a YAML/JSON file.

    Accepts both the nested README schema (README.md:111-132 — shared with
    ``load_config``) and flat ExperimentConfig field names; unknown keys are
    ignored rather than raising, so a single config file can feed both
    console scripts.  Flag overrides win over file values.
    """
    raw = _load_mapping(path)
    flat = _config_from_mapping(raw)
    valid = {f.name for f in dataclasses.fields(ExperimentConfig)}
    kwargs = {k: v for k, v in flat.items() if k in valid}
    for key, value in raw.items():
        if key in valid:
            kwargs[key] = value
    kwargs.update({k: v for k, v in overrides.items() if v is not None})
    kwargs.setdefault("experiment_name", "experiment")
    return ExperimentConfig(**kwargs)

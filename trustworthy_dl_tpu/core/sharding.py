"""The unified logical-axis sharding registry — the ONE place
PartitionSpecs are spelled.

Every parallel mode used to hand-wire its own specs (`core/mesh.py`,
`parallel/tensor_parallel.py` alone spelled 17) and optimizer/param
state was fully replicated except for the lone ZeRO-1 shim.  This
module adopts the Transformer-Engine pattern (named logical axes + one
rule table per parallelism mode + constraints applied by name): models
and subsystems declare *logical* axes once (``batch``, ``seqlen``,
``head``, ``node``, ``w_tp``, ``w_fsdp``, …) and the registry
translates them to mesh axes for the active mode.  dp/fsdp/tp/pp/sp
become configuration, not code paths.

The contract, enforced by the ``sharding-registry-only`` lint rule
(analysis/rules/locality.py): ``PartitionSpec(...)`` / bare ``P(...)``
construction outside THIS module (plus the explicit whitelist in
analysis/contracts.py) is a finding.  Call sites either resolve
logical names through :class:`ShardingRules` or use the mesh-axis
helpers below (:func:`row_sharding`, :func:`replicated_sharding`,
:func:`place_zero_sharded`, …) — which is what keeps every layout's
placement identical across trainer init, checkpoint restore, elastic
migration and serve-replica builds.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from trustworthy_dl_tpu.core.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    STAGE_AXIS,
)

# ---------------------------------------------------------------------------
# Logical axis vocabulary (the names models/subsystems declare once).
# ---------------------------------------------------------------------------

BATCH = "batch"      #: per-step batch rows (data-parallel shards)
SEQLEN = "seqlen"    #: sequence/context positions (activations)
HEAD = "head"        #: attention heads (activations; Ulysses shards these)
HIDDEN = "hidden"    #: embedding/feature dims that stay whole under TP
LAYER = "layer"      #: stacked-layer leading dim of block params
NODE = "node"        #: trust-node rows ([num_nodes, ...] state/plan leaves)
STAGE = "stage"      #: pipeline-stage dim of stage-stacked leaves
EXPERT = "expert"    #: MoE expert dim
W_TP = "w_tp"        #: tensor-parallel weight dim (Megatron col/row split)
W_FSDP = "w_fsdp"    #: FSDP/ZeRO weight+optimizer shard dim

LOGICAL_AXES = frozenset({
    BATCH, SEQLEN, HEAD, HIDDEN, LAYER, NODE, STAGE, EXPERT, W_TP, W_FSDP,
})


def axis_rules(parallelism: str, *,
               fsdp: bool = False) -> Dict[str, Optional[str]]:
    """Logical-axis → mesh-axis table for one parallelism mode.

    Axes not named by a mode map to ``None`` (replicated on that dim).
    ``fsdp=True`` additionally maps :data:`W_FSDP` onto the data axis —
    ZeRO/FSDP sharding is a *rule*, not a code path.  Note the mode-
    dependent renames the table exists for: under pipelining the trust
    node IS the stage; under sequence parallelism the Ulysses exchange
    shards attention *heads* over the same mesh axis that shards
    *positions* elsewhere in the layer.
    """
    base: Dict[str, Optional[str]] = {a: None for a in LOGICAL_AXES}
    base[BATCH] = DATA_AXIS
    base[NODE] = DATA_AXIS
    if parallelism == "model":
        base[NODE] = STAGE_AXIS
        base[STAGE] = STAGE_AXIS
    elif parallelism == "tensor":
        base[W_TP] = MODEL_AXIS
    elif parallelism == "sequence":
        base[SEQLEN] = SEQ_AXIS
        base[HEAD] = SEQ_AXIS
    elif parallelism == "expert":
        base[EXPERT] = EXPERT_AXIS
    elif parallelism == "hybrid":
        # Hybrid meshes carry whatever axes the mesh_shape names; the
        # resolver drops rules whose mesh axis is absent, so one table
        # serves every hybrid composition.
        base[STAGE] = STAGE_AXIS
        base[W_TP] = MODEL_AXIS
        base[SEQLEN] = SEQ_AXIS
        base[HEAD] = SEQ_AXIS
        base[EXPERT] = EXPERT_AXIS
    elif parallelism != "data":
        raise ValueError(f"no sharding rules for parallelism={parallelism!r}")
    if fsdp:
        base[W_FSDP] = DATA_AXIS
    return base


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """One mode's resolved rule table.  The only spec 'constructor' call
    sites are allowed to hold: they name logical axes, this object
    translates — unknown names fail loudly (a typo'd axis silently
    replicating is exactly the drift the registry exists to prevent)."""

    parallelism: str
    table: Mapping[str, Optional[str]]

    def mesh_axis(self, logical: Optional[str]) -> Optional[str]:
        if logical is None:
            return None
        try:
            return self.table[logical]
        except KeyError:
            raise ValueError(
                f"unknown logical axis {logical!r} (known: "
                f"{sorted(LOGICAL_AXES)})") from None

    def partition_spec(self, *axes: Optional[str]) -> PartitionSpec:
        """Mesh-independent resolution (e.g. spec trees built before a
        mesh exists, shard_map in/out specs)."""
        return PartitionSpec(*(self.mesh_axis(a) for a in axes))

    def named_sharding(self, mesh: Mesh, *axes: Optional[str]
                       ) -> NamedSharding:
        """Mesh-aware resolution: rules whose mesh axis is absent from
        ``mesh`` resolve to None instead of failing, so one logical
        declaration serves every mesh the mode can build."""
        resolved = [self.mesh_axis(a) for a in axes]
        resolved = [a if a in mesh.axis_names else None for a in resolved]
        return NamedSharding(mesh, PartitionSpec(*resolved))

    def constrain(self, x: Any, *axes: Optional[str]) -> Any:
        """``with_sharding_constraint`` by logical name (inside jit,
        under a mesh context)."""
        return jax.lax.with_sharding_constraint(
            x, self.partition_spec(*axes))


def rules_for(parallelism: str, *, fsdp: bool = False) -> ShardingRules:
    return ShardingRules(parallelism, axis_rules(parallelism, fsdp=fsdp))


def resolve_tree(axes_tree: Any, rules: ShardingRules) -> Any:
    """Translate a logical-axis declaration tree (leaves are tuples of
    logical names, one per dim) into a PartitionSpec tree."""
    return jax.tree_util.tree_map(
        lambda axes: rules.partition_spec(*axes), axes_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


# ---------------------------------------------------------------------------
# Mesh-axis helpers: the shared spellings every placement site funnels
# through (trainer init/restore, elastic migration, serve builds).
# ---------------------------------------------------------------------------


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def row_spec(mesh_axis: str, ndim: int = 1) -> PartitionSpec:
    """Leading-dim sharding for a per-node/per-stage row array."""
    return PartitionSpec(mesh_axis, *([None] * (max(ndim, 1) - 1)))


def row_sharding(mesh: Mesh, mesh_axis: str, ndim: int = 1) -> NamedSharding:
    return NamedSharding(mesh, row_spec(mesh_axis, ndim))


def axis_size(mesh: Mesh, mesh_axis: str) -> int:
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(sizes.get(mesh_axis, 1))


def row_placer(mesh: Mesh, mesh_axis: str, n: int):
    """The ONE per-node-row placement rule, shared by trainer placement
    and elastic migration: a leaf with leading dim ``n`` shards its rows
    over ``mesh_axis`` when that divides evenly; everything else
    replicates."""
    size = axis_size(mesh, mesh_axis)
    repl = replicated_sharding(mesh)

    def place(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == n \
                and size > 1 and n % size == 0:
            return jax.device_put(leaf, row_sharding(mesh, mesh_axis,
                                                     leaf.ndim))
        return jax.device_put(leaf, repl)

    return place


# ---------------------------------------------------------------------------
# ZeRO/FSDP placement — the generalized `zero1_place_opt_state`.
# ---------------------------------------------------------------------------


def zero_shard_spec(shape: Sequence[int], n_shards: int,
                    mesh_axis: str) -> PartitionSpec:
    """First evenly-divisible dim shards over ``mesh_axis``; leaves with
    no such dim (scalars, odd shapes) replicate.  This is the ZeRO-1
    moment rule generalized to any tree (params under FSDP use it too)."""
    for i, dim in enumerate(shape):
        if dim >= n_shards and dim % n_shards == 0:
            spec: list = [None] * len(shape)
            spec[i] = mesh_axis
            return PartitionSpec(*spec)
    return PartitionSpec()


def place_zero_sharded(tree: Any, mesh: Mesh,
                       mesh_axis: str = DATA_AXIS) -> Any:
    """ZeRO/FSDP-style placement of a whole pytree: every leaf shards on
    its first evenly-divisible dim over ``mesh_axis`` (annotation-only —
    GSPMD partitions the update and gathers where needed, so an n-way
    mesh keeps ~1/n of the bytes per chip).  Replicates everything when
    the axis is absent or size 1, so the helper is safe at any layout.

    This is THE placement both the trainer (`_place_on_mesh`) and
    elastic migration (`elastic/reassignment.py`) use — one spelling, so
    an evict/readmit cycle reproduces exactly the shardings a fresh
    trainer would choose."""
    n = axis_size(mesh, mesh_axis)
    repl = replicated_sharding(mesh)

    def place(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and n > 1:
            spec = zero_shard_spec(leaf.shape, n, mesh_axis)
            return jax.device_put(leaf, NamedSharding(mesh, spec))
        return jax.device_put(leaf, repl)

    return jax.tree_util.tree_map(place, tree)


def tree_bytes_per_device(tree: Any) -> int:
    """Actual per-device bytes of a placed pytree: each leaf contributes
    its shard size on the busiest device (replicated leaves count fully).
    The bench's ``params_bytes_per_device`` — measured from shardings,
    not estimated."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = getattr(leaf, "sharding", None)
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        if sh is None or not hasattr(sh, "shard_shape"):
            total += nbytes
            continue
        try:
            shard = sh.shard_shape(leaf.shape)
            size = 1
            for d in shard:
                size *= int(d)
            itemsize = leaf.dtype.itemsize
            total += size * itemsize
        except Exception:
            total += nbytes
    return total


# ---------------------------------------------------------------------------
# Serving: tensor-parallel replica submeshes.
# ---------------------------------------------------------------------------


def serve_tp_mesh(tp_size: int,
                  devices: Optional[Sequence[Any]] = None) -> Mesh:
    """A serve replica's TP submesh: ``tp_size`` devices over the
    'model' axis.  The fleet carves per-replica device slices and passes
    them here; a single-engine caller defaults to the first ``tp_size``
    local devices."""
    if tp_size < 1:
        raise ValueError("tp_size must be >= 1")
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < tp_size:
        raise ValueError(
            f"serve TP mesh needs {tp_size} devices, have {len(devices)}")
    import numpy as np

    return Mesh(np.array(devices[:tp_size]), (MODEL_AXIS,))


def place_serve_tp(params: Any, mesh: Mesh) -> Any:
    """Place serve params with the model's declared TP layout on a
    replica submesh (no-op when the mesh has no 'model' axis).  Resolves
    through the same registry rules training TP uses — one layout, both
    planes."""
    from trustworthy_dl_tpu.parallel.tensor_parallel import apply_tp_sharding

    return apply_tp_sharding(params, mesh)


def mesh_spec_tree(params: Any) -> Any:
    """Sharding specs of a placed tree (None for uncommitted leaves) —
    the regression surface layout tests pin against."""
    def spec_of(leaf):
        sh = getattr(leaf, "sharding", None)
        return getattr(sh, "spec", None)

    return jax.tree_util.tree_map(spec_of, params)

"""Device-mesh construction — the L1 communication layer, TPU-native.

The reference's L1 is an NCCL process group that is initialised and destroyed
but never used for a collective (distributed_trainer.py:99-114,523-527; see
SURVEY §2.5).  Here L1 is a real `jax.sharding.Mesh`: collectives are XLA ops
(psum / ppermute / all_gather / all_to_all) compiled into the train step and
riding ICI (intra-slice) or DCN (multi-slice).  There is no rendezvous config
to manage — `jax.distributed.initialize()` handles multi-host.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

logger = logging.getLogger(__name__)


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions: new jax exposes it at the top
    level with a ``check_vma`` flag; older releases (<= 0.4.x, as baked into
    this container) only have ``jax.experimental.shard_map`` where the same
    knob is named ``check_rep``.  One shim so every call site is
    version-agnostic.  The check defaults ON, matching jax's own default —
    call sites that need it off (the pipeline/sequence rings) say so
    explicitly."""
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_vma=check_vma)


# Canonical axis names (SURVEY §7.1).  The reference's "node" maps onto
# whichever axis the chosen parallelism strategy uses.
DATA_AXIS = "data"     # data parallel shards
STAGE_AXIS = "stage"   # pipeline stages (reference's layer-split "nodes")
MODEL_AXIS = "model"   # tensor parallel (attention heads / mlp hidden)
SEQ_AXIS = "seq"       # sequence/context parallel
EXPERT_AXIS = "expert"  # expert parallel (MoE expert dim)

_PARALLELISM_AXIS = {
    "data": DATA_AXIS,
    "model": STAGE_AXIS,
    "tensor": MODEL_AXIS,
    "sequence": SEQ_AXIS,
    "expert": EXPERT_AXIS,
}


def node_axis_for(parallelism: str) -> str:
    """Mesh axis that plays the role of the reference's node index."""
    try:
        return _PARALLELISM_AXIS[parallelism]
    except KeyError:
        raise ValueError(f"no canonical node axis for parallelism={parallelism!r}")


# Canonical outermost-first axis order: DCN-adjacent axes (data, stage —
# the ones whose collectives tolerate lower bandwidth) come first, per the
# scaling-book recipe; bandwidth-hungry axes (model/seq/expert) innermost
# so their collectives ride ICI.
AXIS_ORDER = (DATA_AXIS, STAGE_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS)


def build_hybrid_mesh(
    ici_mesh_shape: Dict[str, int],
    dcn_mesh_shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: per-axis ICI size within a slice and DCN size
    across slices.  Axes with a DCN extent >1 replicate/parallelise across
    slices (typically 'data' and/or 'stage'); everything else stays inside
    one slice so its collectives never touch DCN.

    On real multi-slice TPU hardware the device grid comes from
    ``mesh_utils.create_hybrid_device_mesh`` (which groups by slice
    index); when every DCN extent is 1 — single slice, CPU test meshes —
    the layout degenerates to a plain reshape in AXIS_ORDER.
    """
    dcn_mesh_shape = dcn_mesh_shape or {}
    extra = (set(ici_mesh_shape) | set(dcn_mesh_shape)) - set(AXIS_ORDER)
    if extra:
        raise ValueError(f"unknown mesh axes {extra}")
    order = [a for a in AXIS_ORDER
             if a in ici_mesh_shape or a in dcn_mesh_shape]
    ici = [int(ici_mesh_shape.get(a, 1)) for a in order]
    dcn = [int(dcn_mesh_shape.get(a, 1)) for a in order]
    devices = list(devices if devices is not None else jax.devices())
    total = int(np.prod(ici)) * int(np.prod(dcn))
    if total > len(devices):
        raise ValueError(
            f"hybrid mesh ici={ici_mesh_shape} dcn={dcn_mesh_shape} needs "
            f"{total} devices, have {len(devices)}"
        )
    if any(d > 1 for d in dcn):
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            ici, dcn, devices=devices[:total]
        )
        return Mesh(arr, tuple(order))
    arr = np.array(devices[:total]).reshape(ici)
    return Mesh(arr, tuple(order))


def build_mesh(
    num_nodes: int,
    parallelism: str = "data",
    mesh_shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_mesh_shape: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Build the mesh for a training run.

    For single-axis strategies the node axis gets ``num_nodes`` entries.
    Tensor/sequence modes fold leftover devices into each node's TP/seq
    group; pipeline ("model") uses exactly one device per stage and
    leaves surplus devices out of the mesh (see the stage branch below
    for why).  For "hybrid", ``mesh_shape`` gives the within-slice
    {axis: size} explicitly and ``dcn_mesh_shape`` the optional
    across-slice extents (see build_hybrid_mesh).
    """
    devices = list(devices if devices is not None else jax.devices())
    n_dev = len(devices)

    if parallelism == "hybrid":
        if not mesh_shape:
            raise ValueError("hybrid parallelism requires mesh_shape")
        return build_hybrid_mesh(mesh_shape, dcn_mesh_shape, devices)
    if dcn_mesh_shape:
        # Silently dropping the DCN extents would lay collectives across
        # slices with no slice-aware grouping — the failure hybrid meshes
        # exist to prevent.
        raise ValueError(
            "dcn_mesh_shape requires parallelism='hybrid' (got "
            f"{parallelism!r}); express the within-slice layout in "
            "mesh_shape and the across-slice extents in dcn_mesh_shape"
        )

    axis = node_axis_for(parallelism)
    if num_nodes > n_dev:
        # Degenerate/dev mode: more logical nodes than devices.  The node
        # axis still exists logically (vmapped); the mesh axis takes the
        # largest divisor of num_nodes that fits so [num_nodes, ...] arrays
        # still shard evenly (worst case 1 → fully replicated execution).
        fit = max(d for d in range(1, n_dev + 1) if num_nodes % d == 0)
        logger.warning(
            "num_nodes=%d exceeds device count %d; using a %d-wide mesh "
            "(logical nodes are vmapped within devices)",
            num_nodes, n_dev, fit,
        )
        num_nodes = fit
    if axis == DATA_AXIS:
        # Pure DP: the data axis IS the node axis — per-node arrays shard
        # one (or an equal group of) logical node(s) per device.
        arr = np.array(devices[:num_nodes])
        return Mesh(arr, (DATA_AXIS,))
    usable = (n_dev // num_nodes) * num_nodes
    group = usable // num_nodes
    if axis == STAGE_AXIS:
        # Pipeline: the stage axis carries the nodes.  On TPU, surplus
        # devices form DP pipeline replica rows — a (group, S) mesh whose
        # data axis shards the microbatches (parallel/pipeline.py), so
        # adding chips beyond S scales batch throughput.  On CPU the mesh
        # stays exactly (1, S): the DP×PP composition races independent
        # subgroup collectives (stage-row psum vs GSPMD-inserted data
        # all-reduces), which nondeterministically aborts XLA:CPU's
        # in-process communicator — a backend bug TPU's compiled
        # collectives don't have.  (Verified r3: the bare pipe matched
        # sequential grads under the (2, 4) mesh; only XLA:CPU crashed.)
        if group >= 2 and devices[0].platform == "tpu":
            arr = np.array(devices[:usable]).reshape(group, num_nodes)
        else:
            arr = np.array(devices[:num_nodes]).reshape(1, num_nodes)
        return Mesh(arr, (DATA_AXIS, axis))
    # Tensor / sequence: trust nodes stay data shards; each node owns a
    # TP / sequence group of the remaining devices (SURVEY §2.4 plan — the
    # detection unit is the DP shard, intra-node sharding is transparent).
    arr = np.array(devices[:usable]).reshape(num_nodes, group)
    return Mesh(arr, (DATA_AXIS, axis))


def bind_mode_mesh(mesh: Mesh, parallelism: str) -> None:
    """Bind the global collectives mesh for the modes whose forwards read
    one (sequence ring/Ulysses, MoE expert dispatch); no-op otherwise.

    The ONE binding ladder — shared by trainer construction, elastic mesh
    rebuilds (eviction/readmission) and checkpoint topology adoption, so
    a new rebuild site (or a new mode) cannot silently miss a binding.
    Imports are lazy to keep core/ free of parallel/models dependencies."""
    if parallelism == "sequence":
        from trustworthy_dl_tpu.parallel.sequence import set_sequence_mesh

        set_sequence_mesh(mesh)
    elif parallelism == "expert":
        from trustworthy_dl_tpu.models.moe import set_expert_mesh

        set_expert_mesh(mesh)


def node_sharding(mesh: Mesh, axis: str) -> NamedSharding:
    """Sharding for a per-node leading-axis array (e.g. [num_nodes, ...]).
    Spec resolution lives in the registry (core/sharding.py — lazy import:
    the registry imports this module's axis names)."""
    from trustworthy_dl_tpu.core import sharding as shreg

    return shreg.row_sharding(mesh, axis)


def replicated(mesh: Mesh) -> NamedSharding:
    from trustworthy_dl_tpu.core import sharding as shreg

    return shreg.replicated_sharding(mesh)


def local_device_count() -> int:
    return jax.local_device_count()


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host init — TPU replacement for the reference's
    init_process_group (distributed_trainer.py:99-114).  On TPU pods all
    arguments are discovered from the environment."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    logger.info(
        "Initialized distributed environment: process %d/%d",
        jax.process_index(), jax.process_count(),
    )


def shutdown_multihost() -> None:
    """Teardown parity with dist.destroy_process_group
    (distributed_trainer.py:523-527)."""
    try:
        jax.distributed.shutdown()
    except (RuntimeError, ValueError):
        pass  # never initialised — mirrors the reference's is_initialized() guard

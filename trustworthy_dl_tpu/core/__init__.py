from trustworthy_dl_tpu.core.config import (
    AttackConfig,
    ExperimentConfig,
    NodeConfig,
    TrainingConfig,
    load_config,
)
from trustworthy_dl_tpu.core.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    STAGE_AXIS,
    build_hybrid_mesh,
    build_mesh,
    node_axis_for,
)

__all__ = [
    "AttackConfig",
    "DATA_AXIS",
    "ExperimentConfig",
    "MODEL_AXIS",
    "NodeConfig",
    "SEQ_AXIS",
    "STAGE_AXIS",
    "TrainingConfig",
    "build_hybrid_mesh",
    "build_mesh",
    "load_config",
    "node_axis_for",
]

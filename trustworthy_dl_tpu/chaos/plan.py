"""Deterministic fault plans — the chaos counterpart of
``attacks.adversarial.AttackPlan``.

An ``AttackPlan`` schedules *adversarial* behaviour (a node lying about its
gradients); a ``FaultPlan`` schedules *infrastructure* failure: non-finite
gradients from corrupted state, wedged hosts, preemptions, truncated or
bit-rotten checkpoint shards, data-iterator failures, and poisoned serving
replicas.  Production recovery machinery is only trustworthy if it is
continuously exercised (Gemini's in-memory recovery, SOSP '23; Bamboo,
NSDI '23) — the plan is the exercise schedule, and it is **seeded and
reproducible**: the same ``(seed, horizon, rates)`` always generates the
same events, so a survival drill can assert the *exact* number of retries,
rollbacks and restarts the supervisor should perform (``predict``).

Events are consumed by ``chaos.injector.FaultInjector`` at explicit hook
points in ``DistributedTrainer.train_epoch``, ``CheckpointManager`` and
``serve.ServingEngine``.  Each event fires **once** (the injector tracks
fired events), so a post-rollback replay of the same global steps does not
re-trigger the fault that caused the rollback.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np


class FaultKind(str, enum.Enum):
    """What breaks.  ``step`` semantics per kind are documented on
    ``FaultEvent``."""

    #: Corrupt live parameters with NaN after step ``step`` completes —
    #: every subsequent loss is genuinely non-finite until state is
    #: restored from a checkpoint (the "silently corrupted optimizer
    #: state" failure the supervisor's rollback path exists for).
    GRAD_NAN = "grad_nan"
    #: Host stall / straggler: sleep ``severity`` seconds before the step.
    STALL = "stall"
    #: Simulated preemption signal raised before the step runs — the
    #: supervisor must save-on-signal and auto-resume.
    PREEMPT = "preempt"
    #: Flip bytes in a committed checkpoint's payload (bit-rot): fires on
    #: the first checkpoint committed at global step >= ``step``.
    CKPT_CORRUPT = "ckpt_corrupt"
    #: Die between payload write and COMMIT marker: the first save at
    #: global step >= ``step`` is left uncommitted on disk.
    CKPT_CRASH = "ckpt_crash"
    #: Data-iterator failure: the batch at ``step`` is lost (the loader
    #: "raised"); training must continue on the next batch.
    DATA_LOSS = "data_loss"
    #: Poison a serving slot's output signals for request id ``step`` —
    #: the engine's output monitor must flag and quarantine the slot.
    #: With ``target >= 0`` the poison is replica-addressed: it only
    #: fires on the engine whose ``replica_id`` matches (fleet request
    #: ids are namespaced replica-locally, so an unaddressed poison
    #: would be ambiguous once N replicas share the id space).
    SERVE_POISON = "serve_poison"
    # -- fleet-granularity kinds (serve/fleet.py).  ``step`` is the
    # fleet TICK the event fires on; ``target`` the replica index. --
    #: Kill replica ``target`` at tick ``step``: its engine (and KV
    #: pool, allocator journal, in-flight work) is gone.  The fleet must
    #: fail over every accepted request it held and restart the replica.
    REPLICA_CRASH = "replica_crash"
    #: Wedge replica ``target`` for ``severity`` ticks (its engine stops
    #: making progress) — the missed-tick heartbeat must catch it, drain
    #: it, and migrate its in-flight requests.
    REPLICA_STALL = "replica_stall"
    #: Compromise replica ``target`` from tick ``step`` on: every
    #: request retiring there gets a collapsed-entropy/inflated-margin
    #: signal profile, so its monitor flag-rate must cross the
    #: quarantine threshold → drain → quarantine.  Persists until the
    #: injector's :meth:`FaultInjector.heal_replica` (a readmission
    #: probe of a still-poisoned replica must fail again).
    REPLICA_POISON = "replica_poison"
    #: Replica ``target`` restarts slowly: after tick ``step`` it takes
    #: ``severity`` extra ticks of warmup during which it accepts no new
    #: admissions (goodput dip, no failover/drain).
    REPLICA_SLOWSTART = "replica_slowstart"


#: The serving-fleet kinds (consumed by ``FaultInjector.on_fleet_tick``
#: / ``on_serve_retire`` rather than the trainer hooks).
FLEET_KINDS = (FaultKind.REPLICA_CRASH, FaultKind.REPLICA_STALL,
               FaultKind.REPLICA_POISON, FaultKind.REPLICA_SLOWSTART)


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.  ``step`` is the trainer's *global step* for
    training-side kinds, the minimum save step for checkpoint kinds, the
    request id for ``SERVE_POISON`` and the fleet tick for the
    ``REPLICA_*`` kinds.  ``severity`` is kind-specific (stall
    seconds/ticks, poison magnitude, slow-start warmup ticks); unused
    kinds ignore it.  ``target`` addresses a replica (fleet kinds and
    replica-gated serve poison); ``-1`` = unaddressed (any replica)."""

    step: int
    kind: FaultKind
    severity: float = 1.0
    target: int = -1


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Immutable, seeded schedule of ``FaultEvent``s.

    Build with :meth:`generate` (seeded rates over a step horizon) or
    :meth:`scripted` (explicit events, for drills that must predict exact
    recovery counts).  The plan itself is pure; all firing state lives in
    the injector.
    """

    seed: int
    events: Tuple[FaultEvent, ...]

    @classmethod
    def scripted(cls, events: Sequence[FaultEvent], seed: int = 0
                 ) -> "FaultPlan":
        return cls(seed=seed,
                   events=tuple(sorted(events, key=lambda e: e.step)))

    @classmethod
    def generate(cls, seed: int, num_steps: int,
                 rates: Mapping[FaultKind, float],
                 severity: float = 1.0,
                 num_replicas: Optional[int] = None) -> "FaultPlan":
        """Seeded Bernoulli draw per (step, kind): the same arguments
        always produce the same plan, so a drill is reproducible from its
        seed alone.  ``rates`` maps kind -> per-step probability.
        ``num_replicas`` seeds a replica ``target`` for the fleet kinds
        (drawn from the same stream — required when their rates are
        nonzero, since an unaddressed fleet fault has no victim)."""
        rng = np.random.default_rng(seed)
        events: List[FaultEvent] = []
        # Fixed kind order (enum declaration order) keeps the draw stream
        # stable across python versions / dict orderings.
        kinds = [k for k in FaultKind if rates.get(k, 0.0) > 0.0]
        if num_replicas is None and any(k in FLEET_KINDS for k in kinds):
            raise ValueError(
                "fleet fault rates need num_replicas to draw targets"
            )
        for step in range(num_steps):
            for kind in kinds:
                if rng.random() < rates[kind]:
                    target = (int(rng.integers(num_replicas))
                              if kind in FLEET_KINDS else -1)
                    events.append(FaultEvent(
                        step=step, kind=kind,
                        severity=float(severity * (0.5 + rng.random())),
                        target=target,
                    ))
        return cls(seed=seed, events=tuple(events))

    def of_kind(self, kind: FaultKind) -> List[FaultEvent]:
        return [e for e in self.events if e.kind is kind]

    def count(self, kind: FaultKind) -> int:
        return len(self.of_kind(kind))

    def at(self, step: int, kind: Optional[FaultKind] = None
           ) -> List[FaultEvent]:
        """Events scheduled exactly at ``step`` (optionally one kind)."""
        return [e for e in self.events
                if e.step == step and (kind is None or e.kind is kind)]

    def predict(self, max_retries: int, rollback_after: int
                ) -> Dict[str, int]:
        """Expected supervisor recovery counts for this plan under a
        ``TrainingSupervisor(max_retries=..., rollback_after=...)``.

        Valid when events are *isolated*: GRAD_NAN events spaced further
        apart than the rollback window, and a verified checkpoint existing
        before each (the supervisor writes one at start, so this holds for
        any plan whose first GRAD_NAN is after step 0).  Each GRAD_NAN
        corrupts state persistently, so every retry of a bad step fails:
        the supervisor burns ``max_retries`` retries on each of
        ``rollback_after`` consecutive bad steps, then rolls back once.
        """
        n_nan = self.count(FaultKind.GRAD_NAN)
        return {
            "retries": n_nan * rollback_after * max_retries,
            "rollbacks": n_nan,
            "restarts": self.count(FaultKind.PREEMPT),
            "preemptions": self.count(FaultKind.PREEMPT),
            "dropped_batches": self.count(FaultKind.DATA_LOSS),
            "stalls": self.count(FaultKind.STALL),
        }

    def predict_fleet(self) -> Dict[str, int]:
        """Expected ``ServingFleet`` recovery counts for this plan's
        REPLICA_* events (the serving mirror of :meth:`predict`).

        Valid when events are *isolated* — at most one fleet fault per
        replica, each given room to complete its recovery arc: a STALL's
        severity (ticks) exceeds the fleet's heartbeat-miss limit, a
        poisoned replica retires at least ``flag_min_count`` requests
        while poisoned, and the drill runs long enough for every drain
        to complete — but ENDS before any quarantined replica's
        cool-off expires (or the poison is healed first): an unhealed
        replica re-trips on every readmission probe by design, adding a
        drain + quarantine per probe beyond the first.  Drills pin
        ``quarantine_cooloff_ticks`` past their horizon.  Under those
        conditions each event's recovery arc is exact:

        * CRASH  → 1 failover episode (everything the replica held
          migrates at once) + 1 restart;
        * STALL  → 1 drain (heartbeat trips) + 1 failover episode;
        * POISON → 1 drain (monitor flag-rate crosses the quarantine
          threshold) + 1 quarantine;
        * SLOWSTART → 1 slow-start warmup (goodput only — no failover,
          drain or quarantine).
        """
        crashes = self.count(FaultKind.REPLICA_CRASH)
        stalls = self.count(FaultKind.REPLICA_STALL)
        poisons = self.count(FaultKind.REPLICA_POISON)
        return {
            "crashes": crashes,
            "restarts": crashes,
            "stalls": stalls,
            "poisons": poisons,
            "slowstarts": self.count(FaultKind.REPLICA_SLOWSTART),
            "failover_episodes": crashes + stalls,
            "drains": stalls + poisons,
            "quarantines": poisons,
        }

"""Deterministic fault plans — the chaos counterpart of
``attacks.adversarial.AttackPlan``.

An ``AttackPlan`` schedules *adversarial* behaviour (a node lying about its
gradients); a ``FaultPlan`` schedules *infrastructure* failure: non-finite
gradients from corrupted state, wedged hosts, preemptions, truncated or
bit-rotten checkpoint shards, data-iterator failures, and poisoned serving
replicas.  Production recovery machinery is only trustworthy if it is
continuously exercised (Gemini's in-memory recovery, SOSP '23; Bamboo,
NSDI '23) — the plan is the exercise schedule, and it is **seeded and
reproducible**: the same ``(seed, horizon, rates)`` always generates the
same events, so a survival drill can assert the *exact* number of retries,
rollbacks and restarts the supervisor should perform (``predict``).

Events are consumed by ``chaos.injector.FaultInjector`` at explicit hook
points in ``DistributedTrainer.train_epoch``, ``CheckpointManager`` and
``serve.ServingEngine``.  Each event fires **once** (the injector tracks
fired events), so a post-rollback replay of the same global steps does not
re-trigger the fault that caused the rollback.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np


class FaultKind(str, enum.Enum):
    """What breaks.  ``step`` semantics per kind are documented on
    ``FaultEvent``."""

    #: Corrupt live parameters with NaN after step ``step`` completes —
    #: every subsequent loss is genuinely non-finite until state is
    #: restored from a checkpoint (the "silently corrupted optimizer
    #: state" failure the supervisor's rollback path exists for).
    GRAD_NAN = "grad_nan"
    #: Host stall / straggler: sleep ``severity`` seconds before the step.
    STALL = "stall"
    #: Simulated preemption signal raised before the step runs — the
    #: supervisor must save-on-signal and auto-resume.
    PREEMPT = "preempt"
    #: Flip bytes in a committed checkpoint's payload (bit-rot): fires on
    #: the first checkpoint committed at global step >= ``step``.
    CKPT_CORRUPT = "ckpt_corrupt"
    #: Die between payload write and COMMIT marker: the first save at
    #: global step >= ``step`` is left uncommitted on disk.
    CKPT_CRASH = "ckpt_crash"
    #: Data-iterator failure: the batch at ``step`` is lost (the loader
    #: "raised"); training must continue on the next batch.
    DATA_LOSS = "data_loss"
    #: Poison a serving slot's output signals for request id ``step`` —
    #: the engine's output monitor must flag and quarantine the slot.
    #: With ``target >= 0`` the poison is replica-addressed: it only
    #: fires on the engine whose ``replica_id`` matches (fleet request
    #: ids are namespaced replica-locally, so an unaddressed poison
    #: would be ambiguous once N replicas share the id space).
    SERVE_POISON = "serve_poison"
    # -- fleet-granularity kinds (serve/fleet.py).  ``step`` is the
    # fleet TICK the event fires on; ``target`` the replica index. --
    #: Kill replica ``target`` at tick ``step``: its engine (and KV
    #: pool, allocator journal, in-flight work) is gone.  The fleet must
    #: fail over every accepted request it held and restart the replica.
    REPLICA_CRASH = "replica_crash"
    #: Wedge replica ``target`` for ``severity`` ticks (its engine stops
    #: making progress) — the missed-tick heartbeat must catch it, drain
    #: it, and migrate its in-flight requests.
    REPLICA_STALL = "replica_stall"
    #: Compromise replica ``target`` from tick ``step`` on: every
    #: request retiring there gets a collapsed-entropy/inflated-margin
    #: signal profile, so its monitor flag-rate must cross the
    #: quarantine threshold → drain → quarantine.  Persists until the
    #: injector's :meth:`FaultInjector.heal_replica` (a readmission
    #: probe of a still-poisoned replica must fail again).
    REPLICA_POISON = "replica_poison"
    #: Replica ``target`` restarts slowly: after tick ``step`` it takes
    #: ``severity`` extra ticks of warmup during which it accepts no new
    #: admissions (goodput dip, no failover/drain).
    REPLICA_SLOWSTART = "replica_slowstart"
    #: Compromise replica ``target`` ADAPTIVELY from tick ``step`` on:
    #: corruption is driven by a ``chaos.adversary.AdaptivePoisonAttacker``
    #: (``FaultInjector(adversary=...)``) that corrupts the served token
    #: stream and tunes its signal shaping to hold the replica's public
    #: flag rate just below ``FleetConfig.flag_rate_quarantine`` — the
    #: PR 8 ladder never trips.  Caught by the fleet's cross-replica
    #: verdict voting (``FleetConfig.vote_k``): corrupted streams
    #: disagree with their bit-identical replays.  Persists until
    #: :meth:`FaultInjector.heal_replica`.
    REPLICA_ADAPTIVE_POISON = "replica_adaptive_poison"
    #: Overload-as-a-fault: at fleet tick ``step``, tenant ``tenant``
    #: (default "flood") bursts ``severity`` requests through the
    #: fleet's admission path in one tick.  With a per-tenant token
    #: bucket configured (``FleetConfig.tenant_quota``) the bucket
    #: admits what it can pay for and THROTTLES the rest — loudly
    #: (``tenant_throttle`` events +
    #: ``tddl_fleet_tenant_throttled_total{tenant=}``) — so the flood
    #: backpressures itself, not the fleet; admitted flood requests are
    #: real accepted work and drive the autoscaler like any burst.  The
    #: replica ``target`` is meaningless for this kind (-1).
    TENANT_FLOOD = "tenant_flood"
    #: Compromise ADAPTER ``tenant`` from tick ``step`` on (the adapter
    #: id rides the ``tenant`` field — like TENANT_FLOOD the fault is
    #: artifact-addressed, not replica-addressed: a poisoned adapter is
    #: wherever its pool page is resident).  Every request retiring
    #: UNDER that adapter — on any replica — gets the collapsed-entropy
    #: poison signal profile, so the fleet's per-ADAPTER flag-rate
    #: window must trip and quarantine the adapter fleet-wide while the
    #: replicas that hosted it stay HEALTHY (zero drains, zero replica
    #: quarantines).  Persists until
    #: :meth:`FaultInjector.heal_adapter`.
    ADAPTER_POISON = "adapter_poison"
    #: Preempt replica ``target`` at tick ``step`` — the serving twin of
    #: the training-side ``PREEMPT``: the capacity is GOING AWAY (spot
    #: reclaim, eviction) but the fleet gets one tick of warning, so
    #: every in-flight request it holds must MIGRATE (live KV
    #: block-table copy, ``serve/migrate.py``) to a surviving replica
    #: instead of replaying from scratch; queued work re-queues.  The
    #: replica then restarts like a crash (``restart_ticks`` warmup) but
    #: with zero lost decode work and zero failover episodes.
    #: Declared LAST so generated plans' seeded draw streams for the
    #: older kinds are unchanged (``generate`` iterates in enum order).
    REPLICA_PREEMPT = "replica_preempt"


#: The serving-fleet kinds (consumed by ``FaultInjector.on_fleet_tick``
#: / ``on_serve_retire`` rather than the trainer hooks).
FLEET_KINDS = (FaultKind.REPLICA_CRASH, FaultKind.REPLICA_STALL,
               FaultKind.REPLICA_POISON, FaultKind.REPLICA_SLOWSTART,
               FaultKind.REPLICA_ADAPTIVE_POISON,
               FaultKind.TENANT_FLOOD, FaultKind.ADAPTER_POISON,
               FaultKind.REPLICA_PREEMPT)


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.  ``step`` is the trainer's *global step* for
    training-side kinds, the minimum save step for checkpoint kinds, the
    request id for ``SERVE_POISON`` and the fleet tick for the
    ``REPLICA_*`` kinds.  ``severity`` is kind-specific (stall
    seconds/ticks, poison magnitude, slow-start warmup ticks); unused
    kinds ignore it.  ``target`` addresses a replica (fleet kinds and
    replica-gated serve poison); ``-1`` = unaddressed (any replica).
    ``tenant`` names the flooding tenant for ``TENANT_FLOOD`` (None =
    the fleet's default flood tenant); other kinds ignore it."""

    step: int
    kind: FaultKind
    severity: float = 1.0
    target: int = -1
    tenant: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Immutable, seeded schedule of ``FaultEvent``s.

    Build with :meth:`generate` (seeded rates over a step horizon) or
    :meth:`scripted` (explicit events, for drills that must predict exact
    recovery counts).  The plan itself is pure; all firing state lives in
    the injector.
    """

    seed: int
    events: Tuple[FaultEvent, ...]

    @classmethod
    def scripted(cls, events: Sequence[FaultEvent], seed: int = 0
                 ) -> "FaultPlan":
        return cls(seed=seed,
                   events=tuple(sorted(events, key=lambda e: e.step)))

    @classmethod
    def generate(cls, seed: int, num_steps: int,
                 rates: Mapping[FaultKind, float],
                 severity: float = 1.0,
                 num_replicas: Optional[int] = None) -> "FaultPlan":
        """Seeded Bernoulli draw per (step, kind): the same arguments
        always produce the same plan, so a drill is reproducible from its
        seed alone.  ``rates`` maps kind -> per-step probability.
        ``num_replicas`` seeds a replica ``target`` for the fleet kinds
        (drawn from the same stream — required when their rates are
        nonzero, since an unaddressed fleet fault has no victim)."""
        rng = np.random.default_rng(seed)
        events: List[FaultEvent] = []
        # Fixed kind order (enum declaration order) keeps the draw stream
        # stable across python versions / dict orderings.
        kinds = [k for k in FaultKind if rates.get(k, 0.0) > 0.0]
        # TENANT_FLOOD and ADAPTER_POISON are fleet-granularity but
        # tenant-/adapter-addressed, not replica-addressed — they need
        # no target draw.
        addressed = [k for k in kinds
                     if k in FLEET_KINDS
                     and k not in (FaultKind.TENANT_FLOOD,
                                   FaultKind.ADAPTER_POISON)]
        if num_replicas is None and addressed:
            raise ValueError(
                "fleet fault rates need num_replicas to draw targets"
            )
        for step in range(num_steps):
            for kind in kinds:
                if rng.random() < rates[kind]:
                    target = (int(rng.integers(num_replicas))
                              if kind in addressed else -1)
                    events.append(FaultEvent(
                        step=step, kind=kind,
                        severity=float(severity * (0.5 + rng.random())),
                        target=target,
                    ))
        return cls(seed=seed, events=tuple(events))

    def of_kind(self, kind: FaultKind) -> List[FaultEvent]:
        return [e for e in self.events if e.kind is kind]

    def count(self, kind: FaultKind) -> int:
        return len(self.of_kind(kind))

    def at(self, step: int, kind: Optional[FaultKind] = None
           ) -> List[FaultEvent]:
        """Events scheduled exactly at ``step`` (optionally one kind)."""
        return [e for e in self.events
                if e.step == step and (kind is None or e.kind is kind)]

    def predict(self, max_retries: int, rollback_after: int
                ) -> Dict[str, int]:
        """Expected supervisor recovery counts for this plan under a
        ``TrainingSupervisor(max_retries=..., rollback_after=...)``.

        Valid when events are *isolated*: GRAD_NAN events spaced further
        apart than the rollback window, and a verified checkpoint existing
        before each (the supervisor writes one at start, so this holds for
        any plan whose first GRAD_NAN is after step 0).  Each GRAD_NAN
        corrupts state persistently, so every retry of a bad step fails:
        the supervisor burns ``max_retries`` retries on each of
        ``rollback_after`` consecutive bad steps, then rolls back once.
        """
        n_nan = self.count(FaultKind.GRAD_NAN)
        return {
            "retries": n_nan * rollback_after * max_retries,
            "rollbacks": n_nan,
            "restarts": self.count(FaultKind.PREEMPT),
            "preemptions": self.count(FaultKind.PREEMPT),
            "dropped_batches": self.count(FaultKind.DATA_LOSS),
            "stalls": self.count(FaultKind.STALL),
        }

    def predict_fleet(self, vote_k: int = 0, vote_outvote_limit: int = 2,
                      horizon: Optional[int] = None,
                      cooloff_ticks: Optional[int] = None,
                      autoscale: bool = False,
                      quota_tokens: Optional[float] = None,
                      flood_request_tokens: Optional[int] = None,
                      preempt_inflight: Optional[int] = None
                      ) -> Dict[str, int]:
        """Expected ``ServingFleet`` recovery counts for this plan's
        REPLICA_* events (the serving mirror of :meth:`predict`).
        ``vote_k``/``vote_outvote_limit`` mirror the drill's
        ``FleetConfig`` verdict-voting knobs (0 = voting off).

        Valid when events are *isolated* — at most one fleet fault per
        replica, each given room to complete its recovery arc: a STALL's
        severity (ticks) exceeds the fleet's heartbeat-miss limit, a
        poisoned replica retires at least ``flag_min_count`` requests
        while poisoned, and the drill runs long enough for every drain
        to complete — but ENDS before any quarantined replica's
        cool-off expires (or the poison is healed first): an unhealed
        replica re-trips on every readmission probe by design, adding a
        drain + quarantine per probe beyond the first.  Drills pin
        ``quarantine_cooloff_ticks`` past their horizon — pass
        ``horizon`` (the drill's tick budget) and ``cooloff_ticks``
        (the config's first cool-off) and this method ENFORCES the
        bound, raising instead of silently producing counts the probe
        churn would falsify.  Under those conditions each event's
        recovery arc is exact:

        * CRASH  → 1 failover episode (everything the replica held
          migrates at once) + 1 restart;
        * STALL  → 1 drain (heartbeat trips) + 1 failover episode;
        * POISON → 1 suspicion episode + 1 drain (monitor flag-rate
          crosses the quarantine threshold) + 1 quarantine (the
          suspicion EWMA crosses on the way to the trip — valid at the
          fleet defaults, where ``suspicion_threshold`` <= the EWMA of
          ``flag_min_count`` consecutive flags);
        * SLOWSTART → 1 slow-start warmup (goodput only — no failover,
          drain or quarantine);
        * ADAPTIVE_POISON → 1 suspicion episode always; with
          ``vote_k >= 2``: exactly ``vote_outvote_limit`` verdict votes
          (sequential per suspect, every one outvoted — the attacker
          corrupts every stream while active) then 1 drain +
          1 quarantine; with ``vote_k == 0`` NOTHING else — the
          sub-threshold attacker is the ladder's documented blind spot.
          Additional validity: >= ``vote_k`` other replicas stay
          admitting and the suspect keeps retiring requests until the
          outvote limit lands.  ``vote_k == 1`` is rejected: a lone
          voter can never outvote anyone (majority needs two agreeing
          dissenters), so vote counts are traffic-bound, not pinnable.
        * TENANT_FLOOD → 1 tenant_flood; with a token bucket
          (``quota_tokens`` = the flooding tenant's bucket capacity,
          ``flood_request_tokens`` = the fleet's per-flood-request cost
          ``flood_prompt_len + flood_new_tokens``) each event throttles
          exactly ``severity - quota_tokens // flood_request_tokens``
          submissions (floored at 0).  Valid when flood events are
          *isolated*: the bucket sits at capacity when each fires
          (events spaced >= capacity / refill ticks apart) and no other
          traffic spends the flooding tenant's bucket.  With
          ``autoscale=True`` each flood additionally trips exactly ONE
          scale-up and ONE scale-down — valid when the admitted burst
          crosses the scale-up predicate (and the background traffic
          never does), ``max_replicas - min_replicas`` equals the flood
          count (the bound absorbs repeat pressure), and the run idles
          past the drain + ``scale_down_idle_ticks`` + cool-down so
          every extra replica retires back to the floor.  Scale-downs
          drain, so they are COUNTED in ``drains`` too.
        * ADAPTER_POISON → 1 adapter_poison + 1 adapter_quarantine (the
          fleet-wide per-ADAPTER flag window trips once the adapter
          retires ``flag_min_count`` requests while poisoned) — and
          NOTHING on the replica side: zero drains, zero replica
          quarantines, zero suspicions.  The replicas hosting the
          poisoned page stay HEALTHY by design; the quarantine lands on
          the artifact.  Valid when at least ``flag_min_count``
          adapter-attributed requests retire after the event and the
          adapter is not released before the drill ends.
        * REPLICA_PREEMPT → 1 preempt + 1 restart, and with
          ``preempt_inflight`` (the number of LIVE in-flight requests
          each preempted replica holds when its event fires) exactly
          ``preempt_inflight`` live KV migrations per event — the
          ``migrations`` key is emitted ONLY when the caller pins that
          number, since it is traffic-determined.  Valid when every
          migration finds a destination (surviving admitting capacity
          with pool headroom for every block table) — then the arc is
          a block copy, not a recovery: zero failover episodes, zero
          drains, zero lost accepted requests.
        """
        if vote_k == 1:
            raise ValueError(
                "vote_k=1 is not predictable (a lone voter can never "
                "outvote — votes recur per suspect retirement); use "
                "vote_k >= 2 for verdict quarantines or 0 for off"
            )
        crashes = self.count(FaultKind.REPLICA_CRASH)
        preempts = self.count(FaultKind.REPLICA_PREEMPT)
        stalls = self.count(FaultKind.REPLICA_STALL)
        poisons = self.count(FaultKind.REPLICA_POISON)
        adaptive = self.count(FaultKind.REPLICA_ADAPTIVE_POISON)
        if horizon is not None and cooloff_ticks is not None:
            for event in self.events:
                if event.kind not in (FaultKind.REPLICA_POISON,
                                      FaultKind.REPLICA_ADAPTIVE_POISON):
                    continue
                # Conservative earliest quarantine = the event's own
                # tick; if even that cool-off expires inside the
                # horizon, the readmission probe of a still-poisoned
                # replica re-trips and every pinned count below is
                # wrong.  Loud, not silently off-by-a-probe.
                if event.step + cooloff_ticks < horizon:
                    raise ValueError(
                        f"predict_fleet validity bound: {event.kind.value}"
                        f" at tick {event.step} with cooloff_ticks="
                        f"{cooloff_ticks} expires at tick "
                        f"{event.step + cooloff_ticks}, inside the "
                        f"horizon {horizon} — the readmission probe "
                        "re-trips and adds a drain + quarantine per "
                        "probe; pin quarantine_cooloff_ticks past the "
                        "drill or heal the replica first"
                    )
        caught = adaptive if vote_k >= 2 else 0
        floods = self.of_kind(FaultKind.TENANT_FLOOD)
        throttles = 0
        if quota_tokens is not None:
            if not flood_request_tokens or flood_request_tokens < 1:
                raise ValueError(
                    "quota_tokens needs flood_request_tokens (the "
                    "fleet's flood_prompt_len + flood_new_tokens) to "
                    "pin throttle counts"
                )
            per_event = int(quota_tokens) // int(flood_request_tokens)
            for event in floods:
                # Same floor as the fleet's _run_flood: a sub-1
                # severity still bursts one request.
                n = max(int(event.severity), 1)
                throttles += max(0, n - per_event)
        scale_events = len(floods) if autoscale else 0
        counts = {
            "crashes": crashes,
            "preempts": preempts,
            "restarts": crashes + preempts,
            "stalls": stalls,
            "poisons": poisons,
            "adaptive_poisons": adaptive,
            "slowstarts": self.count(FaultKind.REPLICA_SLOWSTART),
            "failover_episodes": crashes + stalls,
            "suspicions": poisons + adaptive,
            "votes": caught * vote_outvote_limit,
            "outvotes": caught * vote_outvote_limit,
            "drains": stalls + poisons + caught + scale_events,
            "quarantines": poisons + caught,
            "tenant_floods": len(floods),
            "throttles": throttles,
            "scale_ups": scale_events,
            "scale_downs": scale_events,
            "adapter_poisons": self.count(FaultKind.ADAPTER_POISON),
            "adapter_quarantines": self.count(FaultKind.ADAPTER_POISON),
            "adapter_throttles": 0,
        }
        if preempt_inflight is not None:
            counts["migrations"] = preempts * int(preempt_inflight)
        return counts

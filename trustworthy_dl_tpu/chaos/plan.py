"""Deterministic fault plans — the chaos counterpart of
``attacks.adversarial.AttackPlan``.

An ``AttackPlan`` schedules *adversarial* behaviour (a node lying about its
gradients); a ``FaultPlan`` schedules *infrastructure* failure: non-finite
gradients from corrupted state, wedged hosts, preemptions, truncated or
bit-rotten checkpoint shards, data-iterator failures, and poisoned serving
replicas.  Production recovery machinery is only trustworthy if it is
continuously exercised (Gemini's in-memory recovery, SOSP '23; Bamboo,
NSDI '23) — the plan is the exercise schedule, and it is **seeded and
reproducible**: the same ``(seed, horizon, rates)`` always generates the
same events, so a survival drill can assert the *exact* number of retries,
rollbacks and restarts the supervisor should perform (``predict``).

Events are consumed by ``chaos.injector.FaultInjector`` at explicit hook
points in ``DistributedTrainer.train_epoch``, ``CheckpointManager`` and
``serve.ServingEngine``.  Each event fires **once** (the injector tracks
fired events), so a post-rollback replay of the same global steps does not
re-trigger the fault that caused the rollback.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np


class FaultKind(str, enum.Enum):
    """What breaks.  ``step`` semantics per kind are documented on
    ``FaultEvent``."""

    #: Corrupt live parameters with NaN after step ``step`` completes —
    #: every subsequent loss is genuinely non-finite until state is
    #: restored from a checkpoint (the "silently corrupted optimizer
    #: state" failure the supervisor's rollback path exists for).
    GRAD_NAN = "grad_nan"
    #: Host stall / straggler: sleep ``severity`` seconds before the step.
    STALL = "stall"
    #: Simulated preemption signal raised before the step runs — the
    #: supervisor must save-on-signal and auto-resume.
    PREEMPT = "preempt"
    #: Flip bytes in a committed checkpoint's payload (bit-rot): fires on
    #: the first checkpoint committed at global step >= ``step``.
    CKPT_CORRUPT = "ckpt_corrupt"
    #: Die between payload write and COMMIT marker: the first save at
    #: global step >= ``step`` is left uncommitted on disk.
    CKPT_CRASH = "ckpt_crash"
    #: Data-iterator failure: the batch at ``step`` is lost (the loader
    #: "raised"); training must continue on the next batch.
    DATA_LOSS = "data_loss"
    #: Poison a serving slot's output signals for request id ``step`` —
    #: the engine's output monitor must flag and quarantine the slot.
    SERVE_POISON = "serve_poison"


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.  ``step`` is the trainer's *global step* for
    training-side kinds, the minimum save step for checkpoint kinds, and
    the request id for ``SERVE_POISON``.  ``severity`` is kind-specific
    (stall seconds, poison magnitude); unused kinds ignore it."""

    step: int
    kind: FaultKind
    severity: float = 1.0


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Immutable, seeded schedule of ``FaultEvent``s.

    Build with :meth:`generate` (seeded rates over a step horizon) or
    :meth:`scripted` (explicit events, for drills that must predict exact
    recovery counts).  The plan itself is pure; all firing state lives in
    the injector.
    """

    seed: int
    events: Tuple[FaultEvent, ...]

    @classmethod
    def scripted(cls, events: Sequence[FaultEvent], seed: int = 0
                 ) -> "FaultPlan":
        return cls(seed=seed,
                   events=tuple(sorted(events, key=lambda e: e.step)))

    @classmethod
    def generate(cls, seed: int, num_steps: int,
                 rates: Mapping[FaultKind, float],
                 severity: float = 1.0) -> "FaultPlan":
        """Seeded Bernoulli draw per (step, kind): the same arguments
        always produce the same plan, so a drill is reproducible from its
        seed alone.  ``rates`` maps kind -> per-step probability."""
        rng = np.random.default_rng(seed)
        events: List[FaultEvent] = []
        # Fixed kind order (enum declaration order) keeps the draw stream
        # stable across python versions / dict orderings.
        kinds = [k for k in FaultKind if rates.get(k, 0.0) > 0.0]
        for step in range(num_steps):
            for kind in kinds:
                if rng.random() < rates[kind]:
                    events.append(FaultEvent(
                        step=step, kind=kind,
                        severity=float(severity * (0.5 + rng.random())),
                    ))
        return cls(seed=seed, events=tuple(events))

    def of_kind(self, kind: FaultKind) -> List[FaultEvent]:
        return [e for e in self.events if e.kind is kind]

    def count(self, kind: FaultKind) -> int:
        return len(self.of_kind(kind))

    def at(self, step: int, kind: Optional[FaultKind] = None
           ) -> List[FaultEvent]:
        """Events scheduled exactly at ``step`` (optionally one kind)."""
        return [e for e in self.events
                if e.step == step and (kind is None or e.kind is kind)]

    def predict(self, max_retries: int, rollback_after: int
                ) -> Dict[str, int]:
        """Expected supervisor recovery counts for this plan under a
        ``TrainingSupervisor(max_retries=..., rollback_after=...)``.

        Valid when events are *isolated*: GRAD_NAN events spaced further
        apart than the rollback window, and a verified checkpoint existing
        before each (the supervisor writes one at start, so this holds for
        any plan whose first GRAD_NAN is after step 0).  Each GRAD_NAN
        corrupts state persistently, so every retry of a bad step fails:
        the supervisor burns ``max_retries`` retries on each of
        ``rollback_after`` consecutive bad steps, then rolls back once.
        """
        n_nan = self.count(FaultKind.GRAD_NAN)
        return {
            "retries": n_nan * rollback_after * max_retries,
            "rollbacks": n_nan,
            "restarts": self.count(FaultKind.PREEMPT),
            "preemptions": self.count(FaultKind.PREEMPT),
            "dropped_batches": self.count(FaultKind.DATA_LOSS),
            "stalls": self.count(FaultKind.STALL),
        }

"""Adaptive serving-side adversary: the attacker the PR 8 flag-rate
ladder cannot see.

Every serve-side chaos fault so far was FIXED-strength: a
``REPLICA_POISON`` overwrites the retiring request's output signals so
hard the monitor flags every retirement and the flag-rate ladder drains
the replica within ``flag_min_count`` requests.  The paper's actual
threat model is an *adaptive* adversary — one that observes the
defence's public signals and tunes its corruption to stay under them.
This module is that adversary, for serving:

* :class:`AdaptivePoisonAttacker` drives the persistent
  ``REPLICA_ADAPTIVE_POISON`` fault.  At every retirement on the
  compromised replica it (a) corrupts the served TOKEN stream (seeded,
  per-request deterministic — the user-visible damage) and (b) shapes
  the request's entropy/margin signals with a controllable strength.
  Between retirements it observes the replica's PUBLIC flag-rate window
  (the same number the ``tddl_fleet_*`` gauges export — an adversary
  does not need inside access to read a Prometheus endpoint) and walks
  strength up while the rate is comfortable, multiplicatively backing
  off as it approaches ``flag_rate_quarantine``: the rate is *held just
  below the ladder's trip point*, indefinitely.
* The controller is a pure function of its observations
  (:func:`predict_attacker_trajectory` replays it exactly), so drills
  pin the attacker's full strength trajectory and final strength the
  same way ``FaultPlan.predict_fleet`` pins recovery counts.
* ``mode="prompt_drift"`` is the prompt-level variant: instead of a
  flat signal shift it injects a jailbreak-shaped profile — entropy
  collapsing progressively along the stream while margin inflates (a
  generation drifting into a low-entropy loop) — and corrupts the tail
  of the stream into a repetition loop.

What catches it: the cross-replica verdict-voting tier in
``serve/fleet.py``.  Token corruption cannot be tuned away — a
corrupted stream disagrees with its bit-identical replay on ANY clean
replica, whatever the monitor says — so a sub-threshold attacker is
caught by *disagreement* rather than flag rate.  The serve-side
detection envelope (``experiments/serve_envelope.py``) measures exactly
where each tier's boundary lies.
"""

from __future__ import annotations

import dataclasses
import logging
from collections import deque
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

ADVERSARY_MODES = ("signal", "prompt_drift")


@dataclasses.dataclass(frozen=True)
class AdversaryConfig:
    """Knobs of the adaptive attacker.  Everything is seeded and
    deterministic: the same config against the same flag observations
    produces the same corruption, byte for byte."""

    #: Replica index the attacker compromises (must match the
    #: ``REPLICA_ADAPTIVE_POISON`` event's ``target``).
    target: int
    seed: int = 0
    #: The defence threshold the attacker reverse-engineered
    #: (``FleetConfig.flag_rate_quarantine``).  Public knowledge: it is
    #: in this repo's README.
    flag_rate_quarantine: float = 0.25
    #: Hold the observed rate at least this far below the threshold.
    safety_margin: float = 0.05
    #: Corruption strength walk: start, additive step up while the rate
    #: is comfortable, multiplicative backoff when it approaches the
    #: danger zone, clamped to [min_strength, max_strength].  Pin
    #: ``min == max == initial`` (or ``step_up=0, backoff=1``) for a
    #: FIXED-strength attacker (the serve-envelope sweep does).
    initial_strength: float = 0.3
    step_up: float = 0.1
    backoff: float = 0.5
    min_strength: float = 0.05
    max_strength: float = 1.0
    #: Token corruption: fraction of the stream corrupted at full
    #: strength (always at least one token while active — the
    #: disagreement voting detects cannot be tuned to zero without
    #: giving up the attack entirely).
    corrupt_fraction: float = 0.25
    #: Signal shaping: margin shift per unit strength.  ``signal_jitter``
    #: adds a seeded per-request uniform factor in
    #: ``[1 - jitter, 1 + jitter]`` so flag probability varies smoothly
    #: with strength (the envelope sweep uses it; keep 0.0 when pinning
    #: the controller trajectory with a deterministic flag function).
    signal_scale: float = 40.0
    signal_jitter: float = 0.0
    #: "signal" = flat entropy-collapse/margin-shift; "prompt_drift" =
    #: jailbreak-shaped progressive drift + repetition-loop tokens.
    mode: str = "signal"
    #: Token ids wrap modulo this when corrupting (None = bit-flip the
    #: low bit, which stays in-vocab for any vocab >= 2 power-of-two
    #: neighbourhood; pass the real vocab for in-distribution garbage).
    vocab_size: Optional[int] = None

    def __post_init__(self) -> None:
        if self.mode not in ADVERSARY_MODES:
            raise ValueError(f"mode must be one of {ADVERSARY_MODES}")
        if not 0.0 < self.flag_rate_quarantine <= 1.0:
            raise ValueError("flag_rate_quarantine must be in (0, 1]")
        if self.safety_margin <= 0.0:
            raise ValueError("safety_margin must be > 0")
        if not 0.0 < self.min_strength <= self.max_strength:
            raise ValueError("need 0 < min_strength <= max_strength")
        if not (self.min_strength <= self.initial_strength
                <= self.max_strength):
            raise ValueError("initial_strength outside [min, max]")
        if self.step_up < 0.0 or not 0.0 < self.backoff <= 1.0:
            raise ValueError("step_up must be >= 0 and backoff in (0, 1]")
        if not 0.0 < self.corrupt_fraction <= 1.0:
            raise ValueError("corrupt_fraction must be in (0, 1]")
        if not 0.0 <= self.signal_jitter <= 1.0:
            raise ValueError("signal_jitter must be in [0, 1]")


def _controller_step(cfg: AdversaryConfig, strength: float,
                     flag_rate: float) -> float:
    """ONE spelling of the strength controller, shared by the live
    attacker and :func:`predict_attacker_trajectory` so the pinned
    trajectory is the executed one by construction.  Hold band:
    ``[danger - safety_margin, danger)`` with
    ``danger = flag_rate_quarantine - safety_margin``."""
    danger = cfg.flag_rate_quarantine - cfg.safety_margin
    if flag_rate >= danger:
        return max(strength * cfg.backoff, cfg.min_strength)
    if flag_rate < danger - cfg.safety_margin:
        return min(strength + cfg.step_up, cfg.max_strength)
    return strength


def predict_attacker_trajectory(cfg: AdversaryConfig,
                                flags: Sequence[bool],
                                flag_window: int) -> List[float]:
    """Replay the controller against an observed (or modelled) flag
    sequence: returns the strength after each observation, starting at
    ``initial_strength`` (``len(flags) + 1`` entries) — the serving
    mirror of ``FaultPlan.predict_fleet``'s pinned counts.

    Valid when the target replica's retirements are SERIAL with respect
    to the controller (at most one monitor-scored retirement between
    consecutive observations — the fleet feeds the attacker once per
    slot-side retirement, so this holds whenever the drill's requests
    retire on distinct ticks), the target's flag WINDOW is clean at
    activation and never reset mid-attack (the replayed deque here
    starts empty, so pre-attack retirements in the live window — an
    adaptive event scheduled into an already-serving replica — would
    shift every replayed rate), and ``signal_jitter == 0`` if ``flags``
    came from a strength-threshold model rather than a recording."""
    window: deque = deque(maxlen=flag_window)
    strength = cfg.initial_strength
    out = [strength]
    for flagged in flags:
        window.append(1 if flagged else 0)
        rate = sum(window) / len(window)
        strength = _controller_step(cfg, strength, rate)
        out.append(strength)
    return out


class AdaptivePoisonAttacker:
    """The live adversary.  ``FaultInjector(adversary=...)`` owns one
    instance; a fired ``REPLICA_ADAPTIVE_POISON`` event activates it and
    routes every retirement on the target replica through
    :meth:`corrupt`; the fleet feeds the public flag-rate window back
    through :meth:`observe` (via ``FaultInjector.on_flag_observed``)."""

    def __init__(self, config: AdversaryConfig):
        self.config = config
        self.strength = config.initial_strength
        #: Strength after construction and after each observation —
        #: asserted equal to :func:`predict_attacker_trajectory` in the
        #: drill (the trajectory pin).
        self.strength_history: List[float] = [self.strength]
        #: (flagged, public flag_rate) per observation.
        self.flag_observations: List[Tuple[bool, float]] = []
        #: Request ids whose token stream was corrupted.
        self.corrupted_ids: List[int] = []
        self.active = False

    # -- lifecycle (driven by the injector) --------------------------------

    def activate(self) -> None:
        self.active = True

    def deactivate(self) -> None:
        """``heal_replica``: the operator patched the compromise."""
        self.active = False

    # -- the observe/act loop ----------------------------------------------

    def observe(self, flagged: bool, flag_rate: float) -> None:
        """One public-signal observation (the replica's flag-rate window
        after a monitor-scored retirement) → one controller step."""
        if not self.active:
            return
        self.flag_observations.append((bool(flagged), float(flag_rate)))
        self.strength = _controller_step(self.config, self.strength,
                                         float(flag_rate))
        self.strength_history.append(self.strength)

    def corrupt(self, task: Any) -> None:
        """Corrupt one retiring request in place: tokens (always — the
        attack's payload) and monitor signals (strength-scaled — the
        evasion).  Deterministic per (seed, request_id)."""
        if not self.active:
            return
        cfg = self.config
        rng = np.random.default_rng([cfg.seed, int(task.request_id)])
        emitted = task.emitted
        if emitted:
            n = max(1, int(np.ceil(self.strength * cfg.corrupt_fraction
                                   * len(emitted))))
            n = min(n, len(emitted))
            if cfg.mode == "prompt_drift":
                # Repetition loop: the stream's tail locks onto one
                # off-by-one token — the low-entropy looping shape a
                # jailbroken/drifted generation shows.
                loop_tok = self._shift(emitted[-1], 1)
                for i in range(len(emitted) - n, len(emitted)):
                    emitted[i] = loop_tok
            else:
                positions = rng.choice(len(emitted), size=n, replace=False)
                for pos in positions:
                    emitted[int(pos)] = self._shift(
                        emitted[int(pos)], 1 + int(rng.integers(5)))
            self.corrupted_ids.append(int(task.request_id))
        n_sig = len(task.entropies)
        if n_sig == 0:
            return
        jitter = 1.0
        if cfg.signal_jitter > 0.0:
            jitter = 1.0 + cfg.signal_jitter * (2.0 * rng.random() - 1.0)
        shift = self.strength * cfg.signal_scale * jitter
        if cfg.mode == "prompt_drift":
            # Progressive drift: clean start, collapsed-entropy/
            # inflated-margin finish.
            ramp = np.linspace(0.0, 1.0, n_sig)
            task.entropies[:] = [
                e * (1.0 - self.strength * r)
                for e, r in zip(task.entropies, ramp)
            ]
            task.margins[:] = [m + shift * r
                               for m, r in zip(task.margins, ramp)]
        else:
            task.entropies[:] = [e * max(1.0 - self.strength, 0.1)
                                 for e in task.entropies]
            task.margins[:] = [m + shift for m in task.margins]

    def _shift(self, token: int, delta: int) -> int:
        vocab = self.config.vocab_size
        if vocab is not None and vocab > 1:
            shifted = (int(token) + delta) % vocab
            return shifted if shifted != token else (shifted + 1) % vocab
        return int(token) ^ 1


class MarginSignatureMonitor:
    """Deterministic output monitor for drills/bench/envelope cells:
    flags iff the request's mean top-1 margin exceeds ``threshold``.

    The real :class:`~trustworthy_dl_tpu.serve.engine.OutputMonitor`
    z-scores against a rolling baseline — correct in production, but its
    verdicts depend on warmup history, which an exact-count drill cannot
    pin.  Against the adversary's margin-shift corruption this signature
    monitor's verdict is a pure function of attacker strength (at
    ``signal_jitter == 0``): ``flagged ⇔ strength * signal_scale + base
    > threshold`` — exactly the deterministic flag function
    :func:`predict_attacker_trajectory` needs."""

    def __init__(self, threshold: float):
        self.threshold = float(threshold)

    def observe(self, entropies: Sequence[float],
                margins: Sequence[float]) -> Tuple[bool, float]:
        score = float(np.mean(margins)) if len(margins) else 0.0
        return score > self.threshold, score

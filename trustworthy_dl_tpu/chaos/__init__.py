"""Chaos subsystem: seeded, deterministic fault injection.

Recovery machinery that is never exercised is decorative — this package
makes the failure modes (non-finite state, stalls, preemptions, corrupt or
uncommitted checkpoints, lost batches, poisoned serving replicas) a
reproducible, scriptable schedule that drills the self-healing loop in
``engine/supervisor.py`` end to end.  See ``examples/chaos_drill.py``.
"""

from trustworthy_dl_tpu.chaos.adversary import (
    AdaptivePoisonAttacker,
    AdversaryConfig,
    MarginSignatureMonitor,
    predict_attacker_trajectory,
)
from trustworthy_dl_tpu.chaos.injector import (
    FaultInjector,
    SimulatedPreemption,
    corrupt_file,
)
from trustworthy_dl_tpu.chaos.plan import (
    FLEET_KINDS,
    FaultEvent,
    FaultKind,
    FaultPlan,
)

__all__ = [
    "FLEET_KINDS",
    "AdaptivePoisonAttacker",
    "AdversaryConfig",
    "FaultEvent",
    "FaultInjector",
    "FaultKind",
    "FaultPlan",
    "MarginSignatureMonitor",
    "SimulatedPreemption",
    "corrupt_file",
    "predict_attacker_trajectory",
]

"""Fault injector — executes a ``FaultPlan`` at explicit hook points.

Hook points (all host-side, none on the jitted hot path):

* ``DistributedTrainer.train_epoch``: ``on_batch`` (data-iterator
  failures), ``on_step_start`` (stalls, preemption signals),
  ``on_step_end`` (state corruption → genuinely non-finite losses);
* ``CheckpointManager``: ``on_checkpoint_commit`` (crash-before-COMMIT),
  ``on_checkpoint_saved`` (post-commit shard bit-rot);
* ``serve.ServingEngine``: ``on_serve_retire`` (poisoned replica output).

The injector is the *stateful* half of the chaos subsystem: each event
fires exactly once (``fired``), so when the supervisor rolls global steps
back past an already-fired event, the replayed steps run clean — recovery
converges instead of looping.  ``counts()`` reports fired events by kind
for the supervisor's survival report.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from trustworthy_dl_tpu.chaos.plan import (
    FLEET_KINDS,
    FaultEvent,
    FaultKind,
    FaultPlan,
)

logger = logging.getLogger(__name__)


class SimulatedPreemption(Exception):
    """Raised at a PREEMPT event's hook point — stands in for the
    SIGTERM/maintenance notice a real preemptible host receives.  The
    supervisor catches it, checkpoints, and auto-resumes."""


class FaultInjector:
    """Consumes a :class:`FaultPlan`; one instance per run.

    ``sleep_fn`` is injectable so tests exercise STALL events without
    real wall-clock cost.
    """

    def __init__(self, plan: FaultPlan,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 max_stall_s: float = 30.0, adversary: Any = None):
        self.plan = plan
        self._sleep = sleep_fn
        self._max_stall_s = max_stall_s
        self.fired: List[FaultEvent] = []
        # Optional obs TraceBus (obs/events.py): every fired fault is
        # emitted as a ``chaos_fault`` event, so a flight-recorder dump
        # can be diffed against ``FaultPlan.predict`` counts.
        self.trace: Any = None
        # Replicas with an ACTIVE REPLICA_POISON: the event fires once
        # (at its fleet tick), but the compromise persists — every
        # request retiring on the replica is poisoned until
        # :meth:`heal_replica` (so a readmission probe of a
        # still-compromised replica fails again, as it must).
        self._poisoned_replicas: Dict[int, float] = {}
        # The adaptive counterpart: a chaos.adversary.AdaptivePoisonAttacker
        # activated by a REPLICA_ADAPTIVE_POISON event.  It owns the
        # corruption (tokens + strength-scaled signals) and the
        # strength controller; the fleet feeds the replica's public
        # flag-rate window back through :meth:`on_flag_observed`.
        self.adversary = adversary
        self._adaptive_replicas: Dict[int, Any] = {}
        # Adapters with an ACTIVE ADAPTER_POISON (adapter id -> poison
        # severity): artifact-addressed like TENANT_FLOOD, replica-blind
        # by design — every request retiring UNDER the adapter, on ANY
        # replica, is poisoned until :meth:`heal_adapter`.
        self._poisoned_adapters: Dict[str, float] = {}

    # -- bookkeeping -------------------------------------------------------

    def _fire(self, event: FaultEvent, at_step: int) -> FaultEvent:
        self.fired.append(event)
        if self.trace is not None:
            from trustworthy_dl_tpu.obs.events import EventType

            self.trace.emit(EventType.CHAOS_FAULT, step=at_step,
                            kind=event.kind.value,
                            scheduled_step=event.step,
                            severity=event.severity,
                            target=(event.target if event.target >= 0
                                    else None),
                            **({"tenant": event.tenant}
                               if event.tenant else {}))
        return event

    def _take_at(self, step: int, kind: FaultKind) -> Optional[FaultEvent]:
        """Fire-once event scheduled exactly at ``step``."""
        for event in self.plan.at(step, kind):
            if event not in self.fired:
                return self._fire(event, step)
        return None

    def _take_due(self, step: int, kind: FaultKind) -> Optional[FaultEvent]:
        """Fire-once event whose schedule step has been reached (for
        checkpoint kinds, which fire on the first save at/after it)."""
        for event in self.plan.of_kind(kind):
            if event.step <= step and event not in self.fired:
                return self._fire(event, step)
        return None

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for event in self.fired:
            out[event.kind.value] = out.get(event.kind.value, 0) + 1
        return out

    # -- trainer hooks -----------------------------------------------------

    def on_batch(self, step: int, batch: Any) -> Optional[Any]:
        """DATA_LOSS: the batch for this step is lost (simulated iterator
        failure) — return None and the trainer skips it, exactly like its
        stale-batch path."""
        if self._take_at(step, FaultKind.DATA_LOSS) is not None:
            logger.warning("chaos: data-iterator failure at step %d "
                           "(batch dropped)", step)
            return None
        return batch

    def on_step_start(self, step: int) -> None:
        """STALL: block the host like a straggling/wedged peer.
        PREEMPT: raise the simulated preemption signal."""
        stall = self._take_at(step, FaultKind.STALL)
        if stall is not None:
            seconds = min(float(stall.severity), self._max_stall_s)
            logger.warning("chaos: host stall %.2fs at step %d",
                           seconds, step)
            self._sleep(seconds)
        if self._take_at(step, FaultKind.PREEMPT) is not None:
            logger.warning("chaos: simulated preemption at step %d", step)
            raise SimulatedPreemption(f"preempted at step {step}")

    def on_step_end(self, step: int, state: Any, metrics: Any
                    ) -> Tuple[Any, Any]:
        """GRAD_NAN: corrupt the live parameters with NaN AFTER this step
        commits — from the next step on, every node's loss and gradients
        are genuinely non-finite (the in-step gate freezes the params, so
        without a rollback the run is wedged forever: exactly the failure
        the supervisor's verified-checkpoint rollback exists for)."""
        if self._take_at(step, FaultKind.GRAD_NAN) is not None:
            logger.warning("chaos: corrupting params with NaN after "
                           "step %d", step)
            state = state._replace(params=_corrupt_largest_leaf(state.params))
        return state, metrics

    # -- checkpoint hooks --------------------------------------------------

    def on_checkpoint_commit(self, step: int) -> bool:
        """CKPT_CRASH: return False to simulate dying between the payload
        write and the COMMIT marker — the save stays uncommitted and
        restore/latest_step must walk past it."""
        if self._take_due(step, FaultKind.CKPT_CRASH) is not None:
            logger.warning("chaos: simulated crash before COMMIT of "
                           "checkpoint step %d", step)
            return False
        return True

    def on_checkpoint_saved(self, step: int, path: str) -> None:
        """CKPT_CORRUPT: flip bytes in the committed payload's largest
        file (bit-rot after a clean commit) — the manifest's checksums no
        longer match, so a later restore must detect it and fall back."""
        if self._take_due(step, FaultKind.CKPT_CORRUPT) is None:
            return
        target = _largest_file(path)
        if target is None:
            logger.warning("chaos: no payload file to corrupt in %s", path)
            return
        logger.warning("chaos: corrupting checkpoint shard %s (step %d)",
                       target, step)
        corrupt_file(target)

    # -- serving hooks -----------------------------------------------------

    def _poison_signals(self, task: Any, severity: float) -> None:
        n = max(len(task.entropies), 1)
        task.entropies[:] = [0.0] * n
        task.margins[:] = [1e3 * float(severity)] * n

    def on_serve_retire(self, task: Any,
                        replica: Optional[int] = None) -> None:
        """SERVE_POISON: overwrite the retiring request's output signals
        with a collapsed-entropy / inflated-margin profile (a poisoned
        replica looping on one token) so the engine's output monitor must
        flag it and quarantine the slot it ran on.

        ``replica`` is the retiring engine's ``replica_id`` (None for a
        standalone engine).  Request ids are replica-LOCAL in a fleet, so
        a replica-addressed event (``target >= 0``) only fires when the
        target matches — a poison aimed at replica 1's request 3 must
        never fire on replica 0's request 3.  An active REPLICA_POISON
        on this replica poisons EVERY retirement (the fired-once event
        is the onset; the compromise persists until healed); an active
        REPLICA_ADAPTIVE_POISON delegates every retirement to the
        attached adversary (seeded token corruption + strength-scaled
        signal shaping).  An active ADAPTER_POISON matching the task's
        adapter outranks ALL replica-scoped compromises — checked FIRST,
        because the drill's exactness depends on the flag landing in the
        per-ADAPTER window (the attribution record carries the adapter
        id) regardless of which replica happened to host the page."""
        if self._poisoned_adapters:
            adapter = getattr(task, "adapter", None)
            sev = (self._poisoned_adapters.get(adapter)
                   if adapter is not None else None)
            if sev is not None:
                self._poison_signals(task, sev)
                return
        adv = self._adaptive_replicas.get(-1 if replica is None else replica)
        if adv is not None:
            adv.corrupt(task)
            return
        rep = self._poisoned_replicas.get(-1 if replica is None else replica)
        if rep is not None:
            self._poison_signals(task, rep)
            return
        for event in self.plan.at(int(task.request_id),
                                  FaultKind.SERVE_POISON):
            if event in self.fired:
                continue
            if event.target >= 0 and event.target != replica:
                continue
            self._fire(event, int(task.request_id))
            logger.warning("chaos: poisoning serve output of request %d"
                           "%s", task.request_id,
                           "" if replica is None
                           else f" on replica {replica}")
            self._poison_signals(task, event.severity)
            return

    # -- fleet hooks -------------------------------------------------------

    def on_fleet_tick(self, tick: int) -> List[FaultEvent]:
        """Fire every fleet-granularity event scheduled at/before this
        tick (fire-once each) and return them — the ``ServingFleet``
        applies the mechanics (kill/skip/warmup); the injector only
        keeps the persistent replica-poison state."""
        out: List[FaultEvent] = []
        for kind in FLEET_KINDS:
            for event in self.plan.of_kind(kind):
                if event.step <= tick and event not in self.fired:
                    self._fire(event, tick)
                    if kind is FaultKind.TENANT_FLOOD:
                        # Overload fault: the FLEET runs the burst
                        # through its admission path (token buckets
                        # throttle, classes schedule, the autoscaler
                        # reacts) — the injector only schedules it.
                        logger.warning(
                            "chaos: tenant flood (%d requests from %r) "
                            "at tick %d", max(int(event.severity), 1),
                            event.tenant or "flood", tick)
                        out.append(event)
                        continue
                    if kind is FaultKind.ADAPTER_POISON:
                        # Artifact-addressed: the adapter id rides the
                        # event's ``tenant`` field; the injector arms
                        # the persistent per-adapter compromise and the
                        # fleet only counts the onset.
                        name = event.tenant or "adapter"
                        logger.warning(
                            "chaos: adapter poison on %r at tick %d",
                            name, tick)
                        self._poisoned_adapters[name] = \
                            float(event.severity)
                        out.append(event)
                        continue
                    logger.warning("chaos: %s on replica %d at tick %d",
                                   kind.value, event.target, tick)
                    if kind is FaultKind.REPLICA_POISON:
                        self._poisoned_replicas[event.target] = \
                            float(event.severity)
                    elif kind is FaultKind.REPLICA_ADAPTIVE_POISON:
                        # Loud: an adaptive event with no (or a
                        # mis-targeted) attacker attached would silently
                        # degrade into "no fault at all" — the opposite
                        # of a drill.
                        if self.adversary is None:
                            raise ValueError(
                                "REPLICA_ADAPTIVE_POISON fired but no "
                                "adversary is attached — build the "
                                "injector with FaultInjector(plan, "
                                "adversary=AdaptivePoisonAttacker(...))"
                            )
                        if self.adversary.config.target != event.target:
                            raise ValueError(
                                f"REPLICA_ADAPTIVE_POISON targets replica "
                                f"{event.target} but the attached "
                                f"adversary is configured for replica "
                                f"{self.adversary.config.target}"
                            )
                        self._adaptive_replicas[event.target] = \
                            self.adversary
                        self.adversary.activate()
                    out.append(event)
        return out

    def on_flag_observed(self, replica: int, flagged: bool,
                         flag_rate: float) -> None:
        """Fleet feedback hook: the target replica's PUBLIC flag-rate
        window after a monitor-scored retirement (the number the
        ``tddl_fleet_suspicion``/flag gauges export — adversary-visible
        by construction).  Drives the adaptive attacker's strength
        controller; a no-op without an active adaptive compromise."""
        adv = self._adaptive_replicas.get(replica)
        if adv is not None:
            adv.observe(flagged, flag_rate)

    def heal_replica(self, replica: int) -> None:
        """Operator action: clear an active REPLICA_POISON or
        REPLICA_ADAPTIVE_POISON (until then a readmitted replica is
        immediately re-flagged/re-outvoted)."""
        self._poisoned_replicas.pop(replica, None)
        adv = self._adaptive_replicas.pop(replica, None)
        if adv is not None:
            adv.deactivate()

    def replica_poisoned(self, replica: int) -> bool:
        return (replica in self._poisoned_replicas
                or replica in self._adaptive_replicas)

    def heal_adapter(self, adapter: str) -> None:
        """Operator action: clear an active ADAPTER_POISON (until then a
        readmitted adapter is immediately re-flagged — the fleet's
        ``release_adapter_quarantine`` of a still-poisoned adapter must
        re-trip, exactly like a replica readmission probe)."""
        self._poisoned_adapters.pop(adapter, None)

    def adapter_poisoned(self, adapter: str) -> bool:
        return adapter in self._poisoned_adapters


def _corrupt_largest_leaf(params: Any) -> Any:
    """NaN-out the largest parameter leaf (for transformer LMs that is the
    tied embedding/LM-head matrix, guaranteed on the compute path).
    Multiplication preserves the leaf's sharding/placement, so the
    corrupted state feeds back into the jitted step without a relayout."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(params)
    idx = int(np.argmax([int(np.prod(np.shape(l))) for l in leaves]))
    leaves[idx] = leaves[idx] * jnp.asarray(
        float("nan"), dtype=leaves[idx].dtype
    )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _largest_file(root: str) -> Optional[str]:
    best, best_size = None, -1
    for dirpath, _, names in os.walk(root):
        for name in names:
            p = os.path.join(dirpath, name)
            size = os.path.getsize(p)
            if size > best_size:
                best, best_size = p, size
    return best


def corrupt_file(path: str, offset: int = 0, nbytes: int = 64) -> None:
    """Flip bytes in-place (XOR 0xFF) — deterministic, size-preserving
    corruption a checksum catches but a directory listing does not."""
    size = os.path.getsize(path)
    if size == 0:
        with open(path, "wb") as f:
            f.write(b"\xff")
        return
    offset = min(offset, size - 1)
    nbytes = min(nbytes, size - offset)
    with open(path, "r+b") as f:
        f.seek(offset)
        chunk = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))

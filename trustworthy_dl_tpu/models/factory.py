"""ModelFactory — the implied ``models.model_factory`` module
(imported at distributed_trainer.py:24, used at :118-119).

``create_model(name)`` returns a ``ModelBundle``: a functional model record
(init / apply / loss over explicit param pytrees) instead of the reference's
nn.Module.  The reference's only structural requirement is that GPT models
expose a sliceable block list (``model.transformer.h``,
distributed_trainer.py:126); the bundle generalises that to ``num_blocks`` +
``block_slice`` for every family, so the pipeline partitioner can split
ResNets and VGGs too (the reference's ResNet branch was an empty ``pass``,
distributed_trainer.py:137-140).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import gpt2, resnet, vgg

Params = Dict[str, Any]


@dataclasses.dataclass
class ModelBundle:
    """A model as data: pure functions + metadata."""

    name: str
    kind: str                     # "lm" | "vision"
    config: Any
    init: Callable[[jax.Array], Params]
    apply: Callable[[Params, jax.Array], jax.Array]
    loss: Callable[[Params, Dict[str, jax.Array]], jax.Array]
    num_blocks: int               # partitionable depth (`transformer.h` parity)
    input_spec: Dict[str, Any]    # shape/dtype template for example batches
    # Optional hot-path variant: (params, x) -> (logits, features,
    # mean_logits) where `features` are the boundary activations the
    # detector monitors (cheaper than logits for LMs) and `mean_logits` the
    # class-distribution signature for Byzantine/backdoor consensus.
    # None -> the engine falls back to deriving all three from `apply`.
    apply_monitor: Optional[Callable[
        [Params, jax.Array], "tuple[jax.Array, jax.Array, jax.Array]"
    ]] = None
    # Loss-bearing monitor variant: (params, batch) -> (loss, features,
    # mean_logits).  When present the engine's hot path uses it instead of
    # apply_monitor + cross_entropy — required for the vocab-chunked fused
    # head (ops/fused_ce.py), where the logits never exist to hand back.
    loss_monitor: Optional[Callable[
        [Params, Dict[str, jax.Array]],
        "tuple[jax.Array, jax.Array, jax.Array]"
    ]] = None

    def example_batch(self, batch_size: int, rng: Optional[jax.Array] = None
                      ) -> Dict[str, jax.Array]:
        """Deterministic dummy batch matching the model's input contract."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(rng)
        if self.kind == "lm":
            seq_len = self.input_spec["seq_len"]
            vocab = self.input_spec["vocab_size"]
            tokens = jax.random.randint(k1, (batch_size, seq_len + 1), 0, vocab)
            return {"input": tokens[:, :-1], "target": tokens[:, 1:]}
        h, w, c = self.input_spec["image_shape"]
        return {
            "input": jax.random.normal(k1, (batch_size, h, w, c), jnp.float32),
            "target": jax.random.randint(
                k2, (batch_size,), 0, self.input_spec["num_classes"]
            ),
        }

    def num_params(self, params: Params) -> int:
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))


class ModelFactory:
    """Creates models by name (distributed_trainer.py:116-119).

    Supported (README.md:85-92): gpt2[-small|-medium|-large|-xl],
    resnet32/50/101, vgg11/13/16, plus gpt2[-size]-moe MoE variants
    (beyond-reference; SURVEY §2.4 EP row).  ``overrides`` reach the family
    config — tests use tiny GPT-2s via n_layer/n_embd/vocab_size overrides.
    """

    def create_model(self, model_name: str, **overrides: Any) -> ModelBundle:
        name = model_name.lower()
        if name.startswith("gpt") and name.endswith("-moe"):
            from trustworthy_dl_tpu.models import moe

            seq_len = overrides.pop("seq_len", 128)
            cfg = moe.MoEConfig.from_name(name, **overrides)
            return ModelBundle(
                name=name,
                kind="lm",
                config=cfg,
                init=lambda rng, c=cfg: moe.init_params(rng, c),
                apply=lambda p, x, c=cfg: moe.forward(p, x, c),
                loss=lambda p, b, c=cfg: moe.loss_fn(p, b, c),
                num_blocks=cfg.n_layer,
                input_spec={"seq_len": seq_len, "vocab_size": cfg.vocab_size},
                apply_monitor=lambda p, x, c=cfg: moe.forward_with_monitor(
                    p, x, c
                ),
                loss_monitor=lambda p, b, c=cfg: moe.loss_with_monitor(
                    p, b, c
                ),
            )
        if name.startswith("gpt"):
            seq_len = overrides.pop("seq_len", 128)
            cfg = gpt2.GPT2Config.from_name(name, **overrides)
            return ModelBundle(
                name=name,
                kind="lm",
                config=cfg,
                init=lambda rng, c=cfg: gpt2.init_params(rng, c),
                apply=lambda p, x, c=cfg: gpt2.forward(p, x, c),
                loss=lambda p, b, c=cfg: gpt2.loss_fn(p, b, c),
                num_blocks=cfg.n_layer,
                input_spec={"seq_len": seq_len, "vocab_size": cfg.vocab_size},
                apply_monitor=lambda p, x, c=cfg: gpt2.forward_with_monitor(
                    p, x, c
                ),
                loss_monitor=lambda p, b, c=cfg: gpt2.loss_with_monitor(
                    p, b, c
                ),
            )
        if name.startswith("resnet"):
            num_classes = overrides.pop("num_classes", 10)
            image = overrides.pop("image_shape", (32, 32, 3))
            cfg = resnet.ResNetConfig.from_name(
                name, num_classes=num_classes,
                small_input=image[0] <= 64, **overrides
            )
            return ModelBundle(
                name=name,
                kind="vision",
                config=cfg,
                init=lambda rng, c=cfg: resnet.init_params(rng, c),
                apply=lambda p, x, c=cfg: resnet.forward(p, x, c),
                loss=lambda p, b, c=cfg: resnet.loss_fn(p, b, c),
                num_blocks=sum(cfg.stage_sizes),
                input_spec={"image_shape": image, "num_classes": num_classes},
            )
        if name.startswith("vgg"):
            num_classes = overrides.pop("num_classes", 10)
            image = overrides.pop("image_shape", (32, 32, 3))
            cfg = vgg.VGGConfig.from_name(name, num_classes=num_classes,
                                          **overrides)
            return ModelBundle(
                name=name,
                kind="vision",
                config=cfg,
                init=lambda rng, c=cfg: vgg.init_params(rng, c),
                apply=lambda p, x, c=cfg: vgg.forward(p, x, c),
                loss=lambda p, b, c=cfg: vgg.loss_fn(p, b, c),
                num_blocks=len([e for e in cfg.plan if e != "M"]),
                input_spec={"image_shape": image, "num_classes": num_classes},
            )
        raise ValueError(f"unknown model {model_name!r}")


def create_model(model_name: str, **overrides: Any) -> ModelBundle:
    return ModelFactory().create_model(model_name, **overrides)


# README.md:60 usage-example alias (`from trustworthy_dl.models import get_model`).
get_model = create_model

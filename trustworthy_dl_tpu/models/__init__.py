from trustworthy_dl_tpu.models.factory import ModelBundle, ModelFactory, create_model, get_model

__all__ = ["ModelBundle", "ModelFactory", "create_model", "get_model"]

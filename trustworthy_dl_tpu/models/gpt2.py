"""GPT-2 family, TPU-first.

The reference only ever touches GPT-2 through ``model.transformer.h`` — a
python list of blocks it slices into contiguous per-node chunks
(distributed_trainer.py:124-135).  Here the blocks are a *stacked* pytree
(leading axis = layer), which is the TPU-native analogue: a pipeline stage is
a leading-axis slice, `lax.scan` applies the stack with one compiled block
body, and sharding the leading axis over the 'stage' mesh axis IS the
reference's layer partitioning.

Sizes follow the public GPT-2 family: small 12L/768/12H, medium 24L/1024/16H,
large 36L/1280/20H, xl 48L/1600/25H (vocab 50257, context 1024).

The attention implementation is pluggable (``attn_impl``): "full" (fused
softmax attention), "ring" / "ulysses" (sequence-parallel variants from
trustworthy_dl_tpu.parallel.sequence) — long-context support is first-class,
not bolted on.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import layers as L

Params = Dict[str, Any]

GPT2_SIZES = {
    "gpt2": dict(n_layer=12, n_embd=768, n_head=12),
    "gpt2-small": dict(n_layer=12, n_embd=768, n_head=12),
    "gpt2-medium": dict(n_layer=24, n_embd=1024, n_head=16),
    "gpt2-large": dict(n_layer=36, n_embd=1280, n_head=20),
    "gpt2-xl": dict(n_layer=48, n_embd=1600, n_head=25),
}


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_layer: int = 12
    n_embd: int = 768
    n_head: int = 12
    dtype: Any = jnp.bfloat16
    # full | flash | ring | ulysses | auto.  "auto" (the default) picks
    # the Pallas flash kernel for T >= AUTO_FLASH_MIN_T — where its
    # advantage is measured (BASELINE.md: 1.29-2.92× fwd+bwd) — and the
    # fused XLA path below it (measured faster under block-remat at
    # short T); the branch resolves at trace time from the static shape,
    # so short-T programs are bit-identical to attn_impl="full".
    attn_impl: str = "auto"
    remat: bool = False
    # Remat granularity when ``remat`` is on: "block" rematerialises the
    # whole transformer block (max memory saving, max recompute);
    # "attention" saves every intermediate EXCEPT the O(T²) attention
    # scores/probs — the dominant residuals — so only the attention core
    # recomputes in the backward pass (less recompute, slightly more
    # memory).
    remat_policy: str = "block"
    # Vocab-chunked fused lm-head+CE (ops/fused_ce.py): the loss never
    # materialises the [B, T, V] logits.  0 forces the materialised-logits
    # path, an int > 0 forces chunking with that width, and "auto" (the
    # default, mirroring attn_impl) resolves per shape at trace time:
    # chunked only where the materialised logits would pressure HBM
    # (auto_picks_chunked_ce) — below that the materialised path is
    # measured faster (BASELINE.md: chunked is −8 % at the default batch).
    lm_head_chunk: Any = "auto"

    @staticmethod
    def from_name(name: str, **overrides: Any) -> "GPT2Config":
        key = name.lower()
        if key not in GPT2_SIZES:
            raise ValueError(f"unknown GPT-2 size {name!r}")
        kwargs = dict(GPT2_SIZES[key])
        kwargs.update(overrides)
        return GPT2Config(**kwargs)


# --------------------------------------------------------------------------
# Attention registry — parallel/sequence.py registers "ring" and "ulysses".
# --------------------------------------------------------------------------

AttnFn = Callable[[jax.Array, jax.Array, jax.Array, bool], jax.Array]
_ATTN_REGISTRY: Dict[str, AttnFn] = {}


def register_attention(name: str, fn: AttnFn) -> None:
    _ATTN_REGISTRY[name] = fn


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True) -> jax.Array:
    """[B, H, T, D] softmax attention.  XLA fuses the softmax chain; the
    matmuls land on the MXU in bf16.  The O(T²) intermediates are tagged
    with checkpoint_name so the "attention" remat policy can drop exactly
    them (see apply_blocks)."""
    from jax.ad_checkpoint import checkpoint_name

    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        t_q, t_k = q.shape[-2], k.shape[-2]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    scores = checkpoint_name(scores, "attn_scores")
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    probs = checkpoint_name(probs, "attn_probs")
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


register_attention("full", full_attention)


AUTO_FLASH_MIN_T = 1024


def auto_picks_flash(t: int, d: int) -> bool:
    """THE attn_impl='auto' dispatch predicate — shared by the attention
    registry AND the remat-policy classifier (apply_blocks), so 'does auto
    resolve to the flash kernel here?' has exactly one answer.  Flash is
    picked for long sequences (where its advantage is measured,
    BASELINE.md), only for kernel-eligible shapes, and only on the TPU
    backend (off-TPU the kernel would run in interpret mode — orders of
    magnitude slower, correctness-test territory)."""
    from trustworthy_dl_tpu.ops.flash_attention import supports_flash

    return (t >= AUTO_FLASH_MIN_T and supports_flash(t, d)
            and jax.default_backend() == "tpu")


def _auto_attention(q, k, v, causal=True):
    """Per-shape dispatch (see auto_picks_flash): the Pallas flash kernel
    where its advantage is real, the fused XLA path everywhere else —
    shapes are static under jit, so the branch resolves at trace time."""
    from trustworthy_dl_tpu.ops.flash_attention import flash_attention

    if auto_picks_flash(q.shape[-2], q.shape[-1]):
        return flash_attention(q, k, v, causal)
    return _ATTN_REGISTRY["full"](q, k, v, causal)


# lm_head_chunk="auto": chunk width used when the predicate picks the
# fused path (the bench-swept sweet spot), and the per-node materialised-
# logits budget above which it engages.  The budget is calibrated on the
# measured crossover (BASELINE.md): 4 nodes × b16 × T512 × V50257 bf16
# logits are ~0.82 GiB/node and the materialised path wins by 8 %; at
# b32/node (~1.65 GiB/node) the materialised program exceeds HBM and only
# the chunked path runs.  1 GiB/node splits the two.
AUTO_CE_CHUNK = 8192
AUTO_CE_MAX_LOGITS_BYTES = 1 << 30


def auto_picks_chunked_ce(num_tokens: int, vocab: int,
                          itemsize: int = 2) -> bool:
    """THE lm_head_chunk='auto' dispatch predicate — one answer to 'does
    auto use the vocab-chunked fused CE here?', shared by the train loss,
    both eval steps, and the tests.  Picks chunked exactly when this
    node's materialised [tokens, vocab] logits would exceed
    AUTO_CE_MAX_LOGITS_BYTES."""
    return num_tokens * vocab * itemsize > AUTO_CE_MAX_LOGITS_BYTES


def resolve_lm_head_chunk(cfg: "GPT2Config", num_tokens: int) -> int:
    """Trace-time resolution of ``cfg.lm_head_chunk`` for a loss over
    ``num_tokens`` target positions: explicit settings pass through
    ("auto" is the only non-int value), auto applies the predicate.
    Shapes are static under jit, so the branch costs nothing."""
    chunk = cfg.lm_head_chunk
    if chunk == "auto":
        itemsize = jnp.dtype(cfg.dtype).itemsize
        if auto_picks_chunked_ce(num_tokens, cfg.vocab_size, itemsize):
            return AUTO_CE_CHUNK
        return 0
    return int(chunk or 0)


def get_attention(name: str) -> AttnFn:
    if name not in _ATTN_REGISTRY:
        # Late registration: sequence-parallel impls live in parallel/,
        # the Pallas blockwise kernel in ops/.
        if name in ("ring", "ulysses"):
            import trustworthy_dl_tpu.parallel.sequence  # noqa: F401
        elif name == "flash":
            from trustworthy_dl_tpu.ops.flash_attention import flash_attention
            register_attention("flash", flash_attention)
        elif name == "auto":
            register_attention("auto", _auto_attention)
        if name not in _ATTN_REGISTRY:
            raise ValueError(f"unknown attention impl {name!r}")
    return _ATTN_REGISTRY[name]


# --------------------------------------------------------------------------
# Parameters
# --------------------------------------------------------------------------


def init_block_params(key: jax.Array, cfg: GPT2Config) -> Params:
    ks = jax.random.split(key, 4)
    d = cfg.n_embd
    scale = 0.02
    return {
        "ln_1": L.layernorm_init(d),
        "attn": {
            "qkv": {
                "w": L.uniform_scaling_init(ks[0], (d, 3 * d), scale),
                "b": jnp.zeros((3 * d,), jnp.float32),
            },
            "proj": {
                "w": L.uniform_scaling_init(
                    ks[1], (d, d), scale / math.sqrt(2 * cfg.n_layer)
                ),
                "b": jnp.zeros((d,), jnp.float32),
            },
        },
        "ln_2": L.layernorm_init(d),
        "mlp": {
            "fc": {
                "w": L.uniform_scaling_init(ks[2], (d, 4 * d), scale),
                "b": jnp.zeros((4 * d,), jnp.float32),
            },
            "proj": {
                "w": L.uniform_scaling_init(
                    ks[3], (4 * d, d), scale / math.sqrt(2 * cfg.n_layer)
                ),
                "b": jnp.zeros((d,), jnp.float32),
            },
        },
    }


def init_params(key: jax.Array, cfg: GPT2Config) -> Params:
    k_wte, k_wpe, k_blocks = jax.random.split(key, 3)
    block_keys = jax.random.split(k_blocks, cfg.n_layer)
    # Stacked blocks: every leaf has leading axis n_layer — the
    # `transformer.h` equivalent, partitionable by slicing axis 0.
    blocks = jax.vmap(lambda k: init_block_params(k, cfg))(block_keys)
    return {
        "wte": L.embedding_init(k_wte, cfg.vocab_size, cfg.n_embd),
        "wpe": L.embedding_init(k_wpe, cfg.n_positions, cfg.n_embd),
        "blocks": blocks,
        "ln_f": L.layernorm_init(cfg.n_embd),
        # lm_head is tied to wte (standard GPT-2 weight tying).
    }


def logical_axes() -> Params:
    """The model's sharding declaration — named once, HERE, and resolved
    per parallelism mode by the registry (core/sharding.py).  Each leaf
    is a tuple of logical axis names, one per dim of the matching param
    (blocks carry the stacked ``layer`` leading dim).  Megatron layout:
    qkv/fc shard their output dim (column parallel, ``w_tp``), the two
    proj weights shard their input dim (row parallel) so the pair needs
    one all-reduce; col-parallel biases shard, row-parallel biases and
    norms/embeddings replicate."""
    from trustworthy_dl_tpu.core import sharding as shreg

    LYR, HID, TP = shreg.LAYER, shreg.HIDDEN, shreg.W_TP
    block = {
        "ln_1": {"scale": (LYR, HID), "bias": (LYR, HID)},
        "attn": {
            "qkv": {"w": (LYR, HID, TP), "b": (LYR, TP)},
            "proj": {"w": (LYR, TP, HID), "b": (LYR, HID)},
        },
        "ln_2": {"scale": (LYR, HID), "bias": (LYR, HID)},
        "mlp": {
            "fc": {"w": (LYR, HID, TP), "b": (LYR, TP)},
            "proj": {"w": (LYR, TP, HID), "b": (LYR, HID)},
        },
    }
    return {
        "wte": (None, HID),
        "wpe": (None, HID),
        "blocks": block,
        "ln_f": {"scale": (HID,), "bias": (HID,)},
    }


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------


def block_forward(block: Params, x: jax.Array, cfg: GPT2Config) -> jax.Array:
    """One transformer block on [B, T, D] activations."""
    dtype = cfg.dtype
    attn_fn = get_attention(cfg.attn_impl)
    b, t, d = x.shape
    h = cfg.n_head

    y = L.layernorm(block["ln_1"], x).astype(dtype)
    qkv = L.dense(block["attn"]["qkv"], y, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    # [B, T, D] -> [B, H, T, D/H]
    reshape = lambda a: a.reshape(b, t, h, d // h).transpose(0, 2, 1, 3)
    out = attn_fn(reshape(q), reshape(k), reshape(v), True)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, d)
    x = x + L.dense(block["attn"]["proj"], out, dtype).astype(x.dtype)

    y = L.layernorm(block["ln_2"], x).astype(dtype)
    y = L.dense(block["mlp"]["fc"], y, dtype)
    y = jax.nn.gelu(y)
    x = x + L.dense(block["mlp"]["proj"], y, dtype).astype(x.dtype)
    return x


def apply_blocks(blocks: Params, x: jax.Array, cfg: GPT2Config) -> jax.Array:
    """Scan the stacked block params over the activations — one compiled
    block body regardless of depth."""
    body = block_forward
    if cfg.remat:
        # "auto" resolves per shape: wherever it does NOT pick the flash
        # kernel it IS the full XLA path, so the attention policy's tagged
        # names exist and the cheap policy applies (the shared
        # auto_picks_flash predicate keeps this classification and the
        # dispatch itself from ever drifting apart).
        t, d_head = x.shape[-2], cfg.n_embd // cfg.n_head
        effectively_full = cfg.attn_impl == "full" or (
            cfg.attn_impl == "auto" and not auto_picks_flash(t, d_head)
        )
        if cfg.remat_policy == "attention" and effectively_full:
            # Save everything except the O(T²) scores/probs: only the
            # attention core recomputes in the backward pass.  Only the
            # "full" impl tags those names — the Pallas/ring paths never
            # materialise them (that is their point), so for any other
            # impl the policy would match nothing and silently save ALL
            # intermediates; fall through to block remat instead.
            from jax.ad_checkpoint import checkpoint_policies as cp

            policy = cp.save_anything_except_these_names(
                "attn_scores", "attn_probs"
            )
            body = jax.checkpoint(body, static_argnums=(2,), policy=policy)
        else:
            body = jax.checkpoint(body, static_argnums=(2,))

    def scan_fn(h, block):
        return body(block, h, cfg), None

    x, _ = jax.lax.scan(scan_fn, x, blocks)
    return x


def embed(params: Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    t = tokens.shape[-1]
    pos = jnp.arange(t)
    x = params["wte"][tokens] + params["wpe"][pos]
    return x.astype(jnp.float32)


def project_logits(params: Params, normed: jax.Array, cfg: GPT2Config
                   ) -> jax.Array:
    """Tied-embedding projection [..., D] -> [..., vocab] (shared by
    forward and forward_with_monitor so the monitored logits can never
    drift from the trained ones)."""
    return (normed.astype(cfg.dtype)
            @ params["wte"].T.astype(cfg.dtype)).astype(jnp.float32)


def unembed(params: Params, x: jax.Array, cfg: GPT2Config) -> jax.Array:
    return project_logits(params, L.layernorm(params["ln_f"], x), cfg)


def forward(params: Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens [B, T] -> logits [B, T, vocab]."""
    x = embed(params, tokens, cfg)
    x = apply_blocks(params["blocks"], x, cfg)
    return unembed(params, x, cfg)


def forward_with_monitor(params: Params, tokens: jax.Array, cfg: GPT2Config
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """tokens [B, T] -> (logits [B,T,V], features [B,T,D], mean_logits [V]).

    ``features`` are the final hidden activations *before* ln_f — the
    node-boundary output the reference's detector actually monitored
    (distributed_trainer.py:160-170 watches partition outputs, which are
    hidden activations, not logits).  Pre-norm matters: LayerNorm is
    scale/shift-invariant per position, so post-ln features would read
    identical under an activation-scaling corruption and blind the output
    battery.  They are vocab_size/n_embd ≈ 65× smaller than the logits, so
    detector batteries over them are nearly free and leave the
    cross-entropy's logits computation free to fuse.  ``mean_logits`` (for
    Byzantine/backdoor consensus signatures) is exact: the tied projection
    is linear, so mean over positions commutes with it —
    mean(normed) @ W == mean(normed @ W)."""
    x = embed(params, tokens, cfg)
    x = apply_blocks(params["blocks"], x, cfg)
    normed = L.layernorm(params["ln_f"], x)
    logits = project_logits(params, normed, cfg)
    mean_normed = jnp.mean(normed, axis=tuple(range(normed.ndim - 1)))
    mean_logits = project_logits(params, mean_normed, cfg)
    return logits, x, mean_logits


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: GPT2Config
            ) -> jax.Array:
    """Next-token cross entropy on {'input','target'} batches (targets are
    the shifted stream, produced by data/loader.py)."""
    if resolve_lm_head_chunk(cfg, int(batch["target"].size)):
        loss, _, _ = loss_with_monitor(params, batch, cfg)
        return loss
    logits = forward(params, batch["input"], cfg)
    return L.cross_entropy_loss(logits, batch["target"])


def head_loss_and_signature(params: Params, x: jax.Array,
                            targets: jax.Array, cfg: GPT2Config
                            ) -> Tuple[jax.Array, jax.Array]:
    """Final ln_f + tied head on [B, T, D] hiddens -> (mean CE, mean_logits).

    One implementation shared by the GPT-2 and MoE loss paths.  When
    ``cfg.lm_head_chunk`` is set the cross-entropy goes through the
    vocab-chunked fused head (ops/fused_ce.py), so the [B, T, V] logits
    are never materialised.  ``mean_logits`` (the Byzantine/backdoor
    consensus signature) stays exact and cheap either way: the tied
    projection is linear, so it is computed from the position-mean of the
    normed activations ([D] @ [D, V])."""
    normed = L.layernorm(params["ln_f"], x)
    mean_normed = jnp.mean(normed, axis=tuple(range(normed.ndim - 1)))
    mean_logits = project_logits(params, mean_normed, cfg)
    chunk = resolve_lm_head_chunk(cfg, int(targets.size))
    if chunk:
        from trustworthy_dl_tpu.ops.fused_ce import fused_lm_loss

        loss = fused_lm_loss(normed, params["wte"], targets,
                             chunk, cfg.dtype)
    else:
        logits = project_logits(params, normed, cfg)
        loss = L.cross_entropy_loss(logits, targets)
    return loss, mean_logits


def loss_with_monitor(params: Params, batch: Dict[str, jax.Array],
                      cfg: GPT2Config
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """{'input','target'} -> (loss, features [B,T,D], mean_logits [V]).

    The loss-bearing twin of ``forward_with_monitor`` for the engine's hot
    path: same detector features (pre-ln_f hidden states) and consensus
    signature, with the head fused via ``head_loss_and_signature``."""
    x = embed(params, batch["input"], cfg)
    x = apply_blocks(params["blocks"], x, cfg)
    loss, mean_logits = head_loss_and_signature(
        params, x, batch["target"], cfg
    )
    return loss, x, mean_logits


def num_params(params: Params) -> int:
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))

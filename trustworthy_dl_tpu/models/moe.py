"""Mixture-of-Experts GPT-2 with expert parallelism over the 'expert' axis.

Out of the reference's scope (SURVEY §2.4 lists EP/MoE as absent — "optional
stretch"), built here because the charter makes every parallelism strategy
first-class.  The design is the classic TPU-native dense-dispatch MoE
(GShard/Switch): routing is expressed as two einsums against a
[tokens, experts, capacity] dispatch/combine tensor, so the whole layer is
MXU matmuls with static shapes — no scatters, no dynamic shapes, nothing
XLA can't tile.  Expert weights carry a leading E axis sharded on the
'expert' mesh axis; under a mesh context (``use_expert_mesh``) sharding
constraints on the [E, C, d] expert blocks make GSPMD insert the canonical
all_to_all pair around the expert FFNs.

Routing: top-k (default 2) softmax gating, combine weights renormalised
over the selected experts; per-expert capacity C = ceil(k·S/E · factor);
overflow tokens fall through the residual stream untouched (standard drop
behavior).  The Switch load-balance auxiliary loss
(E · Σ_e fraction_e · mean_prob_e, =1 at perfect balance) is averaged over
layers and added to the LM loss with weight ``aux_weight``.

Everything outside the MLP is exactly models/gpt2.py (attention registry
included), and the params keep the stacked-blocks layout, so pipeline
slicing, checkpointing, and the detector battery all work unchanged.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.core.mesh import EXPERT_AXIS
from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.models import layers as L

Params = Dict[str, Any]

_EXPERT_MESH = None


def set_expert_mesh(mesh) -> None:
    global _EXPERT_MESH
    _EXPERT_MESH = mesh


@contextlib.contextmanager
def use_expert_mesh(mesh):
    """Make MoE forwards constrain expert blocks to the 'expert' mesh axis
    (same pattern as parallel/sequence.use_sequence_mesh)."""
    global _EXPERT_MESH
    prev = _EXPERT_MESH
    _EXPERT_MESH = mesh
    try:
        yield
    finally:
        _EXPERT_MESH = prev


def _expert_sharding():
    from trustworthy_dl_tpu.core import sharding as shreg

    mesh = _EXPERT_MESH
    if mesh is None or EXPERT_AXIS not in mesh.axis_names:
        return None
    return shreg.rules_for("expert").named_sharding(
        mesh, shreg.EXPERT, None, None)


@dataclasses.dataclass(frozen=True)
class MoEConfig(gpt2.GPT2Config):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    # Slot assignment under capacity pressure:
    #   "positional" — GShard's in-order claim (rank-0 before rank-1,
    #                  earlier tokens before later); overflow drops are
    #                  position-biased (late tokens lose).
    #   "priority"   — per-expert sort by gate probability (one
    #                  [E, S] top_k, static shapes): overflow drops the
    #                  LOWEST-prob assignments, minimising dropped gate
    #                  mass.  The TPU-friendly form of sorted dispatch.
    dispatch: str = "positional"

    def __post_init__(self) -> None:
        if self.dispatch not in ("positional", "priority"):
            raise ValueError(
                f"dispatch must be 'positional' or 'priority', got "
                f"{self.dispatch!r}"
            )

    @staticmethod
    def from_name(name: str, **overrides: Any) -> "MoEConfig":
        key = name.lower().replace("-moe", "")
        if key not in gpt2.GPT2_SIZES:
            raise ValueError(f"unknown GPT-2 size {name!r}")
        kwargs = dict(gpt2.GPT2_SIZES[key])
        kwargs.update(overrides)
        return MoEConfig(**kwargs)


# --------------------------------------------------------------------------
# Parameters: gpt2 block with the dense MLP swapped for router + experts
# --------------------------------------------------------------------------


def init_block_params(key: jax.Array, cfg: MoEConfig) -> Params:
    base = gpt2.init_block_params(key, cfg)
    k_router, k_fc, k_proj = jax.random.split(jax.random.fold_in(key, 17), 3)
    d, e, f = cfg.n_embd, cfg.n_experts, 4 * cfg.n_embd
    del base["mlp"]
    base["moe"] = {
        # Router kept f32: gating decisions are control flow, not compute.
        "router": {"w": L.uniform_scaling_init(k_router, (d, e), 0.02)},
        "fc": {
            "w": L.uniform_scaling_init(k_fc, (e, d, f), 0.02),
            "b": jnp.zeros((e, f), jnp.float32),
        },
        "proj": {
            "w": L.uniform_scaling_init(
                k_proj, (e, f, d), 0.02 / math.sqrt(2 * cfg.n_layer)
            ),
            "b": jnp.zeros((e, d), jnp.float32),
        },
    }
    return base


def init_params(key: jax.Array, cfg: MoEConfig) -> Params:
    k_wte, k_wpe, k_blocks = jax.random.split(key, 3)
    block_keys = jax.random.split(k_blocks, cfg.n_layer)
    blocks = jax.vmap(lambda k: init_block_params(k, cfg))(block_keys)
    return {
        "wte": L.embedding_init(k_wte, cfg.vocab_size, cfg.n_embd),
        "wpe": L.embedding_init(k_wpe, cfg.n_positions, cfg.n_embd),
        "blocks": blocks,
        "ln_f": L.layernorm_init(cfg.n_embd),
    }


# --------------------------------------------------------------------------
# Routing + expert FFN
# --------------------------------------------------------------------------


def _capacity(num_tokens: int, cfg: MoEConfig) -> int:
    """Per-expert slot count: ceil(k·S/E · factor), floored at 4 (tiny
    batches would otherwise drop most assignments) and ALWAYS clamped to
    ``num_tokens`` — the num_tokens clamp must come last, because a
    capacity above S is meaningless (an expert can hold at most every
    token) and the priority dispatcher's ``lax.top_k(rank.T, capacity)``
    trace-crashes when capacity exceeds its [E, S] operand width."""
    c = math.ceil(num_tokens * cfg.top_k / cfg.n_experts
                  * cfg.capacity_factor)
    return min(max(4, int(c)), num_tokens)


def _topk_gating(probs: jax.Array, top_k: int
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared by both dispatchers: (raw top-k probs [S, k], renormalised
    combine weights [S, k], expert indices [S, k])."""
    raw_probs, topk_idx = jax.lax.top_k(probs, top_k)
    norm = jnp.sum(raw_probs, axis=-1, keepdims=True)
    return raw_probs, raw_probs / jnp.maximum(norm, 1e-9), topk_idx


def _switch_aux_loss(probs: jax.Array, topk_idx: jax.Array) -> jax.Array:
    """Switch load-balance aux on rank-0 assignments: E · Σ_e f_e · P̄_e
    (=1 at perfect balance).  Shared so the dispatchers cannot drift."""
    e = probs.shape[1]
    top1 = jax.nn.one_hot(topk_idx[:, 0], e, dtype=jnp.float32)
    return e * jnp.sum(jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))


def router_dispatch(
    probs: jax.Array, cfg: MoEConfig, capacity: int
) -> Tuple[jax.Array, jax.Array]:
    """[S, E] gate probs -> (combine f32[S, E, C], aux f32[]).

    Top-k assignment with in-order positions: rank-0 choices claim slots
    before rank-1 (GShard's ordering), positions past capacity drop.  The
    dispatch mask is ``combine > 0``.
    """
    s, e = probs.shape
    _, topk_probs, topk_idx = _topk_gating(probs, cfg.top_k)

    combine = jnp.zeros((s, e, capacity), jnp.float32)
    counts = jnp.zeros((e,), jnp.int32)
    for r in range(cfg.top_k):                                # static k
        onehot = jax.nn.one_hot(topk_idx[:, r], e, dtype=jnp.int32)  # [S,E]
        pos = jnp.cumsum(onehot, axis=0) - 1 + counts[None, :]       # [S,E]
        within = (pos < capacity) & (onehot > 0)
        slot = jax.nn.one_hot(
            jnp.where(within, pos, capacity), capacity, dtype=jnp.float32
        )                                                     # OOB -> all-0
        combine = combine + topk_probs[:, r, None, None] * slot * \
            within[..., None].astype(jnp.float32)
        counts = counts + jnp.sum(onehot, axis=0)

    return combine, _switch_aux_loss(probs, topk_idx)


def router_dispatch_priority(
    probs: jax.Array, cfg: MoEConfig, capacity: int
) -> Tuple[jax.Array, jax.Array]:
    """[S, E] gate probs -> (combine f32[S, E, C], aux f32[]).

    Sorted dispatch: each expert keeps its top-``capacity`` assignments
    BY GATE PROBABILITY (one ``lax.top_k`` over the [E, S] assignment
    matrix — the static-shape TPU spelling of sorting assignments within
    each expert), so capacity overflow sheds the lowest-confidence
    routes instead of whatever arrived last.  Same contract as
    ``router_dispatch``; identical result when nothing overflows.
    """
    s, e = probs.shape
    raw_probs, renorm_probs, topk_idx = _topk_gating(probs, cfg.top_k)

    # Two assignment matrices over (token, expert): rank by the RAW gate
    # probability (the router's confidence — renormalisation would make
    # every top-1 weight 1.0 and destroy the ordering), combine with the
    # renormalised weight (the usual mixture semantics).
    rank = jnp.zeros((s, e), jnp.float32)
    weight = jnp.zeros((s, e), jnp.float32)
    for r in range(cfg.top_k):
        onehot = jax.nn.one_hot(topk_idx[:, r], e, dtype=jnp.float32)
        rank = rank + onehot * raw_probs[:, r, None]
        weight = weight + onehot * renorm_probs[:, r, None]

    vals, token_idx = jax.lax.top_k(rank.T, capacity)        # [E, C]
    keep = (vals > 0.0).astype(jnp.float32)                  # real routes
    w = jnp.take_along_axis(weight.T, token_idx, axis=1)     # [E, C]
    # combine[s, e, c] = w[e, c] iff token_idx[e, c] == s and kept.
    sel = jax.nn.one_hot(token_idx, s, dtype=jnp.float32)    # [E, C, S]
    combine = jnp.einsum("ecs,ec->sec", sel, w * keep)
    return combine, _switch_aux_loss(probs, topk_idx)


def moe_mlp(moe: Params, x: jax.Array, cfg: MoEConfig
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """[B, T, d] -> ([B, T, d], aux loss [], drop fraction []).  Two
    dispatch einsums around the per-expert FFN; expert blocks constrained
    to the 'expert' axis when a mesh context is live.  The drop fraction
    is the share of the S·k routed assignments that exceeded expert
    capacity and fell through the residual stream — invisible in the loss
    on any single step, so it is surfaced as a metric (VERDICT r4 weak #5)."""
    b, t, d = x.shape
    s = b * t
    xf = x.reshape(s, d)
    capacity = _capacity(s, cfg)

    gate_logits = xf.astype(jnp.float32) @ moe["router"]["w"]
    probs = jax.nn.softmax(gate_logits, axis=-1)
    dispatch_fn = (router_dispatch_priority if cfg.dispatch == "priority"
                   else router_dispatch)
    combine, aux = dispatch_fn(probs, cfg, capacity)          # [S, E, C]
    dispatch = (combine > 0).astype(cfg.dtype)
    kept = jnp.sum((combine > 0).astype(jnp.float32))
    drop = 1.0 - kept / (s * cfg.top_k)

    shard = _expert_sharding()
    constrain = (
        (lambda a: jax.lax.with_sharding_constraint(a, shard))
        if shard is not None else (lambda a: a)
    )

    # Token -> expert slots: [E, C, d] (GSPMD: all_to_all when sharded).
    expert_in = constrain(
        jnp.einsum("sec,sd->ecd", dispatch, xf.astype(cfg.dtype))
    )
    h = jnp.einsum("ecd,edf->ecf", expert_in,
                   moe["fc"]["w"].astype(cfg.dtype))
    h = jax.nn.gelu(h + moe["fc"]["b"][:, None].astype(cfg.dtype))
    out = jnp.einsum("ecf,efd->ecd", h, moe["proj"]["w"].astype(cfg.dtype))
    out = constrain(out + moe["proj"]["b"][:, None].astype(cfg.dtype))
    # Expert slots -> tokens, combine-weighted (f32 for the residual add).
    yf = jnp.einsum("sec,ecd->sd", combine, out.astype(jnp.float32))
    return yf.reshape(b, t, d), aux, drop


def block_forward(block: Params, x: jax.Array, cfg: MoEConfig
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """gpt2.block_forward with the MoE MLP; returns (x, aux, drop)."""
    dtype = cfg.dtype
    attn_fn = gpt2.get_attention(cfg.attn_impl)
    b, t, d = x.shape
    h = cfg.n_head

    y = L.layernorm(block["ln_1"], x).astype(dtype)
    qkv = L.dense(block["attn"]["qkv"], y, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    reshape = lambda a: a.reshape(b, t, h, d // h).transpose(0, 2, 1, 3)
    out = attn_fn(reshape(q), reshape(k), reshape(v), True)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, d)
    x = x + L.dense(block["attn"]["proj"], out, dtype).astype(x.dtype)

    y = L.layernorm(block["ln_2"], x)
    y, aux, drop = moe_mlp(block["moe"], y, cfg)
    return x + y.astype(x.dtype), aux, drop


def apply_blocks(blocks: Params, x: jax.Array, cfg: MoEConfig
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (x, mean aux loss, mean capacity-drop fraction)."""
    body = block_forward
    if cfg.remat:
        body = jax.checkpoint(body, static_argnums=(2,))

    def scan_fn(carry, block):
        h, aux_sum, drop_sum = carry
        h, aux, drop = body(block, h, cfg)
        return (h, aux_sum + aux, drop_sum + drop), None

    (x, aux_sum, drop_sum), _ = jax.lax.scan(
        scan_fn,
        (x, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        blocks,
    )
    return x, aux_sum / cfg.n_layer, drop_sum / cfg.n_layer


def forward(params: Params, tokens: jax.Array, cfg: MoEConfig) -> jax.Array:
    x = gpt2.embed(params, tokens, cfg)
    x, _, _ = apply_blocks(params["blocks"], x, cfg)
    return gpt2.unembed(params, x, cfg)


def forward_with_monitor(params: Params, tokens: jax.Array, cfg: MoEConfig
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Same contract as gpt2.forward_with_monitor (pre-ln features +
    mean-logits signature) so the in-step detector works unchanged."""
    x = gpt2.embed(params, tokens, cfg)
    x, _, _ = apply_blocks(params["blocks"], x, cfg)
    normed = L.layernorm(params["ln_f"], x)
    logits = gpt2.project_logits(params, normed, cfg)
    mean_normed = jnp.mean(normed, axis=tuple(range(normed.ndim - 1)))
    mean_logits = gpt2.project_logits(params, mean_normed, cfg)
    return logits, x, mean_logits


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: MoEConfig
            ) -> jax.Array:
    loss = loss_with_monitor(params, batch, cfg)[0]
    return loss


def loss_with_monitor(params: Params, batch: Dict[str, jax.Array],
                      cfg: MoEConfig
                      ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                 Dict[str, jax.Array]]:
    """Same contract as gpt2.loss_with_monitor, with the Switch
    load-balance aux loss folded in (the apply_monitor + external-CE path
    cannot carry it), plus a 4th element: model-aux diagnostics
    ({"moe_drop_fraction": f32[]}) that the trusted step surfaces into
    StepMetrics.  The head — incl. the ``cfg.lm_head_chunk`` fused
    vocab-chunked path — is gpt2.head_loss_and_signature, shared so the
    two families cannot drift."""
    x = gpt2.embed(params, batch["input"], cfg)
    x, aux, drop = apply_blocks(params["blocks"], x, cfg)
    lm, mean_logits = gpt2.head_loss_and_signature(
        params, x, batch["target"], cfg
    )
    return (lm + cfg.aux_weight * aux, x, mean_logits,
            {"moe_drop_fraction": drop})


def moe_ep_specs(params: Params):
    """PartitionSpec tree for expert parallelism: expert-dim arrays shard on
    'expert' (leading axis after the stacked-layer axis), everything else
    replicated.  Feed to NamedSharding/device_put like gpt2_tp_specs."""
    from trustworthy_dl_tpu.core import sharding as shreg

    rules = shreg.rules_for("expert")

    def spec(path, leaf):
        keys = [getattr(p, "key", getattr(p, "idx", None)) for p in path]
        if "moe" in keys and "router" not in keys:
            # [L, E, ...]: layer axis replicated, expert axis sharded.
            return rules.partition_spec(
                shreg.LAYER, shreg.EXPERT, *([None] * (leaf.ndim - 2)))
        return rules.partition_spec()

    return jax.tree_util.tree_map_with_path(spec, params)


def num_params(params: Params) -> int:
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))

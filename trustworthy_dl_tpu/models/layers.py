"""Primitive layers as pure functions over explicit param pytrees.

Hand-rolled (SURVEY §7.1 "flax.nnx or hand-rolled") so that:
  * pipeline stages are literal slices of stacked block params,
  * sharding annotations attach to raw arrays with no framework indirection,
  * everything works identically inside shard_map.

Normalisation is LayerNorm/GroupNorm rather than BatchNorm: BN's cross-device
batch statistics would entangle nodes with each other *outside* the
trust-gated aggregation path, corrupting per-node attribution of anomalies
(and needing extra collectives).  GroupNorm is the standard TPU-friendly
substitution and keeps every node's forward self-contained.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def uniform_scaling_init(key: jax.Array, shape: Tuple[int, ...], scale: float
                         ) -> jax.Array:
    return jax.random.normal(key, shape, jnp.float32) * scale


def dense_init(key: jax.Array, in_dim: int, out_dim: int,
               scale: Optional[float] = None) -> Params:
    if scale is None:
        scale = 1.0 / math.sqrt(in_dim)
    return {
        "w": uniform_scaling_init(key, (in_dim, out_dim), scale),
        "b": jnp.zeros((out_dim,), jnp.float32),
    }


def dense(params: Params, x: jax.Array, dtype: jnp.dtype = jnp.float32
          ) -> jax.Array:
    return x @ params["w"].astype(dtype) + params["b"].astype(dtype)


def layernorm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32),
            "bias": jnp.zeros((dim,), jnp.float32)}


def layernorm(params: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return y * params["scale"] + params["bias"]


def groupnorm_init(channels: int) -> Params:
    return {"scale": jnp.ones((channels,), jnp.float32),
            "bias": jnp.zeros((channels,), jnp.float32)}


def groupnorm(params: Params, x: jax.Array, groups: int = 8, eps: float = 1e-5
              ) -> jax.Array:
    """x: [..., H, W, C] NHWC."""
    *lead, h, w, c = x.shape
    groups = min(groups, c)
    while c % groups:
        groups -= 1
    xg = x.reshape(*lead, h, w, groups, c // groups)
    mean = jnp.mean(xg, axis=(-4, -3, -1), keepdims=True)
    var = jnp.mean((xg - mean) ** 2, axis=(-4, -3, -1), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = xg.reshape(*lead, h, w, c)
    return y * params["scale"] + params["bias"]


def conv_init(key: jax.Array, kh: int, kw: int, cin: int, cout: int) -> Params:
    fan_in = kh * kw * cin
    return {
        "w": uniform_scaling_init(key, (kh, kw, cin, cout),
                                  math.sqrt(2.0 / fan_in)),
        "b": jnp.zeros((cout,), jnp.float32),
    }


def conv2d(params: Params, x: jax.Array, stride: int = 1,
           padding: str = "SAME", dtype: jnp.dtype = jnp.float32) -> jax.Array:
    """NHWC conv — lowers straight onto the MXU via lax.conv_general_dilated."""
    y = jax.lax.conv_general_dilated(
        x.astype(dtype),
        params["w"].astype(dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + params["b"].astype(dtype)


def max_pool(x: jax.Array, window: int = 2, stride: int = 2) -> jax.Array:
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        (1, window, window, 1), (1, stride, stride, 1), "VALID",
    )


def avg_pool_global(x: jax.Array) -> jax.Array:
    return jnp.mean(x, axis=(-3, -2))


def embedding_init(key: jax.Array, vocab: int, dim: int) -> jax.Array:
    return jax.random.normal(key, (vocab, dim), jnp.float32) * 0.02


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       ignore_index: Optional[int] = None) -> jax.Array:
    """Mean token/example cross-entropy — the reference's criterion
    (distributed_trainer.py:435-439).

    Written as ``logsumexp(logits) - logits[target]`` rather than
    ``-log_softmax(logits)[target]``: log_softmax materialises a second
    [..., V] f32 tensor the size of the logits (≈0.8 GB for a b=8, T=512
    GPT-2 batch), while logsumexp is a fused reduction and the target
    gather touches one column.  Same math, same gradient
    (softmax − one-hot), a full logits-sized round-trip less HBM traffic.
    """
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tgt
    if ignore_index is not None:
        mask = (targets != ignore_index).astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def accuracy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32))

"""VGG family (11 / 13 / 16) in pure JAX, NHWC (README.md:90-91).

Convolution plans follow the standard configurations (A/B/D); the classifier
head is size-adaptive (global average pool + linear) so the same model serves
CIFAR-10 (32x32) and ImageNet-sized inputs without hardcoded flatten dims.
GroupNorm replaces BatchNorm (see models/layers.py docstring).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import layers as L

Params = Dict[str, Any]

# 'M' = maxpool; numbers = conv output channels.
VGG_PLANS: Dict[str, Tuple[Union[int, str], ...]] = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"),
}


@dataclasses.dataclass(frozen=True)
class VGGConfig:
    name: str = "vgg16"
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @staticmethod
    def from_name(name: str, num_classes: int = 10, **overrides: Any
                  ) -> "VGGConfig":
        key = name.lower()
        if key not in VGG_PLANS:
            raise ValueError(f"unknown vgg {name!r}")
        return VGGConfig(name=key, num_classes=num_classes, **overrides)

    @property
    def plan(self) -> Tuple[Union[int, str], ...]:
        return VGG_PLANS[self.name]


def init_params(key: jax.Array, cfg: VGGConfig) -> Params:
    convs = [c for c in cfg.plan if c != "M"]
    keys = jax.random.split(key, len(convs) + 1)
    params: Params = {"blocks": []}
    cin = 3
    ki = 0
    for entry in cfg.plan:
        if entry == "M":
            continue
        cout = int(entry)
        params["blocks"].append(
            {"conv": L.conv_init(keys[ki], 3, 3, cin, cout),
             "gn": L.groupnorm_init(cout)}
        )
        cin = cout
        ki += 1
    params["head"] = L.dense_init(keys[-1], cin, cfg.num_classes, scale=0.01)
    return params


def forward(params: Params, x: jax.Array, cfg: VGGConfig) -> jax.Array:
    dtype = cfg.dtype
    y = x.astype(dtype)
    bi = 0
    for entry in cfg.plan:
        if entry == "M":
            # Guard tiny feature maps (CIFAR inputs hit 1x1 before plan end).
            if y.shape[-3] >= 2 and y.shape[-2] >= 2:
                y = L.max_pool(y, 2, 2)
            continue
        p = params["blocks"][bi]
        y = jax.nn.relu(L.groupnorm(p["gn"], L.conv2d(p["conv"], y, 1, "SAME", dtype)))
        bi += 1
    pooled = L.avg_pool_global(y).astype(jnp.float32)
    return L.dense(params["head"], pooled)


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: VGGConfig
            ) -> jax.Array:
    logits = forward(params, batch["input"], cfg)
    return L.cross_entropy_loss(logits, batch["target"])

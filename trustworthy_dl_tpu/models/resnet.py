"""ResNet family (32 / 50 / 101) in pure JAX, NHWC.

The reference lists ResNet-32/50/101 as supported (README.md:90-92) but its
partitioner leaves the ResNet branch as a literal ``pass``
(distributed_trainer.py:137-140).  Here they are real:

* ResNet-32: the CIFAR variant (He et al. §4.2) — 3 stages of 5 basic blocks,
  16/32/64 channels, 3x3 stem.
* ResNet-50/101: bottleneck variant — stages [3,4,6,3] / [3,4,23,3],
  64→512 base widths, 7x7 stem (stride/pooling auto-shrunk for small inputs
  like CIFAR so the same model runs on 32x32 or 224x224).

GroupNorm replaces BatchNorm (see models/layers.py docstring).  For pipeline
partitioning every residual block is an element of a ``blocks`` list, so the
engine's stage splitter can slice ResNets the same way it slices GPT-2 —
closing the reference's empty branch.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import layers as L

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    name: str = "resnet32"
    num_classes: int = 10
    stage_sizes: Tuple[int, ...] = (5, 5, 5)
    widths: Tuple[int, ...] = (16, 32, 64)
    bottleneck: bool = False
    stem_width: int = 16
    small_input: bool = True   # CIFAR-style stem (3x3, no maxpool)
    dtype: Any = jnp.bfloat16

    @staticmethod
    def from_name(name: str, num_classes: int = 10, small_input: bool = True,
                  **overrides: Any) -> "ResNetConfig":
        key = name.lower()
        presets = {
            "resnet32": dict(stage_sizes=(5, 5, 5), widths=(16, 32, 64),
                             bottleneck=False, stem_width=16),
            "resnet50": dict(stage_sizes=(3, 4, 6, 3), widths=(64, 128, 256, 512),
                             bottleneck=True, stem_width=64),
            "resnet101": dict(stage_sizes=(3, 4, 23, 3), widths=(64, 128, 256, 512),
                              bottleneck=True, stem_width=64),
        }
        if key not in presets:
            raise ValueError(f"unknown resnet {name!r}")
        kwargs = dict(presets[key])
        kwargs.update(overrides)
        return ResNetConfig(name=key, num_classes=num_classes,
                            small_input=small_input, **kwargs)


def _block_out_width(cfg: ResNetConfig, width: int) -> int:
    return width * 4 if cfg.bottleneck else width


def init_block(key: jax.Array, cin: int, width: int, stride: int,
               cfg: ResNetConfig) -> Params:
    cout = _block_out_width(cfg, width)
    if cfg.bottleneck:
        ks = jax.random.split(key, 4)
        p: Params = {
            "conv1": L.conv_init(ks[0], 1, 1, cin, width),
            "gn1": L.groupnorm_init(width),
            "conv2": L.conv_init(ks[1], 3, 3, width, width),
            "gn2": L.groupnorm_init(width),
            "conv3": L.conv_init(ks[2], 1, 1, width, cout),
            "gn3": L.groupnorm_init(cout),
        }
        proj_key = ks[3]
    else:
        ks = jax.random.split(key, 3)
        p = {
            "conv1": L.conv_init(ks[0], 3, 3, cin, width),
            "gn1": L.groupnorm_init(width),
            "conv2": L.conv_init(ks[1], 3, 3, width, cout),
            "gn2": L.groupnorm_init(cout),
        }
        proj_key = ks[2]
    if stride != 1 or cin != cout:
        p["proj"] = L.conv_init(proj_key, 1, 1, cin, cout)
        p["gn_proj"] = L.groupnorm_init(cout)
    return p


def block_forward(p: Params, x: jax.Array, stride: int, cfg: ResNetConfig
                  ) -> jax.Array:
    dtype = cfg.dtype
    residual = x
    if cfg.bottleneck:
        y = jax.nn.relu(L.groupnorm(p["gn1"], L.conv2d(p["conv1"], x, 1, "SAME", dtype)))
        y = jax.nn.relu(L.groupnorm(p["gn2"], L.conv2d(p["conv2"], y, stride, "SAME", dtype)))
        y = L.groupnorm(p["gn3"], L.conv2d(p["conv3"], y, 1, "SAME", dtype))
    else:
        y = jax.nn.relu(L.groupnorm(p["gn1"], L.conv2d(p["conv1"], x, stride, "SAME", dtype)))
        y = L.groupnorm(p["gn2"], L.conv2d(p["conv2"], y, 1, "SAME", dtype))
    if "proj" in p:
        residual = L.groupnorm(p["gn_proj"], L.conv2d(p["proj"], x, stride, "SAME", dtype))
    return jax.nn.relu(y + residual.astype(y.dtype))


def _block_plan(cfg: ResNetConfig) -> List[Tuple[int, int]]:
    """[(width, stride), ...] flattened over stages."""
    plan = []
    for stage, (size, width) in enumerate(zip(cfg.stage_sizes, cfg.widths)):
        for i in range(size):
            stride = 2 if (i == 0 and stage > 0) else 1
            plan.append((width, stride))
    return plan


def init_params(key: jax.Array, cfg: ResNetConfig) -> Params:
    plan = _block_plan(cfg)
    keys = jax.random.split(key, len(plan) + 2)
    stem_kernel = 3 if cfg.small_input else 7
    params: Params = {
        "stem": L.conv_init(keys[0], stem_kernel, stem_kernel, 3, cfg.stem_width),
        "gn_stem": L.groupnorm_init(cfg.stem_width),
        "blocks": [],
    }
    cin = cfg.stem_width
    for k, (width, stride) in zip(keys[1:-1], plan):
        params["blocks"].append(init_block(k, cin, width, stride, cfg))
        cin = _block_out_width(cfg, width)
    params["head"] = L.dense_init(keys[-1], cin, cfg.num_classes, scale=0.01)
    return params


def forward(params: Params, x: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """x: [B, H, W, 3] -> logits [B, num_classes]."""
    dtype = cfg.dtype
    stem_stride = 1 if cfg.small_input else 2
    y = L.conv2d(params["stem"], x.astype(dtype), stem_stride, "SAME", dtype)
    y = jax.nn.relu(L.groupnorm(params["gn_stem"], y))
    if not cfg.small_input:
        y = L.max_pool(y, 3, 2)
    for p, (width, stride) in zip(params["blocks"], _block_plan(cfg)):
        y = block_forward(p, y, stride, cfg)
    pooled = L.avg_pool_global(y).astype(jnp.float32)
    return L.dense(params["head"], pooled)


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: ResNetConfig
            ) -> jax.Array:
    logits = forward(params, batch["input"], cfg)
    return L.cross_entropy_loss(logits, batch["target"])
